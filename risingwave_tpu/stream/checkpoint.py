"""Pipelined asynchronous checkpoint persistence.

Reference counterpart: Hummock's uploader (uploader/mod.rs:1478) —
sealed epochs' dirty batches are uploaded OFF the barrier path and the
committed epoch advances only when the upload acks; the barrier loop
never blocks on object-store I/O unless the uploader falls behind
(the write-limit stall).

Shape here: one daemon thread per job.  A snapshot barrier SEALS an
epoch — shadow update dispatched, (epoch, digest vector, shadow leaf
refs, source/spill state) enqueued — and returns immediately.  The
uploader thread then:

1. fetches the epoch's payload device→host (the digest diff picks the
   dirty runs; ``CheckpointStore.prepare``), then marks the task
   FETCHED — the next shadow update donates the shadow buffers, so it
   must wait for this point and no further;
2. encodes + writes the npz/meta objects and commits the manifest
   (``CheckpointStore.commit``), then ACKS the epoch.

The barrier loop polls acks (cheap, lock-free-ish deque) to advance
``committed_epoch`` and deferred sink delivery; ``wait_window`` is the
bounded in-flight contract — sealing stalls when more than N epochs
are unacked, mirroring the storage service's L0-depth write stall.
Recovery and orderly-stop paths call ``drain()`` first, so nothing
sealed is silently dropped by a clean exit.

A failed upload retries FIRST (the unified ``RetryPolicy`` — capped
exponential backoff, deterministic jitter; store blips and injected
chaos faults are transient by construction), and only after the
budget is exhausted turns LOUD: the partial objects are vacuumed and
the error is re-raised on the barrier loop at the next window wait /
drain — a job cannot keep sealing epochs that will never become
durable.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from risingwave_tpu.common.faults import RetryPolicy
from risingwave_tpu.common.trace import GLOBAL_TRACE


@dataclass
class UploadTask:
    """One sealed epoch queued for durable persistence."""

    epoch: int
    #: flat device leaves of the shadow AT SEAL TIME (the next shadow
    #: update donates these buffers — fetch must complete first)
    leaves: tuple
    #: device uint64 digest vector (computed by the shadow update; the
    #: store diffs it against its last persisted digests)
    digests: Any
    shapes: list
    treedef: Any
    source_state: dict
    #: per-leaf (rows, row_elems) digest-lane structure from a
    #: per-shard shadow (mesh-stacked trees), None entries = flat —
    #: prepare() must extract dirty runs on the same block grid
    lanes: Any = None
    #: [(store_key, host_state)] spill-tier saves, persisted FIRST (a
    #: crash between tier and job save leaves the tier ahead, which
    #: recovery rewinds; the reverse order loses absorbed groups)
    spill: list = field(default_factory=list)
    fetched: threading.Event = field(default_factory=threading.Event)
    done: threading.Event = field(default_factory=threading.Event)
    error: Exception | None = None
    #: (trace_id, span_id) captured AT SEAL TIME — the uploader thread
    #: has no thread-local trace context of its own, so the prepare/
    #: commit spans parent under the seal that enqueued this epoch
    trace_ctx: tuple | None = None


class CheckpointUploader:
    """Background uploader for one job's checkpoint chain."""

    def __init__(self, store, job_name: str, metrics=None,
                 retry: "RetryPolicy | None" = None):
        self.store = store
        self.job_name = job_name
        self.metrics = metrics
        #: transient store failures (incl. injected chaos faults)
        #: retry here, OFF the barrier loop, before anything surfaces
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay_s=0.05, max_delay_s=1.0,
            metrics=metrics, op="upload",
        )
        self._q: deque[UploadTask] = deque()
        self._cv = threading.Condition()
        self._pending: list[UploadTask] = []
        self._acked: deque[int] = deque()
        self._thread: threading.Thread | None = None
        self._stop = False
        self.error: Exception | None = None
        #: observability (host counters; exported as gauges on demand)
        self.uploads_total = 0
        self.upload_seconds_total = 0.0
        self.stall_seconds_total = 0.0
        self.max_queue_depth = 0

    @property
    def retries_total(self) -> int:
        return self.retry.retries

    # -- producer side (the barrier loop) --------------------------------
    def enqueue(self, task: UploadTask) -> None:
        with self._cv:
            self._raise_if_failed()
            self._q.append(task)
            self._pending.append(task)
            self.max_queue_depth = max(self.max_queue_depth,
                                       len(self._pending))
            self._cv.notify_all()
        # AFTER the append: an idle thread only exits while the queue
        # is empty (under the cv), so a non-empty queue pins it alive
        # and a dead one is restarted here
        self._ensure_thread()

    def pending(self) -> int:
        with self._cv:
            return len(self._pending)

    def take_acked(self) -> list[int]:
        """Drain acked epochs (ascending — uploads are FIFO)."""
        with self._cv:
            out = list(self._acked)
            self._acked.clear()
            return out

    def wait_fetched(self, timeout: float = 600.0) -> None:
        """Block until every queued task's device→host fetch completed
        — the shadow buffers are about to be donated."""
        with self._cv:
            tasks = list(self._pending)
        deadline = time.monotonic() + timeout
        for t in tasks:
            if not t.fetched.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(
                    f"{self.job_name}: upload fetch of epoch {t.epoch} "
                    f"did not complete within {timeout}s"
                )
        self._raise_if_failed()

    def wait_window(self, window: int, timeout: float = 600.0) -> float:
        """The bounded in-flight contract: block while more than
        ``window`` sealed epochs are unacked.  Returns seconds stalled
        (the job's write-stall meter, like the L0-depth stall)."""
        with self._cv:
            self._raise_if_failed()
            if len(self._pending) <= window:
                return 0.0
            t0 = time.monotonic()
            deadline = t0 + timeout
            while len(self._pending) > window:
                if self.error is not None:
                    self._raise_if_failed()
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"{self.job_name}: checkpoint uploader still "
                        f"{len(self._pending)} epochs behind after "
                        f"{timeout}s"
                    )
                self._cv.wait(min(left, 0.5))
            stalled = time.monotonic() - t0
            self.stall_seconds_total += stalled
            return stalled

    def drain(self, raise_error: bool = True, timeout: float = 600.0,
              ) -> None:
        """Block until the queue is empty (recovery/stop/tick-boundary
        paths: nothing sealed may be dropped)."""
        with self._cv:
            deadline = time.monotonic() + timeout
            while self._pending:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"{self.job_name}: upload queue did not drain "
                        f"within {timeout}s"
                    )
                self._cv.wait(min(left, 0.5))
            if raise_error:
                self._raise_if_failed()

    def clear_error(self) -> None:
        """Recovery acknowledged the failure; the next save re-bases."""
        with self._cv:
            self.error = None

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def _raise_if_failed(self) -> None:
        if self.error is not None:
            raise RuntimeError(
                f"{self.job_name}: checkpoint upload failed — durable "
                "progress is stuck; recover() to rewind to the last "
                "committed epoch"
            ) from self.error

    # -- the uploader thread ---------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name=f"ckpt-upload-{self.job_name}",
                daemon=True,
            )
            self._thread.start()

    #: idle uploader threads exit after this long with an empty queue
    #: (test suites build many engines; re-enqueue restarts the thread)
    _IDLE_EXIT_S = 10.0

    def _run(self) -> None:
        import numpy as np

        idle_since = time.monotonic()
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    if time.monotonic() - idle_since > self._IDLE_EXIT_S:
                        return
                    self._cv.wait(0.5)
                if self._stop and not self._q:
                    return
                task = self._q.popleft()
            idle_since = time.monotonic()
            t0 = time.perf_counter()
            try:
                # tier saves FIRST (see UploadTask.spill).  Every
                # store write retries through the policy: re-putting
                # the same key is idempotent (atomic object replace),
                # so a commit that died between the npz and the
                # manifest just rewrites both.
                for key, host_state in task.spill:
                    self.retry.run(
                        lambda k=key, hs=host_state: self.store.save(
                            k, task.epoch, hs, {}),
                        retry_on=(OSError,), label="spill_save",
                    )
                digests = np.asarray(task.digests) \
                    if task.digests is not None else None
                with GLOBAL_TRACE.span("ckpt_prepare",
                                       ctx=task.trace_ctx,
                                       job=self.job_name,
                                       epoch=task.epoch):
                    prep = self.store.prepare(
                        self.job_name, task.epoch, task.leaves,
                        task.shapes, task.treedef, task.source_state,
                        digests=digests, lanes=task.lanes,
                    )
                # host payload materialized: the shadow may be donated
                task.fetched.set()
                with GLOBAL_TRACE.span("ckpt_commit",
                                       ctx=task.trace_ctx,
                                       job=self.job_name,
                                       epoch=task.epoch):
                    self.retry.run(lambda: self.store.commit(prep),
                                   retry_on=(OSError,), label="commit")
                dt = time.perf_counter() - t0
                with self._cv:
                    self._acked.append(task.epoch)
                    self._pending.remove(task)
                    self.uploads_total += 1
                    self.upload_seconds_total += dt
                    self._cv.notify_all()
                if self.metrics is not None:
                    self.metrics.observe(
                        "checkpoint_upload_seconds", dt,
                        job=self.job_name,
                    )
                task.done.set()
            except Exception as e:  # noqa: BLE001 — surfaced on the loop
                # retry budget exhausted (or a non-transient failure):
                # reap the partial epoch objects so nothing un-durable
                # lingers in the store, then go loud on the loop
                try:
                    self.store.vacuum_orphans(self.job_name)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
                task.error = e
                task.fetched.set()
                task.done.set()
                with self._cv:
                    self.error = e
                    self._pending.remove(task)
                    self._cv.notify_all()
