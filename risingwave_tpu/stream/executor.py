"""Executor protocol + stateless executors.

Reference counterpart: the ``Execute`` trait (src/stream/src/executor/
mod.rs:243) and the stateless row operators (project/, filter.rs, …).

TPU-first design
----------------
The reference models an executor as an async stream of messages.  Here an
executor is a pair of *pure, traceable* transition functions so that an
entire executor chain (a fragment) collapses into ONE jitted XLA program
per chunk (SURVEY.md §7.1 "Fragment = jitted SPMD step function"):

- ``init_state() -> pytree``                         device-resident state
- ``apply(state, chunk) -> (state, chunk | None)``   per-chunk transform
- ``flush(state, epoch) -> (state, chunk | None)``   barrier-time emission

``apply``/``flush`` must make a *static* choice of whether they return a
chunk (so the jitted step has a fixed pytree structure).  Stateless
operators return the transformed chunk from ``apply`` and nothing from
``flush``; aggregations buffer in ``apply`` and emit from ``flush``
(emit-on-barrier, ref hash_agg.rs flush_data).

Filtering never compacts: it narrows the validity mask (the reference's
visibility ``Bitmap``), keeping every kernel shape-static.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp

from risingwave_tpu.common.chunk import (
    Chunk,
    OP_DELETE,
    OP_INSERT,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
)
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.node import Expr


class Executor:
    """Base executor. Subclasses override the three transition fns."""

    #: static: does apply() return a chunk?
    emits_on_apply: bool = True
    #: static: does flush() return a chunk?
    emits_on_flush: bool = False

    def __init__(self, in_schema: Schema):
        self.in_schema = in_schema

    @property
    def out_schema(self) -> Schema:
        return self.in_schema

    # -- pure/traceable ------------------------------------------------
    def init_state(self) -> Any:
        return ()

    def apply(self, state, chunk: Chunk):
        raise NotImplementedError

    def flush(self, state, epoch):
        """Barrier-time emission; epoch is a traced int64 scalar."""
        return state, None

    # -- host-side hooks ----------------------------------------------
    def on_watermark(self, state, watermark):
        """Host hook for watermark-driven state cleaning; default no-op."""
        return state

    def __repr__(self) -> str:
        return type(self).__name__


class ProjectExecutor(Executor):
    """Evaluate expressions into a new chunk (ref executor/project/)."""

    def __init__(self, in_schema: Schema, exprs: Sequence[tuple[str, Expr]]):
        super().__init__(in_schema)
        self.exprs = tuple(exprs)
        self._out_schema = Schema(
            tuple(
                Field(
                    name,
                    e.return_field(in_schema).data_type,
                    str_width=e.return_field(in_schema).str_width,
                    decimal_scale=e.return_field(in_schema).decimal_scale,
                    nullable=e.return_field(in_schema).nullable,
                )
                for name, e in self.exprs
            )
        )

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def apply(self, state, chunk: Chunk):
        from risingwave_tpu.common.chunk import conform_col
        # runtime representation follows the STATIC field nullability so
        # downstream state pytrees keep a fixed structure
        cols = [
            conform_col(e.eval(chunk), f.nullable, chunk.capacity)
            for (_, e), f in zip(self.exprs, self._out_schema)
        ]
        return state, chunk.with_columns(cols, self._out_schema)


class HopWindowExecutor(Executor):
    """Expand each row into the k sliding windows containing it.

    ref: src/stream/src/executor/hop_window.rs (tumble/hop via row
    expansion).  Output capacity = k * input capacity with a
    ``window_start`` column appended; k = size // slide is static.
    """

    def __init__(self, in_schema: Schema, ts_col: int, slide_us: int,
                 size_us: int, window_col: str = "window_start"):
        super().__init__(in_schema)
        if size_us % slide_us:
            raise ValueError("hop size must be a multiple of slide")
        self.ts_col = ts_col
        self.slide_us = slide_us
        self.size_us = size_us
        self.k = size_us // slide_us
        from risingwave_tpu.common.types import DataType as DT
        # window_start AND window_end (= start + size), matching the
        # reference's TUMBLE/HOP output (hop_window.rs)
        self._out_schema = Schema(
            in_schema.fields + (Field(window_col, DT.TIMESTAMP),
                                Field("window_end", DT.TIMESTAMP))
        )

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def apply(self, state, chunk: Chunk):
        from risingwave_tpu.common.chunk import NCol, StrCol

        cap, k = chunk.capacity, self.k

        def rep(col):
            if isinstance(col, NCol):
                return NCol(rep(col.data), rep(col.null))
            if isinstance(col, StrCol):
                return StrCol(rep(col.data), rep(col.lens))
            return jnp.repeat(col, k, axis=0)

        ts = chunk.column(self.ts_col)
        ws0 = ts - ts % self.slide_us           # latest window start
        if k == 1:  # TUMBLE: append the window columns, no expansion
            return state, Chunk(
                chunk.columns + (ws0, ws0 + self.size_us),
                chunk.ops, chunk.valid, self._out_schema,
            )
        offs = jnp.tile(
            jnp.arange(k, dtype=jnp.int64) * self.slide_us, (cap,)
        )
        # every generated window contains its row: ws = ws0 - i*slide
        # with i < k gives ts - ws < slide + (k-1)*slide = size
        ws = rep(ws0) - offs
        cols = tuple(rep(c) for c in chunk.columns) + (ws, ws + self.size_us)
        return state, Chunk(
            cols, rep(chunk.ops), rep(chunk.valid), self._out_schema,
        )


class FilterExecutor(Executor):
    """Narrow visibility by a predicate (ref executor/filter.rs).

    Op rewriting mirrors the reference (filter.rs): an Update pair whose
    sides land on different sides of the predicate degrades to a plain
    Insert/Delete of the surviving side.
    """

    def __init__(self, in_schema: Schema, predicate: Expr):
        super().__init__(in_schema)
        self.predicate = predicate

    def apply(self, state, chunk: Chunk):
        keep = self.predicate.eval(chunk)
        from risingwave_tpu.common.chunk import split_col
        keep, null = split_col(keep)
        if null is not None:
            keep = keep & ~null  # SQL WHERE: NULL predicate drops the row
        keep = keep & chunk.valid
        # Update-pair degradation: U- at i pairs with U+ at i+1.
        is_ud = chunk.ops == OP_UPDATE_DELETE
        is_ui = chunk.ops == OP_UPDATE_INSERT
        partner_keep_for_ud = jnp.roll(keep, -1)  # the U+ after a U-
        partner_keep_for_ui = jnp.roll(keep, 1)   # the U- before a U+
        ops = chunk.ops
        ops = jnp.where(is_ud & keep & ~partner_keep_for_ud, OP_DELETE, ops)
        ops = jnp.where(is_ui & keep & ~partner_keep_for_ui, OP_INSERT, ops)
        return state, Chunk(chunk.columns, ops, keep, chunk.schema)


class ChangelogExecutor(Executor):
    """Expose the changelog as append-only rows with an op column.

    ref: src/stream/src/executor/changelog.rs (CHANGELOG syntax /
    debezium-style sinks): every Insert/Delete/U-/U+ becomes a plain
    Insert carrying its original op code.
    """

    def __init__(self, in_schema: Schema, op_col: str = "changelog_op"):
        super().__init__(in_schema)
        from risingwave_tpu.common.types import DataType as DT
        self._out_schema = Schema(
            in_schema.fields + (Field(op_col, DT.INT16),)
        )

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def apply(self, state, chunk: Chunk):
        op_col = chunk.ops.astype(jnp.int16)
        ops = jnp.zeros_like(chunk.ops)  # all Insert
        return state, Chunk(
            chunk.columns + (op_col,), ops, chunk.valid, self._out_schema
        )


class RowIdGenExecutor(Executor):
    """Append a monotonically increasing serial row id.

    ref: src/stream/src/executor/row_id_gen.rs — pk generation for
    tables without one.  Ids are dense per executor instance; the
    vnode-prefixed id space of the reference arrives with the graph
    scheduler's per-shard id ranges.
    """

    def __init__(self, in_schema: Schema, id_col: str = "_row_id"):
        super().__init__(in_schema)
        from risingwave_tpu.common.types import DataType as DT
        self._out_schema = Schema(
            in_schema.fields + (Field(id_col, DT.SERIAL),)
        )

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def init_state(self):
        return jnp.zeros((), jnp.int64)

    def apply(self, state, chunk: Chunk):
        cap = chunk.capacity
        # ids assigned to VISIBLE rows only, densely
        rank = jnp.cumsum(chunk.valid.astype(jnp.int64)) - 1
        ids = jnp.where(chunk.valid, state + rank, -1)
        n = chunk.cardinality().astype(jnp.int64)
        return state + n, Chunk(
            chunk.columns + (ids,), chunk.ops, chunk.valid,
            self._out_schema,
        )
