"""Streaming runtime: executors, fragments, barriers.

Reference counterpart: ``src/stream`` (SURVEY.md §2.3). The TPU
restructuring collapses "one actor = one tokio task" into "one fragment =
one jitted SPMD step function"; barriers are host-side control flow
between steps (SURVEY.md §7.1).
"""

from risingwave_tpu.stream.message import (
    Barrier,
    BarrierKind,
    Watermark,
)
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.fragment import Fragment

__all__ = [
    "Barrier",
    "BarrierKind",
    "Watermark",
    "Executor",
    "Fragment",
]
