"""Stream messages: barriers and watermarks (host-side control values).

Reference counterparts:
- ``Message`` enum — src/stream/src/executor/mod.rs:1311
  (``Chunk | Barrier | Watermark``)
- ``Barrier``      — src/stream/src/executor/mod.rs:400-411
- ``Mutation``     — src/stream/src/executor/mod.rs:359-399
- ``Watermark``    — src/stream/src/executor/mod.rs:1234

TPU-first design: data (``Chunk``) flows through jitted fragment step
functions; barriers and watermarks are *host* control flow between
steps, so they are plain Python values, never traced.  A mutation rides
a barrier exactly as in the reference — it is applied by the runtime
between jitted steps (pause/resume/update-vnode-bitmaps/stop).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Mapping

from risingwave_tpu.common.epoch import EpochPair


class BarrierKind(enum.Enum):
    """ref: proto stream_plan Barrier kind (Initial/Barrier/Checkpoint)."""

    INITIAL = "initial"
    BARRIER = "barrier"
    CHECKPOINT = "checkpoint"


@dataclass(frozen=True)
class Mutation:
    """Graph-change command piggybacked on a barrier.

    ref: ``Mutation`` (src/stream/src/executor/mod.rs:359) — the variants
    carried here are the subset the runtime implements; ``conf`` holds
    variant-specific payload (e.g. new vnode→shard mapping for rescale).
    """

    kind: str  # "stop" | "pause" | "resume" | "update" | "add" | "source_change_split" | "throttle"
    conf: Mapping[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class Barrier:
    """An epoch barrier (ref executor/mod.rs:400).

    ``epoch.curr`` is the epoch the barrier *opens*; state flushed when
    this barrier passes an executor is attributed to ``epoch.prev``.
    """

    epoch: EpochPair
    kind: BarrierKind = BarrierKind.BARRIER
    mutation: Mutation | None = None

    @property
    def is_checkpoint(self) -> bool:
        return self.kind in (BarrierKind.CHECKPOINT, BarrierKind.INITIAL)

    def is_stop(self) -> bool:
        return self.mutation is not None and self.mutation.kind == "stop"

    def is_pause(self) -> bool:
        return self.mutation is not None and self.mutation.kind == "pause"


@dataclass(frozen=True)
class Watermark:
    """Per-column event-time lower bound (ref executor/mod.rs:1234).

    Downstream operators may drop state for keys strictly below ``value``
    (state cleaning) and EOWC operators emit closed windows.
    """

    col_idx: int
    value: Any  # host scalar in the column's physical representation
