"""DAG streaming runtime: arbitrary fragment graphs under one barrier loop.

Reference counterparts:
- the stream fragmenter cuts any plan into a *graph* of fragments
  (src/frontend/src/stream_fragmenter/mod.rs:388), instantiated as
  actors wired by dispatch/exchange edges
  (src/stream/src/executor/dispatch.rs:62);
- merges align barriers at every fan-in
  (src/stream/src/executor/merge.rs:161, barrier_align.rs:44);
- MV-on-MV: a downstream job consumes the upstream MaterializeExecutor's
  output changelog.

TPU-first design (SURVEY.md §7.1): the DAG is *compiled*, not threaded.
Instead of one actor task per fragment connected by channels, the whole
reachable subgraph of a source becomes ONE jitted step program (XLA
fuses across fragment boundaries — a cascade of MVs costs the same as
one fused chain), and the whole graph's barrier crossing is ONE jitted
program.  Barrier alignment at fan-in is implicit: barriers are host
control flow between dispatches, so every node sees the same epoch
boundary by construction — the alignment buffers of ``merge.rs`` have
no analog because there is nothing to align.

Node inputs always reference earlier nodes (list order = topological
order), so in-order traversal is dataflow-correct.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fractions import Fraction
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.stream.fragment import (
    COUNTER_ATTRS,
    Fragment,
    WM_NONE,
    WM_SAFE_FLOOR,
    collect_counters,
)
from risingwave_tpu.stream.runtime import (
    CheckpointPipelineMixin,
    CheckpointSnapshot,
    check_counter_values,
    deliver_sinks,
    restore_source,
    rewind_spill_tier,
)

from risingwave_tpu.parallel.exchange import shard_map_nocheck

#: a dataflow edge endpoint: ("source", name) or ("node", node_id)
Ref = tuple


@dataclass
class FragNode:
    """A fragment (executor chain) with one upstream input."""

    fragment: Fragment
    input: Ref

    def init_state(self):
        return self.fragment.init_states()


@dataclass
class JoinNode:
    """A two-input hash join (ref hash_join.rs:158 as a DAG vertex)."""

    join: Any
    left: Ref
    right: Ref

    def init_state(self):
        return self.join.init_state()


class DagJob(CheckpointPipelineMixin):
    """A streaming job over an arbitrary DAG of fragments and joins.

    ``sources`` maps names to chunk readers; ``nodes`` is a topological
    list (a node's inputs only reference sources or earlier nodes).
    Dropped nodes become ``None`` tombstones so node ids stay stable for
    catalog references.
    """

    #: mesh axis name for sharded DAGs
    AXIS = "shard"

    def __init__(
        self,
        sources: dict[str, Any],
        nodes: list,
        name: str = "dag_job",
        checkpoint_frequency: int = 1,
        checkpoint_store=None,
        mesh=None,
        exchanges: dict | None = None,
        staged: bool = False,
    ):
        self.sources = dict(sources)
        self.nodes: list = list(nodes)
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        self.checkpoint_store = checkpoint_store
        #: sharded execution (ref: every stateful op is vnode-parallel,
        #: src/meta/src/stream/stream_graph/actor.rs:435): the whole
        #: reachable subgraph runs per-shard inside shard_map, with
        #: ``exchanges[(node_id, side)] -> key_fn`` marking the edges
        #: where chunks re-route to their key-owning shard via
        #: all_to_all (the reference's hash dispatchers)
        self.mesh = mesh
        self.exchanges = dict(exchanges or {})
        self.n_shards = int(mesh.devices.size) if mesh is not None else 1
        #: staged execution (meshless): chunks hop between PER-NODE
        #: jitted programs and join emission windows drain in HOST
        #: loops (one pending readback per probed chunk) instead of
        #: device while_loops.  The fused mode embeds each join's
        #: downstream subgraph inside its drain loop body — on deep
        #: multiway plans (TPC-H q2/q8/q9: 8-9 base tables) that
        #: nesting blows up XLA:CPU compile memory (observed LLVM
        #: OOM).  Staging is the reference's actor/exchange boundary:
        #: compile size is linear in plan size, at the cost of host
        #: hops — the right trade for wide analytic MVs.
        self._staged_hint = staged
        self.staged = False  # derived per-topology in _rebuild
        self._staged_progs: dict = {}
        #: n-round fused programs (one dispatch per n scheduling rounds;
        #: per-dispatch host overhead amortized n-fold), keyed by n
        self._fused_multi: dict[int, Any] = {}
        #: windows that could NOT run as one fused dispatch, by reason
        #: (observability: a silent degradation to per-chunk host
        #: dispatches is a throughput cliff — exported as
        #: ``dag_fused_fallback_total{reason}`` by collect_join_metrics)
        self.fused_fallbacks: dict[str, int] = {}
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        self.states = self._init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        self.committed_epoch = 0
        self.paused = False
        #: cumulative seconds stalled on checkpoint-upload backpressure
        self.stall_seconds = 0.0
        self._counters = None
        self.counter_labels: list[str] = []
        self._init_pipeline()
        self._rebuild()

    def _init_states(self):
        if self.mesh is None:
            return tuple(
                n.init_state() if n is not None else None
                for n in self.nodes
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one_shard(_):
            return tuple(
                n.init_state() if n is not None else None
                for n in self.nodes
            )

        stacked = jax.vmap(one_shard)(jnp.arange(self.n_shards))
        return jax.device_put(
            stacked, NamedSharding(self.mesh, P(self.AXIS))
        )

    def _sharding_spec(self):
        from jax.sharding import PartitionSpec as P
        return P(self.AXIS)

    # -- topology -------------------------------------------------------
    def _rebuild(self) -> None:
        """Recompute consumer maps + drop compiled programs (called after
        any topology change; programs re-jit lazily on next use)."""
        self._consumers: dict[Ref, list[int]] = {}
        for idx, node in enumerate(self.nodes):
            if node is None:
                continue
            refs = [node.input] if isinstance(node, FragNode) \
                else [node.left, node.right]
            for ref in refs:
                self._validate_ref(ref, idx)
                lst = self._consumers.setdefault(ref, [])
                # once per node even when both join sides share the ref
                # (a self-join): enqueue() already fans out per side
                if idx not in lst:
                    lst.append(idx)
        self._step_programs: dict[str, Any] = {}
        self._barrier_prog = None
        self._maintain_prog = None
        self._staged_progs = {}
        self._fused_multi = {}
        # staging is a property of the CURRENT topology: attach/merge
        # can grow a fused job past the depth where fused drain loops
        # blow up the compile — re-derive on every rebuild
        n_joins = sum(
            isinstance(n, JoinNode) for n in self.nodes if n is not None
        )
        self.staged = self.mesh is None and (
            getattr(self, "_staged_hint", False) or n_joins >= 4
        )
        self._pulls = self._compute_pulls()

    def _validate_ref(self, ref: Ref, at: int) -> None:
        kind, key = ref
        if kind == "source":
            if key not in self.sources:
                raise ValueError(f"node {at} references unknown source {key!r}")
        elif kind == "node":
            if not (0 <= key < at) or self.nodes[key] is None:
                raise ValueError(
                    f"node {at} must reference an earlier live node, got {key}"
                )
        else:
            raise ValueError(f"bad ref {ref!r}")

    def add_source(self, name: str, reader) -> None:
        if name in self.sources:
            raise ValueError(f"source {name!r} already attached")
        self.sources[name] = reader
        self._rebuild()

    def remove_sources(self, names: list[str]) -> None:
        """Detach sources (a dropped MV's private readers).  Refuses
        while any live node still consumes one."""
        for name in names:
            if self._consumers.get(("source", name)):
                raise ValueError(f"source {name!r} still has consumers")
            self.sources.pop(name, None)
        self._rebuild()

    def add_nodes(self, nodes: list) -> list[int]:
        """Attach new nodes (e.g. a cascaded MV's fragment); returns their
        ids.  Existing states are preserved; new nodes start empty —
        callers backfill upstream history explicitly (see
        ``backfill_node``)."""
        ids = []
        states = list(self.states)
        for n in nodes:
            self.nodes.append(n)
            if self.mesh is None:
                states.append(n.init_state())
            else:
                # sharded job: the new node's state gets the same
                # stacked-and-sharded layout as _init_states
                from jax.sharding import NamedSharding, PartitionSpec as P

                stacked = jax.vmap(lambda _: n.init_state())(
                    jnp.arange(self.n_shards)
                )
                states.append(jax.device_put(
                    stacked, NamedSharding(self.mesh, P(self.AXIS))
                ))
            ids.append(len(self.nodes) - 1)
        self.states = tuple(states)
        self._rebuild()
        return ids

    def remove_nodes(self, ids: list[int]) -> None:
        """Tombstone nodes (a dropped MV).  Refuses while live consumers
        remain — the reference likewise rejects dropping an MV with
        dependents."""
        drop = set(ids)
        for idx, node in enumerate(self.nodes):
            if node is None or idx in drop:
                continue
            refs = [node.input] if isinstance(node, FragNode) \
                else [node.left, node.right]
            for kind, key in refs:
                if kind == "node" and key in drop:
                    raise ValueError(
                        f"node {key} still feeds node {idx} (drop dependents "
                        "first)"
                    )
        states = list(self.states)
        for i in drop:
            self.nodes[i] = None
            states[i] = None
        self.states = tuple(states)
        for key in [k for k in self.exchanges if k[0] in drop]:
            del self.exchanges[key]
        self._rebuild()

    def reseed_checkpoint(self) -> None:
        """Re-snapshot after a topology change: retained checkpoints
        hold the OLD state-tree shape (and old source-name keys), so a
        recover() between the change and the next commit would restore
        a structurally incompatible tree.  Callers invoke this once the
        change (attach/merge/remove + backfill) is complete."""
        self._snapshot_and_save(self.committed_epoch)

    def _snapshot_and_save(self, epoch: int) -> None:
        """The shared checkpoint tail: incremental shadow snapshot +
        async durable upload (used by both the barrier commit and
        topology reseeds).  Sharded meshes ride the SAME pipeline with
        per-shard digest lanes (stream/shadow.py ``shard_rows``): no
        digest block spans a shard row, so dirty tracking — and the
        delta upload — is exact per shard, replacing the old full-copy
        full-upload path."""
        src_state = {
            name: (src.state() if hasattr(src, "state") else {})
            for name, src in self.sources.items()
        }
        # ONE host materialization per tier, shared by the in-memory
        # snapshot and the durable save; keys carry the shard index
        spill_host = {
            (idx, j, s): tier.snapshot()
            for (idx, j), tiers in getattr(self, "_spill_tiers",
                                           {}).items()
            for s, tier in enumerate(tiers)
            if tier.rows_absorbed
        }
        spill_items = [
            (self._spill_key(idx, j, s), host_state)
            for (idx, j, s), host_state in spill_host.items()
        ]
        self._snapshot_commit(epoch, src_state, spill_host, spill_items)

    def _shadow_shard_rows(self) -> int | None:
        """Mesh-stacked trees digest in per-shard lanes (see
        CheckpointPipelineMixin._snapshot_commit)."""
        return self.n_shards if self.mesh is not None else None

    def downstream_closure(self, ref: Ref,
                           through_joins: bool = True) -> list[int]:
        """All node ids transitively consuming ``ref`` (topo order).

        With ``through_joins=False`` the traversal includes a JoinNode
        consumer but does not continue past it (a join's downstream sees
        the MIN of both inputs' watermarks, not either one alone)."""
        seen = set()
        frontier = [ref]
        while frontier:
            r = frontier.pop()
            for idx in self._consumers.get(r, ()):
                if idx in seen:
                    continue
                seen.add(idx)
                if through_joins or isinstance(self.nodes[idx], FragNode):
                    frontier.append(("node", idx))
        return sorted(seen)

    # -- chunk path -----------------------------------------------------
    def _propagate(self, new_states: list, injections) -> None:
        """Push chunks through the DAG in topological order.

        ``injections`` is a list of (ref, chunk).  Mutates new_states.
        A source feeding both sides of a join (self-join) delivers to
        the left side first, then the right — one deterministic order,
        like the reference's dispatcher duplicating a chunk."""
        inbox: dict[int, list] = {}

        def enqueue(ref, chunk):
            for idx in self._consumers.get(ref, ()):
                node = self.nodes[idx]
                if isinstance(node, FragNode):
                    inbox.setdefault(idx, []).append(
                        (self._exchange(idx, None, chunk), None)
                    )
                else:
                    if node.left == ref:
                        inbox.setdefault(idx, []).append(
                            (self._exchange(idx, "left", chunk), "left")
                        )
                    if node.right == ref:
                        inbox.setdefault(idx, []).append(
                            (self._exchange(idx, "right", chunk), "right")
                        )

        for ref, chunk in injections:
            enqueue(ref, chunk)
        for idx in range(len(self.nodes)):
            node = self.nodes[idx]
            if node is None or idx not in inbox:
                continue
            for chunk, side in inbox[idx]:
                if isinstance(node, FragNode):
                    new_states[idx], out = node.fragment._step_impl(
                        new_states[idx], chunk
                    )
                    if out is not None:
                        enqueue(("node", idx), out)
                else:
                    self._apply_join_windowed(new_states, idx, chunk,
                                              side, enqueue)

    def _exchange(self, idx: int, side, chunk):
        """Route a chunk across the vnode exchange on a marked edge
        (sharded DAGs only; linear DAGs deliver in place)."""
        fn = self.exchanges.get((idx, side))
        if fn is None or self.mesh is None:
            return chunk
        from risingwave_tpu.parallel.exchange import shuffle_chunk
        return shuffle_chunk(chunk, fn(chunk), self.AXIS, self.n_shards)

    def _apply_join_windowed(self, new_states: list, idx: int, chunk,
                             side: str, enqueue) -> None:
        """Drive a join with WINDOWED emission: window 0 propagates via
        the normal traversal; any further windows (high-amplification
        probes) drain through the downstream subgraph inside a device
        ``while_loop`` — matches dropped by a fixed out buffer in the
        old design now always reach downstream (ref hash_join.rs
        chunk-sized yielding under amplification)."""
        node = self.nodes[idx]
        join = node.join
        if not hasattr(join, "apply_begin"):
            new_states[idx], out = join.apply(new_states[idx], chunk, side)
            if out is not None:
                enqueue(("node", idx), out)
            return
        new_states[idx], pending = join.apply_begin(
            new_states[idx], chunk, side
        )
        if not self._consumers.get(("node", idx)):
            return  # terminal join: emissions have no consumers
        build_rows = join.build_rows_of(new_states[idx], side)
        # window 0 propagates directly (NOT via the inbox) so windows
        # stay in emission order downstream — a +pair in window 0 must
        # land before its -pair in window 1
        first, probe_bound = join.emit_window(
            build_rows, pending, jnp.int32(0), side
        )
        new_states[idx] = new_states[idx]._replace(
            emit_overflow=new_states[idx].emit_overflow + probe_bound
        )
        self._propagate(new_states, [(("node", idx), first)])
        max_w = join.max_windows(chunk.capacity)
        if max_w <= 1:
            return

        # sharded: the loop body may contain collectives (downstream
        # exchanges), so every shard must run the same trip count —
        # bound by the max pending across shards (extra windows emit
        # empty chunks, which are harmless)
        total = pending.total
        if self.mesh is not None:
            total = jax.lax.pmax(total, self.AXIS)

        def cond(carry):
            sts, w = carry
            return (w * join.out_capacity < total) & (w < max_w)

        def body(carry):
            sts, w = carry
            window, probe_bound = join.emit_window(
                build_rows, pending, w, side
            )
            lst = list(sts)
            lst[idx] = lst[idx]._replace(
                emit_overflow=lst[idx].emit_overflow + probe_bound
            )
            self._propagate(lst, [(("node", idx), window)])
            return tuple(lst), w + 1

        sts, _ = jax.lax.while_loop(
            cond, body, (tuple(new_states), jnp.int32(1))
        )
        new_states[:] = list(sts)

    def _make_step(self, src_name: str):
        reader = self.sources[src_name]
        fused = hasattr(reader, "impl") and hasattr(reader, "next_base")
        if self.mesh is not None:
            spec = self._sharding_spec()
            if fused:
                def body(states, k0):
                    local = jax.tree.map(lambda x: x[0], states)
                    new_states = list(local)
                    chunk = reader.impl(k0[0], reader.cap)
                    self._propagate(
                        new_states, [(("source", src_name), chunk)]
                    )
                    return jax.tree.map(
                        lambda x: x[None], tuple(new_states)
                    )
            else:
                # host-chunk source (DML tables): the chunk arrives
                # stacked [n_shards, ...] with rows on shard 0 only;
                # the first exchange edge (join input) re-routes them
                # to their key owners via all_to_all — the reference's
                # dispatcher on a singleton source fragment
                def body(states, chunk):
                    local = jax.tree.map(lambda x: x[0], states)
                    lchunk = jax.tree.map(lambda x: x[0], chunk)
                    new_states = list(local)
                    self._propagate(
                        new_states, [(("source", src_name), lchunk)]
                    )
                    return jax.tree.map(
                        lambda x: x[None], tuple(new_states)
                    )

            # donated like the linear path: the mesh-stacked state
            # updates in place, no per-step allocation churn
            prog = jax.jit(shard_map_nocheck(
                body, mesh=self.mesh, in_specs=(spec, spec),
                out_specs=spec,
            ), donate_argnums=(0,))
            return prog, fused
        if fused:
            # traceable source: generation fuses into the step program
            def fn(states, k0):
                chunk = reader.impl(k0, reader.cap)
                new_states = list(states)
                self._propagate(new_states, [(("source", src_name), chunk)])
                return tuple(new_states)
        else:
            def fn(states, chunk):
                new_states = list(states)
                self._propagate(new_states, [(("source", src_name), chunk)])
                return tuple(new_states)
        return jax.jit(fn, donate_argnums=(0,)), fused

    # -- staged execution (host-hop scheduling) -------------------------
    def _staged_prog(self, key, builder, donate: bool = True):
        """Per-node jitted program cache.  ``donate`` donates arg 0
        (the state, reassigned immediately after every call) — emit
        programs must NOT donate (the same state feeds every window)."""
        prog = self._staged_progs.get(key)
        if prog is None:
            prog = jax.jit(
                builder(), donate_argnums=(0,) if donate else ()
            )
            self._staged_progs[key] = prog
        return prog

    def _staged_deliver(self, injections: list) -> None:
        """Host-level chunk propagation, DEPTH-FIRST: each chunk flows
        all the way downstream before the next emission window is even
        gathered — breadth-first queuing held every cascaded window in
        memory at once (a 7-join chain OOM'd the host).  Per-node
        dispatches; join windows drain in host loops with ONE pending
        readback per probed chunk."""
        for ref, chunk in injections:
            for idx in self._consumers.get(ref, ()):
                node = self.nodes[idx]
                if node is None:
                    continue
                if isinstance(node, FragNode):
                    prog = self._staged_prog(
                        ("frag", idx),
                        lambda node=node: node.fragment._step_impl,
                    )
                    st, out = prog(self.states[idx], chunk)
                    self._set_state(idx, st)
                    if out is not None:
                        self._staged_deliver([(("node", idx), out)])
                else:
                    if node.left == ref:
                        self._staged_join(idx, chunk, "left")
                    if node.right == ref:
                        self._staged_join(idx, chunk, "right")

    def _set_state(self, idx: int, st) -> None:
        lst = list(self.states)
        lst[idx] = st
        self.states = tuple(lst)

    def _staged_join(self, idx: int, chunk, side: str) -> None:
        node = self.nodes[idx]
        join = node.join
        if not hasattr(join, "apply_begin"):
            prog = self._staged_prog(
                ("japply", idx, side),
                lambda join=join, side=side:
                    lambda st, c: join.apply(st, c, side),
            )
            st, out = prog(self.states[idx], chunk)
            self._set_state(idx, st)
            if out is not None:
                self._staged_deliver([(("node", idx), out)])
            return
        begin = self._staged_prog(
            ("jbegin", idx, side),
            lambda join=join, side=side:
                lambda st, c: join.apply_begin(st, c, side),
        )
        st, pending = begin(self.states[idx], chunk)
        self._set_state(idx, st)
        if not self._consumers.get(("node", idx)):
            return
        emit = self._staged_prog(
            ("jemit", idx, side),
            lambda join=join, side=side:
                lambda st, pend, w: join.emit_window(
                    join.build_rows_of(st, side), pend, w, side
                ),
            donate=False,
        )
        total = int(pending.total)  # the one host readback
        n_w = max(1, -(-total // join.out_capacity))
        n_w = min(n_w, join.max_windows(chunk.capacity))
        for w in range(n_w):
            out, probe_bound = emit(
                self.states[idx], pending, jnp.int32(w)
            )
            self._set_state(idx, self.states[idx]._replace(
                emit_overflow=self.states[idx].emit_overflow
                + probe_bound
            ))
            # window w flows ALL the way down before w+1 is gathered
            self._staged_deliver([(("node", idx), out)])

    def _staged_flush_all(self, sealed) -> None:
        for idx, node in enumerate(self.nodes):
            if not isinstance(node, FragNode):
                continue
            frag = node.fragment
            flush = self._staged_prog(
                ("flush", idx),
                lambda frag=frag: frag._flush_impl,
            )
            rounds = frag.MAX_DRAIN_ROUNDS + 64
            for _ in range(rounds):
                st, outs = flush(self.states[idx], sealed)
                self._set_state(idx, st)
                for out in outs:
                    self._staged_deliver([(("node", idx), out)])
                if not frag.has_pending_protocol():
                    break
                pend = self._staged_prog(
                    ("pending", idx),
                    lambda frag=frag: frag.pending_total,
                )
                if int(pend(self.states[idx])) == 0:
                    break

    def _staged_barrier(self, sealed):
        """The barrier crossing, staged: flush → watermarks → EOWC
        flush → clean + counters (same order as _barrier_impl)."""
        self._staged_flush_all(sealed)

        def wm_tail(states):
            new_states = list(states)
            self._wm_all(new_states)
            return tuple(new_states)

        prog_wm = self._staged_prog(("wm_tail",), lambda: wm_tail)
        self.states = prog_wm(self.states)
        self._staged_flush_all(sealed)

        def clean_tail(states):
            new_states = list(states)
            self._clean_joins(new_states)
            labels, counters = self._collect_counters(new_states)
            self.counter_labels = labels
            return tuple(new_states), counters

        prog_cl = self._staged_prog(("clean_tail",), lambda: clean_tail)
        self.states, counters = prog_cl(self.states)
        return counters

    def run_chunk(self, src_name: str) -> int:
        """Pull one chunk from one source through its reachable subgraph."""
        if self.paused:
            return 0
        if self.staged:
            reader = self.sources[src_name]
            chunk = reader.next_chunk()
            self._staged_deliver([(("source", src_name), chunk)])
            return chunk.capacity
        if src_name not in self._step_programs:
            self._step_programs[src_name] = self._make_step(src_name)
        prog, fused = self._step_programs[src_name]
        reader = self.sources[src_name]
        if self.mesh is not None:
            if not fused:
                from jax.sharding import NamedSharding, PartitionSpec as P
                chunk = reader.next_chunk()
                host = jax.device_get(chunk)
                empty = jax.tree.map(np.zeros_like, host)
                stacked = jax.tree.map(
                    lambda *xs: np.stack(xs),
                    *([host] + [empty] * (self.n_shards - 1)),
                )
                stacked = jax.device_put(
                    stacked, NamedSharding(self.mesh, P(self.AXIS))
                )
                self.states = prog(self.states, stacked)
                return chunk.capacity
            # one cap-stride ordinal block per shard (split readers own
            # disjoint ordinal ranges, like the reference's source
            # splits)
            k0 = jnp.asarray(
                [reader.next_base() for _ in range(self.n_shards)],
                jnp.int64,
            )
            self.states = prog(self.states, k0)
            return reader.cap * self.n_shards
        if fused:
            self.states = prog(self.states, jnp.int64(reader.next_base()))
            return reader.cap
        chunk = reader.next_chunk()
        self.states = prog(self.states, chunk)
        return chunk.capacity

    def _compute_pulls(self) -> list[tuple[str, int]]:
        """Chunks pulled per scheduling round per source: sources whose
        rows sweep event time faster pull proportionally fewer chunks so
        no watermark runs unboundedly ahead (ref: per-source rate
        limits; BinaryJob.chunk_ratio generalized to N sources)."""
        names = list(self.sources)
        eprs = []
        for n in names:
            epr = getattr(self.sources[n], "events_per_row", None)
            if epr is None:
                return [(n, 1) for n in names]
            eprs.append(Fraction(epr))
        inv = [1 / e for e in eprs]
        lo = min(inv)
        pulls = []
        for n, f in zip(names, inv):
            ratio = f / lo
            if ratio.denominator != 1 or ratio.numerator > 16:
                return [(n, 1) for n in names]
            pulls.append((n, int(ratio)))
        return pulls

    def chunk_round(self) -> int:
        """One scheduling round: pull each source by its pacing ratio."""
        rows = 0
        for name, k in self._pulls:
            for _ in range(k):
                rows += self.run_chunk(name)
        return rows

    def run_chunks(self, n: int) -> int:
        """n scheduling rounds in ONE dispatch when every source is
        traceable.

        The linear runtime's multi-chunk fusion (StreamingJob.
        run_chunks, the q1 attribution fix) extended to DAGs: a
        ``fori_loop`` over n rounds — each round generating and
        propagating every source's chunks through the whole reachable
        subgraph, join emission windows draining in the loop body's
        device ``while_loop`` — amortizes the per-dispatch host cost
        n-fold.  For q8's binary-join DAG that cost was 2n dispatches
        per barrier (one per source chunk); now it is one.

        Sharded meshes fuse too (``_run_chunks_mesh``): the whole
        barrier-to-barrier window runs as ONE ``shard_map`` program,
        exchanges (all_to_all) inside the loop body, mesh-stacked
        state donated.  Falls back to per-chunk dispatch only for
        host-chunk sources and staged plans (whose compile size must
        stay linear) — each fallback is counted by reason
        (``fused_fallbacks``) so the degradation is observable."""
        if self.paused or n <= 0:
            return 0
        reason = None
        if not self.sources:
            reason = "no_sources"
        elif self.staged:
            reason = "staged"
        elif not all(
            hasattr(src, "impl") and hasattr(src, "next_base")
            for src in self.sources.values()
        ):
            reason = "host_chunk_source"
        if reason is not None or n == 1:
            if reason is not None and n > 1:
                self.fused_fallbacks[reason] = \
                    self.fused_fallbacks.get(reason, 0) + 1
            rows = 0
            for _ in range(n):
                rows += self.chunk_round()
            return rows
        if self.mesh is not None:
            return self._run_chunks_mesh(n)
        prog = self._fused_multi.get(n)
        if prog is None:
            pulls = list(self._pulls)
            readers = dict(self.sources)
            strides = {
                nm: readers[nm].cap * getattr(readers[nm], "num_splits", 1)
                for nm, _ in pulls
            }

            def _multi(states, k0s):
                def body(i, st):
                    new_states = list(st)
                    for nm, k in pulls:
                        for rep in range(k):
                            base = k0s[nm] + (i * k + rep) * strides[nm]
                            chunk = readers[nm].impl(base, readers[nm].cap)
                            self._propagate(
                                new_states, [(("source", nm), chunk)]
                            )
                    return tuple(new_states)

                return jax.lax.fori_loop(0, n, body, states)

            prog = jax.jit(_multi, donate_argnums=(0,))
            # bounded cache: chunks_per_barrier is runtime-mutable and
            # each distinct n compiles a program — keep the newest few
            if len(self._fused_multi) >= 4:
                self._fused_multi.pop(next(iter(self._fused_multi)))
            self._fused_multi[n] = prog
        k0s = {}
        rows = 0
        for nm, k in self._pulls:
            reader = self.sources[nm]
            # next_base() consumed one cap block; skip the other n*k-1
            k0s[nm] = jnp.int64(reader.next_base())
            reader.offset += reader.cap * (n * k - 1)
            rows += reader.cap * n * k
        self.states = prog(self.states, k0s)
        return rows

    def _run_chunks_mesh(self, n: int) -> int:
        """The sharded fused window: n scheduling rounds — per-shard
        source generation, every exchange collective, join emission
        drains — as ONE ``shard_map``-ed ``fori_loop`` program between
        barriers, with the mesh-stacked state donated.

        Per-shard base ordinals come in as one ``[n_shards, n*k]``
        int64 column per source, computed host-side by the SAME
        ``next_base()`` sequence the per-chunk path consumes — the
        generated streams are ordinal-identical to n per-chunk rounds,
        so fused and unfused runs stay byte-identical."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        prog = self._fused_multi.get(n)
        if prog is None:
            pulls = list(self._pulls)
            readers = dict(self.sources)
            spec = self._sharding_spec()

            def body(states, *base_cols):
                local = jax.tree.map(lambda x: x[0], states)

                def round_body(i, st):
                    new_states = list(st)
                    for si, (nm, k) in enumerate(pulls):
                        for rep in range(k):
                            b0 = base_cols[si][0, i * k + rep]
                            chunk = readers[nm].impl(b0, readers[nm].cap)
                            self._propagate(
                                new_states, [(("source", nm), chunk)]
                            )
                    return tuple(new_states)

                out = jax.lax.fori_loop(0, n, round_body, tuple(local))
                return jax.tree.map(lambda x: x[None], out)

            prog = jax.jit(shard_map_nocheck(
                body, mesh=self.mesh,
                in_specs=(spec,) + (spec,) * len(pulls),
                out_specs=spec,
            ), donate_argnums=(0,))
            if len(self._fused_multi) >= 4:
                self._fused_multi.pop(next(iter(self._fused_multi)))
            self._fused_multi[n] = prog
        rows = 0
        base_cols = []
        sharding = NamedSharding(self.mesh, P(self.AXIS))
        for nm, k in self._pulls:
            reader = self.sources[nm]
            arr = np.empty((n * k, self.n_shards), np.int64)
            for i in range(n * k):
                for s in range(self.n_shards):
                    arr[i, s] = reader.next_base()
            base_cols.append(jax.device_put(
                jnp.asarray(arr.T), sharding
            ))
            rows += reader.cap * n * k * self.n_shards
        self.states = prog(self.states, *base_cols)
        return rows

    # -- barrier program ------------------------------------------------
    def _flush_node(self, new_states: list, idx: int, epoch) -> None:
        """Flush one fragment node; emissions cross downstream nodes.
        Drains on device while the node reports pending output."""
        node = self.nodes[idx]
        frag = node.fragment
        st, outs = frag._flush_impl(new_states[idx], epoch)
        new_states[idx] = st
        for out in outs:
            self._propagate(new_states, [(("node", idx), out)])
        if not frag.has_pending_protocol():
            return

        def _more(states_idx):
            # sharded: the drain body may cross exchanges (collectives),
            # so shards must agree on the trip count — any shard with
            # pending keeps every shard in the loop (idle shards flush
            # empty, which is harmless)
            p = frag.pending_total(states_idx)
            if self.mesh is not None:
                p = jax.lax.pmax(p, self.AXIS)
            return p > 0

        def cond(carry):
            sts, it, more = carry
            return more & (it < frag.MAX_DRAIN_ROUNDS)

        def body(carry):
            sts, it, _ = carry
            lst = list(sts)
            st2, outs2 = frag._flush_impl(lst[idx], epoch)
            lst[idx] = st2
            for out in outs2:
                self._propagate(lst, [(("node", idx), out)])
            return tuple(lst), it + 1, _more(lst[idx])

        sts, _, _ = jax.lax.while_loop(
            cond, body,
            (tuple(new_states), jnp.int32(0), _more(new_states[idx])),
        )
        new_states[:] = list(sts)

    def _flush_all(self, new_states: list, epoch) -> None:
        for idx, node in enumerate(self.nodes):
            if isinstance(node, FragNode):
                self._flush_node(new_states, idx, epoch)

    def _node_watermarks(self, new_states: list, idx: int):
        """(Watermark, has) pairs produced by a fragment node's wm
        filters (device scalars)."""
        from risingwave_tpu.stream.message import Watermark
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        node = self.nodes[idx]
        out = []
        for i, ex in enumerate(node.fragment.executors):
            if not isinstance(ex, WatermarkFilterExecutor):
                continue
            raw = new_states[idx][i].max_ts
            if self.mesh is not None:
                # global watermark = min over shards (the reference's
                # min-of-upstream-actors alignment, as ONE ICI pmin)
                raw = jax.lax.pmin(raw, self.AXIS)
            has = raw != WM_NONE
            val = jnp.where(has, raw - ex.delay_us, jnp.int64(WM_SAFE_FLOOR))
            out.append((Watermark(ex.ts_col, val), has))
        return out

    def _wm_all(self, new_states: list) -> None:
        """Propagate watermarks: within each fragment, then across node
        boundaries to downstream FRAGMENT nodes (cascaded MVs).  Joins
        block propagation — their two-sided min semantics are handled by
        ``_clean_joins``."""
        for idx, node in enumerate(self.nodes):
            if not isinstance(node, FragNode):
                continue
            new_states[idx] = node.fragment._wm_impl(
                new_states[idx],
                axis=self.AXIS if self.mesh is not None else None,
            )
            for wm, _ in self._node_watermarks(new_states, idx):
                for j in self.downstream_closure(("node", idx),
                                                 through_joins=False):
                    dn = self.nodes[j]
                    if not isinstance(dn, FragNode):
                        continue
                    lst = list(new_states[j])
                    for k, ex2 in enumerate(dn.fragment.executors):
                        lst[k] = ex2.on_watermark(lst[k], wm)
                    new_states[j] = tuple(lst)

    def _upstream_wm(self, new_states: list, ref: Ref, src_col: int):
        """Walk a join input upstream to its wm filter for ``src_col``;
        (value, has) device scalars or None when absent."""
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        while True:
            kind, key = ref
            if kind == "source":
                return None
            node = self.nodes[key]
            if not isinstance(node, FragNode):
                return None  # joins don't forward watermarks (yet)
            for i, ex in enumerate(node.fragment.executors):
                if isinstance(ex, WatermarkFilterExecutor) \
                        and ex.ts_col == src_col:
                    raw = new_states[key][i].max_ts
                    if self.mesh is not None:
                        raw = jax.lax.pmin(raw, self.AXIS)
                    has = raw != WM_NONE
                    val = jnp.where(
                        has, raw - ex.delay_us, jnp.int64(WM_SAFE_FLOOR)
                    )
                    return val, has
            ref = node.input

    def _clean_joins(self, new_states: list) -> None:
        """Watermark-driven join state cleaning (windowed joins): each
        side is cleaned by the MIN watermark across both inputs — a
        build row for window W serves the other side's future probes
        (BinaryJob._clean_join_state, generalized to DAG refs)."""
        for idx, node in enumerate(self.nodes):
            if not isinstance(node, JoinNode):
                continue
            join = node.join
            wms = []
            ok = True
            for side, ref in (("left", node.left), ("right", node.right)):
                clean = getattr(join, f"{side}_clean", None)
                if clean is None:
                    continue
                wm = self._upstream_wm(new_states, ref, clean[2])
                if wm is None:
                    ok = False
                    break
                wms.append(wm)
            if not ok or not wms:
                continue
            has_all = wms[0][1]
            min_wm = wms[0][0]
            for val, has in wms[1:]:
                has_all = has_all & has
                min_wm = jnp.minimum(min_wm, val)

            def do_clean(jstate, join=join, min_wm=min_wm):
                for side in ("left", "right"):
                    clean = getattr(join, f"{side}_clean", None)
                    if clean is None:
                        continue
                    key_idx, lag, _ = clean
                    jstate = join.clean_below(
                        jstate, side, key_idx, min_wm - lag
                    )
                if hasattr(join, "maybe_rehash"):
                    jstate = join.maybe_rehash(jstate)
                return jstate

            new_states[idx] = jax.lax.cond(
                has_all, do_clean, lambda j: j, new_states[idx]
            )

    def _collect_counters(self, new_states: list):
        labels: list[str] = []
        vals: list[jnp.ndarray] = []
        for idx, node in enumerate(self.nodes):
            if node is None:
                continue
            if isinstance(node, FragNode):
                sub_labels, sub = collect_counters(
                    node.fragment.executors, new_states[idx]
                )
                labels.extend(f"n{idx}.{x}" for x in sub_labels)
                if sub.shape[0]:
                    vals.append(sub)
                continue
            jstate = new_states[idx]
            if not hasattr(jstate, "left"):
                # two-input non-join node (dynamic filter): counters
                # live flat on the state itself
                for attr in COUNTER_ATTRS:
                    if hasattr(jstate, attr):
                        labels.append(f"n{idx}.dynfilter.{attr}")
                        vals.append(
                            getattr(jstate, attr).astype(jnp.int64)[None]
                        )
                continue
            for side_name in ("left", "right"):
                s = getattr(jstate, side_name)
                for attr in COUNTER_ATTRS:
                    if hasattr(s, attr):
                        labels.append(f"n{idx}.join.{side_name}.{attr}")
                        vals.append(getattr(s, attr).astype(jnp.int64)[None])
            labels.append(f"n{idx}.join.emit_overflow")
            vals.append(jstate.emit_overflow.astype(jnp.int64)[None])
        counters = jnp.concatenate(vals) if vals \
            else jnp.zeros((0,), jnp.int64)
        return labels, counters

    def _barrier_impl(self, states, epoch):
        new_states = list(states)
        self._flush_all(new_states, epoch)
        # watermarks advance, then a second flush pass emits rows the
        # new watermark closed (EOWC) at THIS barrier
        self._wm_all(new_states)
        self._flush_all(new_states, epoch)
        self._clean_joins(new_states)
        labels, counters = self._collect_counters(new_states)
        self.counter_labels = labels
        return tuple(new_states), counters

    def _make_barrier_prog(self):
        if self.mesh is None:
            return jax.jit(self._barrier_impl, donate_argnums=(0,))
        from jax.sharding import PartitionSpec as P
        spec = self._sharding_spec()

        def body(states, epoch):
            local = jax.tree.map(lambda x: x[0], states)
            new_states, counters = self._barrier_impl(
                tuple(local), epoch[0]
            )
            # shard-summed counters, replicated (ONE host readback later)
            counters = jax.lax.psum(counters, self.AXIS)
            return jax.tree.map(lambda x: x[None], new_states), counters

        return jax.jit(shard_map_nocheck(
            body, mesh=self.mesh, in_specs=(spec, spec),
            out_specs=(spec, P()),
        ), donate_argnums=(0,))

    def _barrier_epoch_arg(self, sealed):
        if self.mesh is None:
            return sealed
        return jnp.full((self.n_shards,), sealed, jnp.int64)

    def inject_barrier(self) -> None:
        self.barriers_seen += 1
        sealed = self.epoch.curr.value
        if self.staged:
            self._counters = self._staged_barrier(sealed)
        else:
            if self._barrier_prog is None:
                self._barrier_prog = self._make_barrier_prog()
            self.states, self._counters = self._barrier_prog(
                self.states, self._barrier_epoch_arg(sealed)
            )

        if self.barriers_seen % self.checkpoint_frequency == 0:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain(sealed)
                self._ckpts_since_maintain = 0
            self._ckpts_since_snapshot += 1
            if self._ckpts_since_snapshot >= self.snapshot_interval:
                self._ckpts_since_snapshot = 0
                self._commit_checkpoint(sealed)
        # cheap ack poll: committed_epoch (and deferred sink delivery)
        # advances while uploads complete in the background
        self._process_upload_acks()
        self.epoch = self.epoch.bump()

    # -- maintenance ----------------------------------------------------
    def _maintain_impl(self, states):
        new_states = list(states)
        for idx, node in enumerate(self.nodes):
            if isinstance(node, FragNode):
                new_states[idx] = node.fragment._maintain_impl(
                    new_states[idx]
                )
            elif isinstance(node, JoinNode) \
                    and hasattr(node.join, "maybe_rehash"):
                new_states[idx] = node.join.maybe_rehash(new_states[idx])
        return tuple(new_states)

    def _maintain(self, sealed) -> None:
        if self._maintain_prog is None:
            if self.mesh is None:
                self._maintain_prog = jax.jit(
                    self._maintain_impl, donate_argnums=(0,)
                )
            else:
                spec = self._sharding_spec()

                def body(states):
                    local = jax.tree.map(lambda x: x[0], states)
                    out = self._maintain_impl(tuple(local))
                    return jax.tree.map(lambda x: x[None], out)

                self._maintain_prog = jax.jit(shard_map_nocheck(
                    body, mesh=self.mesh, in_specs=(spec,),
                    out_specs=spec,
                ), donate_argnums=(0,))
        self.states = self._maintain_prog(self.states)
        if self._counters is None:
            return
        values = np.asarray(self._counters)  # THE one device sync
        residual = check_counter_values(
            self.name, self.counter_labels, values
        )
        for _ in range(64):
            if not residual:
                break
            if self.staged:
                self._counters = self._staged_barrier(sealed)
            else:
                self.states, self._counters = self._barrier_prog(
                    self.states, self._barrier_epoch_arg(sealed)
                )
            residual = check_counter_values(
                self.name, self.counter_labels, np.asarray(self._counters)
            )

    # -- checkpoint / recovery ------------------------------------------
    def _deliver_all_sinks(self, epoch_val) -> None:
        new_states = list(self.states)
        for idx, node in enumerate(self.nodes):
            if isinstance(node, FragNode):
                new_states[idx] = deliver_sinks(
                    node.fragment, new_states[idx], epoch_val
                )
        self.states = tuple(new_states)

    def _commit_checkpoint(self, sealed) -> None:
        # spill tiers drain under the mesh too (per-shard tiers); only
        # sink delivery stays meshless (sharded plans exclude sinks)
        self._drain_spill_tiers(sealed)
        if self.mesh is None:
            up = self._ensure_uploader()
            if up is None or up.pending() == 0:
                self._deliver_all_sinks(sealed)
            else:
                # uploader behind: delivery advances on ack only
                self._sinks_due = True
        self._snapshot_and_save(sealed)

    # -- spill-to-host (stream/spill.py) --------------------------------
    def _restore_spill_tiers(self, epoch: int) -> None:
        """Recovery companion: rewind host tiers via the shared policy
        (see runtime.rewind_spill_tier), one per shard."""
        for idx, j, ex in self._spill_sites():
            self._ensure_spill_tier(idx, j, ex)
            for s, tier in enumerate(self._spill_tiers[(idx, j)]):
                key = self._spill_key(idx, j, s)
                self.checkpoint_store.invalidate(key)
                rewind_spill_tier(
                    self.checkpoint_store, key, epoch, tier
                )

    def _spill_sites(self):
        """[(node_idx, exec_idx, executor)] of spill-enabled aggs."""
        out = []
        for idx, node in enumerate(self.nodes):
            if not isinstance(node, FragNode):
                continue
            for j, ex in enumerate(node.fragment.executors):
                if getattr(ex, "spill_ring", 0):
                    out.append((idx, j, ex))
        return out

    def _spill_key(self, idx: int, j: int, s: int) -> str:
        # keyed by the checkpoint LINEAGE (== name for whole jobs;
        # a partitioned DagJob's spill follows its partition lineage)
        base = f"{self.ckpt_key}@spill{idx}_{j}"
        return base if self.n_shards == 1 else f"{base}_s{s}"

    def _ensure_spill_tier(self, idx: int, j: int, ex) -> None:
        if not hasattr(self, "_spill_tiers"):
            self._spill_tiers = {}
            self._spill_progs = {}
        key = (idx, j)
        if key in self._spill_tiers:
            return
        from risingwave_tpu.stream.spill import AggSpillTier
        # one host tier PER SHARD: the exchange partitions keys by
        # vnode, so a shard's overflow groups live in that shard's
        # tier and the structural-ownership invariant holds per shard
        self._spill_tiers[key] = [
            AggSpillTier(
                ex, getattr(ex, "spill_table_size", ex.table_size * 8)
            )
            for _ in range(self.n_shards)
        ]

        def drain_local(states, idx=idx, j=j, ex=ex):
            new_states = list(states)
            node_states = list(new_states[idx])
            node_states[j], chunk = ex.drain_spill(node_states[j])
            new_states[idx] = tuple(node_states)
            return tuple(new_states), chunk

        def inject_local(states, chunk, idx=idx, j=j):
            new_states = list(states)
            node = self.nodes[idx]
            node_states = list(new_states[idx])
            cur = chunk
            for k in range(j + 1, len(node.fragment.executors)):
                if cur is None:
                    break
                node_states[k], cur = \
                    node.fragment.executors[k].apply(
                        node_states[k], cur
                    )
            new_states[idx] = tuple(node_states)
            if cur is not None:
                self._propagate(new_states, [(("node", idx), cur)])
            return tuple(new_states)

        if self.mesh is None:
            self._spill_progs[key] = (
                jax.jit(drain_local, donate_argnums=(0,)),
                jax.jit(inject_local, donate_argnums=(0,)),
            )
            return

        # mesh: the SAME per-shard bodies run inside shard_map — the
        # inject path may cross exchanges (all_to_all), which is valid
        # only in the sharded program (mirrors _maintain's pattern)
        spec = self._sharding_spec()

        def drain_body(states):
            local = jax.tree.map(lambda x: x[0], states)
            out_states, chunk = drain_local(tuple(local))
            return (
                jax.tree.map(lambda x: x[None], tuple(out_states)),
                jax.tree.map(lambda x: x[None], chunk),
            )

        def inject_body(states, chunk):
            local = jax.tree.map(lambda x: x[0], states)
            lchunk = jax.tree.map(lambda x: x[0], chunk)
            out_states = inject_local(tuple(local), lchunk)
            return jax.tree.map(lambda x: x[None], tuple(out_states))

        self._spill_progs[key] = (
            jax.jit(shard_map_nocheck(
                drain_body, mesh=self.mesh, in_specs=(spec,),
                out_specs=(spec, spec),
            ), donate_argnums=(0,)),
            jax.jit(shard_map_nocheck(
                inject_body, mesh=self.mesh, in_specs=(spec, spec),
                out_specs=spec,
            ), donate_argnums=(0,)),
        )

    def _drain_spill_tiers(self, sealed) -> None:
        """Snapshot-barrier hook: divert ring rows to host tiers and
        inject their changelog downstream of each agg node.  Under the
        mesh every shard drains into its own tier; the merged
        changelogs inject back shard-aligned through the sharded
        program (exchanges included)."""
        import numpy as _np
        for idx, j, ex in self._spill_sites():
            self._ensure_spill_tier(idx, j, ex)
            key = (idx, j)
            counts = _np.asarray(self.states[idx][j].spill_count)
            if int(counts.sum()) == 0:
                continue
            drain_p, inject_p = self._spill_progs[key]
            tiers = self._spill_tiers[key]
            if self.mesh is None:
                self.states, chunk = drain_p(self.states)
                out = tiers[0].process(jax.device_get(chunk), sealed)
                if out is not None:
                    self.states = inject_p(self.states, out)
                continue
            self.states, chunks = drain_p(self.states)
            host = jax.device_get(chunks)  # stacked [n_shards, ...]
            outs = []
            for s in range(self.n_shards):
                shard_chunk = jax.tree.map(lambda x: x[s], host)
                outs.append(tiers[s].process(shard_chunk, sealed))
            if all(o is None for o in outs):
                continue
            import numpy as _np2
            proto = next(o for o in outs if o is not None)
            empty = jax.tree.map(
                lambda x: _np2.zeros_like(_np2.asarray(x)), proto
            )
            stacked = jax.tree.map(
                lambda *xs: _np2.stack([_np2.asarray(x) for x in xs]),
                *[o if o is not None else empty for o in outs],
            )
            from jax.sharding import NamedSharding, PartitionSpec as P
            stacked = jax.device_put(
                stacked, NamedSharding(self.mesh, P(self.AXIS))
            )
            self.states = inject_p(self.states, stacked)

    def recover(self, epoch: int | None = None) -> None:
        """Reset to the last committed checkpoint (ref §3.5).  Drains
        the upload queue first — sealed epochs finish becoming durable
        before the rewind target is chosen.  ``epoch`` pins the rewind
        to a specific retained checkpoint (partitioned DagJobs rewind
        to the handover round before a vnode-slice transplant, exactly
        like StreamingJob partitions); checkpoints live under
        ``ckpt_key`` — a partition's lineage, not the job name."""
        self._counters = None
        if self._uploader is not None:
            self._uploader.drain(raise_error=False)
            self._process_upload_acks()
            self._uploader.clear_error()
            self._sinks_due = False
        if self.checkpoint_store is not None:
            # see StreamingJob.recover: rewinds invalidate the digest
            # cache so the next save re-bases with a full snapshot
            # (and vacuum orphan files of a crashed upload)
            self.checkpoint_store.invalidate(self.ckpt_key)
            loaded = self.checkpoint_store.load(self.ckpt_key, epoch)
            if loaded is not None:
                epoch_v, states, src_state = loaded
                if self.mesh is not None:
                    from jax.sharding import (
                        NamedSharding, PartitionSpec as P,
                    )
                    self.states = jax.device_put(
                        states, NamedSharding(self.mesh, P(self.AXIS))
                    )
                else:
                    self.states = jax.device_put(states)
                self.committed_epoch = epoch_v
                self.sealed_epoch = epoch_v
                for name, src in self.sources.items():
                    restore_source(src, src_state.get(name, {}))
                self._restore_spill_tiers(epoch_v)
                return
        if not self.checkpoints:
            self.states = self._init_states()
            for src in self.sources.values():
                if hasattr(src, "offset"):
                    src.offset = 0
            for tiers in getattr(self, "_spill_tiers", {}).values():
                for tier in tiers:
                    tier.reset()
            return
        snap = self.checkpoints[-1]
        states = self._restore_in_memory(snap)
        if self.mesh is not None:
            # shadow restores land on the default device; re-pin the
            # stacked tree to the mesh layout before programs run
            from jax.sharding import NamedSharding, PartitionSpec as P
            states = jax.device_put(
                states, NamedSharding(self.mesh, P(self.AXIS))
            )
        self.states = states
        for name, src in self.sources.items():
            restore_source(src, snap.source_state.get(name, {}))
        for (idx, j), tiers in getattr(self, "_spill_tiers",
                                       {}).items():
            for s, tier in enumerate(tiers):
                if snap.spill and (idx, j, s) in snap.spill:
                    tier.restore(snap.spill[(idx, j, s)])
                else:
                    tier.reset()

    # -- serving (sharded) ----------------------------------------------
    def mv_rows(self, mv_executor, state_index):
        """Host view of a sharded MV: per-shard partitions merged (the
        serving analog of ShardedStreamingJob.mv_rows)."""
        st = self.states
        for i in state_index:
            st = st[i]
        host = jax.device_get(st)  # one transfer
        rows = []
        for shard in range(self.n_shards):
            rows.extend(mv_executor.to_host(
                jax.tree.map(lambda x: x[shard], host)
            ))
        return rows

    # -- backfill -------------------------------------------------------
    def backfill_node(self, node_id: int, chunks, side: str | None = None,
                      ) -> None:
        """Feed snapshot chunks through ONE node's subtree (a freshly
        attached cascade MV consuming the upstream MV's existing rows —
        ref arrangement_backfill.rs, collapsed to snapshot replay since
        the upstream MV is device-resident).  ``side`` targets a join
        node's build/probe side.

        NOT donated: the snapshot chunk aliases the upstream MV's state
        buffers (it is built zero-copy from them), so donating the state
        tree would donate the chunk's own storage."""
        if self.mesh is None:
            prog = jax.jit(
                lambda states, chunk: self._backfill_impl(
                    states, chunk, node_id, side
                ),
            )
        else:
            # sharded job: the snapshot chunk arrives stacked
            # [n_shards, ...]; each shard replays its own MV partition
            # through the attached subtree inside shard_map (same
            # calling convention as _make_step's per-shard body)
            spec = self._sharding_spec()

            def body(states, chunk):
                local_s = jax.tree.map(lambda x: x[0], states)
                local_c = jax.tree.map(lambda x: x[0], chunk)
                out = self._backfill_impl(
                    tuple(local_s), local_c, node_id, side
                )
                return jax.tree.map(lambda x: x[None], out)

            prog = jax.jit(shard_map_nocheck(
                body, mesh=self.mesh, in_specs=(spec, spec),
                out_specs=spec,
            ))
        for chunk in chunks:
            self.states = prog(self.states, chunk)

    def _backfill_impl(self, states, chunk, node_id: int,
                       side: str | None):
        new_states = list(states)
        node = self.nodes[node_id]
        # a marked attach edge routes the snapshot replay through the
        # SAME exchange live chunks cross (agg-over-reduced-key / join
        # attach edges): each shard's partition re-routes to its new
        # key owners before the first executor sees it
        chunk = self._exchange(
            node_id, side if isinstance(node, JoinNode) else None, chunk
        )
        if isinstance(node, FragNode):
            new_states[node_id], out = node.fragment._step_impl(
                new_states[node_id], chunk
            )
            if out is not None:
                self._propagate(new_states, [(("node", node_id), out)])
        else:
            # joins drain with WINDOWED emission: an MV snapshot is one
            # big chunk, its self-join easily exceeds out_capacity
            def direct(ref, out):
                self._propagate(new_states, [(ref, out)])

            self._apply_join_windowed(
                new_states, node_id, chunk, side, direct
            )
        return tuple(new_states)

    # -- driving --------------------------------------------------------
    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                self.chunk_round()
            self.inject_barrier()
        self.drain_uploads()

    @classmethod
    def binary(
        cls,
        left_source,
        right_source,
        join,
        post_fragment: Fragment,
        left_fragment: Fragment | None = None,
        right_fragment: Fragment | None = None,
        checkpoint_frequency: int = 1,
        name: str = "join_job",
        checkpoint_store=None,
    ) -> "DagJob":
        """Two sources → per-side prep → join → post chain (the former
        BinaryJob shape as a DAG)."""
        nodes: list = []
        lref: Ref = ("source", "left")
        rref: Ref = ("source", "right")
        if left_fragment is not None:
            nodes.append(FragNode(left_fragment, lref))
            lref = ("node", len(nodes) - 1)
        if right_fragment is not None:
            nodes.append(FragNode(right_fragment, rref))
            rref = ("node", len(nodes) - 1)
        nodes.append(JoinNode(join, lref, rref))
        nodes.append(FragNode(post_fragment, ("node", len(nodes) - 1)))
        return cls(
            {"left": left_source, "right": right_source}, nodes,
            name=name, checkpoint_frequency=checkpoint_frequency,
            checkpoint_store=checkpoint_store,
        )
