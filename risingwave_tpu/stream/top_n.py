"""TopN executors (plain + group), device-resident sorted state.

Reference counterpart: ``src/stream/src/executor/top_n/`` — plain/group
variants over a ``TopNCache`` with high/middle/low bands backed by a
state table.

TPU-first design
----------------
State is a flat pool of ``[pool_size]`` rows (SoA) + validity.  Instead
of the reference's per-row BTree cache walk:

- inserts claim free pool slots by rank (one cumsum one-hot per chunk);
- deletes hash-match their victim rows (same row-hash trick as the
  join);
- at barrier flush the WHOLE pool is lexicographically sorted
  (trailing-key-first stable argsorts), ranked within its group by a
  segment scan, and the ``offset <= rank < offset+limit`` band is the
  current TopN.  The emitted changelog is the set difference against
  the previously emitted band, computed by hash membership — sorting
  a few thousand rows on device per barrier beats pointer-chasing a
  BTree per input row.

The pool bounds retraction fidelity like the reference's cache: rows
beyond ``pool_size`` overflow (counted, surfaced at checkpoint).  For
windowed queries (nexmark q5) watermark cleaning frees closed windows.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import (
    Chunk,
    OP_DELETE,
    OP_INSERT,
    StrCol,
)
from risingwave_tpu.common.compact import mask_indices
from risingwave_tpu.common.hash import hash64_columns
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.stream.executor import Executor


def _order_key(col, descending: bool) -> jnp.ndarray:
    """Map a column to uint64 preserving the requested order."""
    if isinstance(col, StrCol):
        # first 8 bytes big-endian (approximate for strings; exact
        # string ordering arrives with the memcomparable encoder)
        w = col.data.shape[1]
        take = min(8, w)
        b = col.data[:, :take].astype(jnp.uint64)
        shifts = (np.arange(take, dtype=np.uint64)[::-1] + (8 - take)) * 8
        k = jnp.sum(b << shifts[None, :], axis=1, dtype=jnp.uint64)
    elif col.dtype == jnp.bool_:
        k = col.astype(jnp.uint64)
    elif jnp.issubdtype(col.dtype, jnp.floating):
        # exact total order without relying on 64-bit float bitcasts
        # (unsupported under the TPU x64 rewrite): hi = f32 rounding,
        # lo = the residual; for equal hi the residual orders the tie
        def f32_order_bits(x32):
            u = x32.view(jnp.uint32)
            neg = (u >> np.uint32(31)) == 1
            return jnp.where(neg, ~u, u | np.uint32(1) << np.uint32(31))

        if col.dtype == jnp.float64:
            hi = col.astype(jnp.float32)
            lo = (col - hi.astype(jnp.float64)).astype(jnp.float32)
            k = (f32_order_bits(hi).astype(jnp.uint64) << np.uint64(32)) | \
                f32_order_bits(lo).astype(jnp.uint64)
        else:
            k = f32_order_bits(col.astype(jnp.float32)).astype(
                jnp.uint64
            ) << np.uint64(32)
    else:
        u = col.astype(jnp.int64).view(jnp.uint64)
        k = u ^ (np.uint64(1) << np.uint64(63))  # flip sign bit
    return ~k if descending else k


class TopNState(NamedTuple):
    rows: tuple            # [pool] column stores
    valid: jnp.ndarray     # bool [pool]
    row_hash: jnp.ndarray  # uint64 [pool]
    prev_rows: tuple       # last emitted band [emit_cap]
    prev_valid: jnp.ndarray
    prev_hash: jnp.ndarray
    overflow: jnp.ndarray
    inconsistency: jnp.ndarray


def _empty_like_col(col_proto, n: int):
    if isinstance(col_proto, StrCol):
        return StrCol(
            jnp.zeros((n, col_proto.data.shape[1]), jnp.uint8),
            jnp.zeros((n,), jnp.int32),
        )
    return jnp.zeros((n,), col_proto.dtype)


def _gather(col, idx):
    if isinstance(col, StrCol):
        return StrCol(col.data[idx], col.lens[idx])
    return col[idx]


def _scatter(store, pos, col):
    if isinstance(store, StrCol):
        return StrCol(
            store.data.at[pos].set(col.data, mode="drop"),
            store.lens.at[pos].set(col.lens, mode="drop"),
        )
    return store.at[pos].set(col, mode="drop")


def schema_protos(schema: Schema) -> list:
    """One-row column prototypes for pool/table creation."""
    protos = []
    for f in schema:
        if f.data_type.is_string:
            protos.append(StrCol(
                jnp.zeros((1, f.str_width), jnp.uint8),
                jnp.zeros((1,), jnp.int32),
            ))
        else:
            protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
    return protos


def pool_apply(rows: tuple, valid, row_hash_store, chunk: Chunk, S: int):
    """Apply a changelog chunk to a flat row pool (shared by TopN,
    OverWindow and DynamicFilter).

    In-chunk +row/-row pairs annihilate first (a delete can only match
    pre-chunk state — same guard as the join's update path), then
    deletes clear their rank-th hash match and inserts claim free
    slots.  Returns (rows, valid, hashes, n_overflow, n_missing)."""
    from risingwave_tpu.stream.hash_join import _group_totals, _rank_by

    cap = chunk.capacity
    signs = chunk.signs()
    is_ins = chunk.valid & (signs > 0)
    is_del = chunk.valid & (signs < 0)
    row_hash = hash64_columns(list(chunk.columns))

    # in-chunk annihilation
    ins_rank_h = _rank_by(row_hash, is_ins)
    del_rank_h = _rank_by(row_hash, is_del)
    n_ins_h = _group_totals(row_hash, is_ins)
    n_del_h = _group_totals(row_hash, is_del)
    is_ins = is_ins & ~(ins_rank_h < n_del_h)
    is_del = is_del & ~(del_rank_h < n_ins_h)

    # deletes: rank-th pool row with matching hash
    match = valid[None, :] & (row_hash_store[None, :] == row_hash[:, None])
    del_rank = _rank_by(row_hash, is_del)
    mrank = jnp.cumsum(match, axis=1) - 1
    clear_onehot = match & (mrank == del_rank[:, None]) & is_del[:, None]
    any_clear = jnp.any(clear_onehot, axis=1)
    j_clear = jnp.argmax(clear_onehot, axis=1).astype(jnp.int32)
    pos_clear = jnp.where(any_clear, j_clear, jnp.int32(S))
    valid = valid.at[pos_clear].set(False, mode="drop")
    n_missing = jnp.sum((is_del & ~any_clear).astype(jnp.int64))

    # inserts: rank-th free slot
    free = ~valid
    free_pos = jnp.cumsum(free) - 1
    slot_of_rank = jnp.full((S,), S, jnp.int32).at[
        jnp.where(free, free_pos.astype(jnp.int32), S)
    ].min(jnp.arange(S, dtype=jnp.int32), mode="drop")
    ins_rank = _rank_by(jnp.zeros((cap,), jnp.uint64), is_ins)
    tgt = jnp.where(
        is_ins & (ins_rank < S),
        slot_of_rank[jnp.minimum(ins_rank, S - 1)],
        jnp.int32(S),
    )
    got = is_ins & (tgt < S)
    valid = valid.at[jnp.where(got, tgt, S)].set(True, mode="drop")
    rows = tuple(
        _scatter(store, jnp.where(got, tgt, S), col)
        for store, col in zip(rows, chunk.columns)
    )
    hashes = row_hash_store.at[jnp.where(got, tgt, S)].set(
        row_hash, mode="drop"
    )
    n_over = jnp.sum((is_ins & ~got).astype(jnp.int64))
    return rows, valid, hashes, n_over, n_missing


class GroupTopNExecutor(Executor):
    """TOP N (+offset) per group over a changelog (plain TopN: no group).

    ``order_by``: (expr, descending) pairs evaluated on the input schema.
    Output = input columns; with ``rank_alias`` set, a 1-based in-band
    row_number column is appended (the row_number-in-subquery rewrite's
    rank output — a row whose rank shifts retracts its old (row, rank)
    pair and emits the new one, ref group_top_n with output row_number).
    """

    emits_on_apply = False
    emits_on_flush = True

    def __init__(
        self,
        in_schema: Schema,
        group_by: Sequence[Expr],
        order_by: Sequence[tuple[Expr, bool]],
        limit: int,
        offset: int = 0,
        pool_size: int = 4096,
        emit_capacity: int = 1024,
        watermark_col_idx: int | None = None,
        watermark_lag: int = 0,
        watermark_src_col: int | None = None,
        append_only: bool = False,
        rank_alias: str | None = None,
    ):
        super().__init__(in_schema)
        self.group_by = tuple(group_by)
        self.order_by = tuple(order_by)
        self.limit = limit
        self.offset = offset
        self.pool_size = pool_size
        self.emit_capacity = emit_capacity
        self.watermark_col_idx = watermark_col_idx
        self.watermark_lag = watermark_lag
        #: only react to Watermark messages with this source col_idx
        #: (None = any — single-watermark fragments)
        self.watermark_src_col = watermark_src_col
        #: append-only input: rows outside the band can never re-enter
        #: (no retractions), so flush evicts them — the pool then only
        #: needs to absorb one epoch of inserts plus the band (the
        #: reference's append_only TopN cache makes the same move)
        self.append_only = append_only
        self.rank_alias = rank_alias
        if rank_alias is not None:
            self._out_schema = Schema(tuple(in_schema) + (
                Field(rank_alias, DataType.INT64),
            ))
        else:
            self._out_schema = in_schema

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def init_state(self) -> TopNState:
        protos = []
        for f in self.in_schema:
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        if self.rank_alias is not None:
            # the emitted-snapshot buffers carry the rank column too
            protos_prev = protos + [jnp.zeros((1,), jnp.int64)]
        else:
            protos_prev = protos
        S, E = self.pool_size, self.emit_capacity
        return TopNState(
            rows=tuple(_empty_like_col(p, S) for p in protos),
            valid=jnp.zeros((S,), jnp.bool_),
            row_hash=jnp.zeros((S,), jnp.uint64),
            prev_rows=tuple(_empty_like_col(p, E) for p in protos_prev),
            prev_valid=jnp.zeros((E,), jnp.bool_),
            prev_hash=jnp.zeros((E,), jnp.uint64),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
        )

    # ------------------------------------------------------------------
    def apply(self, state: TopNState, chunk: Chunk):
        rows, valid, hashes, n_over, n_missing = pool_apply(
            state.rows, state.valid, state.row_hash, chunk, self.pool_size
        )
        return TopNState(
            rows=rows,
            valid=valid,
            row_hash=hashes,
            prev_rows=state.prev_rows,
            prev_valid=state.prev_valid,
            prev_hash=state.prev_hash,
            overflow=state.overflow + n_over,
            inconsistency=state.inconsistency + n_missing,
        ), None

    # ------------------------------------------------------------------
    def _band_mask(self, state: TopNState):
        """(band membership, 1-based in-band rank) per pool slot."""
        S = self.pool_size
        pool_chunk = Chunk(
            state.rows, jnp.zeros((S,), jnp.int8), state.valid,
            self.in_schema,
        )
        # lexicographic sort via stable argsorts, least-significant key
        # first: order keys (last..first), then group hash, then
        # validity (valid rows to the front) as most significant
        order = jnp.arange(S, dtype=jnp.int32)
        for e, desc in reversed(self.order_by):
            k = _order_key(e.eval(pool_chunk), desc)
            order = order[jnp.argsort(k[order], stable=True)]
        if self.group_by:
            gh = hash64_columns([e.eval(pool_chunk) for e in self.group_by])
        else:
            gh = jnp.zeros((S,), jnp.uint64)
        order = order[jnp.argsort(gh[order], stable=True)]
        order = order[jnp.argsort(~state.valid[order], stable=True)]

        group_sorted = jnp.where(
            state.valid[order], gh[order], jnp.uint64(0xFFFFFFFFFFFFFFFF)
        )
        is_new = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), group_sorted[1:] != group_sorted[:-1]]
        )
        start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_new, jnp.arange(S, dtype=jnp.int32), 0)
        )
        rank = jnp.arange(S, dtype=jnp.int32) - start
        in_band_sorted = state.valid[order] & (rank >= self.offset) & (
            rank < self.offset + self.limit
        )
        band = jnp.zeros((S,), jnp.bool_).at[order].set(in_band_sorted)
        # absolute 1-based row_number (NOT band-relative): an rn = k
        # rewrite (limit 1, offset k-1) must still emit rank k
        ranks = jnp.zeros((S,), jnp.int64).at[order].set(
            (rank + 1).astype(jnp.int64)
        )
        return band, ranks

    def flush(self, state: TopNState, epoch):
        S, E = self.pool_size, self.emit_capacity
        band, ranks = self._band_mask(state)
        # compact current band to [E]
        cur_idx = mask_indices(band, E, S)
        cur_live = cur_idx < S
        safe = jnp.minimum(cur_idx, S - 1)
        cur_rows = tuple(_gather(c, safe) for c in state.rows)
        cur_hash = jnp.where(cur_live, state.row_hash[safe], 0)
        if self.rank_alias is not None:
            # the rank is part of the OUTPUT row: fold it into the diff
            # hash so a rank shift retracts the old (row, rank) pair
            cur_rank = jnp.where(cur_live, ranks[safe], 0)
            cur_rows = cur_rows + (cur_rank,)
            cur_hash = jnp.where(
                cur_live,
                cur_hash ^ (cur_rank.astype(jnp.uint64)
                            * jnp.uint64(0x9E3779B97F4A7C15)),
                0,
            )

        # membership diffs by hash multiset (duplicates handled by rank)
        from risingwave_tpu.stream.hash_join import _rank_by as rank_by

        def member(a_hash, a_live, b_hash, b_live):
            """for each a: does b contain a copy (rank-aware)?"""
            eq = (a_hash[:, None] == b_hash[None, :]) & a_live[:, None] & \
                b_live[None, :]
            a_rank = rank_by(a_hash, a_live)
            return jnp.sum(eq, axis=1) > a_rank

        ins_side = cur_live & ~member(
            cur_hash, cur_live, state.prev_hash, state.prev_valid
        )
        del_side = state.prev_valid & ~member(
            state.prev_hash, state.prev_valid, cur_hash, cur_live
        )

        # emit: deletes (from prev) then inserts (from cur), [2E] chunk
        def cat(a, b):
            if isinstance(a, StrCol):
                return StrCol(cat(a.data, b.data), cat(a.lens, b.lens))
            return jnp.concatenate([a, b], axis=0)

        out_cols = tuple(
            cat(p, c) for p, c in zip(state.prev_rows, cur_rows)
        )
        ops = cat(
            jnp.full((E,), OP_DELETE, jnp.int8),
            jnp.full((E,), OP_INSERT, jnp.int8),
        )
        valid = cat(del_side, ins_side)
        out = Chunk(out_cols, ops, valid, self.out_schema)

        # append-only inputs: rows outside the band can never re-enter
        # (no retractions), so evict them — the pool then only needs to
        # absorb one epoch of inserts plus the band
        pool_valid = band if self.append_only else state.valid
        return TopNState(
            rows=state.rows,
            valid=pool_valid,
            row_hash=state.row_hash,
            prev_rows=cur_rows,
            prev_valid=cur_live,
            prev_hash=cur_hash,
            overflow=state.overflow,
            inconsistency=state.inconsistency,
        ), out

    def on_watermark(self, state: TopNState, watermark):
        if self.watermark_col_idx is None:
            return state
        if (self.watermark_src_col is not None
                and watermark.col_idx != self.watermark_src_col):
            return state
        return self.clean_below(
            state, self.watermark_col_idx,
            watermark.value - self.watermark_lag,
        )

    # ------------------------------------------------------------------
    def clean_below(self, state: TopNState, col_idx: int, threshold):
        """Watermark cleaning: drop pool + emitted rows below threshold."""
        stale = state.valid & (state.rows[col_idx] < threshold)
        prev_stale = state.prev_valid & (
            state.prev_rows[col_idx] < threshold
        )
        return TopNState(
            rows=state.rows,
            valid=state.valid & ~stale,
            row_hash=state.row_hash,
            prev_rows=state.prev_rows,
            prev_valid=state.prev_valid & ~prev_stale,
            prev_hash=state.prev_hash,
            overflow=state.overflow,
            inconsistency=state.inconsistency,
        )


class DedupState(NamedTuple):
    table: "HashTable"  # noqa: F821
    overflow: jnp.ndarray  # rows wrongly dropped because the table filled


class AppendOnlyDedupExecutor(Executor):
    """Drop rows whose key was already seen (ref dedup/append_only_dedup.rs).

    A HashTable of seen keys; the chunk keeps only first-occurrence rows
    (both vs state and within the chunk, via insert-rank).  Overflowed
    rows are counted (maintenance raises) — a full table must never
    silently undercount.  With ``watermark_key_idx`` set, watermarks
    evict keys of closed windows (bounding DISTINCT-over-window state).
    """

    emits_on_apply = True
    emits_on_flush = False

    def __init__(self, in_schema: Schema, key_exprs: Sequence[Expr],
                 table_size: int = 1 << 16,
                 watermark_key_idx: int | None = None,
                 watermark_lag: int = 0,
                 watermark_src_col: int | None = None):
        super().__init__(in_schema)
        self.key_exprs = tuple(key_exprs)
        self.table_size = table_size
        self.watermark_key_idx = watermark_key_idx
        self.watermark_lag = watermark_lag
        self.watermark_src_col = watermark_src_col

    def init_state(self) -> DedupState:
        from risingwave_tpu.state.hash_table import HashTable
        protos = []
        for e in self.key_exprs:
            f = e.return_field(self.in_schema)
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        return DedupState(
            HashTable.create(protos, self.table_size),
            jnp.zeros((), jnp.int64),
        )

    def apply(self, state: DedupState, chunk: Chunk):
        key_cols = [e.eval(chunk) for e in self.key_exprs]
        table, slots, inserted, overflow = state.table.lookup_or_insert(
            key_cols, chunk.valid
        )
        n_over = jnp.sum((overflow & chunk.valid).astype(jnp.int64))
        # only rows that inserted a fresh key survive
        return DedupState(
            table, state.overflow + n_over
        ), chunk.mask(inserted)

    def on_watermark(self, state: DedupState, watermark):
        if self.watermark_key_idx is None:
            return state
        if (self.watermark_src_col is not None
                and watermark.col_idx != self.watermark_src_col):
            return state
        key = state.table.key_cols[self.watermark_key_idx]
        stale = state.table.occupied & (
            key < watermark.value - self.watermark_lag
        )
        return DedupState(state.table.clear_where(stale), state.overflow)

    def maybe_rehash(self, state: DedupState) -> DedupState:
        """Traceable: lax.cond on the device tombstone count."""
        def do_rehash(state: DedupState) -> DedupState:
            fresh, _ = state.table.rehashed()
            return DedupState(fresh, state.overflow)

        return jax.lax.cond(
            state.table.tombstone_count() > self.table_size // 4,
            do_rehash, lambda s: s, state,
        )
