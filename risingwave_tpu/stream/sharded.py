"""Sharded streaming jobs: one fragment chain SPMD over a vnode mesh.

Reference counterpart: fragment data parallelism — N parallel actors per
fragment, each owning a disjoint vnode bitmap, connected by hash
dispatchers (SURVEY.md §2.3 parallelism items 1-2).

TPU restructuring: the N actors of the reference become ONE
``shard_map``-ed step function over a mesh axis (``"shard"``).  Each
shard holds its own executor states (leading mesh-sharded axis); the
hash exchange between the stateless prefix and the keyed suffix is an
``all_to_all`` inside the same jitted program, riding ICI.  The barrier
loop drives all shards in lockstep, so merge alignment is structural.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from risingwave_tpu.parallel.exchange import shard_map_nocheck

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.parallel.exchange import shuffle_chunk
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.fragment import WM_NONE, WM_SAFE_FLOOR, Fragment


def make_mesh(n_devices: int | None = None, axis: str = "shard") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(devs[:n], (axis,))


class ShardedJob:
    """source → [local executors] → hash exchange → [keyed executors].

    ``source_fn(k0, cap) -> Chunk`` must be traceable (e.g. the nexmark
    generator impl): each shard generates/reads its own ordinal range, so
    ingestion is embarrassingly parallel like the reference's source
    splits.  ``exchange_keys(chunk) -> [key cols]`` routes rows to the
    shard owning their vnode.
    """

    AXIS = "shard"

    def __init__(
        self,
        mesh: Mesh,
        source_fn: Callable,
        chunk_capacity: int,
        local_executors: Sequence[Executor],
        exchange_key_fn: Callable,
        keyed_executors: Sequence[Executor],
    ):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.source_fn = source_fn
        self.cap = chunk_capacity
        # the two halves of the chain are real Fragments, so chain
        # semantics (None-break, flush cascade) stay single-sourced
        self.local_frag = (
            Fragment(local_executors, "local") if local_executors else None
        )
        self.keyed_frag = Fragment(keyed_executors, "keyed")
        self.exchange_key_fn = exchange_key_fn
        self.executors = list(local_executors) + list(keyed_executors)

        spec = P(self.AXIS)
        self._step = jax.jit(
            shard_map_nocheck(
                self._local_step,
                mesh=self.mesh,
                in_specs=(spec, spec),
                out_specs=spec,
            )
        )
        self._flush = jax.jit(
            shard_map_nocheck(
                self._local_flush,
                mesh=self.mesh,
                in_specs=(spec, spec),
                out_specs=(spec, spec),
            )
        )

    # ------------------------------------------------------------------
    def init_states(self):
        """Per-shard states stacked on a leading mesh-sharded axis."""
        def one_shard(_):
            return tuple(ex.init_state() for ex in self.executors)

        stacked = jax.vmap(one_shard)(jnp.arange(self.n_shards))
        sharding = jax.NamedSharding(self.mesh, P(self.AXIS))
        return jax.device_put(stacked, sharding)

    # -- traced per-shard bodies ----------------------------------------
    def _split(self, states):
        n_local = len(self.local_frag.executors) if self.local_frag else 0
        return tuple(states[:n_local]), tuple(states[n_local:])

    def _local_step(self, states, k0):
        states = jax.tree.map(lambda x: x[0], states)
        local_states, keyed_states = self._split(states)
        chunk = self.source_fn(k0[0], self.cap)
        if self.local_frag is not None:
            local_states, chunk = self.local_frag._step_impl(
                local_states, chunk
            )
        if chunk is not None:
            chunk = shuffle_chunk(
                chunk, self.exchange_key_fn(chunk), self.AXIS, self.n_shards
            )
            keyed_states, _ = self.keyed_frag._step_impl(keyed_states, chunk)
        return jax.tree.map(
            lambda x: x[None], tuple(local_states) + tuple(keyed_states)
        )

    def _feed_exchange(self, keyed_states, emitted):
        """Route a local-half emission across the vnode exchange into
        the keyed half (inside the shard_map body — rides ICI)."""
        shuffled = shuffle_chunk(
            emitted, self.exchange_key_fn(emitted), self.AXIS, self.n_shards
        )
        keyed_states, out = self.keyed_frag._step_impl(
            keyed_states, shuffled
        )
        return keyed_states, out

    def _local_flush(self, states, epoch):
        states = jax.tree.map(lambda x: x[0], states)
        local_states, keyed_states = self._split(states)
        outs = []
        if self.local_frag is not None:
            local_states, local_outs = self.local_frag._flush_impl(
                local_states, epoch[0]
            )
            # barrier emissions from the local half cross the exchange
            for emitted in local_outs:
                keyed_states, out = self._feed_exchange(
                    keyed_states, emitted
                )
                if out is not None:
                    outs.append(out)
            if self.local_frag.has_pending_protocol():
                # device-side drain of the local half, feeding each
                # round across the exchange (no host pending readbacks)
                def cond(carry):
                    ls, ks, it = carry
                    return (self.local_frag.pending_total(ls) > 0) & (
                        it < self.local_frag.MAX_DRAIN_ROUNDS
                    )

                def body(carry):
                    ls, ks, it = carry
                    ls, more = self.local_frag._flush_impl(ls, epoch[0])
                    for emitted in more:
                        ks, _ = self._feed_exchange(ks, emitted)
                    return ls, ks, it + 1

                local_states, keyed_states, _ = jax.lax.while_loop(
                    cond, body,
                    (local_states, keyed_states, jnp.int32(0)),
                )
        keyed_states, keyed_outs = self.keyed_frag._flush_impl(
            keyed_states, epoch[0]
        )
        outs.extend(keyed_outs)
        # keyed half is terminal — drain it on device too
        keyed_states = self.keyed_frag._drain_impl(keyed_states, epoch[0])
        # watermark alignment + state cleaning (mirrors the linear
        # barrier's flush → drain → wm → drain order)
        local_states, keyed_states = self._wm_pass(
            local_states, keyed_states
        )
        keyed_states = self.keyed_frag._drain_impl(keyed_states, epoch[0])
        out_tree = jax.tree.map(lambda x: x[None], tuple(outs))
        new_states = tuple(local_states) + tuple(keyed_states)
        return jax.tree.map(lambda x: x[None], new_states), out_tree

    def _wm_pass(self, local_states, keyed_states):
        """Cross-shard watermark alignment, entirely on device.

        The reference aligns watermarks by flowing them through
        exchange dispatchers and taking the min across upstream actors
        (src/stream/src/executor/merge.rs watermark alignment).  Here
        each shard's WatermarkFilter holds a local max_ts; the global
        watermark is ``lax.pmin`` over the mesh axis — one ICI
        collective per barrier — then every executor in both halves
        applies its cleaning/EOWC hook.  A shard that has seen no data
        pins the global watermark at the WM_NONE sentinel, so cleaning
        never outruns a lagging shard (exactly the reference's
        min-of-upstreams rule)."""
        from risingwave_tpu.stream.message import Watermark
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        local_execs = list(self.local_frag.executors) \
            if self.local_frag else []
        keyed_execs = list(self.keyed_frag.executors)
        locs, keys = list(local_states), list(keyed_states)
        for i, ex in enumerate(local_execs):
            if not isinstance(ex, WatermarkFilterExecutor):
                continue
            graw = jax.lax.pmin(locs[i].max_ts, self.AXIS)
            val = jnp.where(
                graw == WM_NONE,
                jnp.int64(WM_SAFE_FLOOR),
                graw - ex.delay_us,
            )
            wm = Watermark(ex.ts_col, val)
            for j, ex2 in enumerate(local_execs):
                locs[j] = ex2.on_watermark(locs[j], wm)
            for j, ex2 in enumerate(keyed_execs):
                keys[j] = ex2.on_watermark(keys[j], wm)
        return tuple(locs), tuple(keys)

    # -- host API --------------------------------------------------------
    def step(self, states, k0_per_shard: jnp.ndarray):
        """One chunk per shard; ``k0_per_shard`` int64 [n_shards]."""
        return self._step(states, k0_per_shard)

    def flush(self, states, epoch: int):
        epochs = jnp.full((self.n_shards,), epoch, jnp.int64)
        return self._flush(states, epochs)

    def shard_states(self, states, shard: int):
        """Host view of one shard's states (for serving/inspection)."""
        return jax.tree.map(lambda x: x[shard], jax.device_get(states))

    def run_epochs(
        self,
        states,
        barriers: int,
        chunks_per_barrier: int,
        start_ordinal: int = 0,
    ):
        """Drive the barrier loop; returns (states, emitted-per-flush)."""
        ordinal = start_ordinal
        all_outs = []
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                k0 = ordinal + jnp.arange(self.n_shards, dtype=jnp.int64) \
                    * self.cap
                states = self.step(states, k0)
                ordinal += self.n_shards * self.cap
            states, outs = self.flush(states, 0)
            all_outs.append(outs)
        return states, all_outs


class ShardedStreamingJob:
    """StreamingJob-shaped adapter over a ShardedJob.

    Lets the engine drive vnode-sharded MVs with the same barrier-loop
    interface as linear jobs (ref: the reference's adaptive parallelism
    — N actors per fragment — behind one scheduling surface).

    Round-1 scope: traceable sources, no watermark-driven cleaning in
    the sharded path (planner gates eligibility).
    """

    def __init__(self, sharded: ShardedJob, reader, name: str,
                 checkpoint_frequency: int = 1, checkpoint_store=None):
        from risingwave_tpu.common.epoch import EpochPair

        self.sharded = sharded
        self.reader = reader
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        self.checkpoint_store = checkpoint_store
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        self.states = sharded.init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.committed_epoch = 0
        self.paused = False
        self._mem_snapshot = None

    def chunk_round(self) -> int:
        """Uniform driving interface shared with DagJob."""
        return self.run_chunk()

    def run_chunk(self) -> int:
        if self.paused:
            return 0
        n, cap = self.sharded.n_shards, self.sharded.cap
        # next_base() owns split→global ordinal mapping; one cap-stride
        # block per shard
        k0 = jnp.asarray(
            [self.reader.next_base() for _ in range(n)], jnp.int64
        )
        self.states = self.sharded.step(self.states, k0)
        return n * cap

    def _gather_counters(self, states):
        """All shard-summed error counters + residual pending as ONE
        device vector (read back once per maintenance interval)."""
        from risingwave_tpu.stream.fragment import COUNTER_ATTRS

        labels: list[str] = []
        vals: list[jnp.ndarray] = []
        for i, ex in enumerate(self.sharded.executors):
            st = states[i]
            for counter in COUNTER_ATTRS:
                if hasattr(st, counter):
                    labels.append(f"{ex}.{counter}")
                    vals.append(
                        jnp.sum(getattr(st, counter)).astype(jnp.int64)
                    )
            if hasattr(ex, "pending_flush"):
                # pending_flush maps over the [n_shards] leading axis
                labels.append(f"{ex}.pending")
                vals.append(jnp.sum(jax.vmap(ex.pending_flush)(st))
                            .astype(jnp.int64))
        self._counter_labels = labels
        return jnp.stack(vals) if vals else jnp.zeros((0,), jnp.int64)

    def inject_barrier(self, barrier=None) -> None:
        from risingwave_tpu.stream.runtime import (
            _snapshot_copy,
            check_counter_values,
        )

        self.barriers_seen += 1
        sealed = self.epoch.curr.value
        # flush drains on device inside the shard_map body — the host
        # never reads pending counts
        self.states, _ = self.sharded.flush(self.states, sealed)
        if self.barriers_seen % self.checkpoint_frequency == 0:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                values = jax.device_get(
                    self._gather_counters(self.states)
                )  # THE one device sync
                residual = check_counter_values(
                    self.name, self._counter_labels, values
                )
                # pathological pending beyond the device drain bound:
                # finish with host-looped flushes before committing
                for _ in range(64):
                    if not residual:
                        break
                    self.states, _ = self.sharded.flush(self.states, sealed)
                    residual = check_counter_values(
                        self.name, self._counter_labels,
                        jax.device_get(self._gather_counters(self.states)),
                    )
                self._ckpts_since_maintain = 0
            self._ckpts_since_snapshot += 1
            if self._ckpts_since_snapshot >= self.snapshot_interval:
                self._ckpts_since_snapshot = 0
                self._deliver_sinks(sealed)
                snap_states = _snapshot_copy(self.states)
                self._mem_snapshot = (
                    sealed, snap_states, {"offset": self.reader.offset}
                )
                self.committed_epoch = sealed
                if self.checkpoint_store is not None:
                    self.checkpoint_store.save(
                        self.name, sealed, jax.device_get(snap_states),
                        {"offset": self.reader.offset},
                    )
        self.epoch = self.epoch.bump()

    def recover(self) -> None:
        if self.checkpoint_store is not None:
            loaded = self.checkpoint_store.load(self.name)
            if loaded is not None:
                epoch, states, src = loaded
                # an online rescale may have committed a DIFFERENT
                # parallelism than the DDL replanned: rebuild the mesh
                # to the checkpoint's shard dim (state is authoritative
                # — silently truncating shards would drop groups)
                n_ckpt = jax.tree.leaves(states)[0].shape[0]
                if n_ckpt != self.sharded.n_shards:
                    if n_ckpt > len(jax.devices()):
                        raise RuntimeError(
                            f"checkpoint has {n_ckpt} shards but only "
                            f"{len(jax.devices())} devices are visible"
                        )
                    old = self.sharded
                    self.sharded = ShardedJob(
                        make_mesh(n_ckpt),
                        source_fn=old.source_fn,
                        chunk_capacity=old.cap,
                        local_executors=list(
                            old.local_frag.executors
                            if old.local_frag else []
                        ),
                        exchange_key_fn=old.exchange_key_fn,
                        keyed_executors=list(old.keyed_frag.executors),
                    )
                sharding = jax.NamedSharding(
                    self.sharded.mesh, P(self.sharded.AXIS)
                )
                self.states = jax.device_put(states, sharding)
                self.committed_epoch = epoch
                from risingwave_tpu.stream.runtime import restore_source
                restore_source(self.reader, src)
                return
        if self._mem_snapshot is not None:
            import jax.numpy as _jnp
            epoch, states, src = self._mem_snapshot
            self.states = jax.tree.map(_jnp.copy, states)
            self.committed_epoch = epoch
            from risingwave_tpu.stream.runtime import restore_source
            restore_source(self.reader, src)
            return
        # nothing committed yet: reset to initial state (mirrors
        # StreamingJob.recover)
        self.states = self.sharded.init_states()
        if hasattr(self.reader, "offset"):
            self.reader.offset = 0

    def _deliver_sinks(self, sealed: int) -> None:
        """Per-shard sink cursors, merged host-side at the snapshot
        barrier (ref sink.rs delivery; cross-shard row order is
        unspecified, matching the reference's per-parallelism sinks).
        The cursors live in the sharded state tree and share the
        checkpoint cadence, but delivery runs BEFORE the durable save:
        a crash between the two rewinds the cursors and re-delivers the
        epoch's rows — at-least-once, like the linear runtime.
        Downstream readers get exactly-once by honoring the per-epoch
        commit marker (the closed-epoch reader protocol, sinks.py):
        rows of an epoch delivered twice carry the same epoch tag, and
        only one commit marker is ever emitted per epoch."""
        states = list(self.states)
        for i, ex in enumerate(self.sharded.executors):
            if not hasattr(ex, "deliver"):
                continue
            host_shards = []
            for s in range(self.sharded.n_shards):
                st = jax.tree.map(lambda x: x[s], states[i])
                # every shard's rows first; ONE commit marker per epoch
                # (the closed-epoch reader protocol, sinks.py)
                host_shards.append(ex.deliver(st, sealed, commit=False))
            ex.sink.commit(sealed)
            states[i] = jax.device_put(
                jax.tree.map(lambda *xs: jnp.stack(xs), *host_shards),
                jax.NamedSharding(self.sharded.mesh,
                                  P(self.sharded.AXIS)),
            )
        self.states = tuple(states)

    # -- online rescale --------------------------------------------------
    def rescale(self, new_n: int) -> None:
        """Re-parallelize at a barrier: N → new_n shards.

        Ref: ``ScaleController`` reschedules by reassigning vnode
        ownership at a barrier and letting state follow vnodes through
        shared storage (src/meta/src/stream/scale.rs:224,336).  Here
        state is device-resident, so it MOVES: the keyed aggregation's
        live groups are extracted as input-schema rows, re-routed by
        the same vnode map onto the new mesh, and re-applied; every
        downstream state (TopN bands, the MV) rebuilds from the agg's
        first post-rescale flush, which re-emits all groups against
        fresh prev-state.  Watermarks carry over conservatively (the
        old global min seeds every new shard)."""
        from risingwave_tpu.parallel.exchange import (
            compute_vnodes, shard_of_vnode,
        )
        from risingwave_tpu.stream.hash_agg import (
            HashAggExecutor as _A,
        )
        from risingwave_tpu.stream.watermark import (
            WatermarkFilterExecutor as _W,
        )

        old = self.sharded
        if new_n == old.n_shards:
            return
        keyed = old.keyed_frag.executors
        if not (keyed and isinstance(keyed[0], _A)
                and keyed[0].reconstructible_from_rows()):
            raise ValueError(
                "online rescale needs a two-phase keyed aggregation "
                "(partial -> exchange -> global); this job's keyed "
                "stage cannot be re-keyed (minput/distinct state or a "
                "non-agg head): next round"
            )
        if any(hasattr(ex, "deliver") for ex in keyed):
            # downstream rebuild re-emits every group — a sink would
            # re-deliver them as duplicates
            raise ValueError("online rescale of sink jobs: next round")
        agg = keyed[0]
        # 1. seal in-flight state at a barrier
        sealed = self.epoch.curr.value
        self.states, _ = old.flush(self.states, sealed)
        host = jax.device_get(self.states)
        n_local = len(old.local_frag.executors) if old.local_frag else 0

        # 2. extract live groups per OLD shard + the global watermark
        chunks = []
        for s in range(old.n_shards):
            st = jax.tree.map(lambda x: x[s], host)
            chunks.append(agg.extract_chunk(st[n_local]))
        wm_mins: dict[int, int] = {}
        for i, ex in enumerate(
            old.local_frag.executors if old.local_frag else []
        ):
            if isinstance(ex, _W):
                wm_mins[i] = min(
                    int(host[i].max_ts[s]) for s in range(old.n_shards)
                )

        # 3. fresh job on the new mesh (same executor descriptors)
        new = ShardedJob(
            make_mesh(new_n),
            source_fn=old.source_fn,
            chunk_capacity=old.cap,
            local_executors=list(
                old.local_frag.executors if old.local_frag else []
            ),
            exchange_key_fn=old.exchange_key_fn,
            keyed_executors=list(keyed),
        )
        states = jax.device_get(new.init_states())

        # 4. route extracted rows by the SAME vnode map onto new shards
        import numpy as np

        @jax.jit
        def dest_of(chunk):
            keys = old.exchange_key_fn(chunk)
            return shard_of_vnode(compute_vnodes(keys), new_n)

        @jax.jit
        def apply_keyed(keyed_states, chunk):
            out, _ = new.keyed_frag._step_impl(keyed_states, chunk)
            return out

        per_shard = [jax.tree.map(lambda x: x[t], states)
                     for t in range(new_n)]
        for chunk in chunks:
            chunk = jax.tree.map(jnp.asarray, chunk)
            dests = np.asarray(dest_of(chunk))
            for t in range(new_n):
                keep = jnp.asarray((dests == t)) & chunk.valid
                if not bool(np.asarray(keep).any()):
                    continue
                sub = chunk.mask(keep)
                ks = tuple(per_shard[t][n_local:])
                ks = apply_keyed(ks, sub)
                per_shard[t] = tuple(per_shard[t][:n_local]) + tuple(ks)
        # watermark seeds
        for i, wm in wm_mins.items():
            for t in range(new_n):
                lst = list(per_shard[t])
                lst[i] = lst[i]._replace(
                    max_ts=jnp.asarray(wm, jnp.int64)
                )
                per_shard[t] = tuple(lst)

        restacked = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_shard
        )
        sharding = jax.NamedSharding(new.mesh, P(new.AXIS))
        self.sharded = new
        self.states = jax.device_put(restacked, sharding)
        # 5. first flush re-emits every group into the fresh downstream
        # states (TopN bands, MV) before anything is served
        self.states, _ = self.sharded.flush(self.states, sealed)
        self._mem_snapshot = None  # old-shape snapshots are invalid

    # serving: per-shard MV partitions merged host-side
    def mv_rows(self, mv_executor, state_index: int):
        host = jax.device_get(self.states[state_index])  # one transfer
        rows = []
        for shard in range(self.sharded.n_shards):
            st = jax.tree.map(lambda x: x[shard], host)
            rows.extend(mv_executor.to_host(st))
        return rows
