"""Host-resident overflow tier for aggregation state (spill-to-host).

Reference counterpart: state beyond memory is the NORM in the
reference — every stateful operator is backed by an unbounded disk
store behind an in-memory cache (``state_table.rs:187``,
``managed_lru.rs``).  A fixed device hash table cannot grow, so rows
whose group cannot claim a slot divert to a device-side ring
(hash_agg spill_ring) and drain — at snapshot barriers — into this
tier: the SAME HashAggExecutor compiled for the host CPU device with a
much larger table.  Its emissions inject into the dataflow right after
the device aggregation, so downstream (projection, MV) sees one merged
changelog.

Ownership is structural, not tracked: a group lives in the tier iff
its first row overflowed, and the device table only frees slots via
watermark cleaning — which the planner excludes from spill-enabled
plans (windowed aggs keep overflow-as-error; their state is bounded by
cleaning).  A device-resident group never overflows (probes find it),
so no group is ever split across tiers.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


class AggSpillTier:
    """CPU twin of a device HashAggExecutor, fed by its spill ring."""

    def __init__(self, agg, table_size: int):
        self.cpu = jax.devices("cpu")[0]
        with jax.default_device(self.cpu):
            self.agg = agg.make_spill_tier(table_size)
            self.state = self.agg.init_state()
        self.rows_absorbed = 0

    def process(self, drained_chunk_host, epoch) -> "Any | None":
        """Apply one drained ring chunk + flush; returns the tier's
        changelog chunk (host arrays) or None when nothing changed."""
        with jax.default_device(self.cpu):
            chunk = jax.device_put(drained_chunk_host, self.cpu)
            st, _ = self.agg.apply(self.state, chunk)
            st, out = self.agg.flush(st, epoch)
            self.state = st
        self.rows_absorbed += int(np.asarray(drained_chunk_host.valid).sum())
        return out

    def flush_only(self, epoch):
        """Barrier flush with no new rows (emits nothing when clean)."""
        with jax.default_device(self.cpu):
            st, out = self.agg.flush(self.state, epoch)
            self.state = st
        return out

    # -- checkpoint -----------------------------------------------------
    def state_host(self):
        return jax.device_get(self.state)

    def snapshot(self):
        """Owned host copy (np.array forces a copy — device_get of a
        CPU-backed array may alias the live buffer)."""
        return jax.tree.map(np.array, jax.device_get(self.state))

    def restore(self, host_state) -> None:
        with jax.default_device(self.cpu):
            self.state = jax.device_put(host_state, self.cpu)
        self.rows_absorbed = 1

    def reset(self) -> None:
        """Forget every absorbed group: recovery rewound to an epoch
        at/before which this tier had no checkpoint, so its live state
        is from the FUTURE of the recovered epoch — keeping it would
        double-count the replayed rows."""
        with jax.default_device(self.cpu):
            self.state = self.agg.init_state()
        self.rows_absorbed = 0
