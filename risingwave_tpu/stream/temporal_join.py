"""Temporal join: stream probes a table's CURRENT state at process
time.

Reference counterpart: ``src/stream/src/executor/temporal_join.rs`` —
``stream JOIN t FOR SYSTEM_TIME AS OF PROCTIME() ON key = t.pk``: the
probe side looks up the build table as of NOW; later build-side changes
do NOT retract earlier outputs (process-time, not event-time,
semantics), so the output is append-only whenever the probe side is.

TPU-first design: the build side IS a materialize table (pk-keyed
upsert, the same MvState machinery the MV terminal uses); a probe chunk
becomes one vectorized lookup + gather — no per-row cache walk, no
degree bookkeeping (nothing ever retracts).  The planner requires the
join key to cover the build side's primary key, so each probe row
matches at most one build row and the output chunk is probe-sized
(static shapes, no drain loop) — the shape the reference's planner
also requires for its index-lookup temporal join.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from risingwave_tpu.common.chunk import Chunk, NCol, split_col
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.stream.materialize import MaterializeExecutor, MvState


class TjState(NamedTuple):
    right: MvState
    overflow: jnp.ndarray
    inconsistency: jnp.ndarray


class TemporalJoinExecutor:
    """Two-input executor: ``apply(state, chunk, side)`` like the hash
    join; 'right' upserts the build table, 'left' probes it."""

    def __init__(self, left_schema: Schema, right_schema: Schema,
                 left_keys: Sequence, right_pk: Sequence[int],
                 table_size: int = 1 << 12,
                 join_type: str = "inner"):
        if join_type not in ("inner", "left_outer"):
            raise ValueError(
                "temporal join supports inner/left_outer"
            )
        self.left_schema = left_schema
        self.left_keys = tuple(left_keys)
        self.join_type = join_type
        self.right_mat = MaterializeExecutor(
            right_schema, tuple(right_pk), table_size
        )
        pad = join_type == "left_outer"
        fields = list(left_schema) + [
            f.with_nullable() if pad and not f.nullable else f
            for f in right_schema
        ]
        self._out_schema = Schema(tuple(fields))

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def init_state(self) -> TjState:
        return TjState(
            self.right_mat.init_state(),
            jnp.zeros((), jnp.int64),
            jnp.zeros((), jnp.int64),
        )

    def maybe_rehash(self, state: TjState) -> TjState:
        return TjState(
            self.right_mat.maybe_rehash(state.right),
            state.overflow, state.inconsistency,
        )

    def apply(self, state: TjState, chunk: Chunk, side: str):
        if side == "right":
            right, _ = self.right_mat.apply(state.right, chunk)
            return TjState(
                right, right.overflow, state.inconsistency
            ), None
        # probe: one vectorized pk lookup + gather of the build row
        key_cols = [k.eval(chunk) for k in self.left_keys]
        # NULL keys match nothing (SQL equality)
        valid = chunk.valid
        payloads = []
        for c in key_cols:
            d, nmask = split_col(c)
            payloads.append(d)
            if nmask is not None:
                valid = valid & ~nmask
        slots, found, n_over = state.right.table.lookup_counted(
            payloads, valid
        )
        size = self.right_mat.table_size
        safe = jnp.minimum(slots, size - 1)
        found = found & valid
        out_cols = list(chunk.columns)
        for store in state.right.values:
            gathered = jax.tree.map(lambda x: x[safe], store)
            if self.join_type == "left_outer":
                d, nmask = split_col(gathered)
                miss = ~found
                nmask = miss if nmask is None else (nmask | miss)
                gathered = NCol(d, nmask)
            out_cols.append(gathered)
        out_valid = chunk.valid & found if self.join_type == "inner" \
            else chunk.valid
        out = Chunk(tuple(out_cols), chunk.ops, out_valid,
                    self._out_schema)
        # probe-bound overflow would silently drop matches — count it
        # so the maintenance barrier raises loudly
        return TjState(
            state.right, state.overflow + n_over, state.inconsistency
        ), out

    def __repr__(self):
        return (f"TemporalJoin({self.join_type}, "
                f"keys={len(self.left_keys)})")
