"""ShadowSnapshot: incremental device-side in-memory snapshots.

Reference counterpart: Hummock never re-uploads a full state snapshot
per epoch — ``commit_epoch`` persists only each epoch's dirty deltas
(docs/dev/src/design/checkpoint.md).  The old in-memory snapshot here
(``_snapshot_copy``) was the opposite: a full device tree copy every
snapshot barrier, a periodic multi-second stall that PERF_ATTRIBUTION
round 6 measured at roughly HALF the q8 window.

TPU-first incremental design: the snapshot is a persistent device-side
SHADOW of the state tree plus its block-digest vector.  One jitted
program per state shape, dispatched once per snapshot barrier:

1. digest every live leaf in fixed-size blocks (storage/digest.py —
   the SAME scheme the durable store diffs with, so the digest pass
   runs ONCE and is shared);
2. diff against the shadow's digest vector → per-block dirty mask;
3. copy only the dirty blocks live→shadow, through a budget ladder
   (1/64 → 1/8 → full per leaf, selected on device by ``lax.switch``
   on the dirty count) — gather/scatter traffic is O(dirty blocks),
   never O(state), and the shadow buffers are donated so no new
   allocation happens on the steady path.

The program is dispatched asynchronously — zero synchronous
device→host transfers; the dirty count stays a device scalar until an
observability surface explicitly asks for it.

Invariant: ``self.digests`` always equals the digest of the shadow's
CONTENTS.  The update diffs live digests against shadow digests, so
the shadow self-heals toward whatever the live tree is — recovery may
restore live state older than the shadow (durable rewind) and the next
update still converges, because every differing block is by definition
dirty under the diff.

Programs are cached process-wide by (state signature, block size) —
tests and restarted jobs with identical tree shapes reuse compiles,
like the global ``_snapshot_copy`` jit cache they replace.

Collision caveat: a 64-bit block digest collision would silently skip
a changed block.  The durable delta store has always accepted this
(2^-64-ish per block); the shadow inherits the same odds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.storage.digest import (
    DEFAULT_BLOCK_ELEMS,
    lane_block_count,
    leaf_block_count,
    leaf_digest,
    leaf_digest_lanes,
)

#: leaves at/below this many blocks skip the ladder and copy whole
#: (scalars/counters — a gather program costs more than the copy)
_SMALL_NB = 8

#: compiled (init, update, restore) per (sig, block) — bounded
_PROG_CACHE: dict = {}
_PROG_CACHE_MAX = 16


def _copy_leaf(flat, sh, dirty, nb: int, n: int, block: int):
    """Dirty-budget ladder for one leaf: windowed gather/scatter of K
    whole blocks when K bounds the dirty count, else the next rung,
    else a full leaf copy.  All rungs run on device — no host
    readback.  The windowed ops move contiguous ``block``-element runs
    (near-memcpy per block), not per-element indices."""
    nb_full = n // block
    if nb <= _SMALL_NB or nb_full < 2:
        return flat, jnp.int64(0)
    nd = jnp.sum(dirty)
    # dirty FULL-block ids first, ascending (stable argsort of ~dirty);
    # the ragged tail block is copied unconditionally below
    order = jnp.argsort(jnp.logical_not(dirty[:nb_full]), stable=True)

    gdims = jax.lax.GatherDimensionNumbers(
        offset_dims=(1,), collapsed_slice_dims=(),
        start_index_map=(0,),
    )
    sdims = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,), inserted_window_dims=(),
        scatter_dims_to_operand_dims=(0,),
    )

    def rung(k: int):
        def body(operand):
            flat, sh = operand
            starts = (order[:k] * block).astype(jnp.int32)[:, None]
            vals = jax.lax.gather(
                flat, starts, gdims, slice_sizes=(block,),
                unique_indices=True,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )
            return jax.lax.scatter(
                sh, starts, vals, sdims, unique_indices=True,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )

        return body

    k0 = max(1, nb_full // 64)
    k1 = max(1, nb_full // 8)
    level = (nd > k0).astype(jnp.int32) + (nd > k1).astype(jnp.int32)
    new_sh = jax.lax.switch(
        level,
        [rung(k0), rung(k1), lambda operand: operand[0]],
        (flat, sh),
    )
    tail = n - nb_full * block
    if tail:
        new_sh = jax.lax.dynamic_update_slice(
            new_sh, flat[nb_full * block:], (nb_full * block,)
        )
    return new_sh, nd.astype(jnp.int64)


def _copy_leaf_rows(flat, sh, dirty, rows: int, m: int, block: int):
    """Lane-aware dirty-budget ladder (mesh-stacked leaves): like
    ``_copy_leaf``, but block starts are computed per (lane, block)
    pair — ``start = lane*m + b*block`` — so the windowed gather/
    scatter never crosses a shard row's boundary, and each lane's
    ragged tail copies unconditionally as ONE static slice update
    over the shard axis."""
    nb_row = max(1, -(-m // block))
    nbf = m // block  # full blocks per lane
    if rows * nb_row <= _SMALL_NB or rows * nbf < 2:
        return flat, jnp.int64(0)
    nd = jnp.sum(dirty)
    dirty_full = dirty.reshape(rows, nb_row)[:, :nbf].reshape(-1)
    order = jnp.argsort(jnp.logical_not(dirty_full), stable=True)

    gdims = jax.lax.GatherDimensionNumbers(
        offset_dims=(1,), collapsed_slice_dims=(),
        start_index_map=(0,),
    )
    sdims = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(1,), inserted_window_dims=(),
        scatter_dims_to_operand_dims=(0,),
    )

    def rung(k: int):
        def body(operand):
            flat, sh = operand
            ids = order[:k]
            starts = ((ids // nbf) * m + (ids % nbf) * block) \
                .astype(jnp.int32)[:, None]
            vals = jax.lax.gather(
                flat, starts, gdims, slice_sizes=(block,),
                unique_indices=True,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )
            return jax.lax.scatter(
                sh, starts, vals, sdims, unique_indices=True,
                mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
            )

        return body

    k0 = max(1, rows * nbf // 64)
    k1 = max(1, rows * nbf // 8)
    level = (nd > k0).astype(jnp.int32) + (nd > k1).astype(jnp.int32)
    new_sh = jax.lax.switch(
        level,
        [rung(k0), rung(k1), lambda operand: operand[0]],
        (flat, sh),
    )
    tail = m - nbf * block
    if tail:
        new_sh = new_sh.reshape(rows, m).at[:, nbf * block:].set(
            flat.reshape(rows, m)[:, nbf * block:]
        ).reshape(-1)
    return new_sh, nd.astype(jnp.int64)


def leaf_lanes(shape, shard_rows) -> tuple | None:
    """Lane structure of one leaf under a per-shard digest scheme:
    ``(rows, row_elems)`` when the leaf carries the mesh-stacked
    leading axis, else None (flat digesting)."""
    if not shard_rows or not shape or shape[0] != shard_rows:
        return None
    n = int(np.prod(shape)) if shape else 1
    return (shard_rows, n // shard_rows)


def _build_programs(sig, block: int, digest: bool, shard_rows):
    shapes = [s for _, s in sig]
    lanes = [leaf_lanes(s, shard_rows) for s in shapes]
    nblocks = [
        lane_block_count(s, ln[0], block) if ln
        else leaf_block_count(s, block)
        for s, ln in zip(shapes, lanes)
    ]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    total = sum(nblocks)

    def digest_one(flat, nb, ln):
        return leaf_digest_lanes(flat, ln[0], block) if ln \
            else leaf_digest(flat, nb, block)

    def init(leaves):
        flat = tuple(jnp.copy(jnp.asarray(x).reshape(-1))
                     for x in leaves)
        d = jnp.concatenate([
            digest_one(x, nb, ln)
            for x, nb, ln in zip(flat, nblocks, lanes)
        ]) if digest else jnp.zeros((0,), jnp.uint64)
        return flat, d

    def update(live_leaves, shadow_leaves, old_digests):
        if not digest:
            # store-less mode: no durable delta wants the digest, so
            # the cheapest correct snapshot is a straight copy INTO
            # the donated shadow buffers (no allocation churn — the
            # part of the old full-copy path that actually hurt)
            new_shadow = tuple(
                jnp.copy(jnp.asarray(x).reshape(-1))
                for x in live_leaves
            )
            return (new_shadow, old_digests, jnp.int64(total))
        new_shadow = []
        new_digests = []
        dirty_total = jnp.zeros((), jnp.int64)
        off = 0
        for x, sh, nb, n, ln in zip(live_leaves, shadow_leaves,
                                    nblocks, sizes, lanes):
            flat = jnp.asarray(x).reshape(-1)
            d = digest_one(flat, nb, ln)
            dirty = d != jax.lax.dynamic_slice(
                old_digests, (off,), (nb,)
            )
            off += nb
            if ln:
                new_sh, nd = _copy_leaf_rows(
                    flat, sh, dirty, ln[0], ln[1], block
                )
            else:
                new_sh, nd = _copy_leaf(flat, sh, dirty, nb, n, block)
            new_shadow.append(new_sh)
            new_digests.append(d)
            dirty_total = dirty_total + nd
        return (tuple(new_shadow), jnp.concatenate(new_digests),
                dirty_total)

    def restore(shadow_leaves):
        return tuple(
            jnp.copy(f).reshape(s)
            for f, s in zip(shadow_leaves, shapes)
        )

    return (
        jax.jit(init),
        jax.jit(update, donate_argnums=(1, 2)),
        jax.jit(restore),
    )


def _programs(sig, block: int, digest: bool, shard_rows):
    key = (sig, block, digest, shard_rows)
    hit = _PROG_CACHE.get(key)
    if hit is None:
        if len(_PROG_CACHE) >= _PROG_CACHE_MAX:
            _PROG_CACHE.pop(next(iter(_PROG_CACHE)))
        hit = _build_programs(sig, block, digest, shard_rows)
        _PROG_CACHE[key] = hit
    return hit


class ShadowSnapshot:
    """A device-resident shadow of one job's state tree.

    ``digest=True`` (the durable mode): block-digest diff + dirty-run
    scatter; the digest vector feeds the checkpoint store's delta
    upload.  ``digest=False`` (store-less jobs): nothing consumes the
    digest, so the update is a straight copy into the persistent
    (donated) shadow buffers — no digest pass, no allocation churn.

    ``shard_rows=N`` (mesh-stacked trees): every leaf whose leading
    axis is the shard axis digests in N per-shard LANES — the block
    grid restarts at each shard row, so no digest block (and no
    dirty-run copy) ever spans two shards.  ``lanes`` records the
    per-leaf structure for the checkpoint store's delta extraction."""

    def __init__(self, states, block_elems: int = DEFAULT_BLOCK_ELEMS,
                 digest: bool = True, shard_rows: int | None = None):
        leaves, self.treedef = jax.tree.flatten(states)
        self.block = block_elems
        self.digest_mode = digest
        self.shard_rows = shard_rows
        self.shapes = [np.shape(x) for x in leaves]
        self.sig = tuple(
            (str(x.dtype), np.shape(x)) for x in leaves
        )
        #: per-leaf (rows, row_elems) lane structure, None = flat —
        #: shipped with every UploadTask so the store's dirty-run
        #: extraction uses the same block grid as the digest
        self.lanes = [leaf_lanes(s, shard_rows) for s in self.shapes]
        self.nblocks = [
            lane_block_count(s, ln[0], block_elems) if ln
            else leaf_block_count(s, block_elems)
            for s, ln in zip(self.shapes, self.lanes)
        ]
        self.total_blocks = int(sum(self.nblocks))
        self._init_prog, self._update_prog, self._restore_prog = \
            _programs(self.sig, block_elems, digest, shard_rows)
        #: flat device copies of every leaf (the shadow contents)
        self.leaves, self.digests = self._init_prog(tuple(leaves))
        #: dirty blocks of the LAST update (device scalar; read only by
        #: observability surfaces — never on the barrier path)
        self.dirty_blocks = jnp.zeros((), jnp.int64)
        #: epoch the shadow currently reflects (host bookkeeping)
        self.epoch = 0
        # warm the update program NOW (a clean no-op diff): the first
        # shadow build lands in a warmup/compile window — the second
        # snapshot must not pay the XLA compile inside the measured
        # steady state
        self.update(states)

    # ------------------------------------------------------------------
    def matches(self, states) -> bool:
        leaves = jax.tree.leaves(states)
        if len(leaves) != len(self.sig):
            return False
        return all(
            (str(x.dtype), np.shape(x)) == s
            for x, s in zip(leaves, self.sig)
        )

    def update(self, states, epoch: int = 0):
        """One async dispatch: diff live vs shadow, copy dirty blocks
        into the (donated) shadow, refresh the digest vector.  Returns
        the new digest vector (device array) for the durable store."""
        leaves = jax.tree.leaves(states)
        self.leaves, self.digests, self.dirty_blocks = self._update_prog(
            tuple(leaves), self.leaves, self.digests
        )
        self.epoch = epoch
        return self.digests

    # ------------------------------------------------------------------
    def restore(self):
        """A fresh device tree equal to the shadow contents (one
        dispatch).  The copies are independent buffers, safe to donate
        into step programs without touching the shadow."""
        leaves = self._restore_prog(self.leaves)
        return jax.tree.unflatten(self.treedef, list(leaves))

    # ------------------------------------------------------------------
    def dirty_ratio(self) -> float:
        """Dirty fraction of the LAST update (host readback — for
        metrics/ctl surfaces only, never the barrier path)."""
        return float(np.asarray(self.dirty_blocks)) / max(
            1, self.total_blocks
        )
