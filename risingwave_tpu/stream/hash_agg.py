"""Hash aggregation executor (device-resident groups, emit-on-barrier).

Reference counterpart: ``HashAggExecutor`` (src/stream/src/executor/
aggregate/hash_agg.rs:64) — LRU AggGroup cache keyed by HashKey, dirty
set, ``apply_chunk`` at :332, flush at :412.

TPU-first design
----------------
Groups live in a dense ``HashTable`` + per-aggregate state arrays in
HBM.  A chunk's worth of updates for thousands of groups lands as ONE
vectorized lookup_or_insert + one scatter per primitive state (vs the
reference's per-group HashMap walk):

    slots = table.lookup_or_insert(keys)
    state = state.at[slots].add(signs * value)     # retractable adds
    state = state.at[slots].min/max(value)         # append-only monoids

Changelog emission happens at barrier flush, exactly like the
reference's emit-on-barrier: dirty slots are compacted with a
fixed-size ``nonzero`` and emitted as an interleaved U-/U+ chunk, with
previous outputs reconstructed from a `prev` copy of the state arrays.
Retraction semantics (Insert if group appears, Update pair if it
changes, Delete if its row count reaches zero) mirror
``AggGroup::build_change``.

min/max over APPEND-ONLY inputs are monotone monoids (one scatter-min/
max per chunk).  Over RETRACTABLE inputs (``retractable_input=True``)
they switch to a **materialized-input state** — the reference's
``minput.rs`` (src/stream/src/executor/aggregate/minput.rs) re-imagined
for slot-aligned HBM: each such aggregate owns a ``[table_size,
minput_bucket_cap]`` value multi-map aligned to the group table's
slots (no second key table).  Inserts claim free bucket positions by
rank, deletes clear value-equal entries by rank, and the aggregate's
``[size]`` prim array becomes a flush-time CACHE recomputed from the
bucket for dirty groups — so the prev-snapshot / U-pair machinery is
untouched.  Bucket overflow is counted loudly (raise at maintenance),
the analog of the reference's bounded cache + state-table fallback.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import (
    Chunk,
    NCol,
    OP_DELETE,
    OP_INSERT,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
    StrCol,
    conform_col,
    split_col,
)
from risingwave_tpu.common.compact import (
    accel_tuned,
    mask_indices,
    segment_start_positions,
    segment_starts,
    segmented_minmax_at_ends,
    segmented_sum,
)
from risingwave_tpu.common.hash import hash64_columns
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.node import Expr, InputRef
from risingwave_tpu.expr.agg import AggCall
from risingwave_tpu.state.hash_table import HashTable, gather_key, keys_equal
from risingwave_tpu.stream.executor import Executor


class AggState(NamedTuple):
    table: HashTable
    #: flattened per-primitive state arrays, each [size]
    prims: tuple
    row_count: jnp.ndarray      # int64 [size]
    dirty: jnp.ndarray          # bool [size]
    prev_prims: tuple           # snapshot at last flush
    prev_row_count: jnp.ndarray
    emitted: jnp.ndarray        # bool [size] — group present downstream
    overflow: jnp.ndarray       # int64 scalar — rows lost to full table
    #: deletes that hit a non-retractable (min/max) state — the
    #: consistency_error! analog (ref src/stream/src/lib.rs:93); the
    #: runtime surfaces this at barrier time
    inconsistency: jnp.ndarray  # int64 scalar
    #: latest watermark received (EOWC emission; INT64_MIN = none)
    wm: jnp.ndarray             # int64 scalar
    #: materialized-input values per retractable min/max agg (ref
    #: minput.rs): ([size, B] values, [size, B] occupied) pairs,
    #: slot-aligned with ``table``
    minput_vals: tuple = ()
    minput_occ: tuple = ()
    #: per-DISTINCT-call dedup state (ref distinct.rs dedup tables):
    #: a hash table keyed (group keys..., arg) and an int64 [size]
    #: row-count per key — 0↔nonzero transitions drive the agg update
    distinct_tables: tuple = ()
    distinct_counts: tuple = ()
    #: spill ring: INPUT rows whose group could not claim a device slot
    #: divert here instead of being dropped; the runtime drains the
    #: ring at snapshot barriers into the host-resident overflow tier
    #: (stream/spill.py — the state_table.rs "state beyond memory is
    #: the norm" analog)
    spill_rows: tuple = ()
    spill_ops: jnp.ndarray = ()
    spill_count: jnp.ndarray = ()


def _empty_input_col(f: Field, n: int):
    """Zeroed [n] storage for one input-schema column (NCol-aware)."""
    if f.data_type.is_string:
        base = StrCol(
            jnp.zeros((n, f.str_width), jnp.uint8),
            jnp.zeros((n,), jnp.int32),
        )
    else:
        base = jnp.zeros((n,), f.data_type.physical_dtype)
    if f.nullable:
        return NCol(base, jnp.zeros((n,), jnp.bool_))
    return base


def _scatter_input_col(store, pos, col):
    """Scatter a chunk column into [R] storage (NCol/StrCol-aware)."""
    if isinstance(store, NCol):
        return NCol(
            _scatter_input_col(store.data, pos,
                               col.data if isinstance(col, NCol)
                               else col),
            store.null.at[pos].set(
                col.null if isinstance(col, NCol)
                else jnp.zeros(pos.shape, jnp.bool_),
                mode="drop",
            ),
        )
    if isinstance(store, StrCol):
        return StrCol(
            store.data.at[pos].set(col.data, mode="drop"),
            store.lens.at[pos].set(col.lens, mode="drop"),
        )
    return store.at[pos].set(col, mode="drop")


def _interleave(old, new):
    """[n] + [n] -> [2n] with old at even, new at odd positions."""
    if isinstance(old, NCol):
        return NCol(
            _interleave(old.data, new.data), _interleave(old.null, new.null)
        )
    if isinstance(old, StrCol):
        return StrCol(
            _interleave(old.data, new.data), _interleave(old.lens, new.lens)
        )
    return jnp.stack([old, new], axis=1).reshape(
        (old.shape[0] * 2,) + old.shape[1:]
    )


class HashAggExecutor(Executor):
    """GROUP BY aggregation over a device hash table."""

    emits_on_apply = False
    emits_on_flush = True

    def __init__(
        self,
        in_schema: Schema,
        group_by: Sequence[tuple[str, Expr]],
        aggs: Sequence[AggCall],
        table_size: int = 1 << 16,
        emit_capacity: int = 4096,
        watermark_group_idx: int | None = None,
        watermark_lag: int = 0,
        watermark_src_col: int | None = None,
        emit_on_window_close: bool = False,
        retractable_input: bool = False,
        minput_bucket_cap: int = 64,
        distinct_table_size: int | None = None,
        spill_ring: int = 0,
    ):
        super().__init__(in_schema)
        #: overflow-row ring capacity (0 = overflow is a hard error);
        #: the planner enables this for non-windowed aggregations whose
        #: key cardinality is unbounded
        self.spill_ring = spill_ring
        self._ctor_kwargs = dict(
            in_schema=in_schema, group_by=tuple(group_by),
            aggs=tuple(aggs), emit_capacity=emit_capacity,
            watermark_group_idx=watermark_group_idx,
            watermark_lag=watermark_lag,
            watermark_src_col=watermark_src_col,
            emit_on_window_close=emit_on_window_close,
            retractable_input=retractable_input,
            minput_bucket_cap=minput_bucket_cap,
        )
        #: EOWC (ref emit_on_window_close plan property): flush emits
        #: only CLOSED windows as final append-only rows and evicts them
        self.emit_on_window_close = emit_on_window_close
        if emit_on_window_close and watermark_group_idx is None:
            raise ValueError(
                "EMIT ON WINDOW CLOSE needs a watermarked window group key"
            )
        self.group_by = tuple(group_by)
        self.aggs = tuple(aggs)
        #: when set, watermarks clean groups whose key[idx] < wm - lag
        #: (lag = window size for tumble windows: a window closes when
        #: the watermark passes window_start + size)
        self.watermark_group_idx = watermark_group_idx
        self.watermark_lag = watermark_lag
        #: only react to Watermark messages with this source col_idx
        #: (None = any — single-watermark fragments)
        self.watermark_src_col = watermark_src_col
        self.table_size = table_size
        self.emit_capacity = emit_capacity
        key_fields = tuple(
            Field(name, e.return_field(in_schema).data_type,
                  str_width=e.return_field(in_schema).str_width,
                  decimal_scale=e.return_field(in_schema).decimal_scale,
                  nullable=e.return_field(in_schema).nullable)
            for name, e in self.group_by
        )
        agg_fields = tuple(a.out_field(in_schema) for a in self.aggs)
        self._out_schema = Schema(key_fields + agg_fields)
        # primitive-state layout: per agg, its PrimStates flattened
        self._prim_specs = []  # (agg_idx, PrimState)
        for ai, a in enumerate(self.aggs):
            for ps in a.spec().states:
                self._prim_specs.append((ai, ps))
        #: retractable min/max via materialized-input buckets (ref
        #: minput.rs); their prim arrays become flush-time caches
        self.minput_bucket_cap = minput_bucket_cap
        self._minput_aggs: list[int] = [
            ai for ai, a in enumerate(self.aggs)
            if retractable_input and a.kind in ("min", "max")
        ]
        #: prim indices whose arrays are minput caches (no apply scatter)
        self._cache_prims = {
            pi for pi, (ai, _) in enumerate(self._prim_specs)
            if ai in self._minput_aggs
        }
        #: DISTINCT calls with their own counted dedup tables (ref
        #: distinct.rs); min/max are distinct-insensitive and handled
        #: as plain calls
        self.distinct_table_size = distinct_table_size or table_size
        self._distinct_aggs: list[int] = [
            ai for ai, a in enumerate(self.aggs)
            if a.distinct and a.kind not in ("min", "max")
        ]
        # hidden non-null-count prims: an aggregate over a NULLABLE
        # argument yields SQL NULL when every argument row in the group
        # is NULL (ref AggregateFunction semantics); count() needs no
        # helper (its own state IS the non-null count)
        from risingwave_tpu.expr.agg import _ADD_COUNT
        self._nn_prim: dict[int, int] = {}
        for ai, a in enumerate(self.aggs):
            if a.arg is None or a.kind in ("count", "count_star"):
                continue
            # a FILTER clause makes any aggregate's input set possibly
            # empty even over a NOT NULL argument → same NULL-output
            # tracking as a nullable argument
            if a.arg.return_field(in_schema).nullable \
                    or a.filter is not None:
                self._nn_prim[ai] = len(self._prim_specs)
                self._prim_specs.append((ai, _ADD_COUNT))

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    # ------------------------------------------------------------------
    def _key_protos(self):
        """Zero-row prototypes of the key columns for table creation.

        Nullable group keys store as NCol (payload + null plane): the
        table's grouping equality treats NULL == NULL, so NULLs form
        one group like the reference's GROUP BY."""
        protos = []
        for _, e in self.group_by:
            f = e.return_field(self.in_schema)
            if f.data_type.is_string:
                p = StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                )
            else:
                p = jnp.zeros((1,), f.data_type.physical_dtype)
            if f.nullable:
                p = NCol(p, jnp.zeros((1,), jnp.bool_))
            protos.append(p)
        return protos

    def _input_dtype(self, agg_idx: int):
        a = self.aggs[agg_idx]
        if a.arg is None:
            return jnp.int64
        return a.arg.return_field(self.in_schema).data_type.physical_dtype

    def _distinct_protos(self, agg_idx: int) -> list:
        """Key prototypes of a distinct call's dedup table:
        (group keys..., arg)."""
        f = self.aggs[agg_idx].arg.return_field(self.in_schema)
        if f.data_type.is_string:
            p = StrCol(
                jnp.zeros((1, f.str_width), jnp.uint8),
                jnp.zeros((1,), jnp.int32),
            )
        else:
            p = jnp.zeros((1,), f.data_type.physical_dtype)
        if f.nullable:
            p = NCol(p, jnp.zeros((1,), jnp.bool_))
        return self._key_protos() + [p]

    def init_state(self) -> AggState:
        size = self.table_size
        table = HashTable.create(self._key_protos(), size)
        def make_prims():
            out = []
            for agg_idx, ps in self._prim_specs:
                in_dt = self._input_dtype(agg_idx)
                st_dt = ps.dtype(in_dt)
                out.append(jnp.full((size,), ps.init(st_dt), st_dt))
            return tuple(out)

        B = self.minput_bucket_cap
        return AggState(
            table=table,
            # prev_prims must be INDEPENDENT buffers (donation forbids
            # the same buffer appearing twice in a donated pytree)
            prims=make_prims(),
            row_count=jnp.zeros((size,), jnp.int64),
            dirty=jnp.zeros((size,), jnp.bool_),
            prev_prims=make_prims(),
            prev_row_count=jnp.zeros((size,), jnp.int64),
            emitted=jnp.zeros((size,), jnp.bool_),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
            wm=jnp.asarray(np.iinfo(np.int64).min, jnp.int64),
            minput_vals=tuple(
                jnp.zeros((size, B), self._input_dtype(ai))
                for ai in self._minput_aggs
            ),
            minput_occ=tuple(
                jnp.zeros((size, B), jnp.bool_)
                for ai in self._minput_aggs
            ),
            distinct_tables=tuple(
                HashTable.create(self._distinct_protos(ai),
                                 self.distinct_table_size)
                for ai in self._distinct_aggs
            ),
            distinct_counts=tuple(
                jnp.zeros((self.distinct_table_size,), jnp.int64)
                for _ in self._distinct_aggs
            ),
            spill_rows=tuple(
                _empty_input_col(f, self.spill_ring)
                for f in self.in_schema
            ) if self.spill_ring else (),
            spill_ops=jnp.zeros((self.spill_ring,), jnp.int8)
            if self.spill_ring else (),
            spill_count=jnp.zeros((), jnp.int32)
            if self.spill_ring else (),
        )

    # ------------------------------------------------------------------
    def apply(self, state: AggState, chunk: Chunk):
        """Apply one chunk of updates; backend-adaptive strategy.

        TPU: chunk-local pre-aggregation, then one sparse scatter per
        prim.  TPU scatters serialize over LIVE updates (~0.25µs/row),
        so a full-chunk scatter costs milliseconds while sort +
        segmented scan cost ~20µs.  The chunk is sorted by key hash,
        adjacent equal keys form segments, each primitive contribution
        is segment-reduced, and only each segment's END row (its
        "representative") probes the table and scatters — O(distinct
        keys) serialized work instead of O(chunk).

        CPU: scatters are cheap (~0.3ms for a full chunk into 2^18)
        while each 8k-row sort costs ~1.6ms, so the chunk probes and
        scatters per-row with no sort at all (the round-1 shape; the
        round-2 always-sort version was the "4x q7 regression")."""
        signs = chunk.signs()
        valid = chunk.valid
        cap = valid.shape[0]
        key_cols = [
            conform_col(e.eval(chunk),
                        e.return_field(self.in_schema).nullable, cap)
            for _, e in self.group_by
        ]

        h = hash64_columns(key_cols)
        preagg = accel_tuned()
        if preagg:
            # invalid rows sort to the very end under the all-ones
            # sentinel (hash64_columns never returns ~0, so no valid
            # row lands there)
            sort_key = jnp.where(valid, h, ~jnp.uint64(0))
            s_h, perm = jax.lax.sort_key_val(
                sort_key, jnp.arange(cap, dtype=jnp.int32)
            )
            s_valid = valid[perm]
            s_signs = signs[perm]
            s_keys = [gather_key(c, perm) for c in key_cols]
            # segment boundary: hash differs OR any key column differs
            # (hash collisions between distinct keys stay distinct)
            neq = s_h[1:] != s_h[:-1]
            for c in s_keys:
                neq = neq | ~keys_equal(
                    gather_key(c, jnp.arange(1, cap)),
                    gather_key(c, jnp.arange(0, cap - 1)))
            starts = segment_starts(neq)
            ends = jnp.concatenate([neq, jnp.ones((1,), jnp.bool_)])
            rep = ends & s_valid
            start_pos = segment_start_positions(starts)
            # unique, monotone segment id (hash-collision-split
            # segments of equal s_h must not merge in the min/max
            # secondary sort)
            seg_id = jnp.cumsum(starts.astype(jnp.int32))
            seg_rows = segmented_sum(s_valid.astype(jnp.int64), start_pos)

            table, slots, inserted, overflow = state.table.lookup_or_insert(
                s_keys, rep, hashes=s_h
            )
            # overflowed representatives drop their whole segment —
            # count rows (or divert them to the spill ring)
            n_over = jnp.sum(jnp.where(rep & overflow, seg_rows, 0))
            if self.spill_ring:
                seg_over = jnp.zeros((cap + 1,), jnp.bool_).at[
                    jnp.where(rep, seg_id, 0)
                ].set(rep & overflow, mode="drop")
                sorted_spill = s_valid & seg_over[seg_id]
                spill_mask = jnp.zeros((cap,), jnp.bool_).at[perm].set(
                    sorted_spill
                )
        else:
            perm = None
            s_signs = signs
            table, slots, inserted, overflow = state.table.lookup_or_insert(
                key_cols, valid, hashes=h
            )
            n_over = jnp.sum((overflow & valid).astype(jnp.int64))
            if self.spill_ring:
                spill_mask = valid & overflow
        spill_rows = state.spill_rows
        spill_ops = state.spill_ops
        spill_count = state.spill_count
        if self.spill_ring:
            # divert overflow rows into the ring (original chunk order);
            # only rows the ring itself cannot hold stay in n_over.
            # The capture runs under lax.cond so the CLEAN path (no
            # overflow — the steady state) skips the ring scatters.
            R = self.spill_ring

            def capture(args):
                spill_rows, spill_ops, spill_count = args
                rank = jnp.cumsum(spill_mask.astype(jnp.int32)) - \
                    spill_mask.astype(jnp.int32)
                pos = spill_count + rank
                ok = spill_mask & (pos < R)
                tgt = jnp.where(ok, pos, jnp.int32(R))
                rows = tuple(
                    _scatter_input_col(store, tgt, col)
                    for store, col in zip(spill_rows, chunk.columns)
                )
                ops2 = spill_ops.at[tgt].set(chunk.ops, mode="drop")
                cnt = jnp.minimum(
                    spill_count + jnp.sum(spill_mask.astype(jnp.int32)),
                    jnp.int32(R),
                ).astype(jnp.int32)
                dropped = jnp.sum((spill_mask & ~ok).astype(jnp.int64))
                return rows, ops2, cnt, dropped

            def skip(args):
                rows, ops2, cnt = args
                return rows, ops2, cnt, jnp.zeros((), jnp.int64)

            spill_rows, spill_ops, spill_count, n_over = jax.lax.cond(
                jnp.any(spill_mask), capture, skip,
                (spill_rows, spill_ops, spill_count),
            )
        # freshly claimed slots may be reclaimed after state cleaning —
        # reset their (stale) primitive state before applying updates
        ins_pos = jnp.where(inserted, slots, jnp.int32(self.table_size))

        prims = list(state.prims)
        arg_cache: dict[int, jnp.ndarray] = {}
        filt_cache: dict[int, jnp.ndarray] = {}

        def filter_mask(a, agg_idx):
            """bool [cap] FILTER (WHERE ...) mask; NULL = excluded."""
            if a.filter is None:
                return None
            if agg_idx not in filt_cache:
                fcol, fnull = split_col(a.filter.eval(chunk))
                filt_cache[agg_idx] = fcol if fnull is None \
                    else fcol & ~fnull
            return filt_cache[agg_idx]

        # DISTINCT dedup (ref distinct.rs): per call, count rows per
        # (group, value) key; only 0↔nonzero transitions reach the
        # aggregate — emitted as a ±1 "transition sign" at one
        # representative row per key, zero elsewhere.  The transition
        # depends only on the key's net delta, so in-chunk ordering is
        # irrelevant.
        d_tables = list(state.distinct_tables)
        d_counts = list(state.distinct_counts)
        d_signs: dict[int, jnp.ndarray] = {}
        n_over_d = jnp.zeros((), jnp.int64)
        n_bad_d = jnp.zeros((), jnp.int64)
        if self._distinct_aggs:
            from risingwave_tpu.stream.hash_join import _rank_by
            for di, agg_idx in enumerate(self._distinct_aggs):
                a = self.aggs[agg_idx]
                if agg_idx not in arg_cache:
                    arg_cache[agg_idx] = a.arg.eval(chunk)
                acol = arg_cache[agg_idx]
                _, anull = split_col(acol)
                eligible = valid & (signs != 0)
                if self.spill_ring:
                    # diverted rows replay in the tier's own dedup state
                    eligible = eligible & ~spill_mask
                if anull is not None:
                    eligible = eligible & ~anull
                fm = filter_mask(a, agg_idx)
                if fm is not None:
                    eligible = eligible & fm
                dt, dslots, dins, dover = d_tables[di].lookup_or_insert(
                    key_cols + [acol], eligible
                )
                d_tables[di] = dt
                size_d = dt.size
                n_over_d = n_over_d + jnp.sum(
                    (dover & eligible).astype(jnp.int64)
                )
                eligible = eligible & ~dover
                safe_d = jnp.minimum(dslots, size_d - 1)
                cnt = d_counts[di]
                # reclaimed (tombstoned→reused) slots carry stale counts
                cnt = cnt.at[
                    jnp.where(dins, dslots, jnp.int32(size_d))
                ].set(0, mode="drop")
                contrib = jnp.where(eligible,
                                    signs.astype(jnp.int64), 0)
                delta = jnp.zeros((size_d,), jnp.int64).at[safe_d].add(
                    jnp.where(eligible, contrib, 0)
                )
                n0 = cnt[safe_d]
                n1 = n0 + delta[safe_d]
                # deletes of never-inserted values drive a count
                # negative — the consistency_error! analog
                n_bad_d = n_bad_d + jnp.sum(
                    (eligible & (n1 < 0)).astype(jnp.int64)
                )
                rep = eligible & (
                    _rank_by(dslots.astype(jnp.uint64), eligible) == 0
                )
                d_signs[agg_idx] = jnp.where(
                    rep,
                    (n1 > 0).astype(jnp.int64)
                    - (n0 > 0).astype(jnp.int64),
                    0,
                )
                d_counts[di] = cnt.at[
                    jnp.where(eligible, safe_d, jnp.int32(size_d))
                ].add(contrib, mode="drop")
                # a (group, value) whose count retracted to 0 frees its
                # slot (tombstone) — churning retractable inputs must
                # not accumulate dead keys (ref distinct.rs deletes
                # count-0 dedup rows)
                died = jnp.zeros((size_d,), jnp.bool_).at[
                    jnp.where(rep & (n1 <= 0) & (n0 > 0), safe_d,
                              jnp.int32(size_d))
                ].set(True, mode="drop")
                d_tables[di] = d_tables[di].clear_where(died)
        for pi, (agg_idx, ps) in enumerate(self._prim_specs):
            a = self.aggs[agg_idx]
            if pi in self._cache_prims:
                continue  # minput cache: recomputed at flush
            if a.arg is None:
                col = jnp.ones_like(signs, jnp.int64)
            else:
                if agg_idx not in arg_cache:
                    arg_cache[agg_idx] = a.arg.eval(chunk)
                col = arg_cache[agg_idx]
            st_dt = prims[pi].dtype
            prims[pi] = prims[pi].at[ins_pos].set(
                ps.init(st_dt), mode="drop"
            )
            # NULL arguments contribute nothing (SQL: aggregates skip
            # NULLs): zero the sign, which every lift mode maps to its
            # identity element.  The payload is zeroed too — a NULL
            # row's payload is unspecified (e.g. inf from x/NULL) and
            # inf * 0 would poison additive states with NaN.
            col, col_null = split_col(col)
            if col_null is not None and not isinstance(col, StrCol):
                col = jnp.where(col_null, jnp.zeros((), col.dtype), col)
            fm = filter_mask(a, agg_idx)
            if perm is None:
                if agg_idx in d_signs:
                    # DISTINCT: the dedup pass already folded filter/
                    # NULL/duplicate semantics into ±1 transition signs
                    prim_signs = d_signs[agg_idx]
                else:
                    prim_signs = signs if col_null is None else jnp.where(
                        col_null, 0, signs
                    )
                    if fm is not None:
                        prim_signs = jnp.where(fm, prim_signs, 0)
                # per-row update scattered directly (invalid rows carry
                # sign 0 ⇒ identity, and sentinel slots drop)
                seg = ps.lift(col, prim_signs)
            else:
                if agg_idx in d_signs:
                    prim_signs = d_signs[agg_idx][perm]
                else:
                    prim_signs = s_signs if col_null is None \
                        else jnp.where(col_null[perm], 0, s_signs)
                    if fm is not None:
                        prim_signs = jnp.where(fm[perm], prim_signs, 0)
                # per-row lift in sorted order, then segment-reduce:
                # the value at each segment END is the segment's update
                contrib = ps.lift(gather_key(col, perm), prim_signs)
                if ps.mode == "add":
                    seg = segmented_sum(contrib, start_pos)
                else:
                    seg = segmented_minmax_at_ends(
                        seg_id, contrib, start_pos, ps.mode
                    )
            # non-representative rows carry sentinel slots (dropped)
            if ps.mode == "add":
                prims[pi] = prims[pi].at[slots].add(seg, mode="drop")
            elif ps.mode == "min":
                prims[pi] = prims[pi].at[slots].min(seg, mode="drop")
            else:
                prims[pi] = prims[pi].at[slots].max(seg, mode="drop")
        if perm is None:
            seg_signs = signs.astype(jnp.int64)
        else:
            seg_signs = segmented_sum(s_signs.astype(jnp.int64), start_pos)
        row_count = state.row_count.at[ins_pos].set(0, mode="drop")
        row_count = row_count.at[slots].add(seg_signs, mode="drop")
        dirty = state.dirty.at[slots].set(True, mode="drop")

        # materialized-input updates (retractable min/max): every row
        # lands in its group's value bucket — per-row slots come from
        # the per-row probe (CPU) or from scattering each segment
        # representative's slot over its segment id (TPU)
        minput_vals = list(state.minput_vals)
        minput_occ = list(state.minput_occ)
        n_over_mi = jnp.zeros((), jnp.int64)
        n_miss_mi = jnp.zeros((), jnp.int64)
        if self._minput_aggs:
            if perm is None:
                row_slots = slots
                row_ok = valid & (row_slots < self.table_size)
            else:
                # seg ids start at 1, so index 0 is a safe dump for
                # non-rep rows; segments whose representative
                # overflowed keep the `size` sentinel and their rows
                # are skipped (already counted in n_over)
                seg_slot = jnp.full((cap + 1,), self.table_size, jnp.int32)
                seg_slot = seg_slot.at[jnp.where(rep, seg_id, 0)].set(
                    jnp.where(rep, slots, self.table_size), mode="drop"
                )
                row_slots = seg_slot[seg_id]
                row_ok = s_valid & (row_slots < self.table_size)
            for mi, agg_idx in enumerate(self._minput_aggs):
                a = self.aggs[agg_idx]
                if agg_idx not in arg_cache:
                    arg_cache[agg_idx] = a.arg.eval(chunk)
                vcol, vnull = split_col(arg_cache[agg_idx])
                v_sorted = vcol if perm is None else gather_key(vcol, perm)
                active = row_ok & (s_signs != 0)
                if vnull is not None:
                    active = active & ~(
                        vnull if perm is None else vnull[perm]
                    )
                fm = filter_mask(a, agg_idx)
                if fm is not None:
                    active = active & (fm if perm is None else fm[perm])
                vals, occ, over, miss = self._minput_update(
                    minput_vals[mi], minput_occ[mi], row_slots,
                    v_sorted, s_signs, active, ins_pos,
                )
                minput_vals[mi] = vals
                minput_occ[mi] = occ
                n_over_mi = n_over_mi + over
                n_miss_mi = n_miss_mi + miss

        n_bad = jnp.zeros((), jnp.int64)
        if any(not a.spec().retractable and ai not in self._minput_aggs
               for ai, a in enumerate(self.aggs)):
            n_bad = jnp.sum((valid & (signs < 0)).astype(jnp.int64))
        return AggState(
            table=table,
            prims=tuple(prims),
            row_count=row_count,
            dirty=dirty,
            prev_prims=state.prev_prims,
            prev_row_count=state.prev_row_count,
            emitted=state.emitted,
            overflow=state.overflow + n_over + n_over_mi + n_over_d,
            inconsistency=state.inconsistency + n_bad + n_miss_mi
            + n_bad_d,
            wm=state.wm,
            minput_vals=tuple(minput_vals),
            minput_occ=tuple(minput_occ),
            distinct_tables=tuple(d_tables),
            distinct_counts=tuple(d_counts),
            spill_rows=spill_rows,
            spill_ops=spill_ops,
            spill_count=spill_count,
        ), None

    def reconstructible_from_rows(self) -> bool:
        """True when the agg's full state round-trips through its own
        input rows: plain InputRef keys in order and one sum/sum0/min/
        max call per trailing input column — exactly the GLOBAL half of
        a two-phase pair (translated_global_calls).  Such an agg can be
        rebuilt on a different mesh by re-applying extracted rows (the
        online-rescale path, ref scale.rs: state follows vnodes)."""
        n_keys = len(self.group_by)
        for ki, (_, e) in enumerate(self.group_by):
            if not (isinstance(e, InputRef) and e.index == ki):
                return False
        if self._minput_aggs or self._distinct_aggs:
            return False
        for ai, a in enumerate(self.aggs):
            if a.kind not in ("sum", "sum0", "min", "max") \
                    or a.distinct or a.filter is not None:
                return False
            if not (isinstance(a.arg, InputRef)
                    and a.arg.index == n_keys + ai):
                return False
            if self.in_schema[n_keys + ai].data_type.is_string:
                # string min/max state is a PACKED int64 (_pack_str8);
                # extract_chunk cannot emit it as the string input col
                return False
        return len(self.in_schema) == n_keys + len(self.aggs)

    def extract_chunk(self, state_host) -> Chunk:
        """One INPUT-schema chunk holding every live group's state
        (host arrays; capacity = table_size).  Re-applying it to a
        fresh state reconstructs the aggregation exactly — valid only
        when ``reconstructible_from_rows()``."""
        n_keys = len(self.group_by)
        cols = list(state_host.table.key_cols)
        pi = 0
        for ai, a in enumerate(self.aggs):
            spec = a.spec()
            val = state_host.prims[pi]
            pi += len(spec.states)
            f = self.in_schema[n_keys + ai]
            if f.nullable and ai in self._nn_prim:
                nn = state_host.prims[self._nn_prim[ai]]
                val = NCol(jnp.asarray(val), jnp.asarray(nn == 0))
            cols.append(val)
        occ = jnp.asarray(state_host.table.occupied)
        return Chunk(
            tuple(jnp.asarray(c) if not isinstance(c, (NCol, StrCol))
                  else c for c in cols),
            jnp.zeros((self.table_size,), jnp.int8),
            occ, self.in_schema,
        )

    def drain_spill(self, state: AggState):
        """(state with an empty ring, Chunk of the diverted rows).

        Jitted by the runtime at snapshot barriers; the chunk feeds the
        host overflow tier (stream/spill.py)."""
        R = self.spill_ring
        valid = jnp.arange(R, dtype=jnp.int32) < state.spill_count
        chunk = Chunk(state.spill_rows, state.spill_ops, valid,
                      self.in_schema)
        return state._replace(
            spill_count=jnp.zeros((), jnp.int32)
        ), chunk

    def make_spill_tier(self, table_size: int) -> "HashAggExecutor":
        """A same-shaped aggregation for the host (CPU) overflow tier."""
        return HashAggExecutor(
            table_size=table_size,
            distinct_table_size=max(table_size,
                                    self.distinct_table_size),
            **self._ctor_kwargs,
        )

    def _minput_update(self, vals, occ, row_slots, v_sorted, s_signs,
                       active, ins_pos):
        """Apply one chunk's (sorted) rows to a value bucket multi-map.

        Same rank-claim/rank-clear mechanics as the join's bucketed
        multi-map (hash_join._update_side), specialized to one scalar
        value column keyed by the group slot."""
        from risingwave_tpu.stream.hash_join import (
            _group_totals,
            _rank_by,
        )

        B = occ.shape[1]
        size = self.table_size
        # reclaimed slots start with an empty bucket
        occ = occ.at[ins_pos].set(False, mode="drop")
        is_ins = active & (s_signs > 0)
        is_del = active & (s_signs < 0)
        # in-chunk annihilation on (slot, value): a +v/-v pair inside
        # one chunk must cancel (the delete pass only sees pre-chunk
        # state)
        pair_h = hash64_columns([
            row_slots.astype(jnp.int64),
            v_sorted,
        ])
        ins_rank_h = _rank_by(pair_h, is_ins)
        del_rank_h = _rank_by(pair_h, is_del)
        n_ins_h = _group_totals(pair_h, is_ins)
        n_del_h = _group_totals(pair_h, is_del)
        is_ins = is_ins & ~(ins_rank_h < n_del_h)
        is_del = is_del & ~(del_rank_h < n_ins_h)

        safe = jnp.minimum(row_slots, size - 1)
        # deletes: clear the rank-th value-equal occupied entry
        del_rank = _rank_by(pair_h, is_del)
        occ_rows = occ[safe]
        val_match = occ_rows & (vals[safe] == v_sorted[:, None])
        match_rank = jnp.cumsum(val_match, axis=1) - 1
        clear_onehot = val_match & (match_rank == del_rank[:, None]) & \
            is_del[:, None]
        any_clear = jnp.any(clear_onehot, axis=1)
        miss = jnp.sum((is_del & ~any_clear).astype(jnp.int64))
        j_clear = jnp.argmax(clear_onehot, axis=1).astype(jnp.int32)
        flat_clear = jnp.where(
            any_clear, safe * B + j_clear, jnp.int32(size * B)
        )
        occ = occ.reshape(-1).at[flat_clear].set(
            False, mode="drop"
        ).reshape(size, B)
        # inserts: claim the rank-th free position of the slot's bucket
        ins_rank = _rank_by(row_slots.astype(jnp.uint64), is_ins)
        free = ~occ[safe]
        free_rank = jnp.cumsum(free, axis=1) - 1
        take = free & (free_rank == ins_rank[:, None]) & is_ins[:, None]
        got = jnp.any(take, axis=1)
        j_take = jnp.argmax(take, axis=1).astype(jnp.int32)
        flat_take = jnp.where(
            got, safe * B + j_take, jnp.int32(size * B)
        )
        occ = occ.reshape(-1).at[flat_take].set(
            True, mode="drop"
        ).reshape(size, B)
        vals = vals.reshape(-1).at[flat_take].set(
            v_sorted, mode="drop"
        ).reshape(size, B)
        over = jnp.sum((is_ins & ~got).astype(jnp.int64))
        return vals, occ, over, miss

    # ------------------------------------------------------------------
    def _outputs(self, prims: tuple, row_count, slots):
        """Per-emitted-slot output columns from the state arrays."""
        size = self.table_size
        safe = jnp.minimum(slots, size - 1)
        cols = []
        pi = 0
        for ai, a in enumerate(self.aggs):
            spec = a.spec()
            n = len(spec.states)
            st = tuple(prims[pi + k][safe] for k in range(n))
            pi += n
            out_f = self._out_schema[len(self.group_by) + ai]
            out = spec.output(st, row_count[safe], out_f)
            if ai in self._nn_prim:
                # all argument rows NULL -> SQL NULL result
                nn = prims[self._nn_prim[ai]][safe]
                out = NCol(out, nn == 0)
            cols.append(out)
        return cols

    def _refresh_minput_caches(self, state: AggState, slots,
                               safe) -> AggState:
        """Recompute retractable min/max outputs for the emitted slots
        from their materialized-input buckets (the prim array is just a
        cache of this reduction)."""
        if not self._minput_aggs:
            return state
        prims = list(state.prims)
        for mi, agg_idx in enumerate(self._minput_aggs):
            pi = next(p for p, (ai, _) in enumerate(self._prim_specs)
                      if ai == agg_idx)
            mode = self.aggs[agg_idx].kind
            vals = state.minput_vals[mi][safe]     # [cap, B]
            occ = state.minput_occ[mi][safe]
            dt = vals.dtype
            if jnp.issubdtype(dt, jnp.floating):
                ident = jnp.asarray(
                    jnp.inf if mode == "min" else -jnp.inf, dt
                )
            else:
                info = jnp.iinfo(dt)
                ident = jnp.asarray(
                    info.max if mode == "min" else info.min, dt
                )
            masked = jnp.where(occ, vals, ident)
            red = masked.min(axis=1) if mode == "min" \
                else masked.max(axis=1)
            prims[pi] = prims[pi].at[slots].set(red, mode="drop")
        return state._replace(prims=tuple(prims))

    def flush(self, state: AggState, epoch):
        if self.emit_on_window_close:
            return self._flush_eowc(state)
        cap = self.emit_capacity
        size = self.table_size
        slots = mask_indices(state.dirty, cap, size)
        slot_live = slots < size
        safe = jnp.minimum(slots, size - 1)
        state = self._refresh_minput_caches(state, slots, safe)

        old_nonempty = state.prev_row_count[safe] > 0
        new_nonempty = state.row_count[safe] > 0
        del_side = slot_live & state.emitted[safe] & old_nonempty
        ins_side = slot_live & new_nonempty

        key_vals = state.table.gather_keys(slots)
        old_cols = self._outputs(state.prev_prims, state.prev_row_count, slots)
        new_cols = self._outputs(state.prims, state.row_count, slots)

        out_cols = []
        for k in key_vals:
            out_cols.append(_interleave(k, k))
        for o, n in zip(old_cols, new_cols):
            out_cols.append(_interleave(o, n))

        both = del_side & ins_side
        op_even = jnp.where(both, OP_UPDATE_DELETE, OP_DELETE).astype(jnp.int8)
        op_odd = jnp.where(both, OP_UPDATE_INSERT, OP_INSERT).astype(jnp.int8)
        ops = _interleave(op_even, op_odd)
        valid = _interleave(del_side, ins_side)

        out = Chunk(out_cols, ops, valid, self._out_schema)

        # persist current as prev for emitted slots; clear their dirty bit.
        # un-emitted dirty slots (overflow beyond emit_capacity) stay dirty
        # and are drained by the runtime calling flush() again.
        prev_prims = tuple(
            p.at[slots].set(c[safe], mode="drop")
            for p, c in zip(state.prev_prims, state.prims)
        )
        prev_row_count = state.prev_row_count.at[slots].set(
            state.row_count[safe], mode="drop"
        )
        emitted = state.emitted.at[slots].set(new_nonempty, mode="drop")
        dirty = state.dirty.at[slots].set(False, mode="drop")
        return state._replace(
            dirty=dirty,
            prev_prims=prev_prims,
            prev_row_count=prev_row_count,
            emitted=emitted,
        ), out

    def _closed_mask(self, state: AggState) -> jnp.ndarray:
        key, key_null = split_col(
            state.table.key_cols[self.watermark_group_idx]
        )
        no_wm = state.wm == np.iinfo(np.int64).min
        closed = state.table.occupied & (
            key + self.watermark_lag <= state.wm
        )
        if key_null is not None:
            closed = closed & ~key_null  # a NULL window never closes
        return closed & ~no_wm

    def _flush_eowc(self, state: AggState):
        """Emit final rows for closed windows; evict them (ref EOWC)."""
        cap = self.emit_capacity
        size = self.table_size
        closed = self._closed_mask(state)
        slots = mask_indices(closed, cap, size)
        slot_live = slots < size
        safe = jnp.minimum(slots, size - 1)
        live = slot_live & (state.row_count[safe] > 0)

        key_vals = state.table.gather_keys(slots)
        out_cols = list(key_vals) + self._outputs(
            state.prims, state.row_count, slots
        )
        out = Chunk(
            tuple(out_cols),
            jnp.full((cap,), OP_INSERT, jnp.int8),
            live,
            self._out_schema,
        )
        emitted_mask = jnp.zeros((size,), jnp.bool_).at[slots].set(
            slot_live, mode="drop"
        )
        table = state.table.clear_where(emitted_mask)
        return state._replace(
            table=table,
            row_count=jnp.where(emitted_mask, 0, state.row_count),
            dirty=state.dirty & ~emitted_mask,
        ), out

    def pending_dirty(self, state: AggState) -> jnp.ndarray:
        return jnp.sum(state.dirty.astype(jnp.int32))

    # runtime drain protocol
    def pending_flush(self, state: AggState) -> jnp.ndarray:
        if self.emit_on_window_close:
            return jnp.sum(self._closed_mask(state).astype(jnp.int32))
        return self.pending_dirty(state)

    def on_watermark(self, state: AggState, watermark):
        if self.watermark_group_idx is None:
            return state
        if (self.watermark_src_col is not None
                and watermark.col_idx != self.watermark_src_col):
            return state
        state = state._replace(
            wm=jnp.maximum(state.wm, jnp.int64(watermark.value))
        )
        if self.emit_on_window_close:
            return state  # emission evicts; no pre-cleaning
        return self.clean_below(
            state, self.watermark_group_idx,
            watermark.value - self.watermark_lag,
        )

    def maybe_rehash(self, state: AggState) -> AggState:
        """Rebuild the group table once tombstones dominate (called by
        the runtime at checkpoint barriers after state cleaning).

        Traceable: the decision is a ``lax.cond`` on the device-resident
        tombstone count, so maintenance never reads back to the host."""

        def do_rehash(state: AggState) -> AggState:
            from risingwave_tpu.state.hash_table import permute_dense

            fresh, moved = state.table.rehashed()
            prims = []
            prev_prims = []
            for pi, (agg_idx, ps) in enumerate(self._prim_specs):
                st_dt = state.prims[pi].dtype
                init = ps.init(st_dt)
                prims.append(permute_dense(state.prims[pi], moved, init))
                prev_prims.append(
                    permute_dense(state.prev_prims[pi], moved, init)
                )
            return state._replace(
                table=fresh,
                prims=tuple(prims),
                row_count=permute_dense(state.row_count, moved),
                dirty=permute_dense(state.dirty, moved),
                prev_prims=tuple(prev_prims),
                prev_row_count=permute_dense(state.prev_row_count, moved),
                emitted=permute_dense(state.emitted, moved),
                minput_vals=tuple(
                    permute_dense(v, moved) for v in state.minput_vals
                ),
                minput_occ=tuple(
                    permute_dense(o, moved) for o in state.minput_occ
                ),
            )

        state = jax.lax.cond(
            state.table.tombstone_count() > self.table_size // 4,
            do_rehash, lambda s: s, state,
        )
        if not self._distinct_aggs:
            return state

        # distinct dedup tables compact independently (their own keys)
        def rehash_d(state: AggState) -> AggState:
            from risingwave_tpu.state.hash_table import permute_dense
            d_tables = []
            d_counts = []
            for dt, cnt in zip(state.distinct_tables,
                               state.distinct_counts):
                fresh, moved = dt.rehashed()
                d_tables.append(fresh)
                d_counts.append(permute_dense(cnt, moved))
            return state._replace(
                distinct_tables=tuple(d_tables),
                distinct_counts=tuple(d_counts),
            )

        any_tomb = state.distinct_tables[0].tombstone_count()
        for dt in state.distinct_tables[1:]:
            any_tomb = jnp.maximum(any_tomb, dt.tombstone_count())
        return jax.lax.cond(
            any_tomb > self.distinct_table_size // 4,
            rehash_d, lambda s: s, state,
        )

    # ------------------------------------------------------------------
    def clean_below(self, state: AggState, key_col_idx: int, threshold):
        """Drop groups whose ``key_col_idx`` group-key < threshold.

        Watermark-driven state cleaning (ref state_table.rs:223): used by
        windowed aggregations once a window can no longer change.
        """
        key, key_null = split_col(state.table.key_cols[key_col_idx])
        stale = state.table.occupied & (key < threshold)
        if key_null is not None:
            stale = stale & ~key_null  # NULL keys are never below a wm
        table = state.table.clear_where(stale)
        # distinct dedup keys carry the same group-key prefix: evict
        # their (group, value) rows with the window too
        d_tables = []
        d_counts = []
        for dt, cnt in zip(state.distinct_tables, state.distinct_counts):
            k, kn = split_col(dt.key_cols[key_col_idx])
            stale_d = dt.occupied & (k < threshold)
            if kn is not None:
                stale_d = stale_d & ~kn
            d_tables.append(dt.clear_where(stale_d))
            d_counts.append(jnp.where(stale_d, 0, cnt))
        return state._replace(
            table=table,
            row_count=jnp.where(stale, 0, state.row_count),
            dirty=state.dirty & ~stale,
            prev_row_count=jnp.where(stale, 0, state.prev_row_count),
            emitted=state.emitted & ~stale,
            minput_occ=tuple(
                o & ~stale[:, None] for o in state.minput_occ
            ),
            distinct_tables=tuple(d_tables),
            distinct_counts=tuple(d_counts),
        )
