"""Chunk-local partial aggregation (phase 1 of two-phase agg).

Reference counterpart: the optimizer's two-phase aggregation rewrite —
local stateless partial agg → hash exchange → global agg (SURVEY.md
§2.3 parallelism item 4; ``stateless_simple_agg.rs`` +
``logical_agg.rs`` two-phase planning).

TPU-first design: the partial phase is STATELESS — one sort + segment
reduce per chunk collapses duplicate keys before the ``all_to_all``,
shrinking shuffle volume by the in-chunk duplication factor (hot
nexmark keys collapse thousands of rows to one partial row).  Output:
one row per distinct key (at its segment leader position, mask
elsewhere) carrying signed partial states, consumed by a translated
global agg (count → sum0 of partials, sum → sum, min/max → min/max).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import (
    Chunk,
    NCol,
    OP_INSERT,
    StrCol,
    split_col,
)
from risingwave_tpu.common.hash import hash64_columns
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.agg import AggCall
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.stream.executor import Executor

#: aggs decomposable into ONE signed/monoid partial column
TWO_PHASE_KINDS = {"count", "count_star", "sum", "sum0", "min", "max"}


def translated_global_calls(aggs: Sequence[AggCall], n_keys: int):
    """Global-phase calls reading the partial columns (same output
    arity/order as the original calls)."""
    from risingwave_tpu.expr.node import InputRef

    combine = {"count": "sum0", "count_star": "sum0", "sum": "sum",
               "sum0": "sum0", "min": "min", "max": "max"}
    return [
        AggCall(combine[a.kind], InputRef(n_keys + i), a.alias or a.kind)
        for i, a in enumerate(aggs)
    ]


class PartialAggExecutor(Executor):
    """Stateless in-chunk combine: distinct keys + signed partials."""

    emits_on_apply = True
    emits_on_flush = False

    def __init__(self, in_schema: Schema,
                 group_by: Sequence[tuple[str, Expr]],
                 aggs: Sequence[AggCall]):
        super().__init__(in_schema)
        for a in aggs:
            if a.kind not in TWO_PHASE_KINDS:
                raise ValueError(f"{a.kind} is not two-phase decomposable")
        self.group_by = tuple(group_by)
        self.aggs = tuple(aggs)
        key_fields = tuple(
            Field(name, e.return_field(in_schema).data_type,
                  str_width=e.return_field(in_schema).str_width,
                  decimal_scale=e.return_field(in_schema).decimal_scale,
                  nullable=e.return_field(in_schema).nullable)
            for name, e in self.group_by
        )
        partial_fields = []
        for a in self.aggs:
            if a.kind in ("count", "count_star"):
                # counts are never NULL (a segment of all-NULL args
                # contributes 0)
                partial_fields.append(
                    Field(f"_p_{a.alias or a.kind}", DataType.INT64)
                )
            else:
                # sum/min/max over a nullable arg: the partial is NULL
                # when the segment has no non-null rows, so the GLOBAL
                # agg's native NULL-skip + all-NULL→NULL semantics
                # compose across the exchange
                f = a.out_field(in_schema)
                partial_fields.append(Field(
                    f"_p_{f.name}", f.data_type,
                    decimal_scale=f.decimal_scale,
                    nullable=a.arg is not None
                    and a.arg.return_field(in_schema).nullable,
                ))
        self._out_schema = Schema(key_fields + tuple(partial_fields))

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def apply(self, state, chunk: Chunk):
        from risingwave_tpu.common.chunk import conform_col
        from risingwave_tpu.state.hash_table import _keys_equal

        cap = chunk.capacity
        key_cols = [
            conform_col(e.eval(chunk),
                        e.return_field(self.in_schema).nullable, cap)
            for _, e in self.group_by
        ]
        signs = chunk.signs()  # 0 for invalid rows
        kh = hash64_columns(key_cols)
        kh = jnp.where(chunk.valid, kh, jnp.uint64(0xFFFFFFFFFFFFFFFF))
        order = jnp.argsort(kh, stable=True)
        valid_s = chunk.valid[order]
        signs_s = signs[order]

        def sort_col(c):
            if isinstance(c, NCol):
                return NCol(sort_col(c.data), c.null[order])
            if isinstance(c, StrCol):
                return StrCol(c.data[order], c.lens[order])
            return c[order]

        sorted_keys = [sort_col(c) for c in key_cols]
        # segment boundaries by FULL key equality of adjacent sorted rows
        # (the hash only orders; colliding distinct keys must still
        # split) and by validity flips (garbage keys of invalid rows
        # must never merge with real groups)
        from risingwave_tpu.state.hash_table import _gather_key
        same_as_prev = jnp.ones((cap,), jnp.bool_)
        for c in sorted_keys:
            cur = _gather_key(c, jnp.arange(1, cap))
            prev = _gather_key(c, jnp.arange(0, cap - 1))
            eq = _keys_equal(cur, prev)
            same_as_prev = same_as_prev.at[1:].min(eq)
        same_validity = jnp.ones((cap,), jnp.bool_).at[1:].set(
            valid_s[1:] == valid_s[:-1]
        )
        is_new = ~(same_as_prev & same_validity)
        is_new = is_new.at[0].set(True)
        seg_id = jnp.cumsum(is_new) - 1  # [cap]

        out_cols = list(sorted_keys)
        for ai, a in enumerate(self.aggs):
            if a.arg is None:
                col_s, null_s = jnp.ones((cap,), jnp.int64), None
            else:
                col_s, null_s = split_col(sort_col(a.arg.eval(chunk)))
            # NULL args contribute nothing (SQL aggregates skip NULLs)
            eff_signs = signs_s if null_s is None else jnp.where(
                null_s, 0, signs_s
            )
            out_nullable = self._out_schema[
                len(self.group_by) + ai].nullable
            if a.kind in ("count", "count_star"):
                contrib = eff_signs.astype(jnp.int64)
                part = jax.ops.segment_sum(contrib, seg_id,
                                           num_segments=cap)
            elif a.kind in ("sum", "sum0"):
                dt = jnp.int64 if jnp.issubdtype(col_s.dtype, jnp.integer) \
                    else col_s.dtype
                # zero NULL payloads: a NULL row's payload is garbage
                # and garbage * 0 can still poison float sums (inf/nan)
                payload = col_s.astype(dt) if null_s is None else \
                    jnp.where(null_s, jnp.zeros((), dt), col_s.astype(dt))
                contrib = payload * eff_signs.astype(dt)
                part = jax.ops.segment_sum(contrib, seg_id,
                                           num_segments=cap)
            else:
                # min/max: mask NULL/inactive rows to the identity so
                # they can't win the segment reduction
                dt = col_s.dtype
                if jnp.issubdtype(dt, jnp.floating):
                    ident = jnp.asarray(
                        jnp.inf if a.kind == "min" else -jnp.inf, dt)
                else:
                    info = jnp.iinfo(dt)
                    ident = jnp.asarray(
                        info.max if a.kind == "min" else info.min, dt)
                masked = col_s if null_s is None else jnp.where(
                    null_s, ident, col_s)
                if a.kind == "min":
                    part = jax.ops.segment_min(masked, seg_id,
                                               num_segments=cap)
                else:
                    part = jax.ops.segment_max(masked, seg_id,
                                               num_segments=cap)
            part = part[seg_id]  # broadcast back; leaders keep it
            if out_nullable:
                # partial is NULL when the segment saw no non-null rows
                nn = jax.ops.segment_sum(
                    jnp.abs(eff_signs).astype(jnp.int64), seg_id,
                    num_segments=cap,
                )[seg_id]
                part = NCol(part, nn == 0)
            out_cols.append(part)

        valid_out = is_new & valid_s
        ops = jnp.full((cap,), OP_INSERT, jnp.int8)
        return state, Chunk(tuple(out_cols), ops, valid_out,
                            self._out_schema)
