"""TroublemakerExecutor: deterministic chaos injection between executors.

Reference counterpart: ``src/stream/src/executor/troublemaker.rs`` —
randomly corrupts ops/values between executors when
``RW_UNSAFE_ENABLE_INSANE_MODE`` is set, to prove the engine degrades
loudly (consistency counters) rather than silently.

Here corruption is derived from a counter-based hash (seeded, fully
deterministic — reproducible chaos like the reference's madsim seeds):
a fraction of Insert rows flip to Delete, which downstream stateful
executors must surface via their ``inconsistency`` counters.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, OP_DELETE, OP_INSERT
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor

_K1 = np.uint64(0x9E3779B97F4A7C15)
_K2 = np.uint64(0xBF58476D1CE4E5B9)


def _mix(x):
    x = (x ^ (x >> np.uint64(30))) * _K2
    return x ^ (x >> np.uint64(31))


class TroublemakerExecutor(Executor):
    """Flip ~1/ratio of Insert ops to Delete (deterministic by seed)."""

    emits_on_apply = True
    emits_on_flush = False

    def __init__(self, in_schema: Schema, seed: int = 0, ratio: int = 16):
        super().__init__(in_schema)
        self.seed = seed
        self.ratio = ratio

    def init_state(self):
        return jnp.zeros((), jnp.uint64)  # chunk counter

    def apply(self, state, chunk: Chunk):
        cap = chunk.capacity
        row = jnp.arange(cap, dtype=jnp.uint64)
        h = _mix(
            row * _K1 ^ state * _K2 ^ np.uint64(self.seed)
        )
        flip = (h % np.uint64(self.ratio) == 0) & chunk.valid & (
            chunk.ops == OP_INSERT
        )
        ops = jnp.where(flip, OP_DELETE, chunk.ops)
        return state + 1, Chunk(chunk.columns, ops, chunk.valid,
                                chunk.schema)
