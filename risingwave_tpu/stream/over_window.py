"""OverWindow executor: SQL window functions over partitions.

Reference counterpart: ``src/stream/src/executor/over_window/general.rs``
(733 LoC range cache + delta_btree_map) and the window function states in
``src/expr/impl/src/window_function/``.

TPU-first design
----------------
State is the same flat device row pool as TopN.  At barrier flush the
WHOLE pool is lexicographically sorted by (partition, order key) — one
device sort replaces the reference's per-partition BTree range cache —
and every window function evaluates as a segment scan over the sorted
array:

- ``row_number``/``rank``/``dense_rank``: segment position arithmetic
- ``lag``/``lead``: shifted gathers masked at partition boundaries
- ``sum``/``count``/``min``/``max`` over UNBOUNDED PRECEDING..CURRENT:
  segment prefix scans (associative_scan re-anchored at partition
  starts)

Emission diffs against the previously emitted output by row hash, so
downstream receives a changelog exactly like the reference's
``OverWindow`` output.  The pool bounds history like TopN; watermark
cleaning frees closed partitions (EOWC-style plans).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import (
    Chunk,
    OP_DELETE,
    OP_INSERT,
    StrCol,
)
from risingwave_tpu.common.hash import hash64_columns
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.top_n import (
    TopNState,
    _empty_like_col,
    _gather,
    _order_key,
    _scatter,
)


@dataclass(frozen=True)
class WindowFuncCall:
    """One window function in the OVER clause plan."""

    kind: str            # row_number | rank | dense_rank | lag | lead |
    #                      sum | count | avg | min | max
    arg: Expr | None = None
    offset: int = 1      # lag/lead distance
    alias: str | None = None
    #: ROWS BETWEEN <pre> PRECEDING AND CURRENT ROW (sum/count/avg);
    #: None = the default frame (unbounded preceding .. current row).
    #: Ref: over_window frame_finder.rs ROWS frames.
    frame: "tuple[int, int] | None" = None

    def out_field(self, in_schema: Schema) -> Field:
        name = self.alias or self.kind
        if self.kind in ("row_number", "rank", "dense_rank", "count"):
            return Field(name, DataType.INT64)
        f = self.arg.return_field(in_schema)
        if self.kind == "sum" and f.data_type in (DataType.INT16,
                                                  DataType.INT32):
            return Field(name, DataType.INT64)
        if self.kind == "avg":
            if f.data_type == DataType.DECIMAL:
                return Field(name, DataType.DECIMAL,
                             decimal_scale=f.decimal_scale)
            return Field(name, DataType.FLOAT64)
        return Field(name, f.data_type, str_width=f.str_width,
                     decimal_scale=f.decimal_scale)


def _segment_starts(part_sorted: jnp.ndarray, valid_sorted: jnp.ndarray):
    """Boolean new-segment markers + running segment-start indices."""
    n = part_sorted.shape[0]
    key = jnp.where(valid_sorted, part_sorted,
                    jnp.uint64(0xFFFFFFFFFFFFFFFF))
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), key[1:] != key[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_new, jnp.arange(n, dtype=jnp.int64), 0)
    )
    return is_new, start


class OverWindowExecutor(Executor):
    """Append window-function columns; emits a changelog at barriers."""

    emits_on_apply = False
    emits_on_flush = True

    def __init__(
        self,
        in_schema: Schema,
        partition_by: Sequence[Expr],
        order_by: Sequence[tuple[Expr, bool]],
        calls: Sequence[WindowFuncCall],
        pool_size: int = 4096,
        emit_capacity: int = 1024,
        watermark_col_idx: int | None = None,
        watermark_lag: int = 0,
    ):
        super().__init__(in_schema)
        self.partition_by = tuple(partition_by)
        self.order_by = tuple(order_by)
        self.calls = tuple(calls)
        self.pool_size = pool_size
        self.emit_capacity = emit_capacity
        self.watermark_col_idx = watermark_col_idx
        self.watermark_lag = watermark_lag
        self._out_schema = Schema(
            in_schema.fields
            + tuple(c.out_field(in_schema) for c in self.calls)
        )
        # reuse the TopN pool apply (insert/delete into flat pool)
        from risingwave_tpu.stream.top_n import GroupTopNExecutor
        self._pool = GroupTopNExecutor(
            in_schema, group_by=[], order_by=[], limit=1,
            pool_size=pool_size, emit_capacity=emit_capacity,
        )

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def init_state(self) -> TopNState:
        st = self._pool.init_state()
        # prev_* must carry the OUTPUT schema width (input + calls)
        E = self.emit_capacity
        protos = []
        for f in self._out_schema:
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        return TopNState(
            rows=st.rows,
            valid=st.valid,
            row_hash=st.row_hash,
            prev_rows=tuple(_empty_like_col(p, E) for p in protos),
            prev_valid=jnp.zeros((E,), jnp.bool_),
            prev_hash=jnp.zeros((E,), jnp.uint64),
            overflow=st.overflow,
            inconsistency=st.inconsistency,
        )

    def apply(self, state: TopNState, chunk: Chunk):
        st, _ = self._pool.apply(state, chunk)
        return st, None

    # ------------------------------------------------------------------
    def _compute_outputs(self, state: TopNState):
        """Sort the pool; evaluate every window call per row.

        Returns (order [S] pool indices sorted, valid_sorted, per-call
        output columns in sorted order)."""
        S = self.pool_size
        pool_chunk = Chunk(
            state.rows, jnp.zeros((S,), jnp.int8), state.valid,
            self.in_schema,
        )
        order = jnp.arange(S, dtype=jnp.int32)
        for e, desc in reversed(self.order_by):
            k = _order_key(e.eval(pool_chunk), desc)
            order = order[jnp.argsort(k[order], stable=True)]
        part = hash64_columns(
            [e.eval(pool_chunk) for e in self.partition_by]
        ) if self.partition_by else jnp.zeros((S,), jnp.uint64)
        order = order[jnp.argsort(part[order], stable=True)]
        order = order[jnp.argsort(~state.valid[order], stable=True)]

        valid_s = state.valid[order]
        part_s = jnp.where(valid_s, part[order],
                           jnp.uint64(0xFFFFFFFFFFFFFFFF))
        is_new, seg_start = _segment_starts(part_s, valid_s)
        idx = jnp.arange(S, dtype=jnp.int64)
        pos_in_part = idx - seg_start  # 0-based position within partition

        # order-key ties for rank/dense_rank
        tie_key = jnp.zeros((S,), jnp.uint64)
        for e, desc in self.order_by:
            tie_key = tie_key * jnp.uint64(1000003) ^ _order_key(
                e.eval(pool_chunk), desc
            )[order]
        new_val = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), tie_key[1:] != tie_key[:-1]]
        ) | is_new

        outs = []
        for call in self.calls:
            if call.kind == "row_number":
                outs.append(pos_in_part + 1)
            elif call.kind == "rank":
                rank_anchor = jax.lax.associative_scan(
                    jnp.maximum, jnp.where(new_val, idx, 0)
                )
                outs.append(rank_anchor - seg_start + 1)
            elif call.kind == "dense_rank":
                seg_newvals = jnp.cumsum(new_val.astype(jnp.int64))
                # dense rank = #distinct keys so far in partition
                start_cum = jax.lax.associative_scan(
                    jnp.maximum,
                    jnp.where(is_new, seg_newvals - 1, 0),
                )
                outs.append(seg_newvals - start_cum)
            elif call.kind in ("lag", "lead"):
                col_s = _gather(call.arg.eval(pool_chunk), order)
                shift = call.offset if call.kind == "lag" else -call.offset
                src = idx - shift
                in_range = (src >= 0) & (src < S)
                src_c = jnp.clip(src, 0, S - 1)
                same_part = in_range & (part_s[src_c] == part_s)
                if isinstance(col_s, StrCol):
                    got = StrCol(
                        jnp.where(same_part[:, None], col_s.data[src_c],
                                  0),
                        jnp.where(same_part, col_s.lens[src_c], 0),
                    )
                else:
                    got = jnp.where(same_part, col_s[src_c],
                                    jnp.zeros((), col_s.dtype))
                outs.append(got)
            elif call.kind in ("sum", "count", "avg", "min", "max"):
                if call.kind == "count":
                    v = valid_s.astype(jnp.int64)
                else:
                    v = _gather(call.arg.eval(pool_chunk), order)
                    if call.kind in ("sum", "avg") and jnp.issubdtype(
                            v.dtype, jnp.integer):
                        v = v.astype(jnp.int64)
                # segment prefix scan re-anchored at partition starts:
                # subtract the prefix total BEFORE this partition (a
                # direct gather at seg_start — correct for negative
                # values too, unlike a running-max anchor)
                if call.kind in ("sum", "count", "avg"):
                    is_dec_avg = (
                        call.kind == "avg"
                        and call.arg.return_field(
                            self.in_schema
                        ).data_type == DataType.DECIMAL
                    )
                    if call.kind == "avg" and not is_dec_avg:
                        v = v.astype(jnp.float64)
                    cum = jnp.cumsum(v, axis=0)
                    before = cum - v
                    if call.frame is not None:
                        # ROWS BETWEEN pre PRECEDING AND CURRENT ROW:
                        # frame start = max(i - pre, partition start)
                        pre = call.frame[0]
                        lo = jnp.maximum(idx - pre, seg_start) \
                            if pre >= 0 else seg_start
                        frame_n = (idx - lo + 1).astype(jnp.int64)
                        agg = cum - before[lo]
                    else:
                        frame_n = (idx - seg_start + 1).astype(jnp.int64)
                        agg = cum - before[seg_start]
                    if call.kind == "avg":
                        if is_dec_avg:
                            # truncate toward zero at the input scale
                            agg = jnp.sign(agg) * (
                                jnp.abs(agg) // frame_n
                            )
                        else:
                            agg = agg / frame_n.astype(jnp.float64)
                    outs.append(agg)
                else:
                    opfn = jnp.minimum if call.kind == "min" \
                        else jnp.maximum
                    # segmented running min/max via scan over (seg, val)
                    def seg_op(a, b):
                        sa, va = a
                        sb, vb = b
                        keep = sa == sb
                        return sb, jnp.where(keep, opfn(va, vb), vb)

                    seg_id = jnp.cumsum(is_new.astype(jnp.int64))
                    _, run = jax.lax.associative_scan(
                        seg_op, (seg_id, v)
                    )
                    outs.append(run)
            else:
                raise ValueError(f"unknown window fn {call.kind!r}")
        return order, valid_s, pool_chunk, outs

    def flush(self, state: TopNState, epoch):
        S, E = self.pool_size, self.emit_capacity
        order, valid_s, pool_chunk, outs = self._compute_outputs(state)

        # compact the first E valid sorted rows (changed-row detection is
        # by full-output hash diff below, so emit window = whole pool,
        # capped at E — partitions beyond E surface via overflow counter)
        in_cols = tuple(_gather(c, order) for c in state.rows)
        full_cols = in_cols + tuple(outs)
        out_hash = hash64_columns(list(full_cols))
        out_hash = jnp.where(valid_s, out_hash, 0)

        take = jnp.arange(E, dtype=jnp.int32)
        cur_live = valid_s[take]
        cur_rows = tuple(_gather(c, take) for c in full_cols)
        cur_hash = out_hash[take]
        n_beyond = jnp.sum(valid_s[E:].astype(jnp.int64)) if S > E \
            else jnp.zeros((), jnp.int64)

        from risingwave_tpu.stream.hash_join import _rank_by

        def member(a_hash, a_live, b_hash, b_live):
            eq = (a_hash[:, None] == b_hash[None, :]) & a_live[:, None] & \
                b_live[None, :]
            a_rank = _rank_by(a_hash, a_live)
            return jnp.sum(eq, axis=1) > a_rank

        ins_side = cur_live & ~member(
            cur_hash, cur_live, state.prev_hash, state.prev_valid
        )
        del_side = state.prev_valid & ~member(
            state.prev_hash, state.prev_valid, cur_hash, cur_live
        )

        def cat(a, b):
            if isinstance(a, StrCol):
                return StrCol(cat(a.data, b.data), cat(a.lens, b.lens))
            return jnp.concatenate([a, b], axis=0)

        out_cols = tuple(
            cat(p, c) for p, c in zip(state.prev_rows, cur_rows)
        )
        ops = cat(
            jnp.full((E,), OP_DELETE, jnp.int8),
            jnp.full((E,), OP_INSERT, jnp.int8),
        )
        valid = cat(del_side, ins_side)
        out = Chunk(out_cols, ops, valid, self._out_schema)
        return TopNState(
            rows=state.rows,
            valid=state.valid,
            row_hash=state.row_hash,
            prev_rows=cur_rows,
            prev_valid=cur_live,
            prev_hash=cur_hash,
            # gauge semantics: rows beyond the emit window are a config
            # error surfaced at maintenance (raise "increase capacity")
            overflow=jnp.maximum(state.overflow, n_beyond),
            inconsistency=state.inconsistency,
        ), out

    def on_watermark(self, state: TopNState, watermark):
        if self.watermark_col_idx is None:
            return state
        return self._pool.clean_below(
            state, self.watermark_col_idx,
            watermark.value - self.watermark_lag,
        )
