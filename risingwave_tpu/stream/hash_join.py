"""Streaming hash join (inner), device-resident two-sided state.

Reference counterpart: ``HashJoinExecutor`` (src/stream/src/executor/
hash_join.rs:158) with ``JoinHashMap`` state+degree tables
(join/hash_join.rs:169) and the probe loop ``eq_join_oneside``
(hash_join.rs:949).

TPU-first design
----------------
Each side's state is a *bucketed multi-map* in HBM:

- ``key_table``: HashTable over the join key — one slot per distinct key;
- ``rows``:     per-column ``[size, bucket_cap]`` dense stores;
- ``occupied``: ``bool [size, bucket_cap]``;
- ``count``:    ``int32 [size]`` live rows per key.

A chunk applies as a handful of gathers/scatters over the whole chunk
(vs the reference's per-row HashMap + Vec walk):

- inserts claim free bucket positions by rank-among-equal-keys
  (cumsum-of-free one-hot), deletes match value-equal entries by rank
  (row-hash disambiguated) and clear them;
- probe gathers the *entire* opposite bucket per row — every entry in a
  bucket shares the join key, so the match mask is just occupancy — and
  compacts all (probe-row × bucket-entry) pairs into a fixed-capacity
  output chunk via prefix sums.

Emitted ops: +/- matching the probe row's changelog sign (the
reference's U-pair reconstruction is a planner nicety, deferred).
Outer joins need degree-tracking NULL rows (ref degree table) — next
round.  State cleaning for window joins (Nexmark q8) is the same
vectorized sweep as hash_agg's ``clean_below``.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, NCol, StrCol, split_col
from risingwave_tpu.common.hash import hash64_columns


def _null_stripped_keys(key_cols):
    """(bare key cols, any-key-null mask | None).

    SQL join equality: NULL matches nothing (unlike grouping equality),
    so rows with a NULL key are masked out of both updates and probes
    and the stored key columns stay bare arrays."""
    null_any = None
    bare = []
    for c in key_cols:
        d, n = split_col(c)
        bare.append(d)
        if n is not None:
            null_any = n if null_any is None else (null_any | n)
    return bare, null_any
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.state.hash_table import HashTable


def _empty_store(f: Field, size: int, bucket: int):
    if f.data_type.is_string:
        col = StrCol(
            jnp.zeros((size, bucket, f.str_width), jnp.uint8),
            jnp.zeros((size, bucket), jnp.int32),
        )
    else:
        col = jnp.zeros((size, bucket), f.data_type.physical_dtype)
    if f.nullable:
        return NCol(col, jnp.zeros((size, bucket), jnp.bool_))
    return col


def _gather_bucket(store, slots):
    """[size, B, ...] gathered at [cap] slots -> [cap, B, ...]."""
    if isinstance(store, NCol):
        return NCol(_gather_bucket(store.data, slots), store.null[slots])
    if isinstance(store, StrCol):
        return StrCol(store.data[slots], store.lens[slots])
    return store[slots]


def _scatter_rows(store, pos, col):
    """Write row values col[[cap]] at flat positions pos[[cap]] into the
    flattened [size*B, ...] view of the store."""
    if isinstance(store, NCol):
        null_flat = store.null.reshape(-1).at[pos].set(
            col.null, mode="drop"
        ).reshape(store.null.shape)
        return NCol(_scatter_rows(store.data, pos, col.data), null_flat)
    if isinstance(store, StrCol):
        flat_d = store.data.reshape((-1,) + store.data.shape[2:])
        flat_l = store.lens.reshape((-1,))
        flat_d = flat_d.at[pos].set(col.data, mode="drop")
        flat_l = flat_l.at[pos].set(col.lens, mode="drop")
        return StrCol(
            flat_d.reshape(store.data.shape), flat_l.reshape(store.lens.shape)
        )
    flat = store.reshape((-1,) + store.shape[2:])
    flat = flat.at[pos].set(col, mode="drop")
    return flat.reshape(store.shape)


def _rank_by(group: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """Stable rank of each active row among rows with equal ``group``."""
    cap = group.shape[0]
    key = jnp.where(active, group, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_new, jnp.arange(cap, dtype=jnp.int32), 0)
    )
    rank_sorted = jnp.arange(cap, dtype=jnp.int32) - start
    return jnp.zeros((cap,), jnp.int32).at[order].set(rank_sorted)


def _group_totals(group: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """Per-row sum of ``values`` over rows sharing the same ``group``."""
    cap = group.shape[0]
    order = jnp.argsort(group, stable=True)
    sorted_g = group[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_g[1:] != sorted_g[:-1]]
    )
    seg_id = jnp.cumsum(is_new) - 1
    sums = jax.ops.segment_sum(
        values[order].astype(jnp.int32), seg_id, num_segments=cap
    )
    totals_sorted = sums[seg_id]
    return jnp.zeros((cap,), jnp.int32).at[order].set(totals_sorted)


class SideState(NamedTuple):
    key_table: HashTable
    rows: tuple          # [size, B] stores, one per input column
    occupied: jnp.ndarray  # bool [size, B]
    count: jnp.ndarray     # int32 [size]
    overflow: jnp.ndarray  # int64 — rows that found no bucket space
    #: deletes with no matching stored row (ref consistency_error!)
    inconsistency: jnp.ndarray


class JoinState(NamedTuple):
    left: SideState
    right: SideState
    emit_overflow: jnp.ndarray  # int64 — matches dropped by out capacity


class HashJoinExecutor:
    """Inner equi-join of two changelog streams.

    Not a linear-``Fragment`` executor: it has two inputs.  The runtime
    (``BinaryJob``) or a graph scheduler calls ``apply(state, chunk,
    side)``; output schema is left columns ++ right columns.
    """

    def __init__(
        self,
        left_schema: Schema,
        right_schema: Schema,
        left_keys: Sequence[Expr],
        right_keys: Sequence[Expr],
        table_size: int = 1 << 14,
        bucket_cap: int = 16,
        out_capacity: int = 16384,
        left_bucket_cap: int | None = None,
        right_bucket_cap: int | None = None,
        left_table_size: int | None = None,
        right_table_size: int | None = None,
    ):
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.table_size = table_size
        # per-side bucket depth: size for the max rows per join key on
        # that side (hot-key skew, e.g. nexmark's hot sellers, needs a
        # deep build side while a unique-keyed side stays shallow)
        self.left_bucket_cap = left_bucket_cap or bucket_cap
        self.right_bucket_cap = right_bucket_cap or bucket_cap
        # per-side key-table sizes: a unique-keyed side wants many slots
        # and shallow buckets; a hot-keyed side the opposite
        self.left_table_size = left_table_size or table_size
        self.right_table_size = right_table_size or table_size
        self.out_capacity = out_capacity
        self._out_schema = left_schema.concat(right_schema)
        #: per-side watermark cleaning: (key_idx, lag_us, src_col) —
        #: at barriers the runtime evicts keys whose key_idx-th join key
        #: < watermark(src_col) - lag (windowed joins, nexmark q8)
        self.left_clean: tuple[int, int, int] | None = None
        self.right_clean: tuple[int, int, int] | None = None

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    # ------------------------------------------------------------------
    def _key_protos(self, schema: Schema, keys: Sequence[Expr]):
        protos = []
        for e in keys:
            f = e.return_field(schema)
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        return protos

    def _side_state(self, schema: Schema, keys: Sequence[Expr],
                    bucket: int, size: int) -> SideState:
        return SideState(
            key_table=HashTable.create(
                self._key_protos(schema, keys), size
            ),
            rows=tuple(_empty_store(f, size, bucket) for f in schema),
            occupied=jnp.zeros((size, bucket), jnp.bool_),
            count=jnp.zeros((size,), jnp.int32),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
        )

    def init_state(self) -> JoinState:
        return JoinState(
            left=self._side_state(
                self.left_schema, self.left_keys, self.left_bucket_cap,
                self.left_table_size,
            ),
            right=self._side_state(
                self.right_schema, self.right_keys, self.right_bucket_cap,
                self.right_table_size,
            ),
            emit_overflow=jnp.zeros((), jnp.int64),
        )

    # ------------------------------------------------------------------
    def _update_side(self, side: SideState, chunk: Chunk,
                     keys: Sequence[Expr]):
        """Apply the chunk's inserts/deletes to this side's multi-map.

        Returns the updated side.
        """
        B = side.occupied.shape[1]
        size = side.key_table.size
        key_cols, null_keys = _null_stripped_keys(
            [e.eval(chunk) for e in keys]
        )
        signs = chunk.signs()
        joinable = chunk.valid if null_keys is None \
            else chunk.valid & ~null_keys
        is_ins = joinable & (signs > 0)
        is_del = joinable & (signs < 0)

        # ---- in-chunk annihilation ------------------------------------
        # a +row and a -row of the same value inside one chunk cancel:
        # the delete pass below only sees *pre-chunk* state, so without
        # this a [-after-+] pair would ghost-insert.  Rows still take
        # part in probing (their +/- matches cancel downstream too).
        row_hash = hash64_columns(list(chunk.columns))
        ins_rank_h = _rank_by(row_hash, is_ins)
        del_rank_h = _rank_by(row_hash, is_del)
        n_ins_h = _group_totals(row_hash, is_ins)
        n_del_h = _group_totals(row_hash, is_del)
        cancelled_ins = is_ins & (ins_rank_h < n_del_h)
        cancelled_del = is_del & (del_rank_h < n_ins_h)
        is_ins = is_ins & ~cancelled_ins
        is_del = is_del & ~cancelled_del

        # ---- key slots: inserts may create, deletes only look up ------
        key_table, slots_ins, _, overflow = side.key_table.lookup_or_insert(
            key_cols, is_ins
        )
        is_ins = is_ins & ~overflow
        slots_del, found_del, probe_over = key_table.lookup_counted(
            key_cols, is_del
        )
        n_missing = jnp.sum((is_del & ~found_del).astype(jnp.int64))
        is_del = is_del & found_del
        safe_ins = jnp.minimum(slots_ins, size - 1)
        safe_del = jnp.minimum(slots_del, size - 1)

        # ---- deletes: clear the rank-th value-equal entry -------------
        # rank among value-equal delete rows: the full row hash is the
        # group key (equal rows share slot AND hash; unequal rows differ
        # in hash w.h.p., and a collision only reorders which duplicate
        # is cleared — harmless for multiset semantics)
        del_rank = _rank_by(row_hash, is_del)
        occ = side.occupied[safe_del]                     # [cap, B]
        bucket_hash = self._bucket_row_hash(side, safe_del)    # [cap, B]
        val_match = occ & (bucket_hash == row_hash[:, None])
        match_rank = jnp.cumsum(val_match, axis=1) - 1    # rank per entry
        clear_onehot = val_match & (match_rank == del_rank[:, None]) & \
            is_del[:, None]
        any_clear = jnp.any(clear_onehot, axis=1)
        n_missing = n_missing + jnp.sum(
            (is_del & ~any_clear).astype(jnp.int64)
        )
        j_clear = jnp.argmax(clear_onehot, axis=1).astype(jnp.int32)
        flat_clear = jnp.where(
            any_clear, safe_del * B + j_clear, jnp.int32(size * B)
        )
        occupied = side.occupied.reshape(-1).at[flat_clear].set(
            False, mode="drop"
        ).reshape(size, B)
        count = side.count.at[
            jnp.where(any_clear, safe_del, jnp.int32(size))
        ].add(-1, mode="drop")

        # ---- inserts: claim rank-th free position ---------------------
        ins_rank = _rank_by(slots_ins.astype(jnp.uint64), is_ins)
        free = ~occupied[safe_ins]                        # [cap, B]
        free_rank = jnp.cumsum(free, axis=1) - 1
        take_onehot = free & (free_rank == ins_rank[:, None]) & \
            is_ins[:, None]
        got = jnp.any(take_onehot, axis=1)
        j_take = jnp.argmax(take_onehot, axis=1).astype(jnp.int32)
        flat_take = jnp.where(
            got, safe_ins * B + j_take, jnp.int32(size * B)
        )
        occupied = occupied.reshape(-1).at[flat_take].set(
            True, mode="drop"
        ).reshape(size, B)
        rows = tuple(
            _scatter_rows(store, flat_take, col)
            for store, col in zip(side.rows, chunk.columns)
        )
        count = count.at[
            jnp.where(got, safe_ins, jnp.int32(size))
        ].add(1, mode="drop")
        n_over = jnp.sum((is_ins & ~got).astype(jnp.int64)) + \
            jnp.sum(overflow.astype(jnp.int64))

        return SideState(
            key_table=key_table,
            rows=rows,
            occupied=occupied,
            count=count,
            overflow=side.overflow + n_over + probe_over,
            inconsistency=side.inconsistency + n_missing,
        )

    def _bucket_row_hash(self, side: SideState, safe_slots) -> jnp.ndarray:
        """Row hashes of a side's buckets gathered at [cap] slots."""

        def flat(g):
            if isinstance(g, NCol):
                return NCol(flat(g.data), g.null.reshape(-1))
            if isinstance(g, StrCol):
                cap, B, w = g.data.shape
                return StrCol(
                    g.data.reshape(cap * B, w), g.lens.reshape(cap * B)
                )
            return g.reshape(-1)

        cols = [flat(_gather_bucket(store, safe_slots))
                for store in side.rows]
        h = hash64_columns(cols)
        cap = safe_slots.shape[0]
        return h.reshape(cap, side.occupied.shape[1])

    # ------------------------------------------------------------------
    def _probe(self, probe_chunk: Chunk, build: SideState,
               probe_is_left: bool, probe_keys: Sequence[Expr]):
        """Emit (probe row × build bucket entry) pairs, compacted."""
        B = build.occupied.shape[1]
        size = build.key_table.size
        out_cap = self.out_capacity
        key_cols, null_keys = _null_stripped_keys(
            [e.eval(probe_chunk) for e in probe_keys]
        )
        probe_valid = probe_chunk.valid if null_keys is None \
            else probe_chunk.valid & ~null_keys
        slots, found, probe_over = build.key_table.lookup_counted(
            key_cols, probe_valid
        )
        safe_slots = jnp.minimum(slots, size - 1)
        occ = build.occupied[safe_slots] & found[:, None]  # [cap, B]

        matches_per_row = jnp.sum(occ, axis=1).astype(jnp.int32)
        row_start = jnp.cumsum(matches_per_row) - matches_per_row
        within = jnp.cumsum(occ, axis=1) - 1               # [cap, B]
        out_pos = row_start[:, None] + within              # [cap, B]
        emit = occ & (out_pos < out_cap)
        flat_pos = jnp.where(emit, out_pos, out_cap).reshape(-1)
        total = row_start[-1] + matches_per_row[-1]
        n_drop = jnp.maximum(total - out_cap, 0).astype(jnp.int64)

        def scatter_probe_col(col):
            # broadcast probe value across its bucket row then compact
            if isinstance(col, NCol):
                cap = col.null.shape[0]
                nb = jnp.broadcast_to(col.null[:, None], (cap, B))
                return NCol(
                    scatter_probe_col(col.data),
                    jnp.zeros((out_cap + 1,), jnp.bool_).at[flat_pos].set(
                        nb.reshape(-1), mode="drop")[:out_cap],
                )
            if isinstance(col, StrCol):
                cap, w = col.data.shape
                d = jnp.broadcast_to(col.data[:, None, :], (cap, B, w))
                l = jnp.broadcast_to(col.lens[:, None], (cap, B))
                return StrCol(
                    jnp.zeros((out_cap + 1, w), jnp.uint8).at[flat_pos].set(
                        d.reshape(cap * B, w), mode="drop")[:out_cap],
                    jnp.zeros((out_cap + 1,), jnp.int32).at[flat_pos].set(
                        l.reshape(-1), mode="drop")[:out_cap],
                )
            cap = col.shape[0]
            v = jnp.broadcast_to(col[:, None], (cap, B))
            return jnp.zeros((out_cap + 1,), col.dtype).at[flat_pos].set(
                v.reshape(-1), mode="drop"
            )[:out_cap]

        def scatter_gathered(g):
            """[cap, B, ...] gathered bucket values -> compacted out."""
            if isinstance(g, NCol):
                return NCol(
                    scatter_gathered(g.data),
                    jnp.zeros((out_cap + 1,), jnp.bool_).at[flat_pos].set(
                        g.null.reshape(-1), mode="drop")[:out_cap],
                )
            if isinstance(g, StrCol):
                cap, Bb, w = g.data.shape
                return StrCol(
                    jnp.zeros((out_cap + 1, w), jnp.uint8).at[flat_pos].set(
                        g.data.reshape(cap * Bb, w), mode="drop")[:out_cap],
                    jnp.zeros((out_cap + 1,), jnp.int32).at[flat_pos].set(
                        g.lens.reshape(-1), mode="drop")[:out_cap],
                )
            cap = g.shape[0]
            return jnp.zeros((out_cap + 1,), g.dtype).at[flat_pos].set(
                g.reshape(-1), mode="drop"
            )[:out_cap]

        def scatter_build_col(store):
            return scatter_gathered(_gather_bucket(store, safe_slots))

        probe_cols = [scatter_probe_col(c) for c in probe_chunk.columns]
        build_cols = [scatter_build_col(s) for s in build.rows]
        out_cols = probe_cols + build_cols if probe_is_left \
            else build_cols + probe_cols

        signs = probe_chunk.signs()
        sign_b = jnp.broadcast_to(signs[:, None], signs.shape + (B,))
        out_sign = jnp.zeros((out_cap + 1,), jnp.int32).at[flat_pos].set(
            sign_b.reshape(-1), mode="drop"
        )[:out_cap]
        ops = jnp.where(out_sign > 0, jnp.int8(0), jnp.int8(1))
        valid = jnp.zeros((out_cap + 1,), jnp.bool_).at[flat_pos].set(
            True, mode="drop"
        )[:out_cap]
        out = Chunk(out_cols, ops, valid, self._out_schema)
        # probe-bound overflow may have hidden real matches: surface it
        # through the same dropped-matches counter so maintenance raises
        # instead of silently missing join output
        return out, n_drop + probe_over

    # ------------------------------------------------------------------
    def apply(self, state: JoinState, chunk: Chunk, side: str):
        """Process one chunk from ``side`` ("left"|"right").

        Order (matching the reference's update-then-probe for correct
        self-consistency): update own side, then probe the other side.
        """
        if side == "left":
            left = self._update_side(state.left, chunk, self.left_keys)
            out, dropped = self._probe(
                chunk, state.right, True, self.left_keys
            )
            return JoinState(
                left, state.right, state.emit_overflow + dropped
            ), out
        right = self._update_side(state.right, chunk, self.right_keys)
        out, dropped = self._probe(
            chunk, state.left, False, self.right_keys
        )
        return JoinState(
            state.left, right, state.emit_overflow + dropped
        ), out

    # ------------------------------------------------------------------
    def maybe_rehash(self, state: JoinState) -> JoinState:
        """Rebuild tombstone-heavy side key tables (runtime maintenance).

        Without this, watermark cleaning would fill the tables with
        unclaimable tombstones and probes would degrade to overflow.
        Traceable: per-side ``lax.cond`` on the device tombstone count."""
        from risingwave_tpu.state.hash_table import permute_dense

        def rebuild(s: SideState) -> SideState:
            fresh, moved = s.key_table.rehashed()
            return SideState(
                key_table=fresh,
                rows=tuple(permute_dense(r, moved) for r in s.rows),
                occupied=permute_dense(s.occupied, moved),
                count=permute_dense(s.count, moved),
                overflow=s.overflow,
                inconsistency=s.inconsistency,
            )

        sides = {}
        for name in ("left", "right"):
            s: SideState = getattr(state, name)
            sides[name] = jax.lax.cond(
                s.key_table.tombstone_count() > s.key_table.size // 4,
                rebuild, lambda x: x, s,
            )
        return JoinState(sides["left"], sides["right"], state.emit_overflow)

    def clean_below(self, state: JoinState, side: str, key_col_idx: int,
                    threshold) -> JoinState:
        """Watermark state cleaning on a window key column (q8 pattern)."""
        s: SideState = getattr(state, side)
        key = s.key_table.key_cols[key_col_idx]
        stale = s.key_table.occupied & (key < threshold)
        cleaned = SideState(
            key_table=s.key_table.clear_where(stale),
            rows=s.rows,
            occupied=s.occupied & ~stale[:, None],
            count=jnp.where(stale, 0, s.count),
            overflow=s.overflow,
            inconsistency=s.inconsistency,
        )
        if side == "left":
            return JoinState(cleaned, state.right, state.emit_overflow)
        return JoinState(state.left, cleaned, state.emit_overflow)
