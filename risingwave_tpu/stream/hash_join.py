"""Streaming hash join — the full matrix: inner / left / right / full
outer / semi / anti — with device-resident two-sided state.

Reference counterpart: ``HashJoinExecutor`` (src/stream/src/executor/
hash_join.rs:158, 6 join types via const-generic ``JoinTypePrimitive``)
with ``JoinHashMap`` state+degree tables (join/hash_join.rs:169) and
the probe loop ``eq_join_oneside`` (hash_join.rs:949).

TPU-first design
----------------
Each side's state is a *bucketed multi-map* in HBM:

- ``key_table``: HashTable over the join key — one slot per distinct key;
- ``rows``:     per-column ``[size, bucket_cap]`` dense stores;
- ``occupied``: ``bool [size, bucket_cap]``;
- ``count``:    ``int32 [size]`` live rows per key.

A chunk applies as a handful of gathers/scatters over the whole chunk
(vs the reference's per-row HashMap + Vec walk): inserts claim free
bucket positions by rank-among-equal-keys, deletes match value-equal
entries by rank (row-hash disambiguated) and clear them.

**Degrees are per-KEY, not per-row** (unlike the reference's degree
table): a stored row's degree — its number of matches on the other
side — is fully determined by its join key, so the other side's
``count[slot]`` IS the degree.  Outer/semi/anti transitions fall out of
comparing a key's own-side count before/after a chunk: 0→n retracts the
NULL-padded (or emits the semi / retracts the anti) rows, n→0 restores
them.  No extra state.

**Emission is output-centric and windowed**: instead of materializing
the (probe-row × bucket-entry) grid and compacting it (O(cap×B) per
chunk), every output slot *gathers* its source via searchsorted over
per-row prefix sums — O(out_capacity) regardless of bucket depth.  One
logical emission space [pairs | self-rows | transition-rows] is cut
into fixed out_capacity windows; ``emit_window(pending, w)`` produces
window ``w``, so the runtime drains arbitrarily amplified joins without
dropping matches (``DagJob`` loops windows on device; the plain
``apply`` emits window 0 and counts the remainder as emit_overflow).

U-pair note: a key's transition emits UPDATE_DELETE/UPDATE_INSERT op
codes, but pads land in the transitions section rather than physically
adjacent to their replacement pair — every consumer in this codebase is
slot-keyed or sign-based, so only the op *codes* carry the pairing.

State cleaning for window joins (Nexmark q8) is the same vectorized
sweep as hash_agg's ``clean_below``.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, NCol, StrCol, split_col
from risingwave_tpu.common.hash import hash64_columns


def _null_stripped_keys(key_cols):
    """(bare key cols, any-key-null mask | None).

    SQL join equality: NULL matches nothing (unlike grouping equality),
    so rows with a NULL key are masked out of both updates and probes
    and the stored key columns stay bare arrays."""
    null_any = None
    bare = []
    for c in key_cols:
        d, n = split_col(c)
        bare.append(d)
        if n is not None:
            null_any = n if null_any is None else (null_any | n)
    return bare, null_any
from risingwave_tpu.common.compact import mask_indices
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.state.hash_table import (
    HashTable,
    TagTable,
    _scatter_key,
    gather_key,
)


def _empty_store(f: Field, size: int, bucket: int):
    if f.data_type.is_string:
        col = StrCol(
            jnp.zeros((size, bucket, f.str_width), jnp.uint8),
            jnp.zeros((size, bucket), jnp.int32),
        )
    else:
        col = jnp.zeros((size, bucket), f.data_type.physical_dtype)
    if f.nullable:
        return NCol(col, jnp.zeros((size, bucket), jnp.bool_))
    return col


def _pool_capacity(rows: tuple) -> int:
    """Row capacity of a pool side's flat stores (static shape)."""
    store = rows[0]
    while isinstance(store, NCol):
        store = store.data
    if isinstance(store, StrCol):
        return store.lens.shape[0]
    return store.shape[0]


def _gather_bucket(store, slots):
    """[size, B, ...] gathered at [cap] slots -> [cap, B, ...]."""
    if isinstance(store, NCol):
        return NCol(_gather_bucket(store.data, slots), store.null[slots])
    if isinstance(store, StrCol):
        return StrCol(store.data[slots], store.lens[slots])
    return store[slots]


def _scatter_rows(store, pos, col):
    """Write row values col[[cap]] at flat positions pos[[cap]] into the
    flattened [size*B, ...] view of the store."""
    if isinstance(store, NCol):
        null_flat = store.null.reshape(-1).at[pos].set(
            col.null, mode="drop"
        ).reshape(store.null.shape)
        return NCol(_scatter_rows(store.data, pos, col.data), null_flat)
    if isinstance(store, StrCol):
        flat_d = store.data.reshape((-1,) + store.data.shape[2:])
        flat_l = store.lens.reshape((-1,))
        flat_d = flat_d.at[pos].set(col.data, mode="drop")
        flat_l = flat_l.at[pos].set(col.lens, mode="drop")
        return StrCol(
            flat_d.reshape(store.data.shape), flat_l.reshape(store.lens.shape)
        )
    flat = store.reshape((-1,) + store.shape[2:])
    flat = flat.at[pos].set(col, mode="drop")
    return flat.reshape(store.shape)


def _rank_by(group: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """Stable rank of each active row among rows with equal ``group``."""
    rank, _, _ = _rank_by_sorted(group, active)
    return rank


def _rank_by_sorted(group: jnp.ndarray, active: jnp.ndarray):
    """``_rank_by`` that also returns its sort artifacts ``(rank,
    order, seg_id)`` so callers can derive further per-group reductions
    (``_totals_from_sort``) without paying a second argsort — the
    chunk-sized sort is a fixed per-chunk cost worth amortizing."""
    cap = group.shape[0]
    key = jnp.where(active, group, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_new, jnp.arange(cap, dtype=jnp.int32), 0)
    )
    rank_sorted = jnp.arange(cap, dtype=jnp.int32) - start
    seg_id = jnp.cumsum(is_new) - 1
    rank = jnp.zeros((cap,), jnp.int32).at[order].set(rank_sorted)
    return rank, order, seg_id


def _totals_from_sort(order, seg_id, values) -> jnp.ndarray:
    """Per-row group total of ``values`` using a prior
    ``_rank_by_sorted`` decomposition (no second sort)."""
    cap = order.shape[0]
    sums = jax.ops.segment_sum(
        values[order].astype(jnp.int32), seg_id, num_segments=cap
    )
    totals_sorted = sums[seg_id]
    return jnp.zeros((cap,), jnp.int32).at[order].set(totals_sorted)


def _group_totals(group: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """Per-row sum of ``values`` over rows sharing the same ``group``."""
    cap = group.shape[0]
    order = jnp.argsort(group, stable=True)
    sorted_g = group[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_g[1:] != sorted_g[:-1]]
    )
    seg_id = jnp.cumsum(is_new) - 1
    sums = jax.ops.segment_sum(
        values[order].astype(jnp.int32), seg_id, num_segments=cap
    )
    totals_sorted = sums[seg_id]
    return jnp.zeros((cap,), jnp.int32).at[order].set(totals_sorted)


class SideState(NamedTuple):
    key_table: HashTable
    rows: tuple          # [size, B] stores, one per input column
    occupied: jnp.ndarray  # bool [size, B]
    count: jnp.ndarray     # int32 [size]
    overflow: jnp.ndarray  # int64 — rows that found no bucket space
    #: deletes with no matching stored row (ref consistency_error!)
    inconsistency: jnp.ndarray


class PoolSideState(NamedTuple):
    """Degree-adaptive side storage: ONE fused ``(key-hash, rank)``
    table over a bump-allocated shared row pool.

    The reference stores unbounded rows per key behind ``JoinHashMap``
    (src/stream/src/executor/join/hash_join.rs:169); dense
    ``[size, bucket_cap]`` buckets cap hot keys (nexmark's hot sellers)
    and waste HBM on cold ones.  TPU-first re-design (round-6 fusion of
    the former key table + rank index pair): the rank-r row of key k
    owns the open-addressed entry for ``(hash(k), r)``, and the key's
    rank-0 entry doubles as its HEAD — the per-key degree counter
    ``count`` lives at the head slot.  Properties:

    - ONE ``lookup_or_insert`` per chunk: the fused two-phase probe
      (``HashTable.lookup_or_insert_ranked``) resolves head + target in
      a single loop, where the old layout paid a key-table pass AND a
      rank-index pass into separate 2^22-entry tables (the q8
      attribution's dominant cost);
    - no per-key cap: a hot key may fill the whole pool;
    - O(1) vectorized random access by (key, rank) — exactly what the
      output-centric windowed emission gathers — with no chain walks
      (pointer chasing is TPU-hostile);
    - pool rows claim CONTIGUOUS positions per chunk (``pool_len`` +
      prefix-sum offsets, a bump allocator): the row-store scatters hit
      a dense window instead of spraying the whole multi-M-row pool
      (locality), and maintenance compacts dead rows wholesale;
    - watermark cleaning via a per-slot ``slot_clean`` copy of the
      window key: closed windows tombstone by ONE vectorized mask, and
      their pool rows are reclaimed by the next compaction.

    Append-only sides only (the bench/windowed-join shape): deletes
    would need value→rank search; retractable sides keep the dense
    bucket layout.
    """

    table: TagTable        # packed (key-hash, rank) tags -> entry slot
    count: jnp.ndarray     # int32 [size] key degree, kept at its head
    pool_pos: jnp.ndarray  # int32 [size] entry slot -> pool position
    slot_clean: jnp.ndarray  # int64 [size] watermark-cleaning key value
    rows: tuple            # [pool] stores, one per input column
    pool_len: jnp.ndarray  # int32 () bump-allocator cursor
    overflow: jnp.ndarray  # int64 — rows that found no table/pool space
    inconsistency: jnp.ndarray  # int64 — retractions on append-only side


class JoinState(NamedTuple):
    left: SideState
    right: SideState
    emit_overflow: jnp.ndarray  # int64 — matches dropped by out capacity
    # -- observability counters (device scalars; exported as Prometheus
    # -- gauges by Engine.collect_join_metrics, never read in the hot
    # -- loop) ---------------------------------------------------------
    chunks: jnp.ndarray        # int64 — probe chunks applied
    probe_iters: jnp.ndarray   # int64 — fused update-probe loop trips
    emit_rows: jnp.ndarray     # int64 — staged emission rows (all wins)
    emit_windows: jnp.ndarray  # int64 — emission windows drained


class JoinEmit(NamedTuple):
    """One chunk's staged emission space (all device arrays; light
    enough to ride a ``lax.while_loop`` carry).

    The logical emission array is ordered
    ``[up-transitions | pairs | self rows | down-transitions]`` —
    a key's first match retracts its pads BEFORE the replacement pairs
    land, and its last unmatch deletes the pairs BEFORE the pads
    return.  The order matters downstream: a projection may collapse a
    pad row and a pair row to identical values, and slot-keyed
    materialization resolves same-slot conflicts by LAST op in row
    order (the reference's U-pair adjacency contract, expressed as
    section order).  ``emit_window`` gathers any out_capacity-sized
    window of it.
    """

    probe_cols: tuple        # the probe chunk's columns
    signs: jnp.ndarray       # int32 [cap]
    slots: jnp.ndarray       # int32 [cap] clamped build-side key slots
    rank_to_idx: jnp.ndarray  # int32 [cap, B] k-th live row -> bucket idx
    #: probe rows' join-key hashes (pool build sides: the emission
    #: addresses build rows by (key-hash, rank) index lookups)
    probe_hash: jnp.ndarray  # uint64 [cap]
    m: jnp.ndarray           # int32 [cap] live build rows per probe row
    up_cnt: jnp.ndarray      # int32 [cap] up-transition rows per probe row
    up_end: jnp.ndarray      # int32 [cap] inclusive cumsum
    U: jnp.ndarray           # int32 total up-transition rows
    pair_end: jnp.ndarray    # int32 [cap] inclusive cumsum of pair counts
    P: jnp.ndarray           # int32 total pairs
    self_sel: jnp.ndarray    # int32 [cap] compacted self-row indices
    S: jnp.ndarray           # int32 total self rows
    down_cnt: jnp.ndarray    # int32 [cap] down-transition rows per row
    down_end: jnp.ndarray    # int32 [cap] inclusive cumsum
    total: jnp.ndarray       # int32 U + P + S + D


#: the join matrix (ref hash_join.rs JoinTypePrimitive + semi/anti)
JOIN_TYPES = (
    "inner", "left_outer", "right_outer", "full_outer",
    "left_semi", "left_anti", "right_semi", "right_anti",
)


class HashJoinExecutor:
    """Equi-join of two changelog streams (full join-type matrix).

    Not a linear-``Fragment`` executor: it has two inputs.  The DAG
    runtime calls ``apply(state, chunk, side)`` (single-window) or the
    windowed pair ``apply_begin`` / ``emit_window``.  Output schema is
    left ++ right columns (NULL-padded side nullable) for inner/outer,
    or the preserved side alone for semi/anti.
    """

    def __init__(
        self,
        left_schema: Schema,
        right_schema: Schema,
        left_keys: Sequence[Expr],
        right_keys: Sequence[Expr],
        table_size: int = 1 << 14,
        bucket_cap: int = 16,
        out_capacity: int = 16384,
        left_bucket_cap: int | None = None,
        right_bucket_cap: int | None = None,
        left_table_size: int | None = None,
        right_table_size: int | None = None,
        join_type: str = "inner",
        left_storage: str = "dense",
        right_storage: str = "dense",
        left_pool_size: int | None = None,
        right_pool_size: int | None = None,
    ):
        if join_type not in JOIN_TYPES:
            raise ValueError(f"unknown join type {join_type!r}")
        self.join_type = join_type
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.table_size = table_size
        # per-side bucket depth: size for the max rows per join key on
        # that side (hot-key skew, e.g. nexmark's hot sellers, needs a
        # deep build side while a unique-keyed side stays shallow)
        self.left_bucket_cap = left_bucket_cap or bucket_cap
        self.right_bucket_cap = right_bucket_cap or bucket_cap
        # per-side key-table sizes: a unique-keyed side wants many slots
        # and shallow buckets; a hot-keyed side the opposite
        self.left_table_size = left_table_size or table_size
        self.right_table_size = right_table_size or table_size
        self.out_capacity = out_capacity
        #: per-side storage: "dense" [size, B] buckets (general; caps
        #: hot keys) or "pool" shared-row-pool (degree-adaptive;
        #: append-only sides)
        if left_storage not in ("dense", "pool") \
                or right_storage not in ("dense", "pool"):
            raise ValueError("storage must be 'dense' or 'pool'")
        self.left_storage = left_storage
        self.right_storage = right_storage
        self.left_pool_size = left_pool_size or (
            self.left_table_size * self.left_bucket_cap
        )
        self.right_pool_size = right_pool_size or (
            self.right_table_size * self.right_bucket_cap
        )
        #: preserved sides: rows survive unmatched (as NULL-padded rows
        #: for outer, as the output itself for semi, inverted for anti)
        self.preserve_left = join_type in (
            "left_outer", "full_outer", "left_semi", "left_anti"
        )
        self.preserve_right = join_type in (
            "right_outer", "full_outer", "right_semi", "right_anti"
        )
        self.is_semi = join_type.endswith("_semi")
        self.is_anti = join_type.endswith("_anti")
        #: inner/outer emit (probe × build) pairs; semi/anti never do
        self.emit_pairs = not (self.is_semi or self.is_anti)
        if self.emit_pairs:
            left_out = left_schema if not self.preserve_right else Schema(
                tuple(f.with_nullable() for f in left_schema)
            )
            right_out = right_schema if not self.preserve_left else Schema(
                tuple(f.with_nullable() for f in right_schema)
            )
            self._out_schema = left_out.concat(right_out)
        else:
            self._out_schema = left_schema if self.preserve_left \
                else right_schema
        #: per-side watermark cleaning: (key_idx, lag_us, src_col) —
        #: at barriers the runtime evicts keys whose key_idx-th join key
        #: < watermark(src_col) - lag (windowed joins, nexmark q8)
        self.left_clean: tuple[int, int, int] | None = None
        self.right_clean: tuple[int, int, int] | None = None

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def _preserved(self, side: str) -> bool:
        return self.preserve_left if side == "left" else self.preserve_right

    # ------------------------------------------------------------------
    def _key_protos(self, schema: Schema, keys: Sequence[Expr]):
        protos = []
        for e in keys:
            f = e.return_field(schema)
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        return protos

    def _side_state(self, schema: Schema, keys: Sequence[Expr],
                    bucket: int, size: int) -> SideState:
        return SideState(
            key_table=HashTable.create(
                self._key_protos(schema, keys), size
            ),
            rows=tuple(_empty_store(f, size, bucket) for f in schema),
            occupied=jnp.zeros((size, bucket), jnp.bool_),
            count=jnp.zeros((size,), jnp.int32),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
        )

    def _pool_side_state(self, schema: Schema, keys: Sequence[Expr],
                         size: int, pool: int) -> PoolSideState:
        def flat_store(f: Field):
            if f.data_type.is_string:
                col = StrCol(
                    jnp.zeros((pool, f.str_width), jnp.uint8),
                    jnp.zeros((pool,), jnp.int32),
                )
            else:
                col = jnp.zeros((pool,), f.data_type.physical_dtype)
            if f.nullable:
                return NCol(col, jnp.zeros((pool,), jnp.bool_))
            return col

        # ONE fused tag table sized for the pool: total live entries ==
        # live pool rows (a key's head IS its rank-0 entry), so the
        # load factor matches the old rank index — and the old
        # key-value key table is gone entirely
        return PoolSideState(
            table=TagTable.create(pool),
            count=jnp.zeros((pool,), jnp.int32),
            pool_pos=jnp.zeros((pool,), jnp.int32),
            slot_clean=jnp.zeros((pool,), jnp.int64),
            rows=tuple(flat_store(f) for f in schema),
            pool_len=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
        )

    def storage_of(self, side: str) -> str:
        return self.left_storage if side == "left" else self.right_storage

    def init_state(self) -> JoinState:
        if self.left_storage == "pool":
            left = self._pool_side_state(
                self.left_schema, self.left_keys,
                self.left_table_size, self.left_pool_size,
            )
        else:
            left = self._side_state(
                self.left_schema, self.left_keys, self.left_bucket_cap,
                self.left_table_size,
            )
        if self.right_storage == "pool":
            right = self._pool_side_state(
                self.right_schema, self.right_keys,
                self.right_table_size, self.right_pool_size,
            )
        else:
            right = self._side_state(
                self.right_schema, self.right_keys, self.right_bucket_cap,
                self.right_table_size,
            )
        return JoinState(
            left=left, right=right,
            emit_overflow=jnp.zeros((), jnp.int64),
            chunks=jnp.zeros((), jnp.int64),
            probe_iters=jnp.zeros((), jnp.int64),
            emit_rows=jnp.zeros((), jnp.int64),
            emit_windows=jnp.zeros((), jnp.int64),
        )

    # ------------------------------------------------------------------
    def _update_side(self, side: SideState, chunk: Chunk,
                     keys: Sequence[Expr]):
        """Apply the chunk's inserts/deletes to this side's multi-map.

        Returns the updated side.
        """
        B = side.occupied.shape[1]
        size = side.key_table.size
        key_cols, null_keys = _null_stripped_keys(
            [e.eval(chunk) for e in keys]
        )
        signs = chunk.signs()
        joinable = chunk.valid if null_keys is None \
            else chunk.valid & ~null_keys
        is_ins = joinable & (signs > 0)
        is_del = joinable & (signs < 0)

        # ---- in-chunk annihilation ------------------------------------
        # a +row and a -row of the same value inside one chunk cancel:
        # the delete pass below only sees *pre-chunk* state, so without
        # this a [-after-+] pair would ghost-insert.  Rows still take
        # part in probing (their +/- matches cancel downstream too).
        row_hash = hash64_columns(list(chunk.columns))
        ins_rank_h = _rank_by(row_hash, is_ins)
        del_rank_h = _rank_by(row_hash, is_del)
        n_ins_h = _group_totals(row_hash, is_ins)
        n_del_h = _group_totals(row_hash, is_del)
        cancelled_ins = is_ins & (ins_rank_h < n_del_h)
        cancelled_del = is_del & (del_rank_h < n_ins_h)
        is_ins = is_ins & ~cancelled_ins
        is_del = is_del & ~cancelled_del

        # ---- key slots: inserts may create, deletes only look up ------
        key_table, slots_ins, _, overflow = side.key_table.lookup_or_insert(
            key_cols, is_ins
        )
        is_ins = is_ins & ~overflow
        slots_del, found_del, probe_over = key_table.lookup_counted(
            key_cols, is_del
        )
        n_missing = jnp.sum((is_del & ~found_del).astype(jnp.int64))
        is_del = is_del & found_del
        safe_ins = jnp.minimum(slots_ins, size - 1)
        safe_del = jnp.minimum(slots_del, size - 1)

        # ---- deletes: clear the rank-th value-equal entry -------------
        # rank among value-equal delete rows: the full row hash is the
        # group key (equal rows share slot AND hash; unequal rows differ
        # in hash w.h.p., and a collision only reorders which duplicate
        # is cleared — harmless for multiset semantics)
        del_rank = _rank_by(row_hash, is_del)
        occ = side.occupied[safe_del]                     # [cap, B]
        bucket_hash = self._bucket_row_hash(side, safe_del)    # [cap, B]
        val_match = occ & (bucket_hash == row_hash[:, None])
        match_rank = jnp.cumsum(val_match, axis=1) - 1    # rank per entry
        clear_onehot = val_match & (match_rank == del_rank[:, None]) & \
            is_del[:, None]
        any_clear = jnp.any(clear_onehot, axis=1)
        n_missing = n_missing + jnp.sum(
            (is_del & ~any_clear).astype(jnp.int64)
        )
        j_clear = jnp.argmax(clear_onehot, axis=1).astype(jnp.int32)
        flat_clear = jnp.where(
            any_clear, safe_del * B + j_clear, jnp.int32(size * B)
        )
        occupied = side.occupied.reshape(-1).at[flat_clear].set(
            False, mode="drop"
        ).reshape(size, B)
        count = side.count.at[
            jnp.where(any_clear, safe_del, jnp.int32(size))
        ].add(-1, mode="drop")

        # ---- inserts: claim rank-th free position ---------------------
        ins_rank = _rank_by(slots_ins.astype(jnp.uint64), is_ins)
        free = ~occupied[safe_ins]                        # [cap, B]
        free_rank = jnp.cumsum(free, axis=1) - 1
        take_onehot = free & (free_rank == ins_rank[:, None]) & \
            is_ins[:, None]
        got = jnp.any(take_onehot, axis=1)
        j_take = jnp.argmax(take_onehot, axis=1).astype(jnp.int32)
        flat_take = jnp.where(
            got, safe_ins * B + j_take, jnp.int32(size * B)
        )
        occupied = occupied.reshape(-1).at[flat_take].set(
            True, mode="drop"
        ).reshape(size, B)
        rows = tuple(
            _scatter_rows(store, flat_take, col)
            for store, col in zip(side.rows, chunk.columns)
        )
        count = count.at[
            jnp.where(got, safe_ins, jnp.int32(size))
        ].add(1, mode="drop")
        n_over = jnp.sum((is_ins & ~got).astype(jnp.int64)) + \
            jnp.sum(overflow.astype(jnp.int64))

        return SideState(
            key_table=key_table,
            rows=rows,
            occupied=occupied,
            count=count,
            overflow=side.overflow + n_over + probe_over,
            inconsistency=side.inconsistency + n_missing,
        )

    def _update_side_pool(self, side: PoolSideState, chunk: Chunk,
                          keys: Sequence[Expr], clean_spec,
                          key_cols=None, null_keys=None, h=None):
        """Apply an append-only chunk to a pool side with ONE fused
        (key-hash, rank) probe: each row resolves its key's head,
        learns the pre-chunk degree, and claims the entry for
        ``(hash, degree + in-chunk rank)`` in a single loop; pool rows
        then take bump-allocated contiguous positions.

        Ranks stay contiguous per key (cleaning removes whole keys
        only), so the emission's (key, j) addressing always lands.

        ``key_cols``/``null_keys``/``h`` accept the caller's already-
        computed values (apply_begin hashes the same chunk for its
        probe pass).

        Returns ``(new_side, probe_iters int32)``."""
        size = side.table.size
        pool = _pool_capacity(side.rows)
        if key_cols is None:
            key_cols, null_keys = _null_stripped_keys(
                [e.eval(chunk) for e in keys]
            )
        signs = chunk.signs()
        joinable = chunk.valid if null_keys is None \
            else chunk.valid & ~null_keys
        is_ins = joinable & (signs > 0)
        # append-only contract: retractions are a loud inconsistency
        n_bad = jnp.sum((joinable & (signs < 0)).astype(jnp.int64))

        if h is None:
            h = hash64_columns(key_cols)
        cr, sort_order, sort_seg = _rank_by_sorted(h, is_ins)
        (table, slots, _, head_slot, inserted, existed, over,
         iters) = side.table.lookup_or_insert_ranked(
            h, cr, side.count, is_ins
        )
        got = is_ins & ~over
        # a target entry that already existed means a prior overflow
        # stranded it while count stalled: this insert overwrites that
        # live pool row.  Count it so maintenance fails loudly instead
        # of silently losing a row.
        n_overwrite = jnp.sum((got & existed).astype(jnp.int64))

        # -- bump allocator: accepted rows take consecutive positions --
        offs = jnp.cumsum(got, dtype=jnp.int32) - 1
        pos = side.pool_len + offs
        fits = pos < pool
        dropped = got & ~fits
        # un-claim entries whose row found no pool space (loud overflow)
        table = table.clear_slots(slots, dropped & inserted)
        got = got & fits
        tgt = jnp.where(got, pos, jnp.int32(pool))
        rows = tuple(
            _scatter_key(store, tgt, col, pool)
            for store, col in zip(side.rows, chunk.columns)
        )
        safe_slot = jnp.minimum(slots, size - 1)
        spos = jnp.where(got, safe_slot, jnp.int32(size))
        pool_pos = side.pool_pos.at[spos].set(tgt, mode="drop")
        if clean_spec is not None:
            ckey = key_cols[clean_spec[0]].astype(jnp.int64)
            slot_clean = side.slot_clean.at[spos].set(ckey, mode="drop")
        else:
            slot_clean = side.slot_clean
        # degree update: each key's rank-0 row (which always knows the
        # head slot) scatters the key's accepted-insert total — every
        # probe above saw the PRE-chunk degree.  Totals reuse the rank
        # sort's decomposition: no second argsort.
        rep = got & (cr == 0) & (head_slot < size)
        key_tot = _totals_from_sort(sort_order, sort_seg, got)
        count = side.count.at[
            jnp.where(rep, head_slot, jnp.int32(size))
        ].add(jnp.where(rep, key_tot, 0), mode="drop")
        pool_len = side.pool_len + jnp.sum(got, dtype=jnp.int32)
        n_over = jnp.sum((is_ins & over).astype(jnp.int64)) + \
            jnp.sum(dropped.astype(jnp.int64)) + n_overwrite
        return PoolSideState(
            table=table,
            count=count,
            pool_pos=pool_pos,
            slot_clean=slot_clean,
            rows=rows,
            pool_len=pool_len,
            overflow=side.overflow + n_over,
            inconsistency=side.inconsistency + n_bad,
        ), iters

    def _bucket_row_hash(self, side: SideState, safe_slots) -> jnp.ndarray:
        """Row hashes of a side's buckets gathered at [cap] slots."""

        def flat(g):
            if isinstance(g, NCol):
                return NCol(flat(g.data), g.null.reshape(-1))
            if isinstance(g, StrCol):
                cap, B, w = g.data.shape
                return StrCol(
                    g.data.reshape(cap * B, w), g.lens.reshape(cap * B)
                )
            return g.reshape(-1)

        cols = [flat(_gather_bucket(store, safe_slots))
                for store in side.rows]
        h = hash64_columns(cols)
        cap = safe_slots.shape[0]
        return h.reshape(cap, side.occupied.shape[1])

    # -- output-centric windowed emission --------------------------------
    def apply_begin(self, state: JoinState, chunk: Chunk, side: str):
        """Update own-side state and stage the emission space.

        Returns (state, pending): ``pending`` describes one logical
        emission array [pairs | self rows | transition rows]; windows
        of it are produced by ``emit_window`` — O(out_capacity) gathers
        each, independent of bucket depth.
        """
        own = state.left if side == "left" else state.right
        other = state.right if side == "left" else state.left
        keys = self.left_keys if side == "left" else self.right_keys
        cap = chunk.capacity

        old_count = own.count  # own per-key row counts BEFORE the chunk
        own_clean = self.left_clean if side == "left" else self.right_clean
        key_cols, null_keys = _null_stripped_keys(
            [e.eval(chunk) for e in keys]
        )
        probe_hash = hash64_columns(key_cols)
        upd_iters = jnp.zeros((), jnp.int32)
        if self.storage_of(side) == "pool":
            own2, upd_iters = self._update_side_pool(
                own, chunk, keys, own_clean,
                key_cols=key_cols, null_keys=null_keys, h=probe_hash,
            )
        else:
            own2 = self._update_side(own, chunk, keys)

        signs = chunk.signs()
        active = chunk.valid & (signs != 0)
        joinable = active if null_keys is None else active & ~null_keys

        # probe the build (other) side: per-row key slot + live rows
        if self.storage_of("right" if side == "left" else "left") \
                == "pool":
            # pool build side: ONE fused-table probe of the key's HEAD
            # entry (hash, 0) yields its degree; rows are addressed at
            # emission time by (key-hash, rank)
            bsize = other.table.size
            slots, found, probe_over = other.table.lookup_pair_counted(
                probe_hash, jnp.zeros((cap,), jnp.int32), joinable
            )
            safe = jnp.minimum(slots, bsize - 1)
            m = jnp.where(found, other.count[safe], 0).astype(jnp.int32)
            rank_to_idx = jnp.zeros((cap, 1), jnp.int32)
        else:
            bsize = other.key_table.size
            slots, found, probe_over = other.key_table.lookup_counted(
                key_cols, joinable, hashes=probe_hash
            )
            safe = jnp.minimum(slots, bsize - 1)
            occ = other.occupied[safe] & found[:, None]        # [cap, B]
            m = jnp.sum(occ, axis=1).astype(jnp.int32)
            # rank -> bucket index of the k-th live row (occupied
            # first, stable: bool sort of the occupancy bitmap only)
            rank_to_idx = jnp.argsort(~occ, axis=1, stable=True) \
                .astype(jnp.int32)

        # section 1: (probe × build) pairs
        pair_cnt = m if self.emit_pairs else jnp.zeros_like(m)
        pair_end = jnp.cumsum(pair_cnt)
        P = pair_end[-1]

        # section 2: self rows (A preserved: pads for outer, the row
        # itself for semi/anti).  NULL-key rows match nothing, so they
        # count as zero-match rows here — SQL outer/anti semantics.
        if self._preserved(side):
            if self.is_semi:
                self_mask = active & (m > 0)
            else:  # outer pad or anti
                self_mask = active & (m == 0)
        else:
            self_mask = jnp.zeros((cap,), jnp.bool_)
        self_sel = mask_indices(self_mask, cap, cap)
        S = jnp.sum(self_mask).astype(jnp.int32)

        # section 3: transitions of the OTHER side's stored rows.  A
        # stored row's degree is its key's count on THIS side, so the
        # chunk flips other-side rows exactly when a key's own count
        # crosses 0 (ref: degree table 0<->1 transitions).
        other_pres = self._preserved(
            "right" if side == "left" else "left"
        )
        if other_pres:
            if self.storage_of(side) == "pool":
                oslots, ofound, _ = own2.table.lookup_pair_counted(
                    probe_hash, jnp.zeros((cap,), jnp.int32), joinable
                )
                osafe = jnp.minimum(oslots, own2.table.size - 1)
            else:
                oslots, ofound, _ = own2.key_table.lookup_counted(
                    key_cols, joinable
                )
                osafe = jnp.minimum(oslots, own2.key_table.size - 1)
            oldc = old_count[osafe]
            newc = own2.count[osafe]
            eligible = joinable & ofound
            up = eligible & (oldc == 0) & (newc > 0)
            down = eligible & (oldc > 0) & (newc == 0)
            first = _rank_by(oslots.astype(jnp.uint64), up | down) == 0
            up_cnt = jnp.where(up & first, m, 0)
            down_cnt = jnp.where(down & first, m, 0)
        else:
            up_cnt = jnp.zeros((cap,), jnp.int32)
            down_cnt = jnp.zeros((cap,), jnp.int32)
        up_end = jnp.cumsum(up_cnt)
        U = up_end[-1]
        down_end = jnp.cumsum(down_cnt)
        D = down_end[-1]

        pending = JoinEmit(
            probe_cols=chunk.columns,
            signs=signs,
            slots=safe,
            rank_to_idx=rank_to_idx,
            probe_hash=probe_hash,
            m=m,
            up_cnt=up_cnt,
            up_end=up_end,
            U=U,
            pair_end=pair_end,
            P=P,
            self_sel=self_sel,
            S=S,
            down_cnt=down_cnt,
            down_end=down_end,
            total=U + P + S + D,
        )
        total = U + P + S + D
        new_state = JoinState(
            left=own2 if side == "left" else state.left,
            right=own2 if side == "right" else state.right,
            emit_overflow=state.emit_overflow
            + probe_over.astype(jnp.int64),
            chunks=state.chunks + 1,
            probe_iters=state.probe_iters + upd_iters.astype(jnp.int64),
            emit_rows=state.emit_rows + total.astype(jnp.int64),
            # window 0 always materializes; amplified chunks drain
            # ceil(total / out_capacity) windows
            emit_windows=state.emit_windows + jnp.maximum(
                (total + self.out_capacity - 1) // self.out_capacity, 1
            ).astype(jnp.int64),
        )
        return new_state, pending

    def emit_window(self, build_rows: tuple, p: JoinEmit, w,
                    side: str):
        """Materialize window ``w`` of the pending emission space.

        ``build_rows`` is the build (non-arriving) side's row stores —
        taken from the CURRENT state so the while_loop carry holds the
        stores once, not per-window copies.

        Returns ``(chunk, probe_bound int64)``: the second value counts
        build-index probes that exhausted the probe-iteration bound —
        rows whose presence is then UNKNOWN and which are dropped from
        the output; callers must fold it into ``emit_overflow`` so
        maintenance fails loudly (hash_table.lookup_counted contract)."""
        out_cap = self.out_capacity
        cap = p.signs.shape[0]
        gpos = w * out_cap + jnp.arange(out_cap, dtype=jnp.int32)
        valid_out = gpos < p.total
        # section layout: [up-transitions | pairs | self | down-trans]
        in_up = valid_out & (gpos < p.U)
        ppos = gpos - p.U
        in_pairs = valid_out & (gpos >= p.U) & (ppos < p.P)
        spos = ppos - p.P
        in_self = valid_out & (ppos >= p.P) & (spos < p.S)
        dpos = spos - p.S
        in_down = valid_out & (spos >= p.S)
        in_trans = in_up | in_down

        def decode(end, cnt, pos):
            """row index + within-row offset for a cumsum section."""
            r_ = jnp.minimum(
                jnp.searchsorted(end, pos, side="right"), cap - 1
            ).astype(jnp.int32)
            return r_, pos - (end[r_] - cnt[r_])

        pair_cnt = p.m if self.emit_pairs else jnp.zeros_like(p.m)
        ur, uj = decode(p.up_end, p.up_cnt, gpos)
        pr, pj = decode(p.pair_end, pair_cnt, ppos)
        sr = p.self_sel[jnp.clip(spos, 0, cap - 1)]
        dr, dj = decode(p.down_end, p.down_cnt, dpos)

        r = jnp.where(in_up, ur,
                      jnp.where(in_pairs, pr,
                                jnp.where(in_self, sr, dr)))
        j = jnp.where(in_up, uj,
                      jnp.where(in_pairs, pj,
                                jnp.where(in_down, dj, 0)))
        slot = p.slots[r]

        def probe_val(col):
            return gather_key(col, r)

        build_rows, build_index = build_rows
        probe_bound = jnp.int64(0)
        if build_index is not None:
            # pool build side: ONE vectorized (key-hash, rank) fused-
            # table lookup resolves every build row this window needs;
            # the entry's pool_pos value addresses the bump-allocated
            # row store
            btable, bpool_pos = build_index
            need = in_pairs | in_trans
            pool = _pool_capacity(build_rows)
            bslot, bfound, probe_bound = btable.lookup_pair_counted(
                p.probe_hash[r], j.astype(jnp.int32), need
            )
            bpos = jnp.clip(
                bpool_pos[jnp.minimum(bslot, btable.size - 1)],
                0, pool - 1,
            )
            # a needed-but-missing build row (pool overflow hole) is
            # dropped; the overflow counter already records the loss
            valid_out = valid_out & (~need | bfound)

            def build_val(store):
                if isinstance(store, NCol):
                    return NCol(build_val(store.data), store.null[bpos])
                if isinstance(store, StrCol):
                    return StrCol(store.data[bpos], store.lens[bpos])
                return store[bpos]
        else:
            bidx = p.rank_to_idx[
                r, jnp.clip(j, 0, p.rank_to_idx.shape[1] - 1)
            ]

            def build_val(store):
                if isinstance(store, NCol):
                    return NCol(
                        build_val(store.data), store.null[slot, bidx]
                    )
                if isinstance(store, StrCol):
                    return StrCol(
                        store.data[slot, bidx], store.lens[slot, bidx]
                    )
                return store[slot, bidx]

        def pad_null(col, is_pad):
            """Wrap/extend a column with pad-row null flags."""
            if isinstance(col, NCol):
                return NCol(col.data, col.null | is_pad)
            return NCol(col, is_pad)

        out_cols = []
        if self.emit_pairs:
            # left ++ right; probe side real except transitions, build
            # side real except self pads
            for src_side in ("left", "right"):
                schema = self.left_schema if src_side == "left" \
                    else self.right_schema
                from_probe = src_side == side
                for ci, f in enumerate(schema):
                    if from_probe:
                        col = probe_val(p.probe_cols[ci])
                        pad = in_trans
                    else:
                        col = build_val(build_rows[ci])
                        pad = in_self
                    nullable = (self.preserve_left
                                if src_side == "right"
                                else self.preserve_right)
                    out_cols.append(
                        pad_null(col, pad) if nullable else col
                    )
        else:
            # semi/anti: preserved side only — self rows come from the
            # probe chunk, transition rows from the build store
            pres = "left" if self.preserve_left else "right"
            schema = self.left_schema if pres == "left" \
                else self.right_schema
            for ci in range(len(schema)):
                if pres == side:
                    out_cols.append(probe_val(p.probe_cols[ci]))
                else:
                    out_cols.append(build_val(build_rows[ci]))

        from risingwave_tpu.common.chunk import (
            OP_DELETE,
            OP_INSERT,
            OP_UPDATE_DELETE,
            OP_UPDATE_INSERT,
        )

        sign_r = p.signs[r]
        base_op = jnp.where(
            sign_r > 0, jnp.int8(OP_INSERT), jnp.int8(OP_DELETE)
        )
        if self.is_semi:
            up_op, down_op = OP_INSERT, OP_DELETE
        elif self.is_anti:
            up_op, down_op = OP_DELETE, OP_INSERT
        else:  # outer pads retract on first match, return on last unmatch
            up_op, down_op = OP_UPDATE_DELETE, OP_UPDATE_INSERT
        ops = jnp.where(
            in_up, jnp.int8(up_op),
            jnp.where(in_down, jnp.int8(down_op), base_op),
        )
        return Chunk(out_cols, ops, valid_out, self._out_schema), \
            probe_bound

    def build_rows_of(self, state: JoinState, side: str) -> tuple:
        """(row stores, addressing-or-None) of the build side for
        emit_window — pool sides address rows via the fused
        (hash, rank) table + its pool_pos values."""
        build = state.right if side == "left" else state.left
        if isinstance(build, PoolSideState):
            return build.rows, (build.table, build.pool_pos)
        return build.rows, None

    # ------------------------------------------------------------------
    def apply(self, state: JoinState, chunk: Chunk, side: str):
        """Process one chunk from ``side`` ("left"|"right"), emitting
        window 0 of the staged emissions; the remainder counts into
        ``emit_overflow`` (the windowed DAG path loses nothing —
        ``apply_begin``/``emit_window``).

        Order (matching the reference's update-then-probe for correct
        self-consistency): update own side, then probe the other side.
        """
        state, pending = self.apply_begin(state, chunk, side)
        out, probe_bound = self.emit_window(
            self.build_rows_of(state, side), pending, jnp.int32(0), side
        )
        dropped = jnp.maximum(pending.total - self.out_capacity, 0)
        return state._replace(
            emit_overflow=state.emit_overflow + dropped.astype(jnp.int64)
            + probe_bound
        ), out

    def max_windows(self, chunk_cap: int) -> int:
        """Static bound on emission windows for one chunk (the dynamic
        ``pending.total`` governs actual trips; pool sides' worst case
        is the whole pool joining one probe row)."""
        depth_l = self.left_pool_size if self.left_storage == "pool" \
            else self.left_bucket_cap
        depth_r = self.right_pool_size if self.right_storage == "pool" \
            else self.right_bucket_cap
        worst = chunk_cap * max(depth_l, depth_r) * 2 + chunk_cap
        return -(-worst // self.out_capacity)

    # ------------------------------------------------------------------
    def maybe_rehash(self, state: JoinState) -> JoinState:
        """Rebuild tombstone-heavy side key tables (runtime maintenance).

        Without this, watermark cleaning would fill the tables with
        unclaimable tombstones and probes would degrade to overflow.
        Traceable: per-side ``lax.cond`` on the device tombstone count."""
        from risingwave_tpu.state.hash_table import permute_dense

        def rebuild(s: SideState) -> SideState:
            fresh, moved = s.key_table.rehashed()
            return SideState(
                key_table=fresh,
                rows=tuple(permute_dense(r, moved) for r in s.rows),
                occupied=permute_dense(s.occupied, moved),
                count=permute_dense(s.count, moved),
                overflow=s.overflow,
                inconsistency=s.inconsistency,
            )

        def rebuild_pool(s: PoolSideState) -> PoolSideState:
            # pool rows are addressed INDIRECTLY through pool_pos, so a
            # table rehash permutes only the dense per-slot companions —
            # the multi-M-row stores never move here
            fresh, moved = s.table.rehashed()
            return s._replace(
                table=fresh,
                count=permute_dense(s.count, moved),
                pool_pos=permute_dense(s.pool_pos, moved),
                slot_clean=permute_dense(s.slot_clean, moved),
            )

        def compact_pool(s: PoolSideState) -> PoolSideState:
            # bump allocation never reuses positions: once enough rows
            # are dead (cleaned keys / stranded overwrites), relocate
            # the live rows to a dense prefix and reset the cursor
            pool = _pool_capacity(s.rows)
            occ = s.table.occupied
            new_pos = jnp.cumsum(occ, dtype=jnp.int32) - 1
            moved = jnp.full((pool,), pool, jnp.int32).at[
                jnp.where(occ, s.pool_pos, pool)
            ].set(jnp.where(occ, new_pos, pool), mode="drop")
            return s._replace(
                rows=tuple(permute_dense(r, moved) for r in s.rows),
                pool_pos=jnp.where(occ, new_pos, s.pool_pos),
                pool_len=jnp.sum(occ, dtype=jnp.int32),
            )

        sides = {}
        for name in ("left", "right"):
            s = getattr(state, name)
            if isinstance(s, PoolSideState):
                s = jax.lax.cond(
                    s.table.tombstone_count() > s.table.size // 4,
                    rebuild_pool, lambda x: x, s,
                )
                pool = _pool_capacity(s.rows)
                dead = s.pool_len - s.table.count()
                s = jax.lax.cond(
                    (s.pool_len >= pool - pool // 4) & (dead > pool // 8),
                    compact_pool, lambda x: x, s,
                )
                sides[name] = s
            else:
                sides[name] = jax.lax.cond(
                    s.key_table.tombstone_count() > s.key_table.size // 4,
                    rebuild, lambda x: x, s,
                )
        return state._replace(left=sides["left"], right=sides["right"])

    def clean_below(self, state: JoinState, side: str, key_col_idx: int,
                    threshold) -> JoinState:
        """Watermark state cleaning on a window key column (q8 pattern)."""
        s = getattr(state, side)
        if isinstance(s, PoolSideState):
            # the fused table stores (hash, rank), not raw keys — every
            # entry carries its window-key value in slot_clean, so a
            # whole closed window tombstones in ONE mask (heads and
            # rank entries together: the window key is part of the join
            # key, so all of a key's entries share the value).  Dead
            # pool rows linger until the next compaction.
            stale = s.table.occupied & (s.slot_clean < threshold)
            cleaned = s._replace(
                table=s.table.clear_where(stale),
                count=jnp.where(stale, 0, s.count),
            )
        else:
            key = s.key_table.key_cols[key_col_idx]
            stale = s.key_table.occupied & (key < threshold)
            cleaned = SideState(
                key_table=s.key_table.clear_where(stale),
                rows=s.rows,
                occupied=s.occupied & ~stale[:, None],
                count=jnp.where(stale, 0, s.count),
                overflow=s.overflow,
                inconsistency=s.inconsistency,
            )
        if side == "left":
            return state._replace(left=cleaned)
        return state._replace(right=cleaned)
