"""MaterializeExecutor: maintain the MV's table from its changelog.

Reference counterpart: ``MaterializeExecutor`` (src/stream/src/executor/
mview/materialize.rs:70) — applies the changelog to the MV's StateTable
with primary-key conflict handling.

TPU-first design
----------------
Two device-resident variants, chosen by the plan:

- ``MaterializeExecutor`` (pk-keyed): a ``HashTable`` on the pk plus
  dense value arrays.  A whole changelog chunk applies as one
  lookup_or_insert + two scatters (delete-side tombstones, insert-side
  writes) — the reference's per-row conflict handling becomes a
  vectorized upsert.
- ``AppendOnlyMaterialize``: a ring buffer + cursor for pk-less /
  append-only MVs (e.g. Nexmark q1) — one dynamic-slice write per chunk.

Snapshot serving reads (`to_host`) gather live slots at barrier time —
the batch-side `BatchTable` scan of SURVEY §3.4, collapsed to a gather.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import (
    Chunk,
    NCol,
    OP_DELETE,
    OP_INSERT,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
    StrCol,
    apply_null_mask,
    decode_strings,
    split_col,
)
from risingwave_tpu.common.compact import mask_indices
from risingwave_tpu.common.types import Schema
from risingwave_tpu.state.hash_table import HashTable
from risingwave_tpu.stream.executor import Executor


def _empty_value_col(f, size: int):
    if f.data_type.is_string:
        col = StrCol(
            jnp.zeros((size, f.str_width), jnp.uint8),
            jnp.zeros((size,), jnp.int32),
        )
    else:
        col = jnp.zeros((size,), f.data_type.physical_dtype)
    if getattr(f, "nullable", False):
        return NCol(col, jnp.zeros((size,), jnp.bool_))
    return col


def _scatter_col(store, pos, values):
    if isinstance(store, NCol):
        return NCol(
            _scatter_col(store.data, pos, values.data),
            store.null.at[pos].set(values.null, mode="drop"),
        )
    if isinstance(store, StrCol):
        return StrCol(
            store.data.at[pos].set(values.data, mode="drop"),
            store.lens.at[pos].set(values.lens, mode="drop"),
        )
    return store.at[pos].set(values, mode="drop")


class MvState(NamedTuple):
    table: HashTable
    values: tuple  # dense [size] column stores (all output columns)
    overflow: jnp.ndarray


class MaterializeExecutor(Executor):
    """Upsert the changelog into a pk-keyed device table."""

    emits_on_apply = False
    emits_on_flush = False

    def __init__(
        self,
        in_schema: Schema,
        pk_indices: Sequence[int],
        table_size: int = 1 << 16,
    ):
        super().__init__(in_schema)
        self.pk_indices = tuple(pk_indices)
        self.table_size = table_size

    def init_state(self) -> MvState:
        protos = []
        for i in self.pk_indices:
            protos.append(_empty_value_col(self.in_schema[i], 1))
        table = HashTable.create(protos, self.table_size)
        values = tuple(
            _empty_value_col(f, self.table_size) for f in self.in_schema
        )
        return MvState(table, values, jnp.zeros((), jnp.int64))

    def apply(self, state: MvState, chunk: Chunk):
        pk_cols = [chunk.column(i) for i in self.pk_indices]
        is_del = (chunk.ops == OP_DELETE) | (chunk.ops == OP_UPDATE_DELETE)
        is_ins = (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT)
        del_rows = chunk.valid & is_del
        ins_rows = chunk.valid & is_ins

        table, slots, _, overflow = state.table.lookup_or_insert(
            pk_cols, chunk.valid
        )
        n_over = jnp.sum((overflow & chunk.valid).astype(jnp.int64))
        # per-slot conflict resolution honors INTRA-CHUNK ROW ORDER (the
        # reference applies conflicts row by row, materialize.rs): the
        # last op in row order wins — a [+pk, -pk] chunk ends absent, a
        # [-pk, +pk] chunk ends present.  XLA scatter order for duplicate
        # indices is unspecified, so the winner is chosen by scatter-max
        # of the row index per side.
        row_idx = jnp.arange(slots.shape[0], dtype=jnp.int32)
        last_del = jnp.full((self.table_size,), -1, jnp.int32).at[
            jnp.where(del_rows, slots, jnp.int32(self.table_size))
        ].max(jnp.where(del_rows, row_idx, -1), mode="drop")
        last_ins = jnp.full((self.table_size,), -1, jnp.int32).at[
            jnp.where(ins_rows, slots, jnp.int32(self.table_size))
        ].max(jnp.where(ins_rows, row_idx, -1), mode="drop")
        safe = jnp.minimum(slots, self.table_size - 1)
        # delete wins where its last row index beats the last insert's
        del_wins = del_rows & (last_del[safe] > last_ins[safe])
        table = table.clear_slots(slots, del_wins)
        is_last = ins_rows & (last_ins[safe] == row_idx) & (
            last_ins[safe] > last_del[safe]
        )
        ins_pos = jnp.where(is_last, slots, jnp.int32(self.table_size))
        table = HashTable(
            table.key_cols,
            table.occupied.at[ins_pos].set(True, mode="drop"),
            table.tombstone.at[ins_pos].set(False, mode="drop"),
            table.size,
        )
        values = tuple(
            _scatter_col(store, ins_pos, col)
            for store, col in zip(state.values, chunk.columns)
        )
        # pass the changelog through: downstream (cascaded) MVs consume
        # this MV's change stream, exactly as the reference's dispatcher
        # forwards the materialize fragment's output to dependent jobs
        return MvState(table, values, state.overflow + n_over), chunk

    # -- maintenance ----------------------------------------------------
    def maybe_rehash(self, state: MvState) -> MvState:
        """Rebuild the pk table once tombstones dominate (traceable:
        lax.cond on the device tombstone count, no host readback)."""

        def do_rehash(state: MvState) -> MvState:
            fresh, moved = state.table.rehashed()
            from risingwave_tpu.state.hash_table import permute_dense

            values = tuple(permute_dense(v, moved) for v in state.values)
            return MvState(fresh, values, state.overflow)

        return jax.lax.cond(
            state.table.tombstone_count() > self.table_size // 4,
            do_rehash, lambda s: s, state,
        )

    # -- serving (snapshot read) ----------------------------------------
    def to_host(self, state: MvState) -> list[tuple]:
        """Read the MV as python rows (batch serving path)."""
        occ = np.asarray(state.table.occupied)
        cols = []
        for f, store in zip(self.in_schema, state.values):
            store, null = split_col(store)
            if isinstance(store, StrCol):
                out = decode_strings(
                    np.asarray(store.data)[occ], np.asarray(store.lens)[occ]
                )
            else:
                arr = np.asarray(store)[occ]
                if f.data_type.value == "numeric":
                    arr = arr.astype(np.float64) / 10**f.decimal_scale
                out = arr
            if null is not None:
                out = apply_null_mask(out, np.asarray(null)[occ])
            cols.append(out)
        n = int(occ.sum())
        return [tuple(c[i] for c in cols) for i in range(n)]


class RingState(NamedTuple):
    values: tuple          # [ring_size] column stores
    cursor: jnp.ndarray    # int64 total rows written (mod ring for slot)
    overflow: jnp.ndarray  # rows evicted before being read


class AppendOnlyMaterialize(Executor):
    """Ring-buffer MV for append-only changelogs (no pk conflicts).

    The reference appends via row-id pks; here an on-device ring buffer
    absorbs inserts with one compaction + dynamic write per chunk.
    """

    emits_on_apply = False
    emits_on_flush = False

    def __init__(self, in_schema: Schema, ring_size: int = 1 << 20):
        super().__init__(in_schema)
        if ring_size & (ring_size - 1):
            raise ValueError("ring_size must be a power of two")
        self.ring_size = ring_size

    def init_state(self) -> RingState:
        return RingState(
            tuple(_empty_value_col(f, self.ring_size) for f in self.in_schema),
            jnp.zeros((), jnp.int64),
            jnp.zeros((), jnp.int64),
        )

    def apply(self, state: RingState, chunk: Chunk):
        cap = chunk.capacity
        # compact visible rows to the front (fixed-size nonzero)
        idx = mask_indices(chunk.valid, cap, cap)
        n = chunk.cardinality().astype(jnp.int64)
        k = jnp.arange(cap, dtype=jnp.int64)
        pos = ((state.cursor + k) % self.ring_size).astype(jnp.int32)
        pos = jnp.where(k < n, pos, jnp.int32(self.ring_size))
        safe_idx = jnp.minimum(idx, cap - 1)
        from risingwave_tpu.state.hash_table import gather_key
        values = []
        for store, col in zip(state.values, chunk.columns):
            values.append(_scatter_col(store, pos, gather_key(col, safe_idx)))
        # ring laps silently overwrite the oldest MV rows — count them as
        # overflow so maintenance fails loudly instead of serving a
        # truncated MV (history beyond ring_size needs the SST spill path)
        lost_before = jnp.maximum(state.cursor - self.ring_size, 0)
        lost_after = jnp.maximum(state.cursor + n - self.ring_size, 0)
        return RingState(
            tuple(values), state.cursor + n,
            state.overflow + (lost_after - lost_before),
        ), chunk  # pass-through: cascaded MVs tap this changelog

    def to_host(self, state: RingState, limit: int | None = None) -> list[tuple]:
        total = int(state.cursor)
        n = min(total, self.ring_size if limit is None else limit)
        start = max(total - n, 0)
        sel = (np.arange(start, start + n) % self.ring_size).astype(np.int64)
        cols = []
        for f, store in zip(self.in_schema, state.values):
            store, null = split_col(store)
            if isinstance(store, StrCol):
                out = decode_strings(
                    np.asarray(store.data)[sel], np.asarray(store.lens)[sel]
                )
            else:
                out = np.asarray(store)[sel]
                if f.data_type.value == "numeric":
                    out = out.astype(np.float64) / 10**f.decimal_scale
            if null is not None:
                out = apply_null_mask(out, np.asarray(null)[sel])
            cols.append(out)
        return [tuple(c[i] for c in cols) for i in range(n)]
