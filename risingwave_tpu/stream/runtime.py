"""Streaming job runtime: the host-side barrier/epoch control loop.

Reference counterparts:
- meta's ``PeriodicBarriers`` + ``GlobalBarrierWorker::run`` loop
  (src/meta/src/barrier/{schedule.rs:508,worker.rs:378})
- CN's ``LocalBarrierWorker`` + actor event loop
  (src/stream/src/task/barrier_worker/mod.rs:303)

TPU-first design (SURVEY.md §7.1): barriers are host control flow, but
the barrier CROSSING is one asynchronously dispatched XLA program.  The
steady-state loop — K chunk steps, then a barrier — performs ZERO
synchronous host↔device round trips:

- emit-capacity drain loops run on device (``lax.while_loop`` inside
  the barrier program) instead of host readback loops;
- watermarks propagate as device scalars inside the same program;
- error counters (overflow/inconsistency) are collected into ONE device
  vector per barrier and read back once per maintenance interval;
- rehash decisions are ``lax.cond`` on device tombstone counts;
- in-memory snapshots are jit-compiled device→device tree copies.

This matters doubly on a tunneled accelerator where every synchronous
readback costs a full round trip (measured ~66 ms on the dev tunnel vs
~40 µs per async dispatch), but it is the right shape for local TPUs
too: the host never stalls the device pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.common.trace import GLOBAL_TRACE
from risingwave_tpu.stream.fragment import (
    COUNTER_ATTRS,
    Fragment,
    WM_NONE,
    WM_SAFE_FLOOR,
    collect_counters,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind


@dataclass
class CheckpointSnapshot:
    """A committed epoch: device snapshot of all state + source offsets.

    ref: Hummock ``commit_epoch`` (src/meta/src/hummock/manager/
    commit_epoch.rs:73) — the in-memory snapshot stays device-resident;
    only the durable store pays a device→host transfer.

    ``states is None`` marks a SHADOW-BACKED snapshot: the state lives
    in the job's incremental ``ShadowSnapshot`` (stream/shadow.py) and
    ``recover()`` restores from there — the full-copy tree is only
    retained on paths that still take it (sharded meshes).
    """

    epoch: int
    states: Any
    source_state: dict
    #: host copies of spill-tier states at this epoch (key → pytree);
    #: None/missing key = the tier had absorbed nothing yet
    spill: dict | None = None


#: jitted device→device snapshot copy (one dispatch for the whole tree)
@jax.jit
def _snapshot_copy(tree):
    return jax.tree.map(jnp.copy, tree)


class CheckpointPipelineMixin:
    """Incremental shadow snapshots + pipelined async durable uploads,
    shared by StreamingJob and DagJob (see stream/shadow.py and
    stream/checkpoint.py).

    Contract: a snapshot barrier SEALS the epoch (``sealed_epoch``) in
    one async device dispatch and enqueues persistence to a background
    uploader; ``committed_epoch`` (the recovery/serving pin) advances
    only when the upload ACKS.  Without a durable store, seal and
    commit coincide (the shadow IS the commit).  The barrier loop
    stalls only when the uploader falls more than ``upload_window``
    epochs behind — the checkpoint analog of the L0-depth write stall.
    """

    #: max sealed-but-unacked epochs before the barrier loop stalls
    upload_window: int = 4
    #: optional MetricsRegistry (the engine attaches its own)
    metrics = None
    _shadow = None
    _uploader = None
    _sinks_due = False

    def _init_pipeline(self) -> None:
        self.sealed_epoch = 0
        self._shadow = None
        self._uploader = None
        self._sinks_due = False

    @property
    def ckpt_key(self) -> str:
        """Durable-store key of this job's checkpoint lineage.  A
        partitioned job (cluster scale plane) runs one replica per
        worker over ONE shared store — each partition checkpoints
        under its own lineage key instead of the job name."""
        return getattr(self, "_ckpt_key", None) or self.name

    @ckpt_key.setter
    def ckpt_key(self, value: str) -> None:
        self._ckpt_key = value

    # -- uploader plumbing ----------------------------------------------
    def _ensure_uploader(self):
        if self._uploader is None and self.checkpoint_store is not None:
            from risingwave_tpu.stream.checkpoint import (
                CheckpointUploader,
            )
            self._uploader = CheckpointUploader(
                self.checkpoint_store, self.ckpt_key,
                metrics=self.metrics,
            )
        return self._uploader

    def _process_upload_acks(self) -> None:
        """Cheap ack poll (no device work): advances committed_epoch
        and runs deferred sink delivery once the queue is empty."""
        up = self._uploader
        if up is None:
            return
        acked = up.take_acked()
        if acked:
            self.committed_epoch = max(self.committed_epoch, acked[-1])
        if self._sinks_due and up.pending() == 0 \
                and self.committed_epoch > 0:
            self._sinks_due = False
            self._deliver_all_sinks(self.committed_epoch)

    def upload_queue_depth(self) -> int:
        return 0 if self._uploader is None else self._uploader.pending()

    def drain_uploads(self, raise_error: bool = True) -> None:
        """Block until every sealed epoch is durable (tick-batch
        boundaries, orderly stop, recovery).  Within a batch the
        uploads pipeline; the batch boundary is the freshness point."""
        if self._uploader is not None:
            self._uploader.drain(raise_error=raise_error)
            self._process_upload_acks()

    def _deliver_all_sinks(self, epoch_val) -> None:
        """Subclass hook: drain sink ring buffers at ``epoch_val``."""

    def _shadow_shard_rows(self) -> int | None:
        """Subclass hook: leading per-shard axis length of every state
        leaf (mesh-stacked trees digest in per-shard lanes), None for
        linear trees."""
        return None

    # -- the shared snapshot-commit tail ---------------------------------
    def _snapshot_commit(self, epoch_val: int, src_state: dict,
                         spill_host: dict, spill_items: list) -> None:
        """Seal one epoch: shadow update (one async dispatch) +
        uploader enqueue (or, with no store, the in-memory commit)."""
        from risingwave_tpu.storage.digest import DEFAULT_BLOCK_ELEMS
        from risingwave_tpu.stream.shadow import ShadowSnapshot

        store = self.checkpoint_store
        up = self._ensure_uploader()
        if up is not None:
            # bounded in-flight window (mirrors the L0-depth stall)
            self.stall_seconds += up.wait_window(self.upload_window)
            self._process_upload_acks()
        if self._shadow is not None and (
                not self._shadow.matches(self.states)
                or self._shadow.digest_mode != (store is not None)):
            # topology changed (or the job gained/lost a durable
            # store): the shadow — and the store's digest chain —
            # describe the OLD configuration; drain in-flight uploads,
            # then rebuild from scratch (full re-base)
            if up is not None:
                up.drain()
                self._process_upload_acks()
            if store is not None:
                store.invalidate(self.ckpt_key)
            self._shadow = None
        with GLOBAL_TRACE.span("snapshot", job=getattr(
                self, "name", "?"), epoch=epoch_val):
            if self._shadow is None:
                self._shadow = ShadowSnapshot(
                    self.states,
                    block_elems=store.block_elems if store is not None
                    else DEFAULT_BLOCK_ELEMS,
                    digest=store is not None,
                    shard_rows=self._shadow_shard_rows(),
                )
                digests = self._shadow.digests
            else:
                if up is not None:
                    # the update donates the shadow buffers in-flight
                    # fetches still read — wait for the fetch point only
                    up.wait_fetched()
                digests = self._shadow.update(self.states, epoch_val)
        self.sealed_epoch = epoch_val
        self.checkpoints = [CheckpointSnapshot(
            epoch=epoch_val, states=None, source_state=src_state,
            spill=spill_host,
        )]
        if store is not None:
            from risingwave_tpu.stream.checkpoint import UploadTask
            up.enqueue(UploadTask(
                epoch=epoch_val, leaves=self._shadow.leaves,
                digests=digests, shapes=self._shadow.shapes,
                treedef=self._shadow.treedef, source_state=src_state,
                spill=spill_items, lanes=self._shadow.lanes,
                trace_ctx=GLOBAL_TRACE.current(),
            ))
            self._process_upload_acks()
        else:
            self.committed_epoch = epoch_val

    def _restore_in_memory(self, snap: CheckpointSnapshot):
        """States tree for an in-memory recover: from the shadow when
        the snapshot is shadow-backed, else the retained full copy."""
        if snap.states is None:
            return self._shadow.restore()
        return _snapshot_copy(snap.states)


def check_counter_values(name: str, labels: list[str],
                         values: np.ndarray) -> list[str]:
    """Raise on error counters; return labels with residual pending.

    ``values`` is the host copy of a barrier program's counters vector.
    """
    residual = []
    for label, v in zip(labels, values):
        if label.endswith(".pending"):
            if v > 0:
                residual.append(label)
        elif v > 0:
            kind = label.rsplit(".", 1)[-1]
            if kind == "inconsistency":
                raise RuntimeError(
                    f"{name}/{label}: {v} inconsistent changelog rows "
                    "(deletes with no matching state)"
                )
            if kind == "emit_overflow":
                raise RuntimeError(
                    f"{name}/{label}: emit overflow ({v} output rows "
                    "dropped) — increase out_capacity"
                )
            hint = "ring_size" if "Ring" in label or "AppendOnly" in label \
                else "table/bucket capacity"
            raise RuntimeError(
                f"{name}/{label}: state overflow ({v} rows dropped) — "
                f"increase {hint}"
            )
    return residual


def check_state_counters(name: str, st) -> None:
    """Eager single-state check (test/debug surface; one readback per
    counter — not for the steady-state loop)."""
    for attr in ("inconsistency", "overflow"):
        if hasattr(st, attr) and int(getattr(st, attr)) > 0:
            check_counter_values(
                name, [f"state.{attr}"],
                np.asarray([int(getattr(st, attr))]),
            )


def restore_source(source, state: dict) -> None:
    """Restore a source from its checkpointed state() dict.

    Sources may implement ``restore(state)`` for full-fidelity recovery;
    the fallback covers plain offset-cursor sources."""
    if hasattr(source, "restore"):
        source.restore(state)
    elif hasattr(source, "offset") and "offset" in state:
        source.offset = state["offset"]


def rewind_spill_tier(store, key: str, epoch: int, tier) -> None:
    """Rewind a host spill tier after job recovery: restore the nearest
    tier epoch <= the job's recovered epoch (a crash between the tier
    save and the job save leaves the tier one epoch ahead); when no
    eligible checkpoint exists the tier postdates every commit and must
    RESET — keeping its live state would double-count the replayed
    rows.  Shared by StreamingJob and DagJob."""
    cands = [e for e in store.epochs(key) if e <= epoch] \
        if store is not None else []
    loaded = store.load(key, cands[-1]) if cands else None
    if loaded is not None:
        tier.restore(loaded[1])
    else:
        tier.reset()


def deliver_sinks(fragment: Fragment, states, epoch_val):
    """Drain sink ring buffers to their connectors (host barrier hook).

    Inherently a device→host read — runs on the snapshot cadence only."""
    states = list(states)
    for i, ex in enumerate(fragment.executors):
        if hasattr(ex, "deliver"):
            states[i] = ex.deliver(states[i], epoch_val)
    return tuple(states)


class StreamingJob(CheckpointPipelineMixin):
    """A linear source → fragment pipeline driven by the barrier loop.

    The fragment typically ends in a Materialize executor (the MV).
    ``source.next_chunk()`` must return a device ``Chunk``.
    """

    def __init__(
        self,
        source,
        fragment: Fragment,
        name: str = "job",
        checkpoint_frequency: int = 1,
        checkpoint_store=None,
    ):
        self.source = source
        self.fragment = fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        #: optional durable store (storage.CheckpointStore); when set,
        #: commits persist across process restarts
        self.checkpoint_store = checkpoint_store
        #: checkpoints between maintenance passes (amortizes the ONE
        #: counters readback + rehash program)
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        #: checkpoints between in-memory snapshot copies
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        #: storage-service backpressure (the Hummock write-limit
        #: contract): when set, every barrier crossing first calls
        #: this hook, which blocks while the storage L0 is deeper than
        #: its stall threshold — ingest yields to the compactor
        #: instead of burying it.  Returns seconds stalled.
        self.write_stall_hook = None
        #: cumulative seconds this job spent write-stalled
        self.stall_seconds = 0.0
        self.states = fragment.init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        #: committed epoch visible to batch reads (ref pinned snapshots)
        self.committed_epoch: int = 0
        self._init_pipeline()
        self.paused = False
        #: counters vector from the last barrier program (device array;
        #: read back once per maintenance interval)
        self._counters = None
        #: spill-to-host tiers (stream/spill.py) per spill-enabled agg:
        #: [(exec_idx, drain_jit, inject_jit, tier)]
        self._spill: list = []
        for i, ex in enumerate(fragment.executors):
            if not getattr(ex, "spill_ring", 0):
                continue
            from risingwave_tpu.stream.spill import AggSpillTier
            drain = jax.jit(
                lambda states, i=i, ex=ex: self._drain_impl(states, i, ex),
                donate_argnums=(0,),
            )
            inject = jax.jit(
                lambda states, chunk, i=i: self._inject_impl(
                    states, chunk, i
                ),
                donate_argnums=(0,),
            )
            tier = AggSpillTier(
                ex, getattr(ex, "spill_table_size", ex.table_size * 8)
            )
            self._spill.append((i, drain, inject, tier))
        # fuse generation into the step when the source is traceable:
        # the source chunk never materializes standalone — XLA fuses
        # generator arithmetic straight into the executor kernels
        self._fused = None
        #: n-chunk fused programs (one dispatch per n chunks; host
        #: dispatch overhead amortized n-fold), keyed by n
        self._fused_multi: dict[int, Any] = {}
        if hasattr(source, "impl") and hasattr(source, "next_base"):

            def _fused(states, k0):
                return fragment._step_impl(
                    states, source.impl(k0, source.cap)
                )

            self._fused = jax.jit(_fused, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def run_chunk(self) -> int:
        """Pull one chunk from the source through the fragment.

        Returns the chunk capacity processed (0 when paused) so callers
        can meter throughput without a device sync."""
        if self.paused:
            return 0
        if self._fused is not None:
            self.states, _ = self._fused(
                self.states, jnp.int64(self.source.next_base())
            )
            return self.source.cap
        chunk = self.source.next_chunk()
        self.states, _ = self.fragment.step(self.states, chunk)
        return chunk.capacity

    def run_chunks(self, n: int) -> int:
        """n chunk steps in ONE dispatch when the source is traceable.

        The stateless-query floor is per-dispatch host work (~hundreds
        of µs of Python per XLA call), not device compute — a
        ``fori_loop`` over n generator+step iterations inside one
        program amortizes it n-fold (the q1 attribution fix)."""
        if self.paused or n <= 0:
            return 0
        if self._fused is None or n == 1:
            rows = 0
            for _ in range(n):
                rows += self.run_chunk()
            return rows
        prog = self._fused_multi.get(n)
        if prog is None:
            cap = self.source.cap
            stride = cap * getattr(self.source, "num_splits", 1)

            def _multi(states, k0):
                def body(i, st):
                    st2, _ = self.fragment._step_impl(
                        st, self.source.impl(k0 + i * stride, cap)
                    )
                    return st2

                return jax.lax.fori_loop(0, n, body, states)

            prog = jax.jit(_multi, donate_argnums=(0,))
            # bounded: chunks_per_barrier is runtime-mutable; distinct
            # values each compile a program — keep only the newest few
            if len(self._fused_multi) >= 4:
                self._fused_multi.pop(next(iter(self._fused_multi)))
            self._fused_multi[n] = prog
        k0 = jnp.int64(self.source.next_base())
        # the cursor already advanced one block; skip the other n-1
        self.source.offset += self.source.cap * (n - 1)
        self.states = prog(self.states, k0)
        return self.source.cap * n

    def inject_barrier(self, barrier: Barrier | None = None) -> list:
        """Cross a barrier: one async dispatch (flush + drain +
        watermarks + counters), then maintenance / checkpoint on their
        cadences.

        Returns the chunks emitted by the first flush pass (they have
        already flowed through the downstream executors inside the
        fragment — e.g. into a trailing Materialize — so callers
        usually ignore them).
        """
        if barrier is None:
            self.barriers_seen += 1
            kind = (
                BarrierKind.CHECKPOINT
                if self.barriers_seen % self.checkpoint_frequency == 0
                else BarrierKind.BARRIER
            )
            # the barrier SEALS the epoch data has been flowing in
            # (epoch.curr) and opens the next one (ref EpochPair)
            barrier = Barrier(
                EpochPair(self.epoch.curr.next(), self.epoch.curr), kind
            )
        if barrier.mutation is not None:
            self._apply_mutation(barrier.mutation)
        if self.write_stall_hook is not None:
            # the barrier loop is the ingest clock: stalling HERE (not
            # per chunk) applies backpressure at epoch granularity
            # without touching the fused steady-state dispatch
            self.stall_seconds += self.write_stall_hook()

        epoch_val = barrier.epoch.prev.value
        self.states, outs, self._counters = self.fragment.barrier(
            self.states, epoch_val
        )
        if barrier.is_checkpoint:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain(epoch_val)
                self._ckpts_since_maintain = 0
            self._commit_checkpoint(barrier)
        # cheap ack poll keeps committed_epoch (and deferred sink
        # delivery) advancing while uploads complete in the background
        self._process_upload_acks()
        self.epoch = barrier.epoch
        return outs

    def _maintain(self, epoch_val) -> None:
        """Rehash (on device) + the single counters readback."""
        self.states = self.fragment.maintain(self.states)
        if self._counters is None:
            return
        values = np.asarray(self._counters)  # THE one device sync
        residual = check_counter_values(
            self.name, self.fragment.counter_labels, values
        )
        # residual pending beyond MAX_DRAIN_ROUNDS×emit_capacity per
        # barrier: pathological; finish draining with host loops
        for _ in range(64):
            if not residual:
                break
            self.states, _, self._counters = self.fragment.barrier(
                self.states, epoch_val
            )
            residual = check_counter_values(
                self.name, self.fragment.counter_labels,
                np.asarray(self._counters),
            )

    def _drain_impl(self, states, i, ex):
        new_states = list(states)
        new_states[i], chunk = ex.drain_spill(states[i])
        return tuple(new_states), chunk

    def _inject_impl(self, states, chunk, i):
        """Feed a tier changelog through the executors AFTER the agg."""
        new_states = list(states)
        cur = chunk
        for j in range(i + 1, len(self.fragment.executors)):
            if cur is None:
                break
            new_states[j], cur = self.fragment.executors[j].apply(
                new_states[j], cur
            )
        return tuple(new_states)

    def _drain_spill_tiers(self, epoch_val) -> None:
        """Snapshot-barrier hook: divert ring rows to the host tier and
        inject its changelog downstream (ref: state beyond memory via
        the state-store tier, state_table.rs:187)."""
        import numpy as _np
        for i, drain, inject, tier in self._spill:
            cnt = int(_np.asarray(self.states[i].spill_count))
            if cnt == 0:
                continue
            self.states, chunk = drain(self.states)
            host_chunk = jax.device_get(chunk)
            out = tier.process(host_chunk, epoch_val)
            if out is not None:
                self.states = inject(self.states, out)

    def _deliver_all_sinks(self, epoch_val) -> None:
        self.states = deliver_sinks(self.fragment, self.states, epoch_val)

    def _commit_checkpoint(self, barrier: Barrier) -> None:
        """Seal one snapshot epoch: spill drain + sink delivery + the
        incremental shadow update, then hand durable persistence to the
        background uploader.  Recovery rewinds to the last DURABLE
        epoch, so ``committed_epoch`` (and deferred sink delivery)
        advance only on uploader ack; without a store, seal == commit
        (the shadow is the recovery point)."""
        epoch_val = barrier.epoch.prev.value
        self._ckpts_since_snapshot += 1
        if self._ckpts_since_snapshot < self.snapshot_interval:
            return
        self._ckpts_since_snapshot = 0
        self._drain_spill_tiers(epoch_val)
        up = self._ensure_uploader()
        if up is None or up.pending() == 0:
            # at-least-once delivery, same window as the synchronous
            # path (rows delivered before their epoch is durable ride
            # THIS epoch's snapshot via the advanced read_cursor)
            self.states = deliver_sinks(
                self.fragment, self.states, epoch_val
            )
        else:
            # uploader behind: defer delivery to the ack poll
            self._sinks_due = True
        src_state = self.source.state() if hasattr(self.source, "state") \
            else {}
        # ONE host materialization per tier, shared by the in-memory
        # snapshot and the durable save
        spill_host = {i: tier.snapshot() for i, _, _, tier in self._spill
                      if tier.rows_absorbed}
        spill_items = [(f"{self.ckpt_key}@spill{i}", spill_host[i])
                       for i in spill_host]
        self._snapshot_commit(epoch_val, src_state, spill_host,
                              spill_items)

    def _apply_mutation(self, mutation) -> None:
        if mutation.kind == "pause":
            self.paused = True
        elif mutation.kind == "resume":
            self.paused = False
        elif mutation.kind == "stop":
            self.paused = True

    # -- recovery -------------------------------------------------------
    def recover(self, epoch: int | None = None) -> None:
        """Reset to the last committed checkpoint (ref §3.5 recovery:
        rebuild actors + resume from last committed epoch).  Drains the
        upload queue first (sealed epochs finish becoming durable, a
        failed upload is swallowed — the rewind IS its resolution),
        then prefers the durable store (survives process restarts) over
        the in-memory shadow.  ``epoch`` pins the rewind to a specific
        retained checkpoint (the scale plane rewinds survivors to the
        handover round before transplanting moved-vnode slices)."""
        self._counters = None
        if self._uploader is not None:
            self._uploader.drain(raise_error=False)
            self._process_upload_acks()
            self._uploader.clear_error()
            self._sinks_due = False
        if self.checkpoint_store is not None:
            # any rewind invalidates the store's in-memory digest
            # cache: the next save must re-base with a full snapshot,
            # or a delta computed against post-rewind live state could
            # overwrite a valid chain entry with a wrong-base delta
            # (invalidate also vacuums orphan files a crashed upload
            # left between object write and manifest commit)
            self.checkpoint_store.invalidate(self.ckpt_key)
            loaded = self.checkpoint_store.load(self.ckpt_key, epoch)
            if loaded is not None:
                epoch_v, states, src_state = loaded
                self.states = jax.device_put(states)
                self.committed_epoch = epoch_v
                self.sealed_epoch = epoch_v
                restore_source(self.source, src_state)
                for i, _, _, tier in self._spill:
                    key = f"{self.ckpt_key}@spill{i}"
                    self.checkpoint_store.invalidate(key)
                    rewind_spill_tier(
                        self.checkpoint_store, key, epoch_v, tier
                    )
                return
        if not self.checkpoints:
            self.states = self.fragment.init_states()
            if hasattr(self.source, "offset"):
                self.source.offset = 0
            for _, _, _, tier in self._spill:
                tier.reset()
            return
        snap = self.checkpoints[-1]
        # copy: the next step donates its input buffers, which must not
        # invalidate the retained snapshot (shadow-backed snapshots
        # restore from the shadow tree — the shadow itself survives)
        self.states = self._restore_in_memory(snap)
        restore_source(self.source, snap.source_state)
        for i, _, _, tier in self._spill:
            if snap.spill and i in snap.spill:
                tier.restore(snap.spill[i])
            else:
                tier.reset()

    # ------------------------------------------------------------------
    def chunk_round(self) -> int:
        """Uniform driving interface shared with DagJob (one scheduling
        round = one chunk for a single-source linear job)."""
        return self.run_chunk()

    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        """The steady-state loop (ref §3.3).  Uploads pipeline within
        the batch; the batch boundary drains them (durability point)."""
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                self.run_chunk()
            self.inject_barrier()
        self.drain_uploads()

    def executor_state(self, idx: int):
        return self.states[idx]
