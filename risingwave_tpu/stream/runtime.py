"""Streaming job runtime: the host-side barrier/epoch control loop.

Reference counterparts:
- meta's ``PeriodicBarriers`` + ``GlobalBarrierWorker::run`` loop
  (src/meta/src/barrier/{schedule.rs:508,worker.rs:378})
- CN's ``LocalBarrierWorker`` + actor event loop
  (src/stream/src/task/barrier_worker/mod.rs:303)

TPU-first design (SURVEY.md §7.1): barriers are host control flow.  The
runtime ticks epochs, runs K jitted fragment steps per epoch (each step
processes one source chunk), then crosses the barrier: flush
emit-on-barrier state, commit the epoch, snapshot on checkpoint
barriers.  "One actor = one tokio task" collapses into "one fragment =
one jitted program", so barrier alignment inside a single fragment is
trivial (sequential steps) and multi-fragment alignment is the loop
order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax

from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.message import Barrier, BarrierKind
from risingwave_tpu.stream.hash_agg import HashAggExecutor


@dataclass
class CheckpointSnapshot:
    """A committed epoch: host copies of all state + source offsets.

    ref: Hummock ``commit_epoch`` (src/meta/src/hummock/manager/
    commit_epoch.rs:73) — here the "SST upload" is a device→host state
    fetch; the persistent-store spill lands with the storage layer.
    """

    epoch: int
    states: Any
    source_state: dict


class StreamingJob:
    """A linear source → fragment pipeline driven by the barrier loop.

    The fragment typically ends in a Materialize executor (the MV).
    ``source.next_chunk()`` must return a device ``Chunk``.
    """

    def __init__(
        self,
        source,
        fragment: Fragment,
        name: str = "job",
        checkpoint_frequency: int = 1,
    ):
        self.source = source
        self.fragment = fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        self.states = fragment.init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        #: committed epoch visible to batch reads (ref pinned snapshots)
        self.committed_epoch: int = 0
        self.paused = False

    # ------------------------------------------------------------------
    def run_chunk(self) -> None:
        """Pull one chunk from the source through the fragment."""
        if self.paused:
            return
        chunk = self.source.next_chunk()
        self.states, _ = self.fragment.step(self.states, chunk)

    def inject_barrier(self, barrier: Barrier | None = None) -> list:
        """Cross a barrier: flush, (maybe) checkpoint, bump the epoch.

        Returns the chunks emitted by flush (they have already flowed
        through the downstream executors inside the fragment — e.g. into
        a trailing Materialize — so callers usually ignore them).
        """
        if barrier is None:
            self.barriers_seen += 1
            kind = (
                BarrierKind.CHECKPOINT
                if self.barriers_seen % self.checkpoint_frequency == 0
                else BarrierKind.BARRIER
            )
            barrier = Barrier(self.epoch, kind)
        if barrier.mutation is not None:
            self._apply_mutation(barrier.mutation)

        epoch_val = barrier.epoch.prev.value
        outs = []
        self.states, emitted = self.fragment.flush(self.states, epoch_val)
        outs.extend(emitted)
        # drain aggregations whose dirty set exceeded one emit chunk
        outs.extend(self._drain_pending(epoch_val))

        if barrier.is_checkpoint:
            self._maintain()
            self._commit_checkpoint(barrier)
        self.epoch = self.epoch.bump()
        return outs

    def _maintain(self) -> None:
        """Checkpoint-time housekeeping: rehash tombstone-heavy tables,
        surface consistency violations (ref consistency_error!)."""
        states = list(self.states)
        for i, ex in enumerate(self.fragment.executors):
            if hasattr(ex, "maybe_rehash"):
                states[i] = ex.maybe_rehash(states[i])
            st = states[i]
            if hasattr(st, "inconsistency") and int(st.inconsistency) > 0:
                raise RuntimeError(
                    f"{ex}: {int(st.inconsistency)} deletes hit a "
                    "non-retractable (min/max) aggregate state"
                )
            if hasattr(st, "overflow") and int(st.overflow) > 0:
                raise RuntimeError(
                    f"{ex}: state table overflow ({int(st.overflow)} rows "
                    "dropped) — increase table_size"
                )
        self.states = tuple(states)

    def _drain_pending(self, epoch_val) -> list:
        outs = []
        for i, ex in enumerate(self.fragment.executors):
            if isinstance(ex, HashAggExecutor):
                # one scalar readback per barrier; loops only under
                # extreme dirty-set sizes
                while int(ex.pending_dirty(self.states[i])) > 0:
                    self.states, emitted = self.fragment.flush(
                        self.states, epoch_val
                    )
                    outs.extend(emitted)
        return outs

    def _commit_checkpoint(self, barrier: Barrier) -> None:
        epoch_val = barrier.epoch.prev.value
        snap = CheckpointSnapshot(
            epoch=epoch_val,
            states=jax.device_get(self.states),
            source_state=self.source.state() if hasattr(self.source, "state")
            else {},
        )
        # retain only the latest committed snapshot (ref: Hummock keeps
        # versions; version history arrives with the storage layer)
        self.checkpoints = [snap]
        self.committed_epoch = epoch_val

    def _apply_mutation(self, mutation) -> None:
        if mutation.kind == "pause":
            self.paused = True
        elif mutation.kind == "resume":
            self.paused = False
        elif mutation.kind == "stop":
            self.paused = True

    # -- recovery -------------------------------------------------------
    def recover(self) -> None:
        """Reset to the last committed checkpoint (ref §3.5 recovery:
        rebuild actors + resume from last committed epoch)."""
        if not self.checkpoints:
            self.states = self.fragment.init_states()
            if hasattr(self.source, "offset"):
                self.source.offset = 0
            return
        snap = self.checkpoints[-1]
        self.states = jax.device_put(snap.states)
        if hasattr(self.source, "offset") and "offset" in snap.source_state:
            self.source.offset = snap.source_state["offset"]

    # ------------------------------------------------------------------
    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        """The steady-state loop (ref §3.3)."""
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                self.run_chunk()
            self.inject_barrier()

    def executor_state(self, idx: int):
        return self.states[idx]
