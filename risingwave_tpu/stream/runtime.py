"""Streaming job runtime: the host-side barrier/epoch control loop.

Reference counterparts:
- meta's ``PeriodicBarriers`` + ``GlobalBarrierWorker::run`` loop
  (src/meta/src/barrier/{schedule.rs:508,worker.rs:378})
- CN's ``LocalBarrierWorker`` + actor event loop
  (src/stream/src/task/barrier_worker/mod.rs:303)

TPU-first design (SURVEY.md §7.1): barriers are host control flow.  The
runtime ticks epochs, runs K jitted fragment steps per epoch (each step
processes one source chunk), then crosses the barrier: flush
emit-on-barrier state, commit the epoch, snapshot on checkpoint
barriers.  "One actor = one tokio task" collapses into "one fragment =
one jitted program", so barrier alignment inside a single fragment is
trivial (sequential steps) and multi-fragment alignment is the loop
order.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax

from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.message import Barrier, BarrierKind


@dataclass
class CheckpointSnapshot:
    """A committed epoch: host copies of all state + source offsets.

    ref: Hummock ``commit_epoch`` (src/meta/src/hummock/manager/
    commit_epoch.rs:73) — here the "SST upload" is a device→host state
    fetch; the persistent-store spill lands with the storage layer.
    """

    epoch: int
    states: Any
    source_state: dict


def drain_agg_pending(fragment: Fragment, states, epoch_val):
    """Re-flush until nothing pending remains (emit-capacity spill).

    Any executor exposing ``pending_flush(state) -> count`` participates
    (hash agg dirty groups, EOWC closed rows, ...).
    """
    outs = []
    for i, ex in enumerate(fragment.executors):
        if hasattr(ex, "pending_flush"):
            # one scalar readback per barrier; loops only under extreme
            # pending-set sizes
            while int(ex.pending_flush(states[i])) > 0:
                states, emitted = fragment.flush(states, epoch_val)
                outs.extend(emitted)
    return states, outs


def propagate_watermarks(fragment: Fragment, states):
    """Read watermark generators (one scalar each), push the control
    message through the fragment (ref watermark_filter.rs emission)."""
    from risingwave_tpu.stream.message import Watermark
    from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

    for i, ex in enumerate(fragment.executors):
        if isinstance(ex, WatermarkFilterExecutor):
            wm = ex.current_watermark(states[i])
            if wm is not None:
                states = fragment.on_watermark(
                    states, Watermark(ex.ts_col, wm)
                )
    return states


def deliver_sinks(fragment: Fragment, states, epoch_val):
    """Drain sink ring buffers to their connectors (host barrier hook)."""
    states = list(states)
    for i, ex in enumerate(fragment.executors):
        if hasattr(ex, "deliver"):
            states[i] = ex.deliver(states[i], epoch_val)
    return tuple(states)


def maintain_fragment(fragment: Fragment, states, name: str):
    """Checkpoint-time housekeeping: rehash tombstone-heavy tables and
    surface consistency violations (ref consistency_error!)."""
    states = list(states)
    for i, ex in enumerate(fragment.executors):
        if hasattr(ex, "maybe_rehash"):
            states[i] = ex.maybe_rehash(states[i])
        check_state_counters(f"{name}/{ex}", states[i])
    return tuple(states)


def restore_source(source, state: dict) -> None:
    """Restore a source from its checkpointed state() dict.

    Sources may implement ``restore(state)`` for full-fidelity recovery;
    the fallback covers plain offset-cursor sources."""
    if hasattr(source, "restore"):
        source.restore(state)
    elif hasattr(source, "offset") and "offset" in state:
        source.offset = state["offset"]


def check_state_counters(name: str, st) -> None:
    if hasattr(st, "inconsistency") and int(st.inconsistency) > 0:
        raise RuntimeError(
            f"{name}: {int(st.inconsistency)} inconsistent changelog rows "
            "(deletes with no matching state)"
        )
    if hasattr(st, "overflow") and int(st.overflow) > 0:
        raise RuntimeError(
            f"{name}: state table overflow ({int(st.overflow)} rows "
            "dropped) — increase table/bucket capacity"
        )


class StreamingJob:
    """A linear source → fragment pipeline driven by the barrier loop.

    The fragment typically ends in a Materialize executor (the MV).
    ``source.next_chunk()`` must return a device ``Chunk``.
    """

    def __init__(
        self,
        source,
        fragment: Fragment,
        name: str = "job",
        checkpoint_frequency: int = 1,
        checkpoint_store=None,
    ):
        self.source = source
        self.fragment = fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        #: optional durable store (storage.CheckpointStore); when set,
        #: commits persist across process restarts
        self.checkpoint_store = checkpoint_store
        #: checkpoints between maintenance passes (amortizes syncs)
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        #: checkpoints between in-memory snapshot copies
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        self.states = fragment.init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        #: committed epoch visible to batch reads (ref pinned snapshots)
        self.committed_epoch: int = 0
        self.paused = False
        # fuse generation into the step when the source is traceable:
        # the source chunk never materializes standalone — XLA fuses
        # generator arithmetic straight into the executor kernels
        self._fused = None
        if hasattr(source, "impl") and hasattr(source, "next_base"):
            import jax as _jax

            def _fused(states, k0):
                return fragment._step_impl(
                    states, source.impl(k0, source.cap)
                )

            self._fused = _jax.jit(_fused, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def run_chunk(self) -> int:
        """Pull one chunk from the source through the fragment.

        Returns the chunk capacity processed (0 when paused) so callers
        can meter throughput without a device sync."""
        if self.paused:
            return 0
        if self._fused is not None:
            import jax.numpy as _jnp
            self.states, _ = self._fused(
                self.states, _jnp.int64(self.source.next_base())
            )
            return self.source.cap
        chunk = self.source.next_chunk()
        self.states, _ = self.fragment.step(self.states, chunk)
        return chunk.capacity

    def inject_barrier(self, barrier: Barrier | None = None) -> list:
        """Cross a barrier: flush, (maybe) checkpoint, bump the epoch.

        Returns the chunks emitted by flush (they have already flowed
        through the downstream executors inside the fragment — e.g. into
        a trailing Materialize — so callers usually ignore them).
        """
        if barrier is None:
            self.barriers_seen += 1
            kind = (
                BarrierKind.CHECKPOINT
                if self.barriers_seen % self.checkpoint_frequency == 0
                else BarrierKind.BARRIER
            )
            # the barrier SEALS the epoch data has been flowing in
            # (epoch.curr) and opens the next one (ref EpochPair)
            barrier = Barrier(
                EpochPair(self.epoch.curr.next(), self.epoch.curr), kind
            )
        if barrier.mutation is not None:
            self._apply_mutation(barrier.mutation)

        epoch_val = barrier.epoch.prev.value
        outs = []
        self.states, emitted = self.fragment.flush(self.states, epoch_val)
        outs.extend(emitted)
        # drain aggregations whose dirty set exceeded one emit chunk
        outs.extend(self._drain_pending(epoch_val))

        # propagate watermarks, then re-drain: EOWC rows closed by THIS
        # barrier's watermark must emit at this barrier, not the next
        self.states = propagate_watermarks(self.fragment, self.states)
        outs.extend(self._drain_pending(epoch_val))
        if barrier.is_checkpoint:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain()
                self._ckpts_since_maintain = 0
            self._commit_checkpoint(barrier)
        self.epoch = barrier.epoch
        return outs


    def _maintain(self) -> None:
        self.states = maintain_fragment(self.fragment, self.states, self.name)

    def _drain_pending(self, epoch_val) -> list:
        self.states, outs = drain_agg_pending(
            self.fragment, self.states, epoch_val
        )
        return outs

    def _commit_checkpoint(self, barrier: Barrier) -> None:
        """Commit = snapshot + sink delivery + committed_epoch, all on
        the SAME cadence: recovery rewinds to the last snapshot, so a
        sink delivery or committed_epoch beyond it would be a lie
        (duplicated sink rows / unrecoverable epochs)."""
        epoch_val = barrier.epoch.prev.value
        self._ckpts_since_snapshot += 1
        if self._ckpts_since_snapshot < self.snapshot_interval:
            return
        self._ckpts_since_snapshot = 0
        self.states = deliver_sinks(self.fragment, self.states, epoch_val)
        self.committed_epoch = epoch_val
        src_state = self.source.state() if hasattr(self.source, "state") \
            else {}
        # the in-memory snapshot device-copies the state: the donated
        # step/flush buffers would otherwise be invalidated under the
        # snapshot (use-after-donation); durable persistence additionally
        # pays the device->host transfer
        import jax.numpy as _jnp
        snap = CheckpointSnapshot(
            epoch=epoch_val,
            states=jax.tree.map(_jnp.copy, self.states),
            source_state=src_state,
        )
        # retain only the latest committed snapshot in memory; the
        # durable store keeps epoch history (ref: Hummock versions)
        self.checkpoints = [snap]
        if self.checkpoint_store is not None:
            self.checkpoint_store.save(
                self.name, epoch_val, jax.device_get(snap.states), src_state
            )

    def _apply_mutation(self, mutation) -> None:
        if mutation.kind == "pause":
            self.paused = True
        elif mutation.kind == "resume":
            self.paused = False
        elif mutation.kind == "stop":
            self.paused = True

    # -- recovery -------------------------------------------------------
    def recover(self) -> None:
        """Reset to the last committed checkpoint (ref §3.5 recovery:
        rebuild actors + resume from last committed epoch).  Prefers the
        durable store (survives process restarts) over the in-memory
        snapshot."""
        if self.checkpoint_store is not None:
            loaded = self.checkpoint_store.load(self.name)
            if loaded is not None:
                epoch, states, src_state = loaded
                self.states = jax.device_put(states)
                self.committed_epoch = epoch
                restore_source(self.source, src_state)
                return
        if not self.checkpoints:
            self.states = self.fragment.init_states()
            if hasattr(self.source, "offset"):
                self.source.offset = 0
            return
        snap = self.checkpoints[-1]
        import jax.numpy as _jnp
        # copy: the next step donates its input buffers, which must not
        # invalidate the retained snapshot
        self.states = jax.tree.map(_jnp.copy, snap.states)
        restore_source(self.source, snap.source_state)

    # ------------------------------------------------------------------
    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        """The steady-state loop (ref §3.3)."""
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                self.run_chunk()
            self.inject_barrier()

    def executor_state(self, idx: int):
        return self.states[idx]


class BinaryJob:
    """Two sources → per-side fragments → join → post fragment.

    The reference runs a join as one actor whose two upstream inputs are
    barrier-aligned by ``barrier_align.rs:44``; here alignment is the
    host loop pulling both sides before each barrier, and the whole
    per-chunk path (side fragment + join update/probe + post fragment)
    is one jitted program per side.
    """

    def __init__(
        self,
        left_source,
        right_source,
        join,
        post_fragment: Fragment,
        left_fragment: Fragment | None = None,
        right_fragment: Fragment | None = None,
        checkpoint_frequency: int = 1,
        name: str = "join_job",
        checkpoint_store=None,
    ):
        self.checkpoint_store = checkpoint_store
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        #: chunks pulled per scheduling unit (left, right) — sides whose
        #: rows represent different event-time spans pace proportionally
        #: so neither watermark runs unboundedly ahead (nexmark persons
        #: sweep event time 3x faster per row than auctions)
        self.chunk_ratio = self._compute_ratio(left_source, right_source)
        self.left_source = left_source
        self.right_source = right_source
        self.join = join
        self.post = post_fragment
        self.left_frag = left_fragment
        self.right_frag = right_fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        self.states = (
            left_fragment.init_states() if left_fragment else (),
            right_fragment.init_states() if right_fragment else (),
            join.init_state(),
            post_fragment.init_states(),
        )
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        self.committed_epoch = 0
        self._step = {
            "left": jax.jit(lambda st, ch: self._side_step(st, ch, "left"),
                            donate_argnums=(0,)),
            "right": jax.jit(lambda st, ch: self._side_step(st, ch, "right"),
                             donate_argnums=(0,)),
        }
        # barrier-time feed: a side fragment's flush emissions cross the
        # join and the post fragment exactly like steady-state chunks
        self._feed = {
            "left": jax.jit(lambda j, p, ch: self._feed_impl(j, p, ch, "left")),
            "right": jax.jit(
                lambda j, p, ch: self._feed_impl(j, p, ch, "right")
            ),
        }

    @staticmethod
    def _compute_ratio(left_source, right_source) -> tuple[int, int]:
        try:
            from fractions import Fraction
            frac = Fraction(left_source.events_per_row) / Fraction(
                right_source.events_per_row
            )
            if frac.numerator <= 16 and frac.denominator <= 16:
                return (frac.denominator, frac.numerator)
        except AttributeError:
            pass
        return (1, 1)

    def _side_step(self, states, chunk, side: str):
        lstate, rstate, jstate, pstate = states
        frag = self.left_frag if side == "left" else self.right_frag
        if frag is not None:
            if side == "left":
                lstate, chunk = frag._step_impl(lstate, chunk)
            else:
                rstate, chunk = frag._step_impl(rstate, chunk)
        if chunk is not None:
            jstate, out = self.join.apply(jstate, chunk, side)
            if out is not None:
                pstate, _ = self.post._step_impl(pstate, out)
        return (lstate, rstate, jstate, pstate)

    def _feed_impl(self, jstate, pstate, chunk, side: str):
        jstate, out = self.join.apply(jstate, chunk, side)
        if out is not None:
            pstate, _ = self.post._step_impl(pstate, out)
        return jstate, pstate

    def run_chunk(self, side: str) -> int:
        source = self.left_source if side == "left" else self.right_source
        chunk = source.next_chunk()
        self.states = self._step[side](self.states, chunk)
        return chunk.capacity

    def inject_barrier(self) -> None:
        self.barriers_seen += 1
        sealed = self.epoch.curr.value
        lstate, rstate, jstate, pstate = self.states

        # side fragments flush first; their emissions cross the join
        for side, frag in (("left", self.left_frag),
                           ("right", self.right_frag)):
            if frag is None:
                continue
            st = lstate if side == "left" else rstate
            st, outs = frag.flush(st, sealed)
            st, more = drain_agg_pending(frag, st, sealed)
            for out in list(outs) + list(more):
                jstate, pstate = self._feed[side](jstate, pstate, out)
            if side == "left":
                lstate = st
            else:
                rstate = st

        pstate, _ = self.post.flush(pstate, sealed)
        pstate, _ = drain_agg_pending(self.post, pstate, sealed)
        # watermarks propagate within each fragment (cross-fragment /
        # through-join propagation arrives with the graph scheduler)
        if self.left_frag is not None:
            lstate = propagate_watermarks(self.left_frag, lstate)
            lstate, more = drain_agg_pending(self.left_frag, lstate, sealed)
            for out in more:
                jstate, pstate = self._feed["left"](jstate, pstate, out)
        if self.right_frag is not None:
            rstate = propagate_watermarks(self.right_frag, rstate)
            rstate, more = drain_agg_pending(self.right_frag, rstate, sealed)
            for out in more:
                jstate, pstate = self._feed["right"](jstate, pstate, out)
        pstate = propagate_watermarks(self.post, pstate)
        pstate, _ = drain_agg_pending(self.post, pstate, sealed)
        jstate = self._clean_join_state(lstate, rstate, jstate)
        self.states = (lstate, rstate, jstate, pstate)

        if self.barriers_seen % self.checkpoint_frequency == 0:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain()
                self._ckpts_since_maintain = 0
            self._ckpts_since_snapshot += 1
            if self._ckpts_since_snapshot >= self.snapshot_interval:
                self._ckpts_since_snapshot = 0
                lstate, rstate, jstate, pstate = self.states
                pstate = deliver_sinks(self.post, pstate, sealed)
                self.states = (lstate, rstate, jstate, pstate)
                self.committed_epoch = sealed
                src_state = {
                    "left": self.left_source.state()
                    if hasattr(self.left_source, "state") else {},
                    "right": self.right_source.state()
                    if hasattr(self.right_source, "state") else {},
                }
                import jax.numpy as _jnp
                snap = CheckpointSnapshot(
                    epoch=sealed,
                    states=jax.tree.map(_jnp.copy, self.states),
                    source_state=src_state,
                )
                self.checkpoints = [snap]
                if self.checkpoint_store is not None:
                    self.checkpoint_store.save(
                        self.name, sealed, jax.device_get(snap.states),
                        src_state,
                    )
        self.epoch = self.epoch.bump()

    def _side_watermark(self, frag, st, src_col):
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        if frag is None:
            return None
        for i, ex in enumerate(frag.executors):
            if isinstance(ex, WatermarkFilterExecutor) \
                    and ex.ts_col == src_col:
                return ex.current_watermark(st[i])
        return None

    def _clean_join_state(self, lstate, rstate, jstate):
        """Watermark-driven join state cleaning (windowed joins).

        A build-side row for window W serves the OTHER side's future
        probes, so each side is cleaned by the MINIMUM watermark across
        both inputs (one side's event time may run far ahead — e.g.
        nexmark persons sweep event numbers ~3x faster than auctions)."""
        wms = []
        for side, frag, st in (("left", self.left_frag, lstate),
                               ("right", self.right_frag, rstate)):
            clean = getattr(self.join, f"{side}_clean", None)
            if clean is None:
                continue
            wm = self._side_watermark(frag, st, clean[2])
            if wm is None:
                return jstate  # one side has no watermark yet
            wms.append(wm)
        if not wms:
            return jstate
        min_wm = min(wms)
        cleaned = False
        for side in ("left", "right"):
            clean = getattr(self.join, f"{side}_clean", None)
            if clean is None:
                continue
            key_idx, lag, _ = clean
            jstate = self.join.clean_below(
                jstate, side, key_idx, min_wm - lag
            )
            cleaned = True
        # cleaning tombstones slots; reclaim promptly (self-gated on
        # tombstone fraction) or the table starves within a few barriers
        if cleaned and hasattr(self.join, "maybe_rehash"):
            jstate = self.join.maybe_rehash(jstate)
        return jstate

    def _maintain(self) -> None:
        lstate, rstate, jstate, pstate = self.states
        if self.left_frag is not None:
            lstate = maintain_fragment(
                self.left_frag, lstate, f"{self.name}/left"
            )
        if self.right_frag is not None:
            rstate = maintain_fragment(
                self.right_frag, rstate, f"{self.name}/right"
            )
        if hasattr(self.join, "maybe_rehash"):
            jstate = self.join.maybe_rehash(jstate)
        check_state_counters(f"{self.name}/join.left", jstate.left)
        check_state_counters(f"{self.name}/join.right", jstate.right)
        if int(jstate.emit_overflow) > 0:
            raise RuntimeError(
                f"{self.name}: join emit overflow "
                f"({int(jstate.emit_overflow)} matches dropped) — "
                "increase out_capacity"
            )
        pstate = maintain_fragment(self.post, pstate, f"{self.name}/post")
        self.states = (lstate, rstate, jstate, pstate)

    def recover(self) -> None:
        """Reset to the last committed checkpoint (ref §3.5)."""
        if self.checkpoint_store is not None:
            loaded = self.checkpoint_store.load(self.name)
            if loaded is not None:
                epoch, states, src_state = loaded
                self.states = jax.device_put(states)
                self.committed_epoch = epoch
                for side, src in (("left", self.left_source),
                                  ("right", self.right_source)):
                    restore_source(src, src_state.get(side, {}))
                return
        if not self.checkpoints:
            self.states = (
                self.left_frag.init_states() if self.left_frag else (),
                self.right_frag.init_states() if self.right_frag else (),
                self.join.init_state(),
                self.post.init_states(),
            )
            for src in (self.left_source, self.right_source):
                if hasattr(src, "offset"):
                    src.offset = 0
            return
        snap = self.checkpoints[-1]
        import jax.numpy as _jnp
        self.states = jax.tree.map(_jnp.copy, snap.states)
        for side, src in (("left", self.left_source),
                          ("right", self.right_source)):
            restore_source(src, snap.source_state.get(side, {}))

    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        l, r = self.chunk_ratio
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                for _ in range(l):
                    self.run_chunk("left")
                for _ in range(r):
                    self.run_chunk("right")
            self.inject_barrier()
