"""Streaming job runtime: the host-side barrier/epoch control loop.

Reference counterparts:
- meta's ``PeriodicBarriers`` + ``GlobalBarrierWorker::run`` loop
  (src/meta/src/barrier/{schedule.rs:508,worker.rs:378})
- CN's ``LocalBarrierWorker`` + actor event loop
  (src/stream/src/task/barrier_worker/mod.rs:303)

TPU-first design (SURVEY.md §7.1): barriers are host control flow, but
the barrier CROSSING is one asynchronously dispatched XLA program.  The
steady-state loop — K chunk steps, then a barrier — performs ZERO
synchronous host↔device round trips:

- emit-capacity drain loops run on device (``lax.while_loop`` inside
  the barrier program) instead of host readback loops;
- watermarks propagate as device scalars inside the same program;
- error counters (overflow/inconsistency) are collected into ONE device
  vector per barrier and read back once per maintenance interval;
- rehash decisions are ``lax.cond`` on device tombstone counts;
- in-memory snapshots are jit-compiled device→device tree copies.

This matters doubly on a tunneled accelerator where every synchronous
readback costs a full round trip (measured ~66 ms on the dev tunnel vs
~40 µs per async dispatch), but it is the right shape for local TPUs
too: the host never stalls the device pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.stream.fragment import (
    COUNTER_ATTRS,
    Fragment,
    WM_NONE,
    WM_SAFE_FLOOR,
    collect_counters,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind


@dataclass
class CheckpointSnapshot:
    """A committed epoch: device copies of all state + source offsets.

    ref: Hummock ``commit_epoch`` (src/meta/src/hummock/manager/
    commit_epoch.rs:73) — the in-memory snapshot stays device-resident
    (a jitted tree copy); only the durable store pays a device→host
    transfer.
    """

    epoch: int
    states: Any
    source_state: dict


#: jitted device→device snapshot copy (one dispatch for the whole tree)
@jax.jit
def _snapshot_copy(tree):
    return jax.tree.map(jnp.copy, tree)


def check_counter_values(name: str, labels: list[str],
                         values: np.ndarray) -> list[str]:
    """Raise on error counters; return labels with residual pending.

    ``values`` is the host copy of a barrier program's counters vector.
    """
    residual = []
    for label, v in zip(labels, values):
        if label.endswith(".pending"):
            if v > 0:
                residual.append(label)
        elif v > 0:
            kind = label.rsplit(".", 1)[-1]
            if kind == "inconsistency":
                raise RuntimeError(
                    f"{name}/{label}: {v} inconsistent changelog rows "
                    "(deletes with no matching state)"
                )
            if kind == "emit_overflow":
                raise RuntimeError(
                    f"{name}/{label}: emit overflow ({v} output rows "
                    "dropped) — increase out_capacity"
                )
            hint = "ring_size" if "Ring" in label or "AppendOnly" in label \
                else "table/bucket capacity"
            raise RuntimeError(
                f"{name}/{label}: state overflow ({v} rows dropped) — "
                f"increase {hint}"
            )
    return residual


def check_state_counters(name: str, st) -> None:
    """Eager single-state check (test/debug surface; one readback per
    counter — not for the steady-state loop)."""
    for attr in ("inconsistency", "overflow"):
        if hasattr(st, attr) and int(getattr(st, attr)) > 0:
            check_counter_values(
                name, [f"state.{attr}"],
                np.asarray([int(getattr(st, attr))]),
            )


def restore_source(source, state: dict) -> None:
    """Restore a source from its checkpointed state() dict.

    Sources may implement ``restore(state)`` for full-fidelity recovery;
    the fallback covers plain offset-cursor sources."""
    if hasattr(source, "restore"):
        source.restore(state)
    elif hasattr(source, "offset") and "offset" in state:
        source.offset = state["offset"]


def deliver_sinks(fragment: Fragment, states, epoch_val):
    """Drain sink ring buffers to their connectors (host barrier hook).

    Inherently a device→host read — runs on the snapshot cadence only."""
    states = list(states)
    for i, ex in enumerate(fragment.executors):
        if hasattr(ex, "deliver"):
            states[i] = ex.deliver(states[i], epoch_val)
    return tuple(states)


class StreamingJob:
    """A linear source → fragment pipeline driven by the barrier loop.

    The fragment typically ends in a Materialize executor (the MV).
    ``source.next_chunk()`` must return a device ``Chunk``.
    """

    def __init__(
        self,
        source,
        fragment: Fragment,
        name: str = "job",
        checkpoint_frequency: int = 1,
        checkpoint_store=None,
    ):
        self.source = source
        self.fragment = fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        #: optional durable store (storage.CheckpointStore); when set,
        #: commits persist across process restarts
        self.checkpoint_store = checkpoint_store
        #: checkpoints between maintenance passes (amortizes the ONE
        #: counters readback + rehash program)
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        #: checkpoints between in-memory snapshot copies
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        self.states = fragment.init_states()
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        #: committed epoch visible to batch reads (ref pinned snapshots)
        self.committed_epoch: int = 0
        self.paused = False
        #: counters vector from the last barrier program (device array;
        #: read back once per maintenance interval)
        self._counters = None
        # fuse generation into the step when the source is traceable:
        # the source chunk never materializes standalone — XLA fuses
        # generator arithmetic straight into the executor kernels
        self._fused = None
        if hasattr(source, "impl") and hasattr(source, "next_base"):

            def _fused(states, k0):
                return fragment._step_impl(
                    states, source.impl(k0, source.cap)
                )

            self._fused = jax.jit(_fused, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def run_chunk(self) -> int:
        """Pull one chunk from the source through the fragment.

        Returns the chunk capacity processed (0 when paused) so callers
        can meter throughput without a device sync."""
        if self.paused:
            return 0
        if self._fused is not None:
            self.states, _ = self._fused(
                self.states, jnp.int64(self.source.next_base())
            )
            return self.source.cap
        chunk = self.source.next_chunk()
        self.states, _ = self.fragment.step(self.states, chunk)
        return chunk.capacity

    def inject_barrier(self, barrier: Barrier | None = None) -> list:
        """Cross a barrier: one async dispatch (flush + drain +
        watermarks + counters), then maintenance / checkpoint on their
        cadences.

        Returns the chunks emitted by the first flush pass (they have
        already flowed through the downstream executors inside the
        fragment — e.g. into a trailing Materialize — so callers
        usually ignore them).
        """
        if barrier is None:
            self.barriers_seen += 1
            kind = (
                BarrierKind.CHECKPOINT
                if self.barriers_seen % self.checkpoint_frequency == 0
                else BarrierKind.BARRIER
            )
            # the barrier SEALS the epoch data has been flowing in
            # (epoch.curr) and opens the next one (ref EpochPair)
            barrier = Barrier(
                EpochPair(self.epoch.curr.next(), self.epoch.curr), kind
            )
        if barrier.mutation is not None:
            self._apply_mutation(barrier.mutation)

        epoch_val = barrier.epoch.prev.value
        self.states, outs, self._counters = self.fragment.barrier(
            self.states, epoch_val
        )
        if barrier.is_checkpoint:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain(epoch_val)
                self._ckpts_since_maintain = 0
            self._commit_checkpoint(barrier)
        self.epoch = barrier.epoch
        return outs

    def _maintain(self, epoch_val) -> None:
        """Rehash (on device) + the single counters readback."""
        self.states = self.fragment.maintain(self.states)
        if self._counters is None:
            return
        values = np.asarray(self._counters)  # THE one device sync
        residual = check_counter_values(
            self.name, self.fragment.counter_labels, values
        )
        # residual pending beyond MAX_DRAIN_ROUNDS×emit_capacity per
        # barrier: pathological; finish draining with host loops
        for _ in range(64):
            if not residual:
                break
            self.states, _, self._counters = self.fragment.barrier(
                self.states, epoch_val
            )
            residual = check_counter_values(
                self.name, self.fragment.counter_labels,
                np.asarray(self._counters),
            )

    def _commit_checkpoint(self, barrier: Barrier) -> None:
        """Commit = snapshot + sink delivery + committed_epoch, all on
        the SAME cadence: recovery rewinds to the last snapshot, so a
        sink delivery or committed_epoch beyond it would be a lie
        (duplicated sink rows / unrecoverable epochs)."""
        epoch_val = barrier.epoch.prev.value
        self._ckpts_since_snapshot += 1
        if self._ckpts_since_snapshot < self.snapshot_interval:
            return
        self._ckpts_since_snapshot = 0
        self.states = deliver_sinks(self.fragment, self.states, epoch_val)
        self.committed_epoch = epoch_val
        src_state = self.source.state() if hasattr(self.source, "state") \
            else {}
        # the in-memory snapshot device-copies the state in ONE jitted
        # dispatch: the donated step/flush buffers would otherwise be
        # invalidated under the snapshot (use-after-donation); durable
        # persistence additionally pays the device→host transfer
        snap = CheckpointSnapshot(
            epoch=epoch_val,
            states=_snapshot_copy(self.states),
            source_state=src_state,
        )
        # retain only the latest committed snapshot in memory; the
        # durable store keeps epoch history (ref: Hummock versions)
        self.checkpoints = [snap]
        if self.checkpoint_store is not None:
            self.checkpoint_store.save(
                self.name, epoch_val, jax.device_get(snap.states), src_state
            )

    def _apply_mutation(self, mutation) -> None:
        if mutation.kind == "pause":
            self.paused = True
        elif mutation.kind == "resume":
            self.paused = False
        elif mutation.kind == "stop":
            self.paused = True

    # -- recovery -------------------------------------------------------
    def recover(self) -> None:
        """Reset to the last committed checkpoint (ref §3.5 recovery:
        rebuild actors + resume from last committed epoch).  Prefers the
        durable store (survives process restarts) over the in-memory
        snapshot."""
        self._counters = None
        if self.checkpoint_store is not None:
            loaded = self.checkpoint_store.load(self.name)
            if loaded is not None:
                epoch, states, src_state = loaded
                self.states = jax.device_put(states)
                self.committed_epoch = epoch
                restore_source(self.source, src_state)
                return
        if not self.checkpoints:
            self.states = self.fragment.init_states()
            if hasattr(self.source, "offset"):
                self.source.offset = 0
            return
        snap = self.checkpoints[-1]
        # copy: the next step donates its input buffers, which must not
        # invalidate the retained snapshot
        self.states = _snapshot_copy(snap.states)
        restore_source(self.source, snap.source_state)

    # ------------------------------------------------------------------
    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        """The steady-state loop (ref §3.3)."""
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                self.run_chunk()
            self.inject_barrier()

    def executor_state(self, idx: int):
        return self.states[idx]


class BinaryJob:
    """Two sources → per-side fragments → join → post fragment.

    The reference runs a join as one actor whose two upstream inputs are
    barrier-aligned by ``barrier_align.rs:44``; here alignment is the
    host loop pulling both sides before each barrier, and the whole
    per-chunk path (side fragment + join update/probe + post fragment)
    is one jitted program per side.  The barrier crossing — side
    flushes + drains feeding the join, watermark propagation, join
    state cleaning, counters — is ONE jitted program, so the loop stays
    fully asynchronous like ``StreamingJob``.
    """

    def __init__(
        self,
        left_source,
        right_source,
        join,
        post_fragment: Fragment,
        left_fragment: Fragment | None = None,
        right_fragment: Fragment | None = None,
        checkpoint_frequency: int = 1,
        name: str = "join_job",
        checkpoint_store=None,
    ):
        self.checkpoint_store = checkpoint_store
        self.maintenance_interval = 1
        self._ckpts_since_maintain = 0
        self.snapshot_interval = 1
        self._ckpts_since_snapshot = 0
        #: chunks pulled per scheduling unit (left, right) — sides whose
        #: rows represent different event-time spans pace proportionally
        #: so neither watermark runs unboundedly ahead (nexmark persons
        #: sweep event time 3x faster per row than auctions)
        self.chunk_ratio = self._compute_ratio(left_source, right_source)
        self.left_source = left_source
        self.right_source = right_source
        self.join = join
        self.post = post_fragment
        self.left_frag = left_fragment
        self.right_frag = right_fragment
        self.name = name
        self.checkpoint_frequency = checkpoint_frequency
        self.states = (
            left_fragment.init_states() if left_fragment else (),
            right_fragment.init_states() if right_fragment else (),
            join.init_state(),
            post_fragment.init_states(),
        )
        self.epoch = EpochPair.first()
        self.barriers_seen = 0
        self.checkpoints: list[CheckpointSnapshot] = []
        self.committed_epoch = 0
        self._counters = None
        self.counter_labels: list[str] = []
        self._step = {
            "left": jax.jit(lambda st, ch: self._side_step(st, ch, "left"),
                            donate_argnums=(0,)),
            "right": jax.jit(lambda st, ch: self._side_step(st, ch, "right"),
                             donate_argnums=(0,)),
        }
        self._barrier = jax.jit(self._barrier_impl, donate_argnums=(0,))
        self._maintain_prog = jax.jit(
            self._maintain_impl, donate_argnums=(0,)
        )

    @staticmethod
    def _compute_ratio(left_source, right_source) -> tuple[int, int]:
        try:
            from fractions import Fraction
            frac = Fraction(left_source.events_per_row) / Fraction(
                right_source.events_per_row
            )
            if frac.numerator <= 16 and frac.denominator <= 16:
                return (frac.denominator, frac.numerator)
        except AttributeError:
            pass
        return (1, 1)

    def _side_step(self, states, chunk, side: str):
        lstate, rstate, jstate, pstate = states
        frag = self.left_frag if side == "left" else self.right_frag
        if frag is not None:
            if side == "left":
                lstate, chunk = frag._step_impl(lstate, chunk)
            else:
                rstate, chunk = frag._step_impl(rstate, chunk)
        if chunk is not None:
            jstate, out = self.join.apply(jstate, chunk, side)
            if out is not None:
                pstate, _ = self.post._step_impl(pstate, out)
        return (lstate, rstate, jstate, pstate)

    def run_chunk(self, side: str) -> int:
        source = self.left_source if side == "left" else self.right_source
        chunk = source.next_chunk()
        self.states = self._step[side](self.states, chunk)
        return chunk.capacity

    # -- the single-dispatch barrier program ----------------------------
    def _feed(self, jstate, pstate, chunk, side: str):
        jstate, out = self.join.apply(jstate, chunk, side)
        if out is not None:
            pstate, _ = self.post._step_impl(pstate, out)
        return jstate, pstate

    def _flush_side(self, frag, st, jstate, pstate, side: str, epoch):
        """Flush one side fragment; its emissions cross the join and the
        post fragment.  Drains on device when the side has pending."""
        st, outs = frag._flush_impl(st, epoch)
        for out in outs:
            jstate, pstate = self._feed(jstate, pstate, out, side)
        if frag.has_pending_protocol():

            def cond(carry):
                st, jstate, pstate, it = carry
                return (frag.pending_total(st) > 0) & (
                    it < frag.MAX_DRAIN_ROUNDS
                )

            def body(carry):
                st, jstate, pstate, it = carry
                st, outs = frag._flush_impl(st, epoch)
                for out in outs:
                    jstate, pstate = self._feed(jstate, pstate, out, side)
                return st, jstate, pstate, it + 1

            st, jstate, pstate, _ = jax.lax.while_loop(
                cond, body, (st, jstate, pstate, jnp.int32(0))
            )
        return st, jstate, pstate

    def _side_wm_device(self, frag, st, src_col):
        """(value, has) device watermark from a side's wm filter, or
        None when the side has no matching generator (static)."""
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        if frag is None:
            return None
        for i, ex in enumerate(frag.executors):
            if isinstance(ex, WatermarkFilterExecutor) \
                    and ex.ts_col == src_col:
                raw = st[i].max_ts
                has = raw != WM_NONE
                val = jnp.where(
                    has, raw - ex.delay_us, jnp.int64(WM_SAFE_FLOOR)
                )
                return val, has
        return None

    def _clean_join_state(self, lstate, rstate, jstate):
        """Watermark-driven join state cleaning (windowed joins).

        A build-side row for window W serves the OTHER side's future
        probes, so each side is cleaned by the MINIMUM watermark across
        both inputs (one side's event time may run far ahead — e.g.
        nexmark persons sweep event numbers ~3x faster than auctions).
        Fully on device: values are traced scalars, the clean+rehash is
        gated by ``lax.cond`` on watermark presence."""
        wms = []
        for side, frag, st in (("left", self.left_frag, lstate),
                               ("right", self.right_frag, rstate)):
            clean = getattr(self.join, f"{side}_clean", None)
            if clean is None:
                continue
            wm = self._side_wm_device(frag, st, clean[2])
            if wm is None:
                return jstate  # side lacks a wm generator (static)
            wms.append(wm)
        if not wms:
            return jstate
        has_all = wms[0][1]
        min_wm = wms[0][0]
        for val, has in wms[1:]:
            has_all = has_all & has
            min_wm = jnp.minimum(min_wm, val)

        def do_clean(jstate):
            for side in ("left", "right"):
                clean = getattr(self.join, f"{side}_clean", None)
                if clean is None:
                    continue
                key_idx, lag, _ = clean
                jstate = self.join.clean_below(
                    jstate, side, key_idx, min_wm - lag
                )
            # cleaning tombstones slots; reclaim promptly (self-gated on
            # tombstone fraction) or the table starves within barriers
            if hasattr(self.join, "maybe_rehash"):
                jstate = self.join.maybe_rehash(jstate)
            return jstate

        return jax.lax.cond(has_all, do_clean, lambda j: j, jstate)

    def _barrier_impl(self, states, epoch):
        lstate, rstate, jstate, pstate = states

        # side fragments flush first; their emissions cross the join
        if self.left_frag is not None:
            lstate, jstate, pstate = self._flush_side(
                self.left_frag, lstate, jstate, pstate, "left", epoch
            )
        if self.right_frag is not None:
            rstate, jstate, pstate = self._flush_side(
                self.right_frag, rstate, jstate, pstate, "right", epoch
            )
        pstate = self.post._flush_states_only(pstate, epoch)
        pstate = self.post._drain_impl(pstate, epoch)

        # watermarks propagate within each fragment, then re-drain:
        # EOWC rows closed by THIS barrier's watermark emit now
        if self.left_frag is not None:
            lstate = self.left_frag._wm_impl(lstate)
            lstate, jstate, pstate = self._flush_side(
                self.left_frag, lstate, jstate, pstate, "left", epoch
            )
        if self.right_frag is not None:
            rstate = self.right_frag._wm_impl(rstate)
            rstate, jstate, pstate = self._flush_side(
                self.right_frag, rstate, jstate, pstate, "right", epoch
            )
        pstate = self.post._wm_impl(pstate)
        pstate = self.post._drain_impl(pstate, epoch)
        jstate = self._clean_join_state(lstate, rstate, jstate)

        # one counters vector for the whole job
        labels: list[str] = []
        vals: list[jnp.ndarray] = []
        for tag, frag, st in (("left", self.left_frag, lstate),
                              ("right", self.right_frag, rstate),
                              ("post", self.post, pstate)):
            if frag is None:
                continue
            sub_labels, sub = collect_counters(frag.executors, st)
            labels.extend(f"{tag}.{x}" for x in sub_labels)
            vals.append(sub)
        for side_name in ("left", "right"):
            s = getattr(jstate, side_name)
            for attr in COUNTER_ATTRS:
                if hasattr(s, attr):
                    labels.append(f"join.{side_name}.{attr}")
                    vals.append(getattr(s, attr).astype(jnp.int64)[None])
        labels.append("join.emit_overflow")
        vals.append(jstate.emit_overflow.astype(jnp.int64)[None])
        counters = jnp.concatenate(vals) if vals \
            else jnp.zeros((0,), jnp.int64)
        self.counter_labels = labels
        return (lstate, rstate, jstate, pstate), counters

    def inject_barrier(self) -> None:
        self.barriers_seen += 1
        sealed = self.epoch.curr.value
        self.states, self._counters = self._barrier(self.states, sealed)

        if self.barriers_seen % self.checkpoint_frequency == 0:
            self._ckpts_since_maintain += 1
            if self._ckpts_since_maintain >= self.maintenance_interval:
                self._maintain(sealed)
                self._ckpts_since_maintain = 0
            self._ckpts_since_snapshot += 1
            if self._ckpts_since_snapshot >= self.snapshot_interval:
                self._ckpts_since_snapshot = 0
                lstate, rstate, jstate, pstate = self.states
                pstate = deliver_sinks(self.post, pstate, sealed)
                self.states = (lstate, rstate, jstate, pstate)
                self.committed_epoch = sealed
                src_state = {
                    "left": self.left_source.state()
                    if hasattr(self.left_source, "state") else {},
                    "right": self.right_source.state()
                    if hasattr(self.right_source, "state") else {},
                }
                snap = CheckpointSnapshot(
                    epoch=sealed,
                    states=_snapshot_copy(self.states),
                    source_state=src_state,
                )
                self.checkpoints = [snap]
                if self.checkpoint_store is not None:
                    self.checkpoint_store.save(
                        self.name, sealed, jax.device_get(snap.states),
                        src_state,
                    )
        self.epoch = self.epoch.bump()

    def _maintain_impl(self, states):
        lstate, rstate, jstate, pstate = states
        if self.left_frag is not None:
            lstate = self.left_frag._maintain_impl(lstate)
        if self.right_frag is not None:
            rstate = self.right_frag._maintain_impl(rstate)
        if hasattr(self.join, "maybe_rehash"):
            jstate = self.join.maybe_rehash(jstate)
        pstate = self.post._maintain_impl(pstate)
        return (lstate, rstate, jstate, pstate)

    def _maintain(self, sealed) -> None:
        self.states = self._maintain_prog(self.states)
        if self._counters is None:
            return
        values = np.asarray(self._counters)  # THE one device sync
        residual = check_counter_values(
            self.name, self.counter_labels, values
        )
        for _ in range(64):
            if not residual:
                break
            self.states, self._counters = self._barrier(self.states, sealed)
            residual = check_counter_values(
                self.name, self.counter_labels, np.asarray(self._counters)
            )

    def recover(self) -> None:
        """Reset to the last committed checkpoint (ref §3.5)."""
        self._counters = None
        if self.checkpoint_store is not None:
            loaded = self.checkpoint_store.load(self.name)
            if loaded is not None:
                epoch, states, src_state = loaded
                self.states = jax.device_put(states)
                self.committed_epoch = epoch
                for side, src in (("left", self.left_source),
                                  ("right", self.right_source)):
                    restore_source(src, src_state.get(side, {}))
                return
        if not self.checkpoints:
            self.states = (
                self.left_frag.init_states() if self.left_frag else (),
                self.right_frag.init_states() if self.right_frag else (),
                self.join.init_state(),
                self.post.init_states(),
            )
            for src in (self.left_source, self.right_source):
                if hasattr(src, "offset"):
                    src.offset = 0
            return
        snap = self.checkpoints[-1]
        self.states = _snapshot_copy(snap.states)
        for side, src in (("left", self.left_source),
                          ("right", self.right_source)):
            restore_source(src, snap.source_state.get(side, {}))

    def run(self, barriers: int, chunks_per_barrier: int) -> None:
        l, r = self.chunk_ratio
        for _ in range(barriers):
            for _ in range(chunks_per_barrier):
                for _ in range(l):
                    self.run_chunk("left")
                for _ in range(r):
                    self.run_chunk("right")
            self.inject_barrier()
