"""DynamicFilterExecutor: filter a stream against a changing scalar.

Reference counterpart: ``src/stream/src/executor/dynamic_filter.rs`` —
the band join behind ``WHERE v > (SELECT max(x) FROM t)``: the left
stream is filtered by a comparison whose right side is a 1-row
changelog (usually a global aggregate).  When the scalar moves, rows
in the band between the old and new thresholds must be emitted
(threshold dropped → inserts) or retracted (threshold rose → deletes).

TPU-first design: the left side lives in the same flat device row pool
as TopN; a threshold change emits the whole flipped band with one
vectorized comparison over the pool — the reference walks a range scan
over its ordered state table.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from risingwave_tpu.common.chunk import Chunk, OP_DELETE, OP_INSERT
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.top_n import (
    _empty_like_col,
    pool_apply,
    schema_protos,
)

_CMPS = {
    "gt": lambda v, t: v > t,
    "ge": lambda v, t: v >= t,
    "lt": lambda v, t: v < t,
    "le": lambda v, t: v <= t,
    # equality band (TPC-H q15: total_revenue = (SELECT max(...)))
    "eq": lambda v, t: v == t,
}


class DynFilterState(NamedTuple):
    rows: tuple
    valid: jnp.ndarray
    row_hash: jnp.ndarray
    threshold: jnp.ndarray      # current RHS scalar
    has_threshold: jnp.ndarray  # bool — RHS seen at least once
    overflow: jnp.ndarray
    inconsistency: jnp.ndarray


class DynamicFilterExecutor:
    """Two-input executor: ``apply(state, chunk, side)`` like the join.

    ``filter_col`` indexes the left schema; the right chunk's column 0
    carries the scalar (its last visible insert-side row wins, matching
    the reference's expectation of a 1-row changelog).
    """

    def __init__(self, left_schema: Schema, filter_col: int,
                 cmp: str = "gt", pool_size: int = 4096):
        if cmp not in _CMPS:
            raise ValueError(f"cmp must be one of {sorted(_CMPS)}")
        self.filter_field = left_schema[filter_col]
        if self.filter_field.data_type.is_string:
            raise ValueError(
                "dynamic filter on string columns is not supported"
            )
        self.left_schema = left_schema
        self.filter_col = filter_col
        self.cmp = _CMPS[cmp]
        self.pool_size = pool_size
        self._out_schema = left_schema

    @property
    def out_schema(self) -> Schema:
        return self._out_schema

    def init_state(self) -> DynFilterState:
        S = self.pool_size
        protos = schema_protos(self.left_schema)
        dt = self.left_schema[self.filter_col].data_type.physical_dtype
        return DynFilterState(
            rows=tuple(_empty_like_col(p, S) for p in protos),
            valid=jnp.zeros((S,), jnp.bool_),
            row_hash=jnp.zeros((S,), jnp.uint64),
            threshold=jnp.zeros((), dt),
            has_threshold=jnp.zeros((), jnp.bool_),
            overflow=jnp.zeros((), jnp.int64),
            inconsistency=jnp.zeros((), jnp.int64),
        )

    # -- left: data rows -------------------------------------------------
    def _apply_left(self, state: DynFilterState, chunk: Chunk):
        rows, valid, hashes, n_over, n_missing = pool_apply(
            state.rows, state.valid, state.row_hash, chunk, self.pool_size
        )
        # pass-through: rows currently clearing the threshold
        v = chunk.column(self.filter_col)
        passing = self.cmp(v, state.threshold) & state.has_threshold
        out = chunk.mask(passing)
        return DynFilterState(
            rows, valid, hashes, state.threshold, state.has_threshold,
            state.overflow + n_over, state.inconsistency + n_missing,
        ), out

    # -- right: the scalar changelog -------------------------------------
    def _apply_right(self, state: DynFilterState, chunk: Chunk):
        # the RHS scalar's logical type must match the filter column's
        # (DECIMAL scales and int/float semantics differ on device)
        rf = chunk.schema[0]
        lf = self.filter_field
        if rf.data_type != lf.data_type or (
            rf.data_type.value == "numeric"
            and rf.decimal_scale != lf.decimal_scale
        ):
            raise ValueError(
                f"dynamic filter RHS type {rf.data_type} does not match "
                f"filter column type {lf.data_type}"
            )
        signs = chunk.signs()
        ins = chunk.valid & (signs > 0)
        dels = chunk.valid & (signs < 0)
        # last visible insert-side row wins; a delete-only chunk means
        # the 1-row RHS became EMPTY (subquery over no rows): nothing
        # passes and everything emitted so far is retracted
        cap = chunk.capacity
        idx = jnp.arange(cap, dtype=jnp.int32)
        last = jnp.max(jnp.where(ins, idx, -1))
        has_new = last >= 0
        rhs_emptied = jnp.any(dels) & ~has_new
        new_thr = jnp.where(
            has_new,
            chunk.column(0)[jnp.maximum(last, 0)].astype(
                state.threshold.dtype
            ),
            state.threshold,
        )
        old_thr = state.threshold
        new_has = (state.has_threshold | has_new) & ~rhs_emptied
        v = state.rows[self.filter_col]
        was = self.cmp(v, old_thr) & state.has_threshold
        now = self.cmp(v, new_thr) & new_has
        emit_ins = state.valid & now & ~was
        emit_del = state.valid & was & ~now
        emit = emit_ins | emit_del
        ops = jnp.where(emit_ins, OP_INSERT, OP_DELETE).astype(jnp.int8)
        out = Chunk(state.rows, ops, emit, self.left_schema)
        return DynFilterState(
            state.rows, state.valid, state.row_hash,
            new_thr, new_has,
            state.overflow, state.inconsistency,
        ), out

    def apply(self, state: DynFilterState, chunk: Chunk, side: str):
        if side == "left":
            return self._apply_left(state, chunk)
        return self._apply_right(state, chunk)
