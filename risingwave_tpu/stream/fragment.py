"""Fragment: a chain of executors compiled to one jitted step.

Reference counterpart: a plan *fragment* (cut at exchange boundaries,
src/frontend/src/stream_fragmenter/mod.rs:388) whose actors each run an
executor chain.  Here the chain is composed into a single pure function
``step(states, chunk) -> (states, out_chunk)`` and jitted once — XLA
fuses the per-executor kernels (SURVEY.md §7.1).

Barrier-time flushing (``flush``) is a second jitted function: executors
that emit on barrier (aggs) produce their changelog, and that changelog
flows through the *remaining* executors in the chain.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor

#: sentinel for "no watermark yet" (matches WmState.max_ts init)
WM_NONE = np.iinfo(np.int64).min
#: safe stand-in threshold when no watermark exists: far enough below
#: any real event time that cleaning predicates match nothing, far
#: enough above INT64_MIN that `value - lag` cannot wrap
WM_SAFE_FLOOR = -(1 << 62)

#: per-executor-state scalar counters surfaced to maintenance checks
COUNTER_ATTRS = ("inconsistency", "overflow", "emit_overflow")


def collect_counters(executors, states):
    """Gather every executor's error counters + residual pending-flush
    into ONE device vector (labels, int64 [n]).

    The host reads this vector once per maintenance interval — a single
    device sync — instead of one sync per counter per barrier (each
    host readback costs a full host↔device round trip; over a tunneled
    accelerator that is ~10^2 ms)."""
    labels: list[str] = []
    vals: list[jnp.ndarray] = []
    for i, ex in enumerate(executors):
        st = states[i]
        for attr in COUNTER_ATTRS:
            if hasattr(st, attr):
                labels.append(f"{ex}.{attr}")
                vals.append(getattr(st, attr).astype(jnp.int64))
        if hasattr(ex, "pending_flush"):
            labels.append(f"{ex}.pending")
            vals.append(ex.pending_flush(st).astype(jnp.int64))
    vec = jnp.stack(vals) if vals else jnp.zeros((0,), jnp.int64)
    return labels, vec


class Fragment:
    """An executor chain with jit-compiled chunk/barrier paths."""

    #: bound on device-side flush re-drain rounds per barrier (each
    #: round emits one emit_capacity chunk per flushing executor)
    MAX_DRAIN_ROUNDS = 64

    def __init__(self, executors: Sequence[Executor], name: str = "fragment"):
        if not executors:
            raise ValueError("fragment needs at least one executor")
        self.executors = list(executors)
        self.name = name
        # donate the state buffers: XLA then mutates HBM in place
        # instead of copying every state array per chunk (the single
        # biggest throughput lever for large state tables).  Snapshot
        # holders copy explicitly before the next step (runtime).
        self._step = jax.jit(self._step_impl, donate_argnums=(0,))
        # epoch is passed as a traced scalar so barriers never retrace
        self._flush = jax.jit(self._flush_impl, donate_argnums=(0,))
        # the whole barrier crossing (flush + drain + watermarks +
        # counter collection) as ONE async dispatch — the steady-state
        # loop never synchronizes with the device
        self._barrier = jax.jit(self._barrier_impl, donate_argnums=(0,))
        self._maintain = jax.jit(self._maintain_impl, donate_argnums=(0,))
        #: counter labels aligned with the barrier counters vector;
        #: populated on first barrier trace
        self.counter_labels: list[str] = []

    # ------------------------------------------------------------------
    @property
    def out_schema(self) -> Schema:
        return self.executors[-1].out_schema

    def init_states(self) -> tuple:
        return tuple(e.init_state() for e in self.executors)

    # -- chunk path -----------------------------------------------------
    def _step_impl(self, states: tuple, chunk: Chunk):
        new_states = list(states)
        cur = chunk
        for i, ex in enumerate(self.executors):
            if cur is None:
                break
            new_states[i], cur = ex.apply(states[i], cur)
        return tuple(new_states), cur

    def step(self, states: tuple, chunk: Chunk):
        """Process one chunk; returns (states, out_chunk_or_None)."""
        return self._step(states, chunk)

    # -- barrier path ---------------------------------------------------
    def _flush_impl(self, states: tuple, epoch):
        new_states = list(states)
        outs: list[Chunk] = []
        for i, ex in enumerate(self.executors):
            if not ex.emits_on_flush:
                new_states[i], _ = ex.flush(new_states[i], epoch)
                continue
            new_states[i], emitted = ex.flush(new_states[i], epoch)
            if emitted is None:
                continue
            # emitted changelog flows through the rest of the chain
            cur = emitted
            for j in range(i + 1, len(self.executors)):
                if cur is None:
                    break
                new_states[j], cur = self.executors[j].apply(new_states[j], cur)
            if cur is not None:
                outs.append(cur)
        return tuple(new_states), outs

    def flush(self, states: tuple, epoch: int):
        """Barrier crossing: flush executors; returns (states, [chunks])."""
        return self._flush(states, epoch)

    def on_watermark(self, states: tuple, watermark):
        new_states = list(states)
        for i, ex in enumerate(self.executors):
            new_states[i] = ex.on_watermark(states[i], watermark)
        return tuple(new_states)

    # -- async barrier machinery (traceable; composed by the runtimes) --
    def has_pending_protocol(self) -> bool:
        return any(hasattr(ex, "pending_flush") for ex in self.executors)

    def pending_total(self, states) -> jnp.ndarray:
        """Total rows awaiting a further flush round (device scalar)."""
        tot = jnp.zeros((), jnp.int64)
        for i, ex in enumerate(self.executors):
            if hasattr(ex, "pending_flush"):
                tot = tot + ex.pending_flush(states[i]).astype(jnp.int64)
        return tot

    def _flush_states_only(self, states, epoch):
        s, _ = self._flush_impl(states, epoch)
        return s

    def _drain_impl(self, states, epoch):
        """Device-side emit-capacity drain: repeat flush passes until no
        executor reports pending output (the reference's re-drain loop
        in the runtime, moved into the program so the host never reads
        the pending count).  Only valid for terminal chains — drained
        emissions feed the rest of the chain and are then discarded."""
        if not self.has_pending_protocol():
            return states

        def cond(carry):
            sts, it = carry
            return (self.pending_total(sts) > 0) & (
                it < self.MAX_DRAIN_ROUNDS
            )

        def body(carry):
            sts, it = carry
            return self._flush_states_only(sts, epoch), it + 1

        states, _ = jax.lax.while_loop(cond, body, (states, jnp.int32(0)))
        return states

    def _wm_impl(self, states, axis: str | None = None):
        """Propagate watermarks from generator executors through the
        chain, entirely on device (no scalar readback).  The "no
        watermark yet" sentinel maps to WM_SAFE_FLOOR so downstream
        cleaning predicates match nothing.  Under a sharded runtime
        (``axis``) the watermark is the pmin across shards — one ICI
        collective, the reference's min-of-upstream-actors rule."""
        from risingwave_tpu.stream.message import Watermark
        from risingwave_tpu.stream.watermark import WatermarkFilterExecutor

        new_states = list(states)
        for i, ex in enumerate(self.executors):
            if not isinstance(ex, WatermarkFilterExecutor):
                continue
            raw = new_states[i].max_ts
            if axis is not None:
                raw = jax.lax.pmin(raw, axis)
            val = jnp.where(
                raw == WM_NONE,
                jnp.int64(WM_SAFE_FLOOR),
                raw - ex.delay_us,
            )
            wm = Watermark(ex.ts_col, val)
            for j, ex2 in enumerate(self.executors):
                new_states[j] = ex2.on_watermark(new_states[j], wm)
        return tuple(new_states)

    def _barrier_impl(self, states, epoch):
        """One-dispatch barrier crossing: flush, drain, watermarks,
        post-watermark drain (EOWC rows closed by THIS barrier emit at
        this barrier), then counter collection."""
        states, outs = self._flush_impl(states, epoch)
        states = self._drain_impl(states, epoch)
        states = self._wm_impl(states)
        states = self._drain_impl(states, epoch)
        labels, counters = collect_counters(self.executors, states)
        self.counter_labels = labels
        return states, outs, counters

    def barrier(self, states, epoch):
        """Cross a barrier asynchronously.

        Returns (states, first-pass emissions, counters int64 vector).
        The counters stay on device; the runtime reads them once per
        maintenance interval."""
        return self._barrier(states, epoch)

    def _maintain_impl(self, states):
        """Checkpoint-time housekeeping, all on device: executors whose
        tombstones dominate rebuild their tables (lax.cond inside
        maybe_rehash — no host readback of tombstone counts)."""
        new_states = list(states)
        for i, ex in enumerate(self.executors):
            if hasattr(ex, "maybe_rehash"):
                new_states[i] = ex.maybe_rehash(new_states[i])
        return tuple(new_states)

    def maintain(self, states):
        return self._maintain(states)

    def __repr__(self) -> str:
        chain = " -> ".join(map(repr, self.executors))
        return f"Fragment({self.name}: {chain})"
