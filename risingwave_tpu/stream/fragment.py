"""Fragment: a chain of executors compiled to one jitted step.

Reference counterpart: a plan *fragment* (cut at exchange boundaries,
src/frontend/src/stream_fragmenter/mod.rs:388) whose actors each run an
executor chain.  Here the chain is composed into a single pure function
``step(states, chunk) -> (states, out_chunk)`` and jitted once — XLA
fuses the per-executor kernels (SURVEY.md §7.1).

Barrier-time flushing (``flush``) is a second jitted function: executors
that emit on barrier (aggs) produce their changelog, and that changelog
flows through the *remaining* executors in the chain.
"""

from __future__ import annotations

from typing import Sequence

import jax

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor


class Fragment:
    """An executor chain with jit-compiled chunk/barrier paths."""

    def __init__(self, executors: Sequence[Executor], name: str = "fragment"):
        if not executors:
            raise ValueError("fragment needs at least one executor")
        self.executors = list(executors)
        self.name = name
        # donate the state buffers: XLA then mutates HBM in place
        # instead of copying every state array per chunk (the single
        # biggest throughput lever for large state tables).  Snapshot
        # holders copy explicitly before the next step (runtime).
        self._step = jax.jit(self._step_impl, donate_argnums=(0,))
        # epoch is passed as a traced scalar so barriers never retrace
        self._flush = jax.jit(self._flush_impl, donate_argnums=(0,))

    # ------------------------------------------------------------------
    @property
    def out_schema(self) -> Schema:
        return self.executors[-1].out_schema

    def init_states(self) -> tuple:
        return tuple(e.init_state() for e in self.executors)

    # -- chunk path -----------------------------------------------------
    def _step_impl(self, states: tuple, chunk: Chunk):
        new_states = list(states)
        cur = chunk
        for i, ex in enumerate(self.executors):
            if cur is None:
                break
            new_states[i], cur = ex.apply(states[i], cur)
        return tuple(new_states), cur

    def step(self, states: tuple, chunk: Chunk):
        """Process one chunk; returns (states, out_chunk_or_None)."""
        return self._step(states, chunk)

    # -- barrier path ---------------------------------------------------
    def _flush_impl(self, states: tuple, epoch):
        new_states = list(states)
        outs: list[Chunk] = []
        for i, ex in enumerate(self.executors):
            if not ex.emits_on_flush:
                new_states[i], _ = ex.flush(new_states[i], epoch)
                continue
            new_states[i], emitted = ex.flush(new_states[i], epoch)
            if emitted is None:
                continue
            # emitted changelog flows through the rest of the chain
            cur = emitted
            for j in range(i + 1, len(self.executors)):
                if cur is None:
                    break
                new_states[j], cur = self.executors[j].apply(new_states[j], cur)
            if cur is not None:
                outs.append(cur)
        return tuple(new_states), outs

    def flush(self, states: tuple, epoch: int):
        """Barrier crossing: flush executors; returns (states, [chunks])."""
        return self._flush(states, epoch)

    def on_watermark(self, states: tuple, watermark):
        new_states = list(states)
        for i, ex in enumerate(self.executors):
            new_states[i] = ex.on_watermark(states[i], watermark)
        return tuple(new_states)

    def __repr__(self) -> str:
        chain = " -> ".join(map(repr, self.executors))
        return f"Fragment({self.name}: {chain})"
