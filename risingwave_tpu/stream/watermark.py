"""Event-time machinery: watermark generation and emit-on-window-close.

Reference counterparts:
- ``WatermarkFilterExecutor`` — src/stream/src/executor/watermark_filter.rs
  (generates watermarks from WATERMARK FOR definitions, drops late rows,
  persists the low-watermark)
- EOWC sort — src/stream/src/executor/eowc/sort.rs + sort_buffer.rs
  (buffer until the watermark passes, emit append-only, clean state)
- state cleaning — StateTable watermark hooks (state_table.rs:223)

TPU-first design: the watermark itself is a device scalar updated inside
the jitted step (a max-reduce fused into the chunk program); the host
reads it once per barrier and propagates a ``Watermark`` control message
through the fragment, which executors translate into vectorized
``clean_below`` sweeps — per-key cleaning becomes one masked store.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, StrCol
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.message import Watermark
from risingwave_tpu.stream.top_n import _empty_like_col, _gather, _scatter


class WmState(NamedTuple):
    max_ts: jnp.ndarray   # int64 scalar — highest event time seen
    late_rows: jnp.ndarray  # int64 — rows dropped as late


class WatermarkFilterExecutor(Executor):
    """Generate watermarks from an event-time column; drop late rows.

    ``delay_us`` is the out-of-orderness allowance (the reference's
    WATERMARK FOR ts AS ts - INTERVAL ...).
    """

    emits_on_apply = True
    emits_on_flush = False

    def __init__(self, in_schema: Schema, ts_col: int, delay_us: int):
        super().__init__(in_schema)
        self.ts_col = ts_col
        self.delay_us = delay_us

    def init_state(self) -> WmState:
        return WmState(
            max_ts=jnp.asarray(np.iinfo(np.int64).min, jnp.int64),
            late_rows=jnp.zeros((), jnp.int64),
        )

    def apply(self, state: WmState, chunk: Chunk):
        ts = chunk.column(self.ts_col)
        no_wm = state.max_ts == np.iinfo(np.int64).min
        # guard the initial state: INT64_MIN - delay would wrap positive
        wm = jnp.where(no_wm, state.max_ts, state.max_ts - self.delay_us)
        late = chunk.valid & (ts < wm)
        new_max = jnp.maximum(
            state.max_ts,
            jnp.max(jnp.where(chunk.valid, ts, np.iinfo(np.int64).min)),
        )
        return WmState(
            max_ts=new_max,
            late_rows=state.late_rows + jnp.sum(late.astype(jnp.int64)),
        ), chunk.mask(~late)

    # -- host API (read once per barrier) -------------------------------
    def current_watermark(self, state: WmState) -> int | None:
        v = int(state.max_ts)
        if v == np.iinfo(np.int64).min:
            return None
        return v - self.delay_us


class EowcSortState(NamedTuple):
    rows: tuple
    valid: jnp.ndarray
    wm: jnp.ndarray  # int64 — latest watermark received
    overflow: jnp.ndarray  # int64 — rows dropped with the pool full


class EowcSortExecutor(Executor):
    """Buffer rows, emit them in order once the watermark passes.

    ref eowc/sort.rs: turns an out-of-order append-only stream into an
    in-order append-only stream (the basis of EOWC aggregations).
    """

    emits_on_apply = False
    emits_on_flush = True

    def __init__(self, in_schema: Schema, ts_col: int,
                 pool_size: int = 8192, emit_capacity: int = 4096):
        super().__init__(in_schema)
        self.ts_col = ts_col
        self.pool_size = pool_size
        self.emit_capacity = emit_capacity

    def init_state(self) -> EowcSortState:
        protos = []
        for f in self.in_schema:
            if f.data_type.is_string:
                protos.append(StrCol(
                    jnp.zeros((1, f.str_width), jnp.uint8),
                    jnp.zeros((1,), jnp.int32),
                ))
            else:
                protos.append(jnp.zeros((1,), f.data_type.physical_dtype))
        S = self.pool_size
        return EowcSortState(
            rows=tuple(_empty_like_col(p, S) for p in protos),
            valid=jnp.zeros((S,), jnp.bool_),
            wm=jnp.asarray(np.iinfo(np.int64).min, jnp.int64),
            overflow=jnp.zeros((), jnp.int64),
        )

    def apply(self, state: EowcSortState, chunk: Chunk):
        S = self.pool_size
        cap = chunk.capacity
        from risingwave_tpu.stream.hash_join import _rank_by
        is_ins = chunk.valid  # append-only input
        free = ~state.valid
        free_pos = jnp.cumsum(free) - 1
        slot_of_rank = jnp.full((S,), S, jnp.int32).at[
            jnp.where(free, free_pos.astype(jnp.int32), S)
        ].min(jnp.arange(S, dtype=jnp.int32), mode="drop")
        ins_rank = _rank_by(jnp.zeros((cap,), jnp.uint64), is_ins)
        tgt = jnp.where(
            is_ins & (ins_rank < S),
            slot_of_rank[jnp.minimum(ins_rank, S - 1)],
            jnp.int32(S),
        )
        got = is_ins & (tgt < S)
        valid = state.valid.at[jnp.where(got, tgt, S)].set(True, mode="drop")
        rows = tuple(
            _scatter(store, jnp.where(got, tgt, S), col)
            for store, col in zip(state.rows, chunk.columns)
        )
        n_over = jnp.sum((is_ins & ~got).astype(jnp.int64))
        return EowcSortState(
            rows, valid, state.wm, state.overflow + n_over
        ), None

    def on_watermark(self, state: EowcSortState, watermark: Watermark):
        if watermark.col_idx != self.ts_col:
            return state
        return EowcSortState(
            state.rows, state.valid,
            jnp.maximum(state.wm, jnp.int64(watermark.value)),
            state.overflow,
        )

    def flush(self, state: EowcSortState, epoch):
        S, E = self.pool_size, self.emit_capacity
        ts = state.rows[self.ts_col]
        closed = state.valid & (ts < state.wm)
        # emit in timestamp order: sort closed rows by ts
        sort_key = jnp.where(closed, ts, np.iinfo(np.int64).max)
        order = jnp.argsort(sort_key, stable=True)
        take = order[:E]
        live = closed[take]
        out_cols = tuple(_gather(c, take) for c in state.rows)
        out = Chunk(
            out_cols, jnp.zeros((E,), jnp.int8), live, self.in_schema
        )
        emitted = jnp.zeros((S,), jnp.bool_).at[take].set(live)
        return EowcSortState(
            state.rows, state.valid & ~emitted, state.wm, state.overflow
        ), out

    def pending_flush(self, state: EowcSortState) -> jnp.ndarray:
        ts = state.rows[self.ts_col]
        return jnp.sum((state.valid & (ts < state.wm)).astype(jnp.int32))
