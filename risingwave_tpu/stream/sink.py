"""SinkExecutor: buffer the changelog on device, deliver at barriers.

Reference counterpart: ``src/stream/src/executor/sink.rs`` — the sink
executor forwards chunks to the connector writer and commits on
checkpoint barriers (optionally decoupled through a log store).

TPU-first design: the traced ``apply`` appends the changelog (ops +
rows) into a device ring buffer — zero host involvement in the hot
path.  At barrier time the runtime calls ``deliver`` (a host hook, like
maintenance), which drains only the NEW rows device→host in one
transfer and hands them to the connector ``Sink``, then commits the
epoch.  This is the log-store-decoupling idea collapsed to a ring: a
slow sink backpressures only the barrier, never the chunk path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, StrCol, decode_strings
from risingwave_tpu.common.compact import mask_indices
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.materialize import _empty_value_col, _scatter_col


class SinkState(NamedTuple):
    values: tuple          # [ring] column stores
    ops: jnp.ndarray       # int8 [ring]
    cursor: jnp.ndarray    # int64 rows written (total)
    overflow: jnp.ndarray  # rows dropped because the ring lapped
    #: rows already delivered to the connector — PART OF THE CHECKPOINT
    #: (a host attribute would reset on restart and re-deliver the
    #: retained ring: duplicate sink rows)
    read_cursor: jnp.ndarray  # int64


class SinkExecutor(Executor):
    emits_on_apply = False
    emits_on_flush = False

    def __init__(self, in_schema: Schema, sink, ring_size: int = 1 << 16):
        super().__init__(in_schema)
        if ring_size & (ring_size - 1):
            raise ValueError("ring_size must be a power of two")
        self.sink = sink
        self.ring_size = ring_size

    def init_state(self) -> SinkState:
        return SinkState(
            values=tuple(
                _empty_value_col(f, self.ring_size) for f in self.in_schema
            ),
            ops=jnp.zeros((self.ring_size,), jnp.int8),
            cursor=jnp.zeros((), jnp.int64),
            overflow=jnp.zeros((), jnp.int64),
            read_cursor=jnp.zeros((), jnp.int64),
        )

    def apply(self, state: SinkState, chunk: Chunk):
        cap = chunk.capacity
        idx = mask_indices(chunk.valid, cap, cap)
        n = chunk.cardinality().astype(jnp.int64)
        k = jnp.arange(cap, dtype=jnp.int64)
        pos = ((state.cursor + k) % self.ring_size).astype(jnp.int32)
        pos = jnp.where(k < n, pos, jnp.int32(self.ring_size))
        safe_idx = jnp.minimum(idx, cap - 1)
        from risingwave_tpu.state.hash_table import gather_key
        values = [
            _scatter_col(store, pos, gather_key(col, safe_idx))
            for store, col in zip(state.values, chunk.columns)
        ]
        ops = state.ops.at[pos].set(chunk.ops[safe_idx], mode="drop")
        return SinkState(
            tuple(values), ops, state.cursor + n, state.overflow,
            state.read_cursor,
        ), None

    # -- host barrier hook ----------------------------------------------
    def deliver(self, state: SinkState, epoch: int,
                commit: bool = True) -> SinkState:
        """Drain new rows to the connector; commit the epoch.

        ``commit=False`` defers the epoch commit marker — the sharded
        runtime drains every shard's ring first and commits ONCE, so
        readers of the closed-epoch protocol see one marker per epoch."""
        from risingwave_tpu.common.chunk import apply_null_mask, split_col

        total = int(state.cursor)
        read = int(state.read_cursor)
        n = total - read
        if n > self.ring_size:
            # ring lapped: the oldest rows are lost — surface loudly
            raise RuntimeError(
                f"sink ring lapped ({n - self.ring_size} rows lost) — "
                "increase ring_size or checkpoint more often"
            )
        if n > 0:
            sel = (np.arange(read, total)
                   % self.ring_size).astype(np.int64)
            cols = []
            for f, store in zip(self.in_schema, state.values):
                store, null = split_col(store)
                if isinstance(store, StrCol):
                    out = decode_strings(
                        np.asarray(store.data)[sel],
                        np.asarray(store.lens)[sel],
                    )
                else:
                    arr = np.asarray(store)[sel]
                    if f.data_type == DataType.DECIMAL:
                        arr = arr.astype(np.float64) / 10**f.decimal_scale
                    out = arr
                if null is not None:
                    out = apply_null_mask(out, np.asarray(null)[sel])
                cols.append(out)
            ops = np.asarray(state.ops)[sel]
            rows = [tuple(c[i] for c in cols) for i in range(n)]
            self.sink.write_batch(self.in_schema.names(), ops, rows)
            state = state._replace(read_cursor=jnp.int64(total))
        if commit:
            self.sink.commit(epoch)
        return state
