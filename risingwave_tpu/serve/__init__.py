"""Serve-lite: the engine-free serving tier.

Reference counterpart: the frontend/batch split for serving reads —
stateless frontend nodes executing batch scans over shared Hummock
storage at a pinned snapshot, without touching the streaming compute
nodes (SURVEY.md §3.4; the Taurus read-replica-over-shared-pages move,
PAPERS.md).

A ``ServingWorker`` process opens the cluster's shared ``data_dir``
through the ObjectStore seam, follows the version manifest at a
META-PINNED epoch (pin leases counted by vacuum), and answers
point-gets / pk-range scans over the ``m:<mv>\\0<pk>`` keyspace
directly from SSTs — no Engine, no JAX on the read path.
"""

_LAZY = {
    "ServingWorker": ("risingwave_tpu.serve.worker", "ServingWorker"),
    "ServeUnsupported": ("risingwave_tpu.serve.worker",
                         "ServeUnsupported"),
    "ResultCache": ("risingwave_tpu.serve.worker", "ResultCache"),
    "ManifestFollower": ("risingwave_tpu.serve.reader",
                         "ManifestFollower"),
    "SstView": ("risingwave_tpu.serve.reader", "SstView"),
    "MvSchema": ("risingwave_tpu.serve.reader", "MvSchema"),
    "mv_key_range": ("risingwave_tpu.serve.reader", "mv_key_range"),
    "schema_key": ("risingwave_tpu.serve.reader", "schema_key"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value
    return value
