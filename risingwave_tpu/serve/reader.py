"""Shared-storage read path for the serving tier (jax-free).

Two pieces:

- ``ManifestFollower`` — a READ-ONLY replica of the version manifest:
  it replays base snapshots + deltas from the shared object store up
  to a caller-supplied vid limit (the meta's pin-lease grant) and
  never commits.  The single-writer invariant of the manifest
  (``VersionManager`` in the owning process) is untouched — any number
  of followers may trail it.

- ``SstView`` — the serving read path over a follower's version:
  newest-first point-gets with bloom/key-range pruning and k-way merge
  range scans, fronted by one process-wide LRU ``BlockCache`` with
  hit/miss/bytes gauges (the foyer-block-cache analog for the
  stateless serving node).

Also here: the MV schema document the export path publishes next to
the data (``serve/schema/<mv>.json``) so a serving replica can encode
pk probe keys and project columns WITHOUT the SQL binder (which would
drag in jax).  ``kind`` strings are deliberately dumb — "string" /
"decimal" / "float" / "int" — the full ``DataType`` never crosses the
seam.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from dataclasses import dataclass

from risingwave_tpu.storage.hummock.object_store import ObjectError
from risingwave_tpu.storage.hummock.version import (
    HummockVersion,
    VersionDelta,
    apply_delta,
    verify_chain_doc,
)
from risingwave_tpu.storage.sst import (
    TOMBSTONE,
    BlockCache,
    SstReader,
    merge_scan,
)

_DELTA_PREFIX = "version/delta_"
_BASE_PREFIX = "version/base_"
_SCHEMA_PREFIX = "serve/schema/"


def mv_key_range(name: str) -> tuple[bytes, bytes]:
    """Key range of one MV in the shared storage keyspace (mirrors
    Engine._mv_storage_range — the TableKey table-prefix scheme)."""
    lo = b"m:" + name.encode() + b"\x00"
    return lo, lo[:-1] + b"\x01"


def schema_key(name: str) -> str:
    return f"{_SCHEMA_PREFIX}{name}.json"


def bytes_successor(b: bytes) -> bytes | None:
    """Smallest byte string greater than every string prefixed by
    ``b`` (None = no finite successor: all 0xff)."""
    arr = bytearray(b)
    while arr:
        if arr[-1] != 0xFF:
            arr[-1] += 1
            return bytes(arr)
        arr.pop()
    return None


@dataclass(frozen=True)
class MvColumn:
    name: str
    kind: str     # "string" | "decimal" | "float" | "int"
    scale: int
    hidden: bool
    #: nullable pk components carry a presence-prefix byte in the
    #: memcomparable encoding (outer-join MV keys); old docs without
    #: the flag default to the prefix-free encoding
    nullable: bool = False


class MvSchema:
    """The serving replica's view of one MV's shape, decoded from the
    schema document the export path publishes."""

    def __init__(self, doc: dict):
        self.mv = doc["mv"]
        self.columns = [
            MvColumn(c["name"], c["kind"], int(c.get("scale", 0)),
                     bool(c.get("hidden", False)),
                     bool(c.get("nullable", False)))
            for c in doc["columns"]
        ]
        self.pk: tuple[int, ...] = tuple(doc["pk"])
        self._by_name = {c.name: i for i, c in enumerate(self.columns)}
        #: secondary indexes ON this MV: [{"name", "cols"}] — the
        #: serving planner rewrites equality predicates on a prefix of
        #: an index's columns into an index range scan + pk lookups
        self.indexes: list[dict] = list(doc.get("indexes", ()))
        #: set when this MV IS an index: the upstream MV name and how
        #: many leading columns are indexed (the rest are the
        #: upstream's pk values, in upstream-pk order)
        self.indexed_mv: str | None = doc.get("index_of")
        self.index_width: int = int(doc.get("index_width", 0))
        #: first epoch the index's rows were exported under — a
        #: replica pinned BEFORE it must not trust the index range
        #: (the doc is an unversioned side-channel; the data is not)
        self.since_epoch: int = int(doc.get("since_epoch", 0))

    @staticmethod
    def load(store, name: str) -> "MvSchema | None":
        try:
            return MvSchema(json.loads(store.get(schema_key(name))))
        except ObjectError:
            return None

    def index_of(self, name: str) -> int | None:
        return self._by_name.get(name)

    def output_indices(self) -> list[int]:
        return [i for i, c in enumerate(self.columns) if not c.hidden]

    def encode_pk_value(self, col: int, v) -> bytes:
        """Memcomparable encoding of one pk component — the jax-free
        twin of checkpoint_store._mc_encode_value (same bytes)."""
        import numpy as np

        from risingwave_tpu.storage import codec as C

        c = self.columns[col]
        prefix = b""
        if c.nullable:
            # presence prefix, mirroring _mc_encode_value exactly
            if v is None:
                return b"\x01"
            prefix = b"\x00"
        if c.kind == "string":
            return prefix + str(v).encode() + b"\x00"
        if c.kind == "decimal":
            scaled = int(round(float(v) * 10 ** c.scale))
            return prefix + C.mc_encode_i64(
                np.asarray([scaled])).tobytes()
        if c.kind == "float":
            return prefix + C.mc_encode_f64(
                np.asarray([float(v)])).tobytes()
        return prefix + C.mc_encode_i64(np.asarray([int(v)])).tobytes()


class StaleLease(RuntimeError):
    """The follower cannot reconstruct the granted vid from the pruned
    log — the caller must request a fresh grant."""


class ManifestFollower:
    """Read-only manifest replica over the shared object store."""

    def __init__(self, store):
        self.store = store
        self.version = HummockVersion.empty()
        #: hash-chain link of the last verified log entry — the
        #: follower verifies every delta it replays against the chain
        #: the writer commits (storage/hummock/version.py)
        self._chain = 0
        self._lock = threading.Lock()

    @property
    def vid(self) -> int:
        return self.version.vid

    def _list_vids(self, prefix: str) -> list[int]:
        return [int(k[len(prefix):-len(".json")])
                for k in self.store.list(prefix)]

    def refresh(self, limit_vid: int | None = None) -> HummockVersion:
        """Advance to exactly ``limit_vid`` (the pin-lease grant), or
        to the newest logged version when None.  Never goes backwards.
        Raises ``StaleLease`` when base pruning has removed the log
        entries needed to reach ``limit_vid`` precisely — re-granting
        (which always points at the writer's CURRENT vid) resolves it.
        """
        with self._lock:
            v = self.version
            if limit_vid is not None and limit_vid <= v.vid:
                return v
            delta_vids = sorted(self._list_vids(_DELTA_PREFIX))
            base_vids = sorted(self._list_vids(_BASE_PREFIX))
            target = limit_vid
            if target is None:
                target = max(delta_vids + base_vids + [v.vid])
            # re-anchor on a base snapshot when the contiguous delta
            # chain from our vid has been pruned away
            chain_start = v.vid + 1
            chain = self._chain
            usable = [b for b in base_vids if v.vid < b <= target]
            if usable and (not delta_vids
                           or min(delta_vids) > chain_start):
                base = max(usable)
                key = _BASE_PREFIX + f"{base:012d}.json"
                # a re-anchor cannot know the base's predecessor (its
                # chain prefix was pruned) — the self-crc still holds
                body, chain = verify_chain_doc(
                    self.store.get(key), "version", key, None
                )
                v = HummockVersion.from_json(body)
                chain_start = base + 1
            for vid in range(chain_start, target + 1):
                key = _DELTA_PREFIX + f"{vid:012d}.json"
                try:
                    raw = self.store.get(key)
                except ObjectError:
                    raise StaleLease(
                        f"delta {vid} pruned before follower reached it"
                    ) from None
                body, chain = verify_chain_doc(raw, "delta", key, chain)
                v = apply_delta(v, VersionDelta.from_json(body))
            if limit_vid is not None and v.vid < limit_vid:
                raise StaleLease(
                    f"cannot reach vid {limit_vid} (log ends at {v.vid})"
                )
            self.version = v
            self._chain = chain
            return v


class SstView:
    """Pinned-version reads over shared SSTs with a block cache.

    Reads capture ONE version snapshot each, so a concurrent refresh
    never tears a scan.  Readers are retained for the last
    ``retain_versions`` refreshed versions (an in-flight read's
    snapshot is always among them) and closed once unreferenced.
    """

    def __init__(self, store, cache_blocks: int = 1024,
                 metrics=None, retain_versions: int = 4):
        self.store = store
        self.follower = ManifestFollower(store)
        self.cache = BlockCache(cache_blocks)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._readers: dict[str, SstReader] = {}
        self._retained: deque[HummockVersion] = deque(
            maxlen=max(2, retain_versions)
        )
        self._schemas: dict[str, MvSchema] = {}

    # -- manifest -------------------------------------------------------
    @property
    def version(self) -> HummockVersion:
        return self.follower.version

    def refresh(self, limit_vid: int | None = None) -> HummockVersion:
        v = self.follower.refresh(limit_vid)
        with self._lock:
            if not self._retained or self._retained[-1].vid != v.vid:
                self._retained.append(v)
                # the version moved: schema docs may have changed too
                # (CREATE/DROP INDEX republishes; DROP MV deletes) —
                # drop the cache so the next read reloads them
                self._schemas.clear()
            live = set()
            for rv in self._retained:
                live |= rv.all_keys()
            for key in [k for k in self._readers if k not in live]:
                try:
                    self._readers.pop(key).close()
                except Exception:  # noqa: BLE001 — best-effort close
                    pass
        self._export_gauges()
        return v

    def _export_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set_gauge("serving_pinned_epoch",
                               self.version.max_committed_epoch)
        self.metrics.set_gauge("serving_pinned_version_id",
                               self.version.vid)
        self.metrics.set_gauge("serving_block_cache_hits",
                               self.cache.hits)
        self.metrics.set_gauge("serving_block_cache_misses",
                               self.cache.misses)
        self.metrics.set_gauge("serving_block_cache_fill_bytes",
                               self.cache.miss_bytes)
        self.metrics.set_gauge("serving_block_cache_hit_ratio",
                               self.cache.hit_ratio())

    # -- schemas --------------------------------------------------------
    def schema(self, mv: str) -> MvSchema | None:
        s = self._schemas.get(mv)
        if s is None:
            s = MvSchema.load(self.store, mv)
            if s is not None:
                self._schemas[mv] = s
        return s

    # -- reads ----------------------------------------------------------
    def _reader(self, key: str) -> SstReader:
        with self._lock:
            r = self._readers.get(key)
            if r is None:
                r = SstReader(store=self.store, key=key,
                              cache=self.cache)
                self._readers[key] = r
            return r

    def point_get(self, key: bytes,
                  version: HummockVersion | None = None) -> bytes | None:
        """Newest-first levels with bloom/key-range pruning (the
        PinnedVersion.get read, replayed replica-side)."""
        v = version if version is not None else self.version
        m = self.metrics
        for lv in v.levels:
            for s in lv:
                r = self._reader(s.key)
                if not r.may_contain(key):
                    if m is not None:
                        m.inc("serving_bloom_filter_total",
                              result="skip")
                    continue
                val = r.get(key)
                if m is not None:
                    m.inc("serving_bloom_filter_total",
                          result="hit" if val is not None else "miss")
                if val is not None:
                    return None if val == TOMBSTONE else val
        return None

    def multi_get(self, keys, version: HummockVersion | None = None,
                  ) -> dict[bytes, bytes | None]:
        """Batched point-gets sharing ONE pinned pass over the SST
        set: keys probe each SST in sorted order, so block loads (and
        block-cache hits) are sequential rather than random — the
        locality that makes a serving multi-get amortize.  Per key the
        semantics are exactly ``point_get`` (newest level wins,
        tombstone → None); keys never found are absent from the
        result."""
        v = version if version is not None else self.version
        m = self.metrics
        pending = dict.fromkeys(sorted(set(keys)))
        out: dict[bytes, bytes | None] = {}
        for lv in v.levels:
            for s in lv:
                if not pending:
                    return out
                r = self._reader(s.key)
                for k in list(pending):
                    if not r.may_contain(k):
                        if m is not None:
                            m.inc("serving_bloom_filter_total",
                                  result="skip")
                        continue
                    val = r.get(k)
                    if m is not None:
                        m.inc("serving_bloom_filter_total",
                              result="hit" if val is not None
                              else "miss")
                    if val is not None:
                        out[k] = None if val == TOMBSTONE else val
                        del pending[k]
        return out

    def scan(self, lo: bytes = b"", hi: bytes | None = None,
             version: HummockVersion | None = None):
        v = version if version is not None else self.version
        readers = [self._reader(s.key) for lv in v.levels for s in lv]
        yield from merge_scan(readers, lo, hi)

    def scan_filtered(self, lo: bytes, hi: bytes | None,
                      prefix: bytes, evaluator, loads,
                      version: HummockVersion | None = None):
        """Pushdown merge scan: residual predicates + projection
        evaluate per block DURING the k-way merge
        (storage/pushdown.scan_filtered) instead of after full-row
        materialization.  ``prefix`` is the MV's table prefix (key
        predicates compare slices of the key AFTER it); ``loads``
        decodes one stored value into a row.  Counters land in
        ``evaluator.stats``."""
        from risingwave_tpu.storage.pushdown import scan_filtered

        v = version if version is not None else self.version
        readers = [self._reader(s.key) for lv in v.levels for s in lv]
        return scan_filtered(readers, lo, hi, prefix, evaluator, loads)

    def scan_mv(self, mv: str,
                version: HummockVersion | None = None) -> list[bytes]:
        """Raw pickled row payloads of one MV (the byte-identity
        surface tests compare against Engine.storage_serve_mv)."""
        lo, hi = mv_key_range(mv)
        return [val for _, val in self.scan(lo, hi, version)]

    def close(self) -> None:
        with self._lock:
            for r in self._readers.values():
                try:
                    r.close()
                except Exception:  # noqa: BLE001
                    pass
            self._readers.clear()
