"""ServingWorker: a stateless serving replica (engine-free, jax-free).

Reference counterpart: the frontend/batch serving split — stateless
nodes that execute batch scans over SHARED storage at a pinned
snapshot, scaling the read path independently of the streaming
compute nodes (SURVEY.md §3.4; Taurus' read replicas over shared
pages, PAPERS.md).  The Hazelcast-Jet tail-latency discipline applies:
serve from the block cache and pinned SSTs, never from the barrier
path.

Shape here: NO Engine, NO JAX — the process imports only the parser
(pure Python), the SST/manifest readers, and the RPC/metrics plumbing.
It registers with the meta like a compute worker (heartbeats, expiry),
holds a meta-side EPOCH PIN LEASE that advances per committed cluster
epoch (the lease pins the replica's manifest version in the meta's
VersionManager, so vacuum can never reap an SST under a live serving
read), and answers the SELECT shapes a key-value read path can serve:

- point-gets:      WHERE covers the MV's full pk with equalities;
- pk-range scans:  predicates on the LEADING pk column (the
  memcomparable encoding makes byte ranges == value ranges);
- projection (named columns or *) and LIMIT/OFFSET.

Anything else raises ``ServeUnsupported`` — the meta frontend falls
back to the owning compute worker, so the SQL surface never narrows.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import time
from dataclasses import dataclass

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    parse_addr,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric
from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.common.trace import GLOBAL_TRACE
from risingwave_tpu.serve.reader import (
    MvSchema,
    SstView,
    StaleLease,
    bytes_successor,
    mv_key_range,
)
from risingwave_tpu.storage.hummock.object_store import ObjectError
from risingwave_tpu.storage.integrity import (
    IntegrityError,
    record_integrity_error,
)
from risingwave_tpu.storage.pushdown import (
    BlockEvaluator,
    PushdownStats,
)


class ServeUnsupported(ValueError):
    """The statement needs the engine — route to the owning worker."""


class ServeUnavailable(RuntimeError):
    """This replica transiently cannot serve (meta unreachable during
    a lease refresh, or stuck behind the pinned epoch) — the meta
    should route the read to another replica or the owning worker,
    NOT surface an error.  A routing signal, never a failed read."""


_CMP_OPS = ("equal", "less_than", "less_than_or_equal",
            "greater_than", "greater_than_or_equal")

#: planner op names → the symbol ops the pushdown evaluator speaks
_PUSH_OPS = {"equal": "=", "less_than": "<",
             "less_than_or_equal": "<=", "greater_than": ">",
             "greater_than_or_equal": ">="}


@dataclass
class ReadPlan:
    mv: str
    cols: list[int]
    col_names: list[str]
    #: "get" (point key), "scan" (byte range), or "index" (range scan
    #: over a secondary-index MV + pk point-gets on the primary)
    mode: str
    key: bytes = b""
    lo: bytes = b""
    hi: bytes | None = None
    limit: int | None = None
    offset: int = 0
    #: mode="index": the index MV whose keyspace lo/hi bound, and how
    #: many leading index columns precede the upstream pk values
    index_mv: str = ""
    index_width: int = 0
    #: residual predicates applied to fetched rows BEFORE projection/
    #: LIMIT: ``[(col_idx, op, value)]`` — the pushdown surface for
    #: composite predicates (index prefix + residual filter) and
    #: non-leading pk compares.  SQL NULL semantics: a NULL operand
    #: never matches.
    residual: list = None  # type: ignore[assignment]


def _conjuncts(expr) -> list:
    from risingwave_tpu.sql import ast

    if isinstance(expr, ast.BinaryOp) and expr.op == "and":
        return _conjuncts(expr.left) + _conjuncts(expr.right)
    return [expr]


def _flip(op: str) -> str:
    return {
        "less_than": "greater_than",
        "less_than_or_equal": "greater_than_or_equal",
        "greater_than": "less_than",
        "greater_than_or_equal": "less_than_or_equal",
    }.get(op, op)


def _cmp(op: str, a, b) -> bool:
    """One residual compare with SQL NULL semantics (NULL never
    matches)."""
    if a is None or b is None:
        return False
    if op == "equal":
        return a == b
    if op == "less_than":
        return a < b
    if op == "less_than_or_equal":
        return a <= b
    if op == "greater_than":
        return a > b
    return a >= b  # greater_than_or_equal


def _range_bounds(base: bytes, hi: bytes, enc_of,
                  preds) -> tuple[bytes, bytes]:
    """Tighten ``[base, hi)`` with compare predicates over ONE
    memcomparable-encoded column that directly follows ``base`` (the
    shared leading-pk / index-column range logic — byte order equals
    value order under the encoding)."""
    lo_b, hi_b = base, hi
    for _, op, v in preds:
        enc = enc_of(v)
        if op in ("equal", "greater_than_or_equal"):
            lo_b = max(lo_b, base + enc)
        elif op == "greater_than":
            succ = bytes_successor(enc)
            lo_b = hi if succ is None else max(lo_b, base + succ)
        if op in ("equal", "less_than_or_equal"):
            succ = bytes_successor(enc)
            if succ is not None:
                hi_b = min(hi_b, base + succ)
        elif op == "less_than":
            hi_b = min(hi_b, base + enc)
    return lo_b, hi_b


def plan_read(select, schema: MvSchema, schema_of=None,
              at_epoch: int | None = None) -> ReadPlan:
    """Compile one SELECT into a key-value read, or raise
    ``ServeUnsupported`` (the meta falls back to the owning worker).

    ``schema_of`` (name → MvSchema | None) enables secondary-index
    rewrites: equality predicates covering a prefix of an index's
    columns become a contiguous range scan over the index MV plus pk
    point-gets on the primary.  ``at_epoch`` is the pinned epoch the
    read will execute at — an index whose first export is newer is
    ignored (the doc is an unversioned side-channel)."""
    from risingwave_tpu.sql import ast

    if select.group_by or select.having is not None:
        raise ServeUnsupported(
            "serving replicas handle projection/point/range reads only"
        )
    if not isinstance(select.from_, ast.TableRef) \
            or select.from_.temporal:
        raise ServeUnsupported("serving reads are SELECT ... FROM <mv>")
    mv = select.from_.name
    if select.order_by:
        # ORDER BY pushdown: the scan already yields memcomparable-pk
        # order, so an ASCENDING prefix of the pk columns is a no-op —
        # accept it (typically ORDER BY pk LIMIT k) instead of falling
        # back to the owning worker.  Anything else still needs the
        # engine's sort.
        for pos, oi in enumerate(select.order_by):
            if oi.descending or not isinstance(oi.expr, ast.ColumnRef):
                raise ServeUnsupported(
                    "serving ORDER BY supports an ascending pk prefix"
                )
            idx = schema.index_of(oi.expr.name)
            if pos >= len(schema.pk) or idx != schema.pk[pos]:
                raise ServeUnsupported(
                    "serving ORDER BY supports an ascending pk prefix"
                )

    # projection
    cols: list[int] = []
    names: list[str] = []
    if len(select.items) == 1 \
            and isinstance(select.items[0].expr, ast.Star):
        cols = schema.output_indices()
        names = [schema.columns[i].name for i in cols]
    else:
        for item in select.items:
            if not isinstance(item.expr, ast.ColumnRef):
                raise ServeUnsupported(
                    "serving projection supports plain columns"
                )
            idx = schema.index_of(item.expr.name)
            if idx is None:
                raise ValueError(
                    f"column {item.expr.name!r} does not exist in {mv!r}"
                )
            cols.append(idx)
            names.append(item.alias or item.expr.name)

    lo, hi = mv_key_range(mv)
    plan = ReadPlan(mv=mv, cols=cols, col_names=names, mode="scan",
                    lo=lo, hi=hi, limit=select.limit,
                    offset=select.offset or 0)
    if select.where is None:
        return plan

    # predicates: col <cmp> literal over pk columns only
    preds: list[tuple[int, str, object]] = []
    for c in _conjuncts(select.where):
        if not isinstance(c, ast.BinaryOp) or c.op not in _CMP_OPS:
            raise ServeUnsupported("serving WHERE supports pk compares")
        left, right, op = c.left, c.right, c.op
        if isinstance(left, ast.Literal) \
                and isinstance(right, ast.ColumnRef):
            left, right, op = right, left, _flip(op)
        if not (isinstance(left, ast.ColumnRef)
                and isinstance(right, ast.Literal)):
            raise ServeUnsupported("serving WHERE supports pk compares")
        idx = schema.index_of(left.name)
        if idx is None:
            raise ValueError(
                f"column {left.name!r} does not exist in {mv!r}"
            )
        preds.append((idx, op, right.value))

    non_pk: list[tuple[int, str, object]] = []
    if any(i not in schema.pk for i, _, _ in preds):
        # non-pk predicate: a prefix of a secondary index absorbs the
        # matching predicates (equality prefix + one ranged column);
        # whatever the index bytes cannot bound becomes a RESIDUAL
        # filter on the fetched rows.  No applicable index → the
        # block-walk evaluator runs every non-pk compare as a
        # residual during the merge scan (near-data filtering; pk
        # predicates still narrow the byte range below)
        ix_plan = _plan_index_read(plan, preds, schema, schema_of,
                                   at_epoch)
        if ix_plan is not None:
            return ix_plan
        non_pk = [p for p in preds if p[0] not in schema.pk]
        preds = [p for p in preds if p[0] in schema.pk]

    eq = {i: v for i, op, v in preds if op == "equal"}
    if not non_pk and len(eq) == len(preds) \
            and set(eq) == set(schema.pk) \
            and len(preds) == len(schema.pk):
        plan.mode = "get"
        plan.key = lo + b"".join(
            schema.encode_pk_value(i, eq[i]) for i in schema.pk
        )
        return plan

    # range on the LEADING pk column (byte order == value order under
    # the memcomparable prefix); compares on OTHER pk columns apply as
    # residual filters over the fetched rows — composite predicates no
    # longer bounce to the owning worker
    lead = schema.pk[0]
    lead_preds = [p for p in preds if p[0] == lead]
    plan.residual = [p for p in preds if p[0] != lead] + non_pk
    plan.lo, plan.hi = _range_bounds(
        lo, hi, lambda v: schema.encode_pk_value(lead, v), lead_preds
    )
    return plan


def _plan_index_read(plan: ReadPlan, preds, schema: MvSchema,
                     schema_of, at_epoch) -> ReadPlan | None:
    """Rewrite predicates against a secondary index: an EQUALITY
    prefix of the index's columns narrows to one contiguous byte
    range, compare predicates on the NEXT index column tighten the
    range bounds (``WHERE col > x`` — the memcomparable encoding
    already sorts), and every remaining predicate survives as a
    residual filter over the fetched primary rows.  None when no
    published index absorbs at least one predicate — the caller falls
    back."""
    if schema_of is None or not schema.indexes:
        return None
    by_name: dict[str, list] = {}
    for i, op, v in preds:
        by_name.setdefault(schema.columns[i].name, []).append(
            (i, op, v)
        )
    best = None
    for ix in schema.indexes:
        cols = list(ix.get("cols", ()))
        # equality prefix: leading index columns pinned by one '='
        k = 0
        while k < len(cols):
            ps = by_name.get(cols[k], ())
            if len([p for p in ps if p[1] == "equal"]) == 1 \
                    and len(ps) == 1:
                k += 1
            else:
                break
        # optional ranged column directly after the prefix
        range_preds = []
        if k < len(cols):
            ps = by_name.get(cols[k], ())
            if ps and all(p[1] in _CMP_OPS for p in ps):
                range_preds = list(ps)
        if k == 0 and not range_preds:
            continue
        score = (k, 1 if range_preds else 0)
        if best is None or score > best[0]:
            best = (score, ix, cols, k, range_preds)
    if best is None:
        return None
    _, ix, cols, k, range_preds = best
    ixs = schema_of(ix["name"])
    if ixs is None or ixs.indexed_mv != schema.mv \
            or ixs.index_width < max(k, 1):
        return None  # not exported yet (or a stale doc)
    if at_epoch is not None and ixs.since_epoch \
            and at_epoch < ixs.since_epoch:
        return None  # pinned before the index's first export
    vals = {schema.columns[i].name: v for i, op, v in preds
            if op == "equal"}
    ix_lo, ix_hi = mv_key_range(ix["name"])
    enc = b"".join(
        ixs.encode_pk_value(j, vals[cols[j]]) for j in range(k)
    )
    succ = bytes_successor(enc)
    base = ix_lo + enc
    hi = ix_hi if succ is None else ix_lo + succ
    if range_preds and k < ixs.index_width:
        base, hi = _range_bounds(
            base, hi, lambda v: ixs.encode_pk_value(k, v),
            range_preds,
        )
        absorbed = {cols[j] for j in range(k)} | {cols[k]}
    else:
        range_preds = []
        absorbed = {cols[j] for j in range(k)}
    # everything the index bytes did not bound filters residually —
    # including range predicates on the ranged column itself (the
    # bounds are exact, but keeping them residual too is harmless and
    # covers multi-predicate corner cases), and predicates on columns
    # outside the index entirely
    plan.residual = [
        (i, op, v) for i, op, v in preds
        if schema.columns[i].name not in absorbed or op != "equal"
    ]
    plan.mode = "index"
    plan.index_mv = ix["name"]
    plan.index_width = ixs.index_width
    plan.lo = base
    plan.hi = hi
    return plan


class NegativeCache:
    """Per-vid set of pks proven ABSENT at the pinned version — the
    replica-side answer to hot miss storms (repeated point-gets for
    keys that do not exist walk every level's bloom filters each
    time).  Invalidation is STRUCTURAL, exactly like the result cache:
    every entry is implicitly keyed by the vid it was proven at, and a
    lease advance to a new vid clears the set wholesale — a row
    inserted at the new epoch can never be masked by a stale
    negative."""

    def __init__(self, max_keys: int = 65536):
        import collections

        self.max_keys = int(max_keys)
        self.vid = -1
        self.hits = 0
        self._keys: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()

    def sync(self, vid: int) -> None:
        with self._lock:
            if vid != self.vid:
                self._keys.clear()
                self.vid = vid

    def check(self, key: bytes, vid: int) -> bool:
        """True = this key is known-missing at ``vid`` (counts a
        hit); False = unknown, probe storage."""
        with self._lock:
            if vid != self.vid or key not in self._keys:
                return False
            self._keys.move_to_end(key)
            self.hits += 1
            return True

    def add(self, key: bytes, vid: int) -> None:
        """Record a proven miss — only at the CURRENT vid (a re-grant
        mid-read must not seed the new vid's set with old facts)."""
        with self._lock:
            if vid != self.vid or self.max_keys <= 0:
                return
            self._keys[key] = True
            self._keys.move_to_end(key)
            while len(self._keys) > self.max_keys:
                self._keys.popitem(last=False)

    def __len__(self) -> int:
        return len(self._keys)


class ResultCache:
    """Bounded-bytes LRU of completed ``plan_read`` results, keyed by
    ``(normalized sql, manifest vid)``.

    Epoch-advance invalidation is STRUCTURAL: a lease re-grant moves
    the replica to a newer vid, which re-keys every lookup — a stale
    entry can never hit again (entries of dead vids are swept when the
    vid advances and by LRU pressure).  A hit skips parse, plan, and
    the SstView entirely: the memcached-class fast path."""

    def __init__(self, max_bytes: int = 32 << 20):
        import collections

        self.max_bytes = int(max_bytes)
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self._od: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _size(entry) -> int:
        cols, rows, _ = entry
        n = 96 + 16 * len(cols)
        for r in rows:
            n += 48
            for v in r:
                n += 16 + (len(v) if isinstance(v, (str, bytes))
                           else 8)
        return n

    def get(self, key):
        with self._lock:
            e = self._od.get(key)
            if e is None:
                self.misses += 1
                return None
            self._od.move_to_end(key)
            self.hits += 1
            e[2] += 1
            return e[0]

    def contains(self, key) -> bool:
        """Presence probe WITHOUT touching hit/miss/LRU state (the
        warmup path peeks before replaying)."""
        with self._lock:
            return key in self._od

    def put(self, key, entry, hits: int = 0) -> None:
        sz = self._size(entry)
        if self.max_bytes <= 0 or sz > max(self.max_bytes // 8, 1):
            return  # jumbo results would churn the whole LRU
        with self._lock:
            old = self._od.pop(key, None)
            if old is not None:
                self.bytes -= old[1]
                hits = max(hits, old[2])
            self._od[key] = [entry, sz, hits]
            self.bytes += sz
            while self.bytes > self.max_bytes and self._od:
                _, (_, osz, _) = self._od.popitem(last=False)
                self.bytes -= osz

    def hot_keys(self, n: int) -> list:
        """The ``n`` hottest normalized sqls by per-entry hit count —
        the warmup candidates a lease advance replays against the new
        vid.  Only re-read entries (>= 1 hit) qualify; a one-shot read
        is not worth pre-paying."""
        with self._lock:
            ranked = sorted(self._od.items(),
                            key=lambda kv: kv[1][2], reverse=True)
        out: list = []
        seen: set = set()
        for (sql, _vid), e in ranked:
            if e[2] <= 0:
                break
            if sql in seen:
                continue
            seen.add(sql)
            out.append(sql)
            if len(out) >= n:
                break
        return out

    def evict_stale(self, vid: int) -> None:
        """Sweep entries keyed at any OTHER vid (they can never hit
        again once the lease advanced past them)."""
        with self._lock:
            for k in [k for k in self._od if k[1] != vid]:
                self.bytes -= self._od.pop(k)[1]

    def hit_ratio(self) -> float:
        t = self.hits + self.misses
        return self.hits / t if t else 0.0

    def __len__(self) -> int:
        return len(self._od)


class ServingWorker:
    """One serving replica process (or in-process object in tests)."""

    def __init__(self, meta_addr: str | None, data_dir: str,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5,
                 cache_blocks: int = 1024, store=None,
                 metrics: MetricsRegistry | None = None,
                 result_cache_bytes: int = 32 << 20,
                 negative_cache_keys: int = 65536,
                 warmup_keys: int = 8):
        if store is None:
            from risingwave_tpu.storage.hummock.object_store import (
                LocalFsObjectStore,
            )
            store = LocalFsObjectStore(os.path.join(data_dir, "hummock"))
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        self.view = SstView(store, cache_blocks=cache_blocks,
                            metrics=self.metrics)
        #: epoch-keyed result cache (block cache below it): repeat
        #: reads at an unchanged pinned vid skip parse/plan/SstView
        self.result_cache = ResultCache(result_cache_bytes)
        #: per-vid known-missing pk set (see NegativeCache) + how many
        #: hot sqls a lease advance replays against the fresh vid
        self.neg_cache = NegativeCache(negative_cache_keys)
        self.warmup_keys = int(warmup_keys)
        self.warmup_replays = 0
        self._warmup_vid = -1
        self._cache_vid = -1
        self.meta_addr = meta_addr
        self.host = host
        self._port_req = port
        self.heartbeat_interval_s = heartbeat_interval_s
        self.replica_id: int | None = None
        self.reads_total = 0
        self.read_errors = 0
        self.retry = RetryPolicy(max_attempts=4, base_delay_s=0.05,
                                 max_delay_s=0.5, metrics=self.metrics,
                                 op="serving")
        #: lease heartbeats that failed transiently (meta restarting)
        self.heartbeat_failures = 0
        #: times this replica (re-)registered with a meta
        self.registrations = 0
        #: meta's manifest epoch from the last heartbeat (lag gauge)
        self._meta_manifest_epoch = 0
        #: last committed round's root span ctx, piggybacked on the
        #: lease grant — SAMPLED read spans attach under it so the
        #: round trace carries the reads served at that epoch
        self._round_trace_ctx: tuple | None = None
        self._server: RpcServer | None = None
        self._meta_client: RpcClient | None = None
        self._hb_thread: threading.Thread | None = None
        self._hb_lock = threading.Lock()
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else 0

    # -- lifecycle ------------------------------------------------------
    def start(self, heartbeat: bool = True) -> "ServingWorker":
        self._stop.clear()
        self._server = RpcServer(self, self.host, self._port_req).start()
        if self.meta_addr is not None:
            mh, mp = parse_addr(self.meta_addr)
            self._meta_client = RpcClient(mh, mp, timeout=30.0,
                                          src="serving", dst="meta")
            # first registration waits out a meta that is still
            # booting (same patience as the compute worker)
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    self._register()
                    break
                except (ConnectionError, OSError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.25)
            if heartbeat:
                self._hb_thread = threading.Thread(
                    target=self._heartbeat_loop,
                    name=f"serving-{self.replica_id}-hb", daemon=True,
                )
                self._hb_thread.start()
        else:
            # standalone follower (offline inspection / single-node
            # benches): trail the newest logged version, no lease
            self.view.refresh(None)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._meta_client is not None:
            try:
                self._meta_client.call("unregister_serving",
                                       replica_id=self.replica_id)
            except Exception:  # noqa: BLE001 — meta reaps by timeout
                pass
            self._meta_client.close()
            self._meta_client = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        self.view.close()

    # -- lease / refresh -------------------------------------------------
    def _register(self) -> None:
        """(Re-)register with the meta and take the first epoch-pin
        grant.  A restarted meta lost our lease wholesale; the fresh
        registration pins the CURRENT version before the grant leaves,
        so the read path is vacuum-safe again the moment this
        returns."""
        res = self.retry.call(
            self._meta_client, "register_serving",
            host=self.host, port=self.port, pid=os.getpid(),
        )
        self.replica_id = int(res["replica_id"])
        self._meta_client.src = f"serving{self.replica_id}"
        if GLOBAL_TRACE.role == "serving":
            # dedicated server.py process: adopt the meta-assigned
            # identity so span_ids are unique cluster-wide
            GLOBAL_TRACE.configure(role=f"serving{self.replica_id}")
        self._meta_manifest_epoch = int(res.get("manifest_epoch", 0))
        self.registrations += 1
        self._refresh_to(int(res["granted_vid"]))

    def _refresh_to(self, granted_vid: int) -> None:
        try:
            self.view.refresh(granted_vid)
        except StaleLease:
            # the grant outlived the pruned log tail: re-grant (the
            # fresh grant always names the writer's current vid)
            self._grant_refresh()

    def _grant_refresh(self) -> None:
        """One lease round-trip: report the held vid (acks the old pin),
        receive + apply the next grant."""
        if self._meta_client is None:
            self.view.refresh(None)
            self._maybe_warmup()
            return
        with self._hb_lock:
            for _ in range(8):
                # idempotent lease round-trip: transient drops retry
                res = self.retry.call(
                    self._meta_client, "serving_heartbeat",
                    replica_id=self.replica_id,
                    vid=self.view.version.vid,
                )
                self._meta_manifest_epoch = int(
                    res.get("manifest_epoch", 0)
                )
                tc = res.get("trace_ctx")
                self._round_trace_ctx = tuple(tc) if tc else None
                try:
                    self.view.refresh(int(res["granted_vid"]))
                    break
                except StaleLease:
                    continue
        self._export_lag_gauge()
        self._maybe_warmup()

    def _maybe_warmup(self) -> None:
        """Result-cache warmup on lease grant: when the vid advanced,
        replay the hottest normalized-sql keys against the NEW vid so
        the first post-epoch reads hit instead of missing.  Hot keys
        are captured BEFORE the stale sweep (they live under the old
        vid); replays are advisory — any failure just leaves a miss.
        """
        vid = self.view.version.vid
        if self.warmup_keys <= 0 or vid == self._warmup_vid:
            return
        self._warmup_vid = vid
        hot = self.result_cache.hot_keys(self.warmup_keys)
        self._sync_cache_vid(vid)
        for sql in hot:
            if self._stop.is_set() or self.view.version.vid != vid:
                break  # the lease moved again mid-warmup
            if self.result_cache.contains((sql, vid)):
                continue  # a read beat us to it
            try:
                plan = self._plan(sql)
                cols, rows = self._execute(plan, self.view.version)
                entry = (cols, rows,
                         self.view.version.max_committed_epoch)
            except Exception:  # noqa: BLE001 — warmup is best-effort
                continue
            self.result_cache.put((sql, vid), entry)
            self.warmup_replays += 1
            self.metrics.inc("serving_warmup_replays_total")

    def _export_lag_gauge(self) -> None:
        self.metrics.set_gauge(
            "serving_pinned_epoch_lag",
            max(0, self._meta_manifest_epoch
                - self.view.version.max_committed_epoch),
        )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._grant_refresh()
            except (ConnectionError, OSError):
                # meta unreachable (restarting / partitioned): keep
                # the lease loop alive — the cadence is the backoff
                self.heartbeat_failures += 1
            except RpcError:
                # the meta answered but doesn't know this replica: a
                # restarted meta lost the serving registry — take a
                # fresh registration (and a fresh pin lease)
                self.heartbeat_failures += 1
                try:
                    self._register()
                except (RpcError, ConnectionError, OSError):
                    pass
            except Exception:  # noqa: BLE001 — never kill the thread
                self.heartbeat_failures += 1
                time.sleep(self.heartbeat_interval_s)

    def _report_corruption(self, err: IntegrityError) -> None:
        """Fire-and-forget corruption report (the meta quarantines and
        repairs in the background) — the read path never blocks on a
        repair round-trip."""
        if self._meta_client is None or not err.key:
            return

        def _send() -> None:
            try:
                self._meta_client.call(
                    "report_corruption", key=err.key, kind=err.kind,
                    reason=str(err),
                    by=f"serving{self.replica_id}",
                )
            except Exception:  # noqa: BLE001 — scrub re-detects
                pass

        threading.Thread(target=_send, name="serving-corruption-report",
                         daemon=True).start()

    # -- the read path ---------------------------------------------------
    def _plan(self, sql: str) -> ReadPlan:
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Select):
            raise ServeUnsupported(
                "serving replicas handle a single SELECT"
            )
        sel = stmts[0]
        if not isinstance(sel.from_, ast.TableRef):
            raise ServeUnsupported(
                "serving reads are SELECT ... FROM <mv>"
            )
        schema = self.view.schema(sel.from_.name)
        if schema is None:
            raise ServeUnsupported(
                f"no schema published for {sel.from_.name!r} "
                "(not exported to shared storage yet)"
            )
        return plan_read(
            sel, schema, schema_of=self.view.schema,
            at_epoch=self.view.version.max_committed_epoch,
        )

    def _ensure_epoch(self, min_epoch: int,
                      timeout_s: float = 10.0) -> None:
        """Catch up to the meta's pinned epoch before reading (a read
        routed right after a cluster commit must see that commit)."""
        if self.view.version.max_committed_epoch >= min_epoch:
            return
        deadline = time.monotonic() + timeout_s
        while self.view.version.max_committed_epoch < min_epoch:
            self._grant_refresh()
            if self.view.version.max_committed_epoch >= min_epoch:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"serving replica stuck behind pinned epoch "
                    f"{min_epoch} (at "
                    f"{self.view.version.max_committed_epoch})"
                )
            time.sleep(0.02)

    def _project(self, plan: ReadPlan, hits):
        rows: list[tuple] = []
        skip = plan.offset
        residual = plan.residual or ()
        for row in hits:
            if residual and not all(
                    _cmp(op, row[i], v) for i, op, v in residual):
                continue  # residual filter BEFORE offset/limit
            if skip > 0:
                skip -= 1
                continue
            rows.append(tuple(row[i] for i in plan.cols))
            if plan.limit is not None and len(rows) >= plan.limit:
                break
        return plan.col_names, rows

    def _execute(self, plan: ReadPlan, version):
        if plan.mode == "get":
            if self.neg_cache.check(plan.key, version.vid):
                hits = []
            else:
                val = self.view.point_get(plan.key, version)
                if val is None:
                    self.neg_cache.add(plan.key, version.vid)
                hits = [] if val is None else [pickle.loads(val)]
        elif plan.mode == "index":
            hits = self._index_lookup(plan, version)
        else:
            return self._scan_pushdown(plan, version)
        return self._project(plan, hits)

    def _scan_pushdown(self, plan: ReadPlan, version):
        """Scan-mode reads run the pushdown merge scan: residual
        predicates (key-byte compares where the mc-encoding allows,
        decoded-row compares otherwise) and the projection evaluate
        per block inside ``SstView.scan_filtered`` — rows the filter
        elides never materialize.  Output is byte-identical to
        fetch-then-filter (`_project` over a plain scan)."""
        schema = self.view.schema(plan.mv)
        if schema is None:
            # schema doc vanished under us (DROP racing the read):
            # the un-pushed path preserves the old error surface
            hits = (pickle.loads(v)
                    for _, v in self.view.scan(plan.lo, plan.hi,
                                               version))
            return self._project(plan, hits)
        stats = PushdownStats()
        residual = [(i, _PUSH_OPS[op], v)
                    for i, op, v in (plan.residual or ())]
        ev = BlockEvaluator(schema, residual, plan.cols, stats)
        prefix, _ = mv_key_range(plan.mv)
        rows = self.view.scan_filtered(plan.lo, plan.hi, prefix, ev,
                                       pickle.loads, version)
        self.metrics.inc("pushdown_rows_elided_total",
                         stats.rows_elided, where="replica")
        self.metrics.inc("pushdown_blocks_skipped_total",
                         stats.blocks_skipped)
        start = plan.offset
        end = None if plan.limit is None else start + plan.limit
        return plan.col_names, rows[start:end]

    def _index_lookup(self, plan: ReadPlan, version) -> list[tuple]:
        """Index range scan → upstream pk values → ONE sorted
        multi-get pass on the primary MV.  Index and primary export in
        the same per-barrier SST, so any pinned version sees them
        consistent; output order (encoded primary pk ascending) is
        byte-identical to a full scan + filter."""
        schema = self.view.schema(plan.mv)
        prim_lo, _ = mv_key_range(plan.mv)
        w = plan.index_width
        keys = []
        for _, v in self.view.scan(plan.lo, plan.hi, version):
            row = pickle.loads(v)
            keys.append(prim_lo + b"".join(
                schema.encode_pk_value(pkcol, row[w + j])
                for j, pkcol in enumerate(schema.pk)
            ))
        self.metrics.inc("serving_index_lookups_total")
        self.metrics.inc("serving_index_keys_total", len(keys))
        vals = self.view.multi_get(keys, version)
        return [pickle.loads(vals[k]) for k in sorted(set(keys))
                if vals.get(k) is not None]

    def _catch_up(self, min_epoch: int) -> None:
        """``_ensure_epoch`` with the read-path error mapping: a
        replica that cannot reach the pinned epoch is UNAVAILABLE for
        this read (routing signal, un-counted — the meta serves it
        elsewhere), not a read error."""
        try:
            self._ensure_epoch(min_epoch)
        except IntegrityError as e:
            # the manifest chain broke under the refresh: report for
            # quarantine and route the read around this replica
            record_integrity_error(self.metrics, e)
            self._report_corruption(e)
            raise ServeUnavailable(
                f"manifest corruption under refresh: {e!r}"
            ) from e
        except (ConnectionError, OSError, RpcError, RuntimeError) as e:
            raise ServeUnavailable(
                f"replica cannot reach the pinned epoch: {e!r}"
            ) from e

    def _run_pinned(self, fn):
        """Run ``fn(version)`` with the pinned-read error contract:
        one re-grant + retry when an SST vanished underneath (lease
        raced a vacuum), detected corruption answers
        ``ServeUnavailable`` (reported for quarantine — never an
        error, never a silently wrong row), anything else counts as a
        read error."""
        try:
            try:
                return fn(self.view.version)
            except ObjectError:
                self._grant_refresh()
                return fn(self.view.version)
        except IntegrityError as e:
            record_integrity_error(self.metrics, e)
            self._report_corruption(e)
            raise ServeUnavailable(
                f"corrupt object under read: {e!r}"
            ) from e
        except BaseException:
            self.read_errors += 1
            self.metrics.inc("serving_read_errors_total")
            raise

    def _sync_cache_vid(self, vid: int) -> None:
        if vid != self._cache_vid:
            self.result_cache.evict_stale(vid)
            self.neg_cache.sync(vid)
            self._cache_vid = vid

    def _export_cache_gauges(self) -> None:
        rc = self.result_cache
        self.metrics.set_gauge("serving_result_cache_hits", rc.hits)
        self.metrics.set_gauge("serving_result_cache_misses",
                               rc.misses)
        self.metrics.set_gauge("serving_result_cache_bytes", rc.bytes)
        self.metrics.set_gauge("serving_result_cache_entries",
                               len(rc))
        self.metrics.set_gauge("serving_result_cache_hit_ratio",
                               rc.hit_ratio())
        self.metrics.set_gauge("serving_negative_cache_hits",
                               self.neg_cache.hits)
        self.metrics.set_gauge("serving_negative_cache_entries",
                               len(self.neg_cache))
        self.view._export_gauges()

    def read(self, sql: str, min_epoch: int = 0):
        """Serve one SELECT at the leased (meta-pinned) epoch.  A
        result-cache hit at the current vid skips parse, plan, and the
        SstView entirely."""
        t0 = time.perf_counter()
        # 1-in-sample_n reads record a span parented under the last
        # committed round's root (the lease piggyback) — the round
        # trace shows what the read tier served at that epoch
        with GLOBAL_TRACE.sampled_span(
                "serving_read", ctx=self._round_trace_ctx) as tsp:
            self._catch_up(int(min_epoch or 0))
            version = self.view.version
            self._sync_cache_vid(version.vid)
            key = (" ".join(sql.split()), version.vid)
            entry = self.result_cache.get(key)
            if entry is None:
                # ServeUnsupported propagates un-counted (owner
                # fallback)
                plan = self._plan(sql)
                cols, rows = self._run_pinned(
                    lambda v: self._execute(plan, v)
                )
                entry = (cols, rows,
                         self.view.version.max_committed_epoch)
                if self.view.version.vid == version.vid:
                    # an ObjectError re-grant may have moved the vid
                    # mid-read: never cache under the stale key
                    self.result_cache.put(key, entry)
                tsp.set(cached=False)
            else:
                tsp.set(cached=True)
            cols, rows, epoch = entry
            tsp.set(rows=len(rows), epoch=epoch)
        self.reads_total += 1
        self.metrics.inc("serving_reads_total")
        self.metrics.observe("serving_read_seconds",
                             time.perf_counter() - t0)
        self._export_cache_gauges()
        return cols, rows, epoch

    def read_batch(self, sqls: list, min_epoch: int = 0) -> list:
        """Serve N SELECTs through ONE epoch catch-up and (for
        point-gets) ONE shared multi-get pass sorted by encoded pk —
        the batched form that amortizes the RPC frame and makes
        block-cache access sequential.  Per item the answer is either
        ``(cols, rows, epoch)`` or a dict marking ``unsupported`` /
        final ``error`` (the meta falls back or re-raises per item)."""
        t0 = time.perf_counter()
        self._catch_up(int(min_epoch or 0))
        version = self.view.version
        self._sync_cache_vid(version.vid)
        results: list = [None] * len(sqls)
        todo: list[tuple[int, tuple, ReadPlan]] = []
        for i, sql in enumerate(sqls):
            key = (" ".join(sql.split()), version.vid)
            entry = self.result_cache.get(key)
            if entry is not None:
                results[i] = entry
                continue
            try:
                todo.append((i, key, self._plan(sql)))
            except ServeUnsupported as e:
                results[i] = {"unsupported": str(e)}
            except ValueError as e:
                results[i] = {"error": str(e)}
        if todo:
            def run(v):
                gets = [t for t in todo if t[2].mode == "get"]
                # known-missing pks skip the storage probe outright; a
                # key absent from `vals` below projects to zero rows,
                # exactly as a probed miss would
                fetch = [p.key for _, _, p in gets
                         if not self.neg_cache.check(p.key, v.vid)]
                vals = self.view.multi_get(fetch, v) if fetch else {}
                for k in fetch:
                    if vals.get(k) is None:
                        self.neg_cache.add(k, v.vid)
                out = []
                for i, key, plan in todo:
                    if plan.mode == "get":
                        raw = vals.get(plan.key)
                        hits = [] if raw is None \
                            else [pickle.loads(raw)]
                        cols, rows = self._project(plan, hits)
                    else:
                        cols, rows = self._execute(plan, v)
                    out.append(
                        (i, key, (cols, rows, v.max_committed_epoch))
                    )
                return out
            for i, key, entry in self._run_pinned(run):
                results[i] = entry
                if self.view.version.vid == version.vid:
                    self.result_cache.put(key, entry)
        n = len(sqls)
        self.reads_total += n
        self.metrics.inc("serving_reads_total", n)
        self.metrics.inc("serving_batch_reads_total", n)
        self.metrics.observe("serving_batch_seconds",
                             time.perf_counter() - t0)
        self._export_cache_gauges()
        return results

    def multi_get(self, mv: str, pks: list, cols: list | None = None,
                  min_epoch: int = 0):
        """First-class multi-get: one MV + N full pks through one RPC
        frame and ONE sorted SstView pass.  Rows come back in encoded
        pk order; pks not present are omitted."""
        t0 = time.perf_counter()
        self._catch_up(int(min_epoch or 0))
        self._sync_cache_vid(self.view.version.vid)
        schema = self.view.schema(mv)
        if schema is None:
            raise ServeUnsupported(
                f"no schema published for {mv!r} "
                "(not exported to shared storage yet)"
            )
        if cols is None:
            proj = schema.output_indices()
        else:
            proj = []
            for c in cols:
                idx = schema.index_of(c)
                if idx is None:
                    raise ValueError(
                        f"column {c!r} does not exist in {mv!r}"
                    )
                proj.append(idx)
        lo, _ = mv_key_range(mv)
        keys = []
        for pk in pks:
            if len(pk) != len(schema.pk):
                raise ValueError(
                    f"multi_get pk arity {len(pk)} != "
                    f"{len(schema.pk)} for {mv!r}"
                )
            keys.append(lo + b"".join(
                schema.encode_pk_value(c, v)
                for c, v in zip(schema.pk, pk)
            ))

        def run(v):
            fetch = [k for k in set(keys)
                     if not self.neg_cache.check(k, v.vid)]
            vals = self.view.multi_get(fetch, v)
            for k in fetch:
                if vals.get(k) is None:
                    self.neg_cache.add(k, v.vid)
            rows = [pickle.loads(vals[k]) for k in sorted(set(keys))
                    if vals.get(k) is not None]
            return ([tuple(r[i] for i in proj) for r in rows],
                    v.max_committed_epoch)

        rows, epoch = self._run_pinned(run)
        n = len(pks)
        self.reads_total += n
        self.metrics.inc("serving_reads_total", n)
        self.metrics.inc("serving_multi_get_keys_total", n)
        self.metrics.observe("serving_batch_seconds",
                             time.perf_counter() - t0)
        self._export_cache_gauges()
        return [schema.columns[i].name for i in proj], rows, epoch

    # -- RPC surface ----------------------------------------------------
    def rpc_read(self, sql: str, min_epoch: int = 0) -> dict:
        cols, rows, epoch = self.read(sql, min_epoch)
        return {"cols": cols, "rows": [list(r) for r in rows],
                "epoch": epoch}

    def rpc_read_batch(self, sqls: list, min_epoch: int = 0) -> dict:
        out = []
        for entry in self.read_batch(list(sqls), min_epoch):
            if isinstance(entry, dict):
                out.append(entry)
            else:
                cols, rows, epoch = entry
                out.append({"cols": cols,
                            "rows": [list(r) for r in rows],
                            "epoch": epoch})
        return {"results": out}

    def rpc_multi_get(self, mv: str, pks: list,
                      cols: list | None = None,
                      min_epoch: int = 0) -> dict:
        names, rows, epoch = self.multi_get(mv, pks, cols, min_epoch)
        return {"cols": names, "rows": [list(r) for r in rows],
                "epoch": epoch}

    def rpc_ping(self) -> dict:
        return {
            "ok": True,
            "replica_id": self.replica_id,
            "vid": self.view.version.vid,
            "epoch": self.view.version.max_committed_epoch,
            "jax_loaded": "jax" in sys.modules,
        }

    def rpc_state(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "vid": self.view.version.vid,
            "pinned_epoch": self.view.version.max_committed_epoch,
            "meta_manifest_epoch": self._meta_manifest_epoch,
            "reads_total": self.reads_total,
            "read_errors": self.read_errors,
            "cache_hits": self.view.cache.hits,
            "cache_misses": self.view.cache.misses,
            "cache_hit_ratio": self.view.cache.hit_ratio(),
            "result_cache_hits": self.result_cache.hits,
            "result_cache_misses": self.result_cache.misses,
            "result_cache_bytes": self.result_cache.bytes,
            "result_cache_hit_ratio": self.result_cache.hit_ratio(),
            "negative_cache_hits": self.neg_cache.hits,
            "negative_cache_entries": len(self.neg_cache),
            "warmup_replays": self.warmup_replays,
            "jax_loaded": "jax" in sys.modules,
        }

    def rpc_metrics(self) -> dict:
        return {"prometheus": self.metrics.render_prometheus()}

    def rpc_trace_dump(self, trace_id: str | None = None) -> dict:
        return {"role": GLOBAL_TRACE.role,
                "spans": GLOBAL_TRACE.dump(trace_id)}

    def rpc_faults(self) -> dict:
        """This process' chaos counters (aggregated by the meta's
        ``cluster_faults`` for the ctl surface)."""
        fabric = get_fabric()
        return {
            "fabric": fabric.stats() if fabric is not None else None,
            "rpc_retries_total": self.retry.retries,
            "rpc_retry_gave_up_total": self.retry.gave_up,
            "heartbeat_failures": self.heartbeat_failures,
            "registrations": self.registrations,
        }
