"""ServingWorker: a stateless serving replica (engine-free, jax-free).

Reference counterpart: the frontend/batch serving split — stateless
nodes that execute batch scans over SHARED storage at a pinned
snapshot, scaling the read path independently of the streaming
compute nodes (SURVEY.md §3.4; Taurus' read replicas over shared
pages, PAPERS.md).  The Hazelcast-Jet tail-latency discipline applies:
serve from the block cache and pinned SSTs, never from the barrier
path.

Shape here: NO Engine, NO JAX — the process imports only the parser
(pure Python), the SST/manifest readers, and the RPC/metrics plumbing.
It registers with the meta like a compute worker (heartbeats, expiry),
holds a meta-side EPOCH PIN LEASE that advances per committed cluster
epoch (the lease pins the replica's manifest version in the meta's
VersionManager, so vacuum can never reap an SST under a live serving
read), and answers the SELECT shapes a key-value read path can serve:

- point-gets:      WHERE covers the MV's full pk with equalities;
- pk-range scans:  predicates on the LEADING pk column (the
  memcomparable encoding makes byte ranges == value ranges);
- projection (named columns or *) and LIMIT/OFFSET.

Anything else raises ``ServeUnsupported`` — the meta frontend falls
back to the owning compute worker, so the SQL surface never narrows.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import time
from dataclasses import dataclass

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    parse_addr,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric
from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.serve.reader import (
    MvSchema,
    SstView,
    StaleLease,
    bytes_successor,
    mv_key_range,
)
from risingwave_tpu.storage.hummock.object_store import ObjectError
from risingwave_tpu.storage.integrity import (
    IntegrityError,
    record_integrity_error,
)


class ServeUnsupported(ValueError):
    """The statement needs the engine — route to the owning worker."""


class ServeUnavailable(RuntimeError):
    """This replica transiently cannot serve (meta unreachable during
    a lease refresh, or stuck behind the pinned epoch) — the meta
    should route the read to another replica or the owning worker,
    NOT surface an error.  A routing signal, never a failed read."""


_CMP_OPS = ("equal", "less_than", "less_than_or_equal",
            "greater_than", "greater_than_or_equal")


@dataclass
class ReadPlan:
    mv: str
    cols: list[int]
    col_names: list[str]
    #: "get" (point key) or "scan" (byte range)
    mode: str
    key: bytes = b""
    lo: bytes = b""
    hi: bytes | None = None
    limit: int | None = None
    offset: int = 0


def _conjuncts(expr) -> list:
    from risingwave_tpu.sql import ast

    if isinstance(expr, ast.BinaryOp) and expr.op == "and":
        return _conjuncts(expr.left) + _conjuncts(expr.right)
    return [expr]


def _flip(op: str) -> str:
    return {
        "less_than": "greater_than",
        "less_than_or_equal": "greater_than_or_equal",
        "greater_than": "less_than",
        "greater_than_or_equal": "less_than_or_equal",
    }.get(op, op)


def plan_read(select, schema: MvSchema) -> ReadPlan:
    """Compile one SELECT into a key-value read, or raise
    ``ServeUnsupported`` (the meta falls back to the owning worker)."""
    from risingwave_tpu.sql import ast

    if select.group_by or select.having is not None:
        raise ServeUnsupported(
            "serving replicas handle projection/point/range reads only"
        )
    if not isinstance(select.from_, ast.TableRef) \
            or select.from_.temporal:
        raise ServeUnsupported("serving reads are SELECT ... FROM <mv>")
    mv = select.from_.name
    if select.order_by:
        # ORDER BY pushdown: the scan already yields memcomparable-pk
        # order, so an ASCENDING prefix of the pk columns is a no-op —
        # accept it (typically ORDER BY pk LIMIT k) instead of falling
        # back to the owning worker.  Anything else still needs the
        # engine's sort.
        for pos, oi in enumerate(select.order_by):
            if oi.descending or not isinstance(oi.expr, ast.ColumnRef):
                raise ServeUnsupported(
                    "serving ORDER BY supports an ascending pk prefix"
                )
            idx = schema.index_of(oi.expr.name)
            if pos >= len(schema.pk) or idx != schema.pk[pos]:
                raise ServeUnsupported(
                    "serving ORDER BY supports an ascending pk prefix"
                )

    # projection
    cols: list[int] = []
    names: list[str] = []
    if len(select.items) == 1 \
            and isinstance(select.items[0].expr, ast.Star):
        cols = schema.output_indices()
        names = [schema.columns[i].name for i in cols]
    else:
        for item in select.items:
            if not isinstance(item.expr, ast.ColumnRef):
                raise ServeUnsupported(
                    "serving projection supports plain columns"
                )
            idx = schema.index_of(item.expr.name)
            if idx is None:
                raise ValueError(
                    f"column {item.expr.name!r} does not exist in {mv!r}"
                )
            cols.append(idx)
            names.append(item.alias or item.expr.name)

    lo, hi = mv_key_range(mv)
    plan = ReadPlan(mv=mv, cols=cols, col_names=names, mode="scan",
                    lo=lo, hi=hi, limit=select.limit,
                    offset=select.offset or 0)
    if select.where is None:
        return plan

    # predicates: col <cmp> literal over pk columns only
    preds: list[tuple[int, str, object]] = []
    for c in _conjuncts(select.where):
        if not isinstance(c, ast.BinaryOp) or c.op not in _CMP_OPS:
            raise ServeUnsupported("serving WHERE supports pk compares")
        left, right, op = c.left, c.right, c.op
        if isinstance(left, ast.Literal) \
                and isinstance(right, ast.ColumnRef):
            left, right, op = right, left, _flip(op)
        if not (isinstance(left, ast.ColumnRef)
                and isinstance(right, ast.Literal)):
            raise ServeUnsupported("serving WHERE supports pk compares")
        idx = schema.index_of(left.name)
        if idx is None:
            raise ValueError(
                f"column {left.name!r} does not exist in {mv!r}"
            )
        if idx not in schema.pk:
            raise ServeUnsupported(
                f"serving WHERE is limited to pk columns "
                f"(got {left.name!r})"
            )
        preds.append((idx, op, right.value))

    eq = {i: v for i, op, v in preds if op == "equal"}
    if len(eq) == len(preds) and set(eq) == set(schema.pk) \
            and len(preds) == len(schema.pk):
        plan.mode = "get"
        plan.key = lo + b"".join(
            schema.encode_pk_value(i, eq[i]) for i in schema.pk
        )
        return plan

    # range: every predicate must sit on the LEADING pk column, where
    # the memcomparable prefix makes byte order == value order
    lead = schema.pk[0]
    if any(i != lead for i, _, _ in preds):
        raise ServeUnsupported(
            "serving range scans bound the leading pk column"
        )
    lo_b, hi_b = lo, hi
    for _, op, v in preds:
        enc = schema.encode_pk_value(lead, v)
        if op in ("equal", "greater_than_or_equal"):
            lo_b = max(lo_b, lo + enc)
        elif op == "greater_than":
            succ = bytes_successor(enc)
            lo_b = hi if succ is None else max(lo_b, lo + succ)
        if op in ("equal", "less_than_or_equal"):
            succ = bytes_successor(enc)
            if succ is not None:
                hi_b = min(hi_b, lo + succ)
        elif op == "less_than":
            hi_b = min(hi_b, lo + enc)
    plan.lo, plan.hi = lo_b, hi_b
    return plan


class ServingWorker:
    """One serving replica process (or in-process object in tests)."""

    def __init__(self, meta_addr: str | None, data_dir: str,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5,
                 cache_blocks: int = 1024, store=None,
                 metrics: MetricsRegistry | None = None):
        if store is None:
            from risingwave_tpu.storage.hummock.object_store import (
                LocalFsObjectStore,
            )
            store = LocalFsObjectStore(os.path.join(data_dir, "hummock"))
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        self.view = SstView(store, cache_blocks=cache_blocks,
                            metrics=self.metrics)
        self.meta_addr = meta_addr
        self.host = host
        self._port_req = port
        self.heartbeat_interval_s = heartbeat_interval_s
        self.replica_id: int | None = None
        self.reads_total = 0
        self.read_errors = 0
        self.retry = RetryPolicy(max_attempts=4, base_delay_s=0.05,
                                 max_delay_s=0.5, metrics=self.metrics,
                                 op="serving")
        #: lease heartbeats that failed transiently (meta restarting)
        self.heartbeat_failures = 0
        #: times this replica (re-)registered with a meta
        self.registrations = 0
        #: meta's manifest epoch from the last heartbeat (lag gauge)
        self._meta_manifest_epoch = 0
        self._server: RpcServer | None = None
        self._meta_client: RpcClient | None = None
        self._hb_thread: threading.Thread | None = None
        self._hb_lock = threading.Lock()
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else 0

    # -- lifecycle ------------------------------------------------------
    def start(self, heartbeat: bool = True) -> "ServingWorker":
        self._stop.clear()
        self._server = RpcServer(self, self.host, self._port_req).start()
        if self.meta_addr is not None:
            mh, mp = parse_addr(self.meta_addr)
            self._meta_client = RpcClient(mh, mp, timeout=30.0,
                                          src="serving", dst="meta")
            # first registration waits out a meta that is still
            # booting (same patience as the compute worker)
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    self._register()
                    break
                except (ConnectionError, OSError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.25)
            if heartbeat:
                self._hb_thread = threading.Thread(
                    target=self._heartbeat_loop,
                    name=f"serving-{self.replica_id}-hb", daemon=True,
                )
                self._hb_thread.start()
        else:
            # standalone follower (offline inspection / single-node
            # benches): trail the newest logged version, no lease
            self.view.refresh(None)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._meta_client is not None:
            try:
                self._meta_client.call("unregister_serving",
                                       replica_id=self.replica_id)
            except Exception:  # noqa: BLE001 — meta reaps by timeout
                pass
            self._meta_client.close()
            self._meta_client = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        self.view.close()

    # -- lease / refresh -------------------------------------------------
    def _register(self) -> None:
        """(Re-)register with the meta and take the first epoch-pin
        grant.  A restarted meta lost our lease wholesale; the fresh
        registration pins the CURRENT version before the grant leaves,
        so the read path is vacuum-safe again the moment this
        returns."""
        res = self.retry.call(
            self._meta_client, "register_serving",
            host=self.host, port=self.port, pid=os.getpid(),
        )
        self.replica_id = int(res["replica_id"])
        self._meta_client.src = f"serving{self.replica_id}"
        self._meta_manifest_epoch = int(res.get("manifest_epoch", 0))
        self.registrations += 1
        self._refresh_to(int(res["granted_vid"]))

    def _refresh_to(self, granted_vid: int) -> None:
        try:
            self.view.refresh(granted_vid)
        except StaleLease:
            # the grant outlived the pruned log tail: re-grant (the
            # fresh grant always names the writer's current vid)
            self._grant_refresh()

    def _grant_refresh(self) -> None:
        """One lease round-trip: report the held vid (acks the old pin),
        receive + apply the next grant."""
        if self._meta_client is None:
            self.view.refresh(None)
            return
        with self._hb_lock:
            for _ in range(8):
                # idempotent lease round-trip: transient drops retry
                res = self.retry.call(
                    self._meta_client, "serving_heartbeat",
                    replica_id=self.replica_id,
                    vid=self.view.version.vid,
                )
                self._meta_manifest_epoch = int(
                    res.get("manifest_epoch", 0)
                )
                try:
                    self.view.refresh(int(res["granted_vid"]))
                    break
                except StaleLease:
                    continue
        self._export_lag_gauge()

    def _export_lag_gauge(self) -> None:
        self.metrics.set_gauge(
            "serving_pinned_epoch_lag",
            max(0, self._meta_manifest_epoch
                - self.view.version.max_committed_epoch),
        )

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._grant_refresh()
            except (ConnectionError, OSError):
                # meta unreachable (restarting / partitioned): keep
                # the lease loop alive — the cadence is the backoff
                self.heartbeat_failures += 1
            except RpcError:
                # the meta answered but doesn't know this replica: a
                # restarted meta lost the serving registry — take a
                # fresh registration (and a fresh pin lease)
                self.heartbeat_failures += 1
                try:
                    self._register()
                except (RpcError, ConnectionError, OSError):
                    pass
            except Exception:  # noqa: BLE001 — never kill the thread
                self.heartbeat_failures += 1
                time.sleep(self.heartbeat_interval_s)

    def _report_corruption(self, err: IntegrityError) -> None:
        """Fire-and-forget corruption report (the meta quarantines and
        repairs in the background) — the read path never blocks on a
        repair round-trip."""
        if self._meta_client is None or not err.key:
            return

        def _send() -> None:
            try:
                self._meta_client.call(
                    "report_corruption", key=err.key, kind=err.kind,
                    reason=str(err),
                    by=f"serving{self.replica_id}",
                )
            except Exception:  # noqa: BLE001 — scrub re-detects
                pass

        threading.Thread(target=_send, name="serving-corruption-report",
                         daemon=True).start()

    # -- the read path ---------------------------------------------------
    def _plan(self, sql: str) -> ReadPlan:
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Select):
            raise ServeUnsupported(
                "serving replicas handle a single SELECT"
            )
        sel = stmts[0]
        if not isinstance(sel.from_, ast.TableRef):
            raise ServeUnsupported(
                "serving reads are SELECT ... FROM <mv>"
            )
        schema = self.view.schema(sel.from_.name)
        if schema is None:
            raise ServeUnsupported(
                f"no schema published for {sel.from_.name!r} "
                "(not exported to shared storage yet)"
            )
        return plan_read(sel, schema)

    def _ensure_epoch(self, min_epoch: int,
                      timeout_s: float = 10.0) -> None:
        """Catch up to the meta's pinned epoch before reading (a read
        routed right after a cluster commit must see that commit)."""
        if self.view.version.max_committed_epoch >= min_epoch:
            return
        deadline = time.monotonic() + timeout_s
        while self.view.version.max_committed_epoch < min_epoch:
            self._grant_refresh()
            if self.view.version.max_committed_epoch >= min_epoch:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"serving replica stuck behind pinned epoch "
                    f"{min_epoch} (at "
                    f"{self.view.version.max_committed_epoch})"
                )
            time.sleep(0.02)

    def _execute(self, plan: ReadPlan, version):
        rows: list[tuple] = []
        if plan.mode == "get":
            val = self.view.point_get(plan.key, version)
            hits = [] if val is None else [pickle.loads(val)]
        else:
            hits = (pickle.loads(v)
                    for _, v in self.view.scan(plan.lo, plan.hi,
                                               version))
        skip = plan.offset
        for row in hits:
            if skip > 0:
                skip -= 1
                continue
            rows.append(tuple(row[i] for i in plan.cols))
            if plan.limit is not None and len(rows) >= plan.limit:
                break
        return plan.col_names, rows

    def read(self, sql: str, min_epoch: int = 0):
        """Serve one SELECT at the leased (meta-pinned) epoch."""
        t0 = time.perf_counter()
        plan = self._plan(sql)  # ServeUnsupported propagates un-counted
        try:
            # catching up may need the meta; a replica that can't is
            # UNAVAILABLE for this read (routing signal, un-counted —
            # the meta serves it elsewhere), not a read error
            self._ensure_epoch(int(min_epoch or 0))
        except IntegrityError as e:
            # the manifest chain broke under the refresh: report for
            # quarantine and route the read around this replica
            record_integrity_error(self.metrics, e)
            self._report_corruption(e)
            raise ServeUnavailable(
                f"manifest corruption under refresh: {e!r}"
            ) from e
        except (ConnectionError, OSError, RpcError, RuntimeError) as e:
            raise ServeUnavailable(
                f"replica cannot reach the pinned epoch: {e!r}"
            ) from e
        try:
            version = self.view.version
            try:
                cols, rows = self._execute(plan, version)
            except ObjectError:
                # an SST vanished under us (lease raced a vacuum —
                # should not happen while the meta honors pins):
                # re-grant and retry once before surfacing an error
                self._grant_refresh()
                version = self.view.version
                cols, rows = self._execute(plan, version)
        except IntegrityError as e:
            # corrupt shared bytes (SST block/footer crc): a DETECTED
            # corruption is a routing event — report it to the meta
            # (quarantine + self-healing repair) and answer
            # ServeUnavailable so the read lands on another replica or
            # the owner; never an error, never a silently wrong row
            record_integrity_error(self.metrics, e)
            self._report_corruption(e)
            raise ServeUnavailable(
                f"corrupt object under read: {e!r}"
            ) from e
        except BaseException:
            self.read_errors += 1
            self.metrics.inc("serving_read_errors_total")
            raise
        self.reads_total += 1
        self.metrics.inc("serving_reads_total")
        self.metrics.observe("serving_read_seconds",
                             time.perf_counter() - t0)
        self.view._export_gauges()
        return cols, rows, version.max_committed_epoch

    # -- RPC surface ----------------------------------------------------
    def rpc_read(self, sql: str, min_epoch: int = 0) -> dict:
        cols, rows, epoch = self.read(sql, min_epoch)
        return {"cols": cols, "rows": [list(r) for r in rows],
                "epoch": epoch}

    def rpc_ping(self) -> dict:
        return {
            "ok": True,
            "replica_id": self.replica_id,
            "vid": self.view.version.vid,
            "epoch": self.view.version.max_committed_epoch,
            "jax_loaded": "jax" in sys.modules,
        }

    def rpc_state(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "vid": self.view.version.vid,
            "pinned_epoch": self.view.version.max_committed_epoch,
            "meta_manifest_epoch": self._meta_manifest_epoch,
            "reads_total": self.reads_total,
            "read_errors": self.read_errors,
            "cache_hits": self.view.cache.hits,
            "cache_misses": self.view.cache.misses,
            "cache_hit_ratio": self.view.cache.hit_ratio(),
            "jax_loaded": "jax" in sys.modules,
        }

    def rpc_metrics(self) -> dict:
        return {"prometheus": self.metrics.render_prometheus()}

    def rpc_faults(self) -> dict:
        """This process' chaos counters (aggregated by the meta's
        ``cluster_faults`` for the ctl surface)."""
        fabric = get_fabric()
        return {
            "fabric": fabric.stats() if fabric is not None else None,
            "rpc_retries_total": self.retry.retries,
            "rpc_retry_gave_up_total": self.retry.gave_up,
            "heartbeat_failures": self.heartbeat_failures,
            "registrations": self.registrations,
        }
