"""CH-benCHmark schema: TPC-C tables + CH's TPC-H extension tables.

A deliberately lean rendition of the TPC-C schema (every column the
transaction mix or the CH query group actually touches; monetary
amounts are integer CENTS so aggregates stay byte-exact under any
chunking), plus the supplier/nation/region reference tables the
CH-benCHmark adds so TPC-H join shapes have somewhere to go.  Tables
the transaction mix UPDATES are created ``WITH (retract = 'true')``
(updates travel as DELETE-old-row + INSERT-new-row retraction pairs);
pure-insert fact tables and the static item catalog stay append-only.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class CHScale:
    """Scale knobs (defaults sized for the 1-core CI box)."""

    warehouses: int = 2
    districts_per_w: int = 2
    customers_per_d: int = 8
    items: int = 32
    suppliers: int = 8
    nations: int = 5
    regions: int = 3
    #: NewOrder picks 2..(2+max_lines-1) order lines
    max_lines: int = 4

    def district_count(self) -> int:
        return self.warehouses * self.districts_per_w


#: table -> True when the transaction mix updates rows in place
#: (retraction pairs), False for append-only / static tables
RETRACT = {
    "warehouse": True,      # Payment bumps w_ytd
    "district": True,       # NewOrder bumps d_next_o_id, Payment d_ytd
    "customer": True,       # Payment / Delivery adjust balances
    "stock": True,          # NewOrder draws down s_quantity
    "orders": True,         # Delivery stamps o_carrier_id
    "order_line": True,     # Delivery stamps ol_delivery_d
    "new_order": True,      # Delivery consumes the queue row
    "item": False,
    "supplier": False,
    "nation": False,
    "region": False,
}

_DDL = {
    "item": """CREATE TABLE item (
        i_id BIGINT, i_name VARCHAR(24), i_price BIGINT,
        i_data VARCHAR(32), PRIMARY KEY (i_id))""",
    "warehouse": """CREATE TABLE warehouse (
        w_id BIGINT, w_name VARCHAR(16), w_tax BIGINT, w_ytd BIGINT,
        PRIMARY KEY (w_id))""",
    "district": """CREATE TABLE district (
        d_w_id BIGINT, d_id BIGINT, d_name VARCHAR(16), d_tax BIGINT,
        d_ytd BIGINT, d_next_o_id BIGINT,
        PRIMARY KEY (d_w_id, d_id))""",
    "customer": """CREATE TABLE customer (
        c_w_id BIGINT, c_d_id BIGINT, c_id BIGINT,
        c_name VARCHAR(24), c_state VARCHAR(2), c_balance BIGINT,
        c_ytd_payment BIGINT, c_payment_cnt BIGINT,
        c_delivery_cnt BIGINT, PRIMARY KEY (c_w_id, c_d_id, c_id))""",
    "orders": """CREATE TABLE orders (
        o_w_id BIGINT, o_d_id BIGINT, o_id BIGINT, o_c_id BIGINT,
        o_entry_d BIGINT, o_carrier_id BIGINT, o_ol_cnt BIGINT,
        PRIMARY KEY (o_w_id, o_d_id, o_id))""",
    "new_order": """CREATE TABLE new_order (
        no_w_id BIGINT, no_d_id BIGINT, no_o_id BIGINT,
        PRIMARY KEY (no_w_id, no_d_id, no_o_id))""",
    "order_line": """CREATE TABLE order_line (
        ol_w_id BIGINT, ol_d_id BIGINT, ol_o_id BIGINT,
        ol_number BIGINT, ol_i_id BIGINT, ol_supply_w_id BIGINT,
        ol_delivery_d BIGINT, ol_quantity BIGINT, ol_amount BIGINT,
        PRIMARY KEY (ol_w_id, ol_d_id, ol_o_id, ol_number))""",
    "stock": """CREATE TABLE stock (
        s_w_id BIGINT, s_i_id BIGINT, s_suppkey BIGINT,
        s_quantity BIGINT, s_ytd BIGINT,
        s_order_cnt BIGINT, s_remote_cnt BIGINT,
        PRIMARY KEY (s_w_id, s_i_id))""",
    "supplier": """CREATE TABLE supplier (
        su_suppkey BIGINT, su_name VARCHAR(20), su_nationkey BIGINT,
        PRIMARY KEY (su_suppkey))""",
    "nation": """CREATE TABLE nation (
        n_nationkey BIGINT, n_name VARCHAR(16), n_regionkey BIGINT,
        PRIMARY KEY (n_nationkey))""",
    "region": """CREATE TABLE region (
        r_regionkey BIGINT, r_name VARCHAR(12),
        PRIMARY KEY (r_regionkey))""",
}

#: creation order (referenced-before-referencing, stable)
TABLES = ("item", "warehouse", "district", "customer", "orders",
          "new_order", "order_line", "stock", "supplier", "nation",
          "region")


def table_ddl(name: str) -> str:
    ddl = " ".join(_DDL[name].split())
    if RETRACT[name]:
        ddl += " WITH (retract = 'true')"
    return ddl


def schema_ddl() -> list[str]:
    """All CREATE TABLE statements in creation order."""
    return [table_ddl(t) for t in TABLES]
