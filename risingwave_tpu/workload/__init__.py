"""Workload plane: CH-benCHmark over the streaming engine.

CH-benCHmark (Cole et al., DBTEST'11) unifies TPC-C (OLTP writes) and
TPC-H (analytics) over one schema: transactional NewOrder / Payment /
Delivery mixes mutate the TPC-C tables while TPC-H-shaped analytical
queries — here materialized views maintained incrementally — read the
same data, and serving traffic reads the views.  This package holds

- ``schema``  — the TPC-C-style table DDL (+ CH's supplier/nation/
  region extension), retraction-enabled where transactions update
  rows;
- ``txgen``   — a deterministic, seeded transaction generator (pure
  splitmix64 arithmetic, no RNG): the same seed always yields the
  identical SQL statement sequence, making every run byte-replayable;
- ``queries`` — the first CH analytical group as MV definitions;
- ``driver``  — the closed-loop harness running ingest, MV
  maintenance, and serving reads concurrently against the real
  multi-process cluster under one SLO gate (scripts/ch_bench.py).
"""

from risingwave_tpu.workload.schema import CHScale, schema_ddl  # noqa: F401
from risingwave_tpu.workload.txgen import TxGen  # noqa: F401
