"""CH-benCHmark closed-loop driver: OLTP + MV maintenance + serving.

One process plays the benchmark coordinator against a REAL 4-role
cluster — in-process meta (driver-paced barrier rounds, direct metrics
access), N compute worker subprocesses, one serving-replica
subprocess — and keeps three planes busy SIMULTANEOUSLY:

- **ingest**: a dedicated thread pumps the seeded ``TxGen`` transaction
  mix (NewOrder/Payment/Delivery) as multi-table DML batches with
  exact-full-row retractions, routed through the meta's DML forwarding
  (ingest leaders for partitioned jobs);
- **maintenance**: the main thread drives global barrier rounds; every
  CH view (including the MV-on-MV chain and the secondary index)
  advances through the same commits;
- **serving**: reader threads mix ``serve_batch`` full-view reads,
  ``serve_multi_get`` point lookups, and secondary-index equality
  reads, all pinned at committed epochs.

The run ends with the workload plane's strongest check: every CH view
on the cluster must be BYTE-IDENTICAL to a single-node replay of the
same seeded transaction log (``TxGen`` is the log — same seed, same
bytes).  ``check()`` folds throughput floors, the barrier-commit p99
ceiling, the serving p99.9 ceiling, zero read errors, and the
byte-identity verdict into one assertion; ``write_artifact`` emits
``CH_BENCH.json`` in the bench-artifact shape.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

from risingwave_tpu.common.metrics import (GLOBAL_METRICS,
                                           WIDE_SECONDS_BUCKETS)
from risingwave_tpu.workload.queries import (CH_INDEXES, CH_READS,
                                             query_group)
from risingwave_tpu.workload.schema import CHScale, schema_ddl
from risingwave_tpu.workload.txgen import TxGen

#: shared by the compute workers AND the single-node replay engine —
#: byte identity only means something when both sides run one config
CONFIG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 11, "agg_emit_capacity": 512,
              "mv_table_size": 1 << 11, "mv_ring_size": 1 << 13},
    "storage": {"checkpoint_keep_epochs": 4},
}


def observe_txn(kind: str, seconds: float, rows: int,
                metrics=None) -> None:
    """Record one transaction on the workload metric families:
    ``workload_txn_total{type=...}``, ``workload_txn_rows_total`` and
    ``workload_txn_seconds{type=...}`` (wide grid: a txn stalled
    behind a compile-heavy barrier legitimately takes seconds)."""
    m = metrics if metrics is not None else GLOBAL_METRICS
    m.inc("workload_txn_total", type=kind)
    m.inc("workload_txn_rows_total", rows)
    m.observe("workload_txn_seconds", seconds,
              buckets=WIDE_SECONDS_BUCKETS, type=kind)


def _dml_rows(sql: str) -> int:
    """Row count of one generated DML statement.  TxGen emits only
    integer and paren-free string literals, so every ``(`` opens
    exactly one VALUES tuple."""
    return sql.count("(")


def _percentile(samples: list, q: float) -> float:
    """Weighted percentile over (latency_s, n_reads) batch samples
    (the serve_bench idiom: every read in a batch experiences the
    batch's latency)."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    total = sum(n for _, n in ordered)
    target = q * total
    seen = 0
    for lat, n in ordered:
        seen += n
        if seen >= target:
            return lat
    return ordered[-1][0]


def _spawn(role: str, meta_port: int, data_dir: str, idx: int = 0):
    argv = [sys.executable, "-m", "risingwave_tpu.server",
            "--role", role, "--meta", f"127.0.0.1:{meta_port}",
            "--data-dir", data_dir, "--heartbeat-interval", "0.25"]
    if role == "compute":
        argv += ["--config-json", json.dumps(CONFIG)]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    return subprocess.Popen(
        argv, stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"{role}{idx}.log"), "wb"),
        env=env,
    )


def _norm(rows) -> list:
    return sorted(
        tuple(x if isinstance(x, str) else int(x) for x in r)
        for r in rows
    )


def run(rounds: int = 60, seed: int = 11, workers: int = 2,
        readers: int = 2, small: bool = False,
        chunks_per_barrier: int = 1, txn_pause_s: float = 0.0,
        scale: CHScale | None = None,
        data_dir: str | None = None) -> dict:
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    scale = scale or CHScale()
    group = query_group(small=small)
    group_names = [n for n, _ in group]
    reads = {n: CH_READS[n] for n in group_names}

    data_dir = data_dir or tempfile.mkdtemp(prefix="ch_bench_")
    meta = MetaService(data_dir, heartbeat_timeout_s=4.0)
    meta.start(port=0)
    procs = [_spawn("compute", meta.rpc_port, data_dir, i)
             for i in range(workers)]
    procs.append(_spawn("serving", meta.rpc_port, data_dir))

    state = {
        "reads": 0, "read_errors": [], "ingest_errors": [],
        "rounds_committed": 0, "tick_retries": 0,
        "txns": {"new_order": 0, "payment": 0, "delivery": 0},
        "ingest_rows": 0, "multi_gets": 0, "index_reads": 0,
        "last_cnt": None,
    }
    samples: list[tuple[float, int]] = []
    replay_log: list[str] = []
    stop_ingest = threading.Event()
    stop_read = threading.Event()
    gen = TxGen(seed, scale)

    def ingest_loop():
        while not stop_ingest.is_set():
            kind, stmts = gen.next_transaction()
            if not stmts:  # a delivery with nothing undelivered
                state["txns"][kind] += 1
                continue
            # one multi-statement text per transaction: the meta
            # parses once and forwards statement-by-statement, and
            # the replay engine applies the identical text
            text = ";\n".join(stmts)
            nrows = _dml_rows(text)
            t0 = time.perf_counter()
            try:
                meta.execute_ddl(text)
                replay_log.append(text)
            except Exception as e:  # noqa: BLE001
                state["ingest_errors"].append(repr(e))
                stop_ingest.set()
                return
            observe_txn(kind, time.perf_counter() - t0, nrows)
            state["txns"][kind] += 1
            state["ingest_rows"] += nrows
            if txn_pause_s:
                time.sleep(txn_pause_s)

    def read_loop():
        batch = list(reads.values())
        mg_keys = [[n] for n in range(1, scale.max_lines + 2)]
        while not stop_read.is_set():
            try:
                t0 = time.perf_counter()
                res = meta.serve_batch(batch)
                samples.append((time.perf_counter() - t0, len(batch)))
                state["reads"] += len(batch)
                for (cols, rows), name in zip(res, reads):
                    if name == "ch_q1" and rows:
                        state["last_cnt"] = int(rows[0][-1])
                t0 = time.perf_counter()
                meta.serve_multi_get(
                    "ch_q1", mg_keys,
                    cols=["ol_number", "count_order"])
                samples.append((time.perf_counter() - t0, 1))
                state["reads"] += 1
                state["multi_gets"] += 1
                cnt = state["last_cnt"]
                if cnt is not None:
                    # equality probe on the indexed non-key column:
                    # served through the ch_q1_cnt secondary index
                    t0 = time.perf_counter()
                    meta.serve(
                        "SELECT ol_number, count_order FROM ch_q1 "
                        f"WHERE count_order = {cnt}")
                    samples.append((time.perf_counter() - t0, 1))
                    state["reads"] += 1
                    state["index_reads"] += 1
            except Exception as e:  # noqa: BLE001
                state["read_errors"].append(repr(e))
            time.sleep(0.02)

    def tick_committed(deadline_s: float = 900.0) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            if meta.tick(chunks_per_barrier)["committed"]:
                return
            state["tick_retries"] += 1
            if time.monotonic() > deadline:
                raise TimeoutError("barrier round never committed")
            time.sleep(0.2)

    threads: list[threading.Thread] = []
    try:
        deadline = time.monotonic() + 120
        while len(meta.live_workers()) < workers:
            if time.monotonic() > deadline:
                raise TimeoutError("workers never registered")
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"a role died at startup (logs in {data_dir})")
            time.sleep(0.25)

        # DDL + static load, recorded verbatim for the replay engine
        ddl: list[str] = list(schema_ddl())
        ddl += [d for _, d in group]
        if "ch_q1" in group_names:
            ddl += [d for _, d in CH_INDEXES]
        for sql in ddl:
            meta.execute_ddl(sql)
            replay_log.append(sql)
        for sql in gen.initial_load():
            meta.execute_ddl(sql)
            replay_log.append(sql)

        # warmup: rounds 1-2 pay the jit compiles; the barrier-commit
        # p99 gate starts from this snapshot
        for _ in range(2):
            tick_committed()
        state["rounds_committed"] = 2
        barrier_baseline = meta.metrics.hist_counts(
            "cluster_barrier_commit_seconds")

        ingester = threading.Thread(target=ingest_loop, daemon=True)
        ingester.start()
        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        t_ingest0 = time.monotonic()
        for r in range(3, rounds + 1):
            tick_committed()
            state["rounds_committed"] = r
            if state["ingest_errors"]:
                break

        stop_ingest.set()
        ingester.join(timeout=60)
        ingest_wall = max(time.monotonic() - t_ingest0, 1e-9)
        stop_read.set()
        for t in threads:
            t.join(timeout=10)

        # single-node replay of the SAME seeded log (DDL + load + txn
        # stream in recorded order) — the byte-identity oracle
        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in replay_log:
            eng.execute(sql)
        eng.execute("FLUSH")
        expected = {n: _norm(eng.execute(q))
                    for n, q in reads.items()}

        # convergence fence: keep committing rounds until the cluster
        # has drained every forwarded row and each CH view matches
        mismatched = list(reads)
        fence_ticks = 0
        deadline = time.monotonic() + 600
        while mismatched and time.monotonic() < deadline:
            tick_committed()
            fence_ticks += 1
            mismatched = [
                n for n, q in reads.items()
                if _norm(meta.serve(q)[1]) != expected[n]
            ]
        query_rows = {n: len(expected[n]) for n in reads}

        barrier_commits = sum(meta.metrics.hist_counts(
            "cluster_barrier_commit_seconds"))
        barrier_p99 = meta.metrics.quantile_delta(
            "cluster_barrier_commit_seconds", 0.99, barrier_baseline)

        return {
            "rounds": rounds,
            "rounds_committed": state["rounds_committed"],
            "fence_ticks": fence_ticks,
            "tick_retries": state["tick_retries"],
            "workers": workers,
            "seed": seed,
            "small": small,
            "queries": list(reads),
            "query_rows": query_rows,
            "txns": dict(state["txns"]),
            "txn_total": sum(state["txns"].values()),
            "ingest_rows": state["ingest_rows"],
            "ingest_rows_per_s": round(
                state["ingest_rows"] / ingest_wall, 2),
            "ingest_errors": len(state["ingest_errors"]),
            "ingest_error_samples": state["ingest_errors"][:3],
            "reads": state["reads"],
            "multi_gets": state["multi_gets"],
            "index_reads": state["index_reads"],
            "read_errors": len(state["read_errors"]),
            "read_error_samples": state["read_errors"][:3],
            "latency_ms": {
                "p50": round(_percentile(samples, 0.50) * 1e3, 3),
                "p99": round(_percentile(samples, 0.99) * 1e3, 3),
                "p999": round(_percentile(samples, 0.999) * 1e3, 3),
            },
            "barrier_commits": barrier_commits,
            "barrier_commit_p99_s": barrier_p99,
            "mv_mismatches": len(mismatched),
            "mv_mismatched": mismatched,
            "data_dir": data_dir,
        }
    finally:
        stop_ingest.set()
        stop_read.set()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        meta.stop()


def check(summary: dict, min_ingest_rows_s: float = 5.0,
          max_barrier_p99_s: float = 120.0,
          max_serve_p999_ms: float = 2000.0) -> list[str]:
    """The --assert SLO gate; returns violations (empty = pass)."""
    bad = []
    if summary["rounds_committed"] < summary["rounds"]:
        bad.append(f"rounds_committed={summary['rounds_committed']} "
                   f"< {summary['rounds']}")
    if summary["read_errors"] != 0:
        bad.append(f"read_errors={summary['read_errors']} != 0 "
                   f"({summary['read_error_samples']})")
    if summary["ingest_errors"] != 0:
        bad.append(f"ingest_errors={summary['ingest_errors']} != 0 "
                   f"({summary['ingest_error_samples']})")
    if summary["mv_mismatches"] != 0:
        bad.append("byte-identity FAILED for "
                   f"{summary['mv_mismatched']}")
    if summary["ingest_rows_per_s"] < min_ingest_rows_s:
        bad.append(f"ingest_rows_per_s={summary['ingest_rows_per_s']} "
                   f"< {min_ingest_rows_s}")
    if not (0.0 < summary["barrier_commit_p99_s"]
            <= max_barrier_p99_s):
        bad.append("barrier_commit_p99_s="
                   f"{summary['barrier_commit_p99_s']} not in "
                   f"(0, {max_barrier_p99_s}]")
    if summary["latency_ms"]["p999"] > max_serve_p999_ms:
        bad.append(f"serving p99.9={summary['latency_ms']['p999']}ms "
                   f"> {max_serve_p999_ms}ms")
    for kind, n in summary["txns"].items():
        if n <= 0:
            bad.append(f"txn mix never exercised {kind!r}")
    for name, n in summary["query_rows"].items():
        if n <= 0:
            bad.append(f"CH view {name!r} ended empty")
    if summary["multi_gets"] <= 0 or summary["index_reads"] <= 0:
        bad.append("serving mix missed multi_get or index reads")
    return bad


def write_artifact(summary: dict, path: str | None = None) -> None:
    """``CH_BENCH.json`` in the bench-artifact shape (next to
    SERVE_BENCH.json / MULTICHIP_BENCH.json)."""
    rec = {
        "benchmark": "ch_bench",
        "value": summary["ingest_rows_per_s"],
        "unit": "rows/s",
        "latency_ms": summary["latency_ms"],
        "queries": {
            name: {"rows": summary["query_rows"][name]}
            for name in summary["queries"]
        },
        "invariants": {
            "read_errors": summary["read_errors"],
            "ingest_errors": summary["ingest_errors"],
            "mv_mismatches": summary["mv_mismatches"],
            "rounds_committed": summary["rounds_committed"],
            "barrier_commit_p99_s": summary["barrier_commit_p99_s"],
            "txns": summary["txns"],
        },
        "errors": (summary["read_error_samples"]
                   + summary["ingest_error_samples"]) or None,
        "blocker": None,
    }
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "CH_BENCH.json",
        )
    try:
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except OSError:
        pass
