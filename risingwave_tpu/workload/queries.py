"""CH-benCHmark analytical query group — TPC-H shapes over TPC-C.

The CH queries keep their TPC-H ancestors' plan shapes but read the
live TPC-C tables the transaction mix mutates, so every view is
incrementally maintained THROUGH retractions (the DELETE+INSERT pairs
NewOrder/Payment/Delivery emit).  The group deliberately covers the
engine's plan-shape taxonomy:

- single-table aggregation (``ch_q1``, ``ch_q6`` — TPC-H q1/q6);
- join + aggregation (``ch_q12``, ``ch_q14`` — q12/q14);
- deep multiway join chain (``ch_q5`` — q5's
  region→nation→supplier→stock);
- multi-way join + agg feeding an MV-on-MV second aggregation
  (``ch_q3_flat`` → ``ch_q3`` — q3's unshipped-order revenue);
- correlated EXISTS (``ch_q4`` — q4) and the q21 shape: EXISTS with a
  correlated NON-equality (``ch_q21``, decorrelated through the
  min/max rewrite this round added);
- a secondary-index-served point-read workload (``ch_q1`` +
  ``CREATE INDEX`` on its aggregate column).
"""

from __future__ import annotations

#: (name, DDL) in creation order.  ch_q3 reads ch_q3_flat (MV-on-MV).
CH_QUERIES: list[tuple[str, str]] = [
    # q1: per-line-number order_line rollup (pure agg, retractable)
    ("ch_q1",
     "CREATE MATERIALIZED VIEW ch_q1 AS "
     "SELECT ol_number, sum(ol_quantity) AS sum_qty, "
     "sum(ol_amount) AS sum_amount, count(*) AS count_order "
     "FROM order_line GROUP BY ol_number"),
    # q6: tight-range revenue (global aggregate, no grouping)
    ("ch_q6",
     "CREATE MATERIALIZED VIEW ch_q6 AS "
     "SELECT sum(ol_amount) AS revenue, count(*) AS n "
     "FROM order_line "
     "WHERE ol_quantity >= 1 AND ol_quantity <= 3"),
    # q3 stage 1: unshipped-order revenue — 3-way join + agg
    ("ch_q3_flat",
     "CREATE MATERIALIZED VIEW ch_q3_flat AS "
     "SELECT ol_w_id AS w, ol_d_id AS d, ol_o_id AS o, "
     "o_entry_d AS entry_d, sum(ol_amount) AS revenue "
     "FROM new_order, orders, order_line "
     "WHERE no_w_id = o_w_id AND no_d_id = o_d_id "
     "AND no_o_id = o_id "
     "AND ol_w_id = o_w_id AND ol_d_id = o_d_id AND ol_o_id = o_id "
     "GROUP BY ol_w_id, ol_d_id, ol_o_id, o_entry_d"),
    # q3 stage 2: MV-on-MV — per-district open order book.  Delivery
    # retracts the new_order row, which retracts the flat row, which
    # retracts HERE: the full retraction chain in one query pair.
    ("ch_q3",
     "CREATE MATERIALIZED VIEW ch_q3 AS "
     "SELECT w, d, count(*) AS open_orders, "
     "sum(revenue) AS open_revenue "
     "FROM ch_q3_flat GROUP BY w, d"),
    # q4: orders with at least one substantial line (correlated
    # equality EXISTS -> semi join)
    ("ch_q4",
     "CREATE MATERIALIZED VIEW ch_q4 AS "
     "SELECT o_ol_cnt, count(*) AS order_count FROM orders "
     "WHERE EXISTS (SELECT ol_o_id FROM order_line "
     "WHERE ol_w_id = o_w_id AND ol_d_id = o_d_id "
     "AND ol_o_id = o_id AND ol_quantity >= 3) "
     "GROUP BY o_ol_cnt"),
    # q5: region -> nation -> supplier -> stock chain (stored
    # s_suppkey is CH's mod(s_w_id * s_i_id, #suppliers) mapping)
    ("ch_q5",
     "CREATE MATERIALIZED VIEW ch_q5 AS "
     "SELECT n_name, sum(s_ytd) AS moved_qty, "
     "count(*) AS stock_lines "
     "FROM region, nation, supplier, stock "
     "WHERE r_regionkey = n_regionkey "
     "AND n_nationkey = su_nationkey "
     "AND su_suppkey = s_suppkey AND r_name <> 'region-00' "
     "GROUP BY n_name"),
    # q12: delivered vs total lines by declared order size
    ("ch_q12",
     "CREATE MATERIALIZED VIEW ch_q12 AS "
     "SELECT o_ol_cnt, "
     "sum(CASE WHEN ol_delivery_d > 0 THEN 1 ELSE 0 END) "
     "AS delivered_lines, count(*) AS total_lines "
     "FROM orders, order_line "
     "WHERE ol_w_id = o_w_id AND ol_d_id = o_d_id "
     "AND ol_o_id = o_id GROUP BY o_ol_cnt"),
    # q14: promo revenue share inputs
    ("ch_q14",
     "CREATE MATERIALIZED VIEW ch_q14 AS "
     "SELECT sum(CASE WHEN i_data = 'PROMO' THEN ol_amount "
     "ELSE 0 END) AS promo_revenue, "
     "sum(ol_amount) AS total_revenue "
     "FROM order_line, item WHERE ol_i_id = i_id"),
    # q21 shape: order lines sharing an order with a DIFFERENT supply
    # warehouse — correlated non-equality EXISTS (min/max
    # decorrelation), self-join on a retractable table
    ("ch_q21",
     "CREATE MATERIALIZED VIEW ch_q21 AS "
     "SELECT l1.ol_supply_w_id AS supply_w, "
     "count(*) AS multi_supply_lines "
     "FROM order_line l1 "
     "WHERE EXISTS (SELECT l2.ol_o_id FROM order_line l2 "
     "WHERE l2.ol_w_id = l1.ol_w_id AND l2.ol_d_id = l1.ol_d_id "
     "AND l2.ol_o_id = l1.ol_o_id "
     "AND l2.ol_supply_w_id <> l1.ol_supply_w_id) "
     "GROUP BY l1.ol_supply_w_id"),
]

#: secondary index for the point-read serving mix: equality reads on
#: ch_q1's non-pk aggregate column route through this index MV
CH_INDEXES: list[tuple[str, str]] = [
    ("ch_q1_cnt", "CREATE INDEX ch_q1_cnt ON ch_q1(count_order)"),
]

#: serving reads per view (plain projections every placement serves)
CH_READS: dict[str, str] = {
    "ch_q1": "SELECT ol_number, sum_qty, sum_amount, count_order "
             "FROM ch_q1",
    "ch_q6": "SELECT revenue, n FROM ch_q6",
    "ch_q3_flat": "SELECT w, d, o, entry_d, revenue FROM ch_q3_flat",
    "ch_q3": "SELECT w, d, open_orders, open_revenue FROM ch_q3",
    "ch_q4": "SELECT o_ol_cnt, order_count FROM ch_q4",
    "ch_q5": "SELECT n_name, moved_qty, stock_lines FROM ch_q5",
    "ch_q12": "SELECT o_ol_cnt, delivered_lines, total_lines "
              "FROM ch_q12",
    "ch_q14": "SELECT promo_revenue, total_revenue FROM ch_q14",
    "ch_q21": "SELECT supply_w, multi_supply_lines FROM ch_q21",
}

#: the --small subset: the cheap-to-compile views (CI wrapper); the
#: full set adds the EXISTS pair and the deep chains
SMALL_SET = ("ch_q1", "ch_q6", "ch_q3_flat", "ch_q3", "ch_q12")


def query_group(small: bool = False) -> list[tuple[str, str]]:
    if not small:
        return list(CH_QUERIES)
    return [(n, d) for (n, d) in CH_QUERIES if n in SMALL_SET]
