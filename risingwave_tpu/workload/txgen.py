"""Deterministic CH-benCHmark transaction generator.

Pure splitmix64 arithmetic (common/faults.py) — NO ``random`` module,
no wall clock: the generator's entire behaviour is a function of
``(seed, scale)``, so two generators with the same inputs emit the
byte-identical SQL statement sequence, in-process or across processes.
That is the replay contract the workload driver's byte-identity gate
is built on: re-running the generator IS the transaction log.

The generator keeps full deterministic shadow state (district
counters, customer balances, stock levels, undelivered-order queues),
which lets every UPDATE travel as an exact-full-row retraction pair —
``DELETE FROM t VALUES (<old full row>)`` + ``INSERT INTO t VALUES
(<new full row>)`` — the changelog shape the engine's marker-tail DML
plane executes without any lookup path.

Transaction mix (TPC-C's big three, CH-benCHmark style):

- ``new_order`` (45%): allocate ``d_next_o_id``, insert the order, its
  queue row, and 2..N order lines; draw down stock per line.
- ``payment``   (45%): bump warehouse/district YTD, adjust the
  customer's balance and payment counters.
- ``delivery``  (10%): pop the oldest undelivered order of each
  district of one warehouse, stamp carrier + delivery time on the
  order and its lines, credit the customer.

All monetary amounts are integer cents: sums stay byte-exact under
any chunking or partitioning.
"""

from __future__ import annotations

from risingwave_tpu.common.faults import splitmix64
from risingwave_tpu.workload.schema import CHScale

_GOLDEN = 0x9E3779B97F4A7C15
_MASK = (1 << 64) - 1


def _fmt(v) -> str:
    return f"'{v}'" if isinstance(v, str) else str(int(v))


def _values(rows) -> str:
    return ", ".join(
        "(" + ", ".join(_fmt(v) for v in r) + ")" for r in rows)


def _ins(table: str, rows) -> str:
    return f"INSERT INTO {table} VALUES {_values(rows)}"


def _del(table: str, rows) -> str:
    return f"DELETE FROM {table} VALUES {_values(rows)}"


class TxGen:
    """Seeded CH transaction stream with deterministic shadow state."""

    def __init__(self, seed: int, scale: CHScale | None = None):
        self.scale = scale or CHScale()
        self._state = (int(seed) * 0x9E3779B97F4A7C15 + 1) & _MASK
        #: logical clock: one tick per transaction (o_entry_d,
        #: ol_delivery_d) — deterministic, never wall time
        self.clock = 0
        self.txn_count = 0
        s = self.scale
        # -- shadow state -------------------------------------------------
        self.item_price = {
            i: 100 + self._pure(7, i) % 9900
            for i in range(1, s.items + 1)
        }
        self.warehouse = {w: 0 for w in range(1, s.warehouses + 1)}
        self.district = {
            (w, d): [0, 1]  # [d_ytd, d_next_o_id]
            for w in range(1, s.warehouses + 1)
            for d in range(1, s.districts_per_w + 1)
        }
        # [c_balance, c_ytd_payment, c_payment_cnt, c_delivery_cnt]
        self.customer = {
            (w, d, c): [0, 0, 0, 0]
            for w in range(1, s.warehouses + 1)
            for d in range(1, s.districts_per_w + 1)
            for c in range(1, s.customers_per_d + 1)
        }
        # [s_quantity, s_ytd, s_order_cnt, s_remote_cnt]
        self.stock = {
            (w, i): [50 + self._pure(11, w * 1000 + i) % 50, 0, 0, 0]
            for w in range(1, s.warehouses + 1)
            for i in range(1, s.items + 1)
        }
        #: FIFO of undelivered o_id per district
        self.undelivered: dict[tuple, list[int]] = {
            k: [] for k in self.district
        }
        #: (w, d, o_id) -> [o_c_id, o_entry_d, o_carrier_id, o_ol_cnt]
        self.orders: dict[tuple, list[int]] = {}
        #: (w, d, o_id) -> list of full order_line rows
        self.order_lines: dict[tuple, list[tuple]] = {}

    # -- deterministic draws ---------------------------------------------
    def _pure(self, stream: int, x: int) -> int:
        """Stateless draw (load-time attributes): f(seed, stream, x)."""
        return splitmix64(
            (self._seed0() + stream * _GOLDEN + x * 0x94D049BB133111EB)
            & _MASK)

    def _seed0(self) -> int:
        # the constructor-time state doubles as the stateless base so
        # _pure draws do not disturb the sequential stream
        return getattr(self, "_base", None) or self.__dict__.setdefault(
            "_base", self._state)

    def _u64(self) -> int:
        self._state = (self._state + _GOLDEN) & _MASK
        return splitmix64(self._state)

    def _rand(self, n: int) -> int:
        """Uniform-ish in [0, n)."""
        return self._u64() % n

    # -- initial load -----------------------------------------------------
    def initial_load(self) -> list[str]:
        """INSERT statements for the static load (consumes no draws
        from the sequential stream — call order vs transactions does
        not matter for determinism)."""
        s = self.scale
        out = [
            _ins("item", [
                (i, f"item-{i:05d}",
                 self.item_price[i],
                 "PROMO" if self._pure(13, i) % 5 == 0 else "plain")
                for i in range(1, s.items + 1)
            ]),
            _ins("warehouse", [
                self._warehouse_row(w) for w in sorted(self.warehouse)
            ]),
            _ins("district", [
                self._district_row(w, d)
                for (w, d) in sorted(self.district)
            ]),
            _ins("customer", [
                self._customer_row(w, d, c)
                for (w, d, c) in sorted(self.customer)
            ]),
            _ins("stock", [
                self._stock_row(w, i) for (w, i) in sorted(self.stock)
            ]),
            _ins("supplier", [
                (k, f"Supplier#{k:05d}", k % s.nations)
                for k in range(s.suppliers)
            ]),
            _ins("nation", [
                (n, f"nation-{n:02d}", n % s.regions)
                for n in range(s.nations)
            ]),
            _ins("region", [
                (r, f"region-{r:02d}") for r in range(s.regions)
            ]),
        ]
        return out

    # -- full-row builders (the retraction pairs need exact rows) ---------
    def _warehouse_row(self, w) -> tuple:
        return (w, f"wh-{w:03d}", 5 + self._pure(17, w) % 15,
                self.warehouse[w])

    def _district_row(self, w, d) -> tuple:
        ytd, next_o = self.district[(w, d)]
        return (w, d, f"dist-{w:02d}-{d:02d}",
                5 + self._pure(19, w * 100 + d) % 15, ytd, next_o)

    def _customer_row(self, w, d, c) -> tuple:
        bal, ytd, pcnt, dcnt = self.customer[(w, d, c)]
        st = "AZ" if self._pure(23, (w * 100 + d) * 1000 + c) % 4 == 0 \
            else "CA"
        return (w, d, c, f"cust-{w:02d}-{d:02d}-{c:04d}", st,
                bal, ytd, pcnt, dcnt)

    def _stock_row(self, w, i) -> tuple:
        # s_suppkey is CH-benCHmark's stored supplier mapping
        # (mod(s_w_id * s_i_id, #suppliers) in the original spec)
        q, ytd, ocnt, rcnt = self.stock[(w, i)]
        return (w, i, (w * i) % self.scale.suppliers, q, ytd, ocnt,
                rcnt)

    def _order_row(self, w, d, o) -> tuple:
        c, entry, carrier, ol_cnt = self.orders[(w, d, o)]
        return (w, d, o, c, entry, carrier, ol_cnt)

    # -- transactions ------------------------------------------------------
    def next_transaction(self) -> tuple[str, list[str]]:
        """One transaction: ``(type, [sql, ...])``."""
        self.txn_count += 1
        self.clock += 1
        r = self._rand(100)
        if r < 45:
            return "new_order", self._new_order()
        if r < 90:
            return "payment", self._payment()
        return "delivery", self._delivery()

    def _pick_wd(self) -> tuple[int, int]:
        s = self.scale
        return (1 + self._rand(s.warehouses),
                1 + self._rand(s.districts_per_w))

    def _new_order(self) -> list[str]:
        s = self.scale
        w, d = self._pick_wd()
        c = 1 + self._rand(s.customers_per_d)
        old_district = self._district_row(w, d)
        o_id = self.district[(w, d)][1]
        self.district[(w, d)][1] += 1
        ol_cnt = 2 + self._rand(s.max_lines)
        sql = [
            _del("district", [old_district]),
            _ins("district", [self._district_row(w, d)]),
        ]
        lines = []
        stock_pairs = []
        for n in range(1, ol_cnt + 1):
            i_id = 1 + self._rand(s.items)
            remote = s.warehouses > 1 and self._rand(10) == 0
            supply_w = (1 + self._rand(s.warehouses)) if remote else w
            qty = 1 + self._rand(5)
            amount = qty * self.item_price[i_id]
            lines.append((w, d, o_id, n, i_id, supply_w, 0, qty,
                          amount))
            old_stock = self._stock_row(supply_w, i_id)
            st = self.stock[(supply_w, i_id)]
            st[0] = st[0] - qty if st[0] - qty >= 10 else st[0] - qty + 91
            st[1] += qty
            st[2] += 1
            if supply_w != w:
                st[3] += 1
            stock_pairs.append(
                (old_stock, self._stock_row(supply_w, i_id)))
        self.orders[(w, d, o_id)] = [c, self.clock, 0, ol_cnt]
        self.order_lines[(w, d, o_id)] = list(lines)
        self.undelivered[(w, d)].append(o_id)
        sql.append(_ins("orders", [self._order_row(w, d, o_id)]))
        sql.append(_ins("new_order", [(w, d, o_id)]))
        sql.append(_ins("order_line", lines))
        for old, new in stock_pairs:
            sql.append(_del("stock", [old]))
            sql.append(_ins("stock", [new]))
        return sql

    def _payment(self) -> list[str]:
        s = self.scale
        w, d = self._pick_wd()
        c = 1 + self._rand(s.customers_per_d)
        amount = 100 + self._rand(50000)
        self.warehouse[w] += amount
        old_d = self._district_row(w, d)
        self.district[(w, d)][0] += amount
        old_c = self._customer_row(w, d, c)
        cust = self.customer[(w, d, c)]
        cust[0] -= amount
        cust[1] += amount
        cust[2] += 1
        return [
            # single-column bump on a full-pk match: the UPDATE sugar
            # (the engine resolves the live row and desugars to the
            # same retraction pair the explicit form ships)
            f"UPDATE warehouse SET w_ytd = {self.warehouse[w]} "
            f"WHERE w_id = {w}",
            _del("district", [old_d]),
            _ins("district", [self._district_row(w, d)]),
            _del("customer", [old_c]),
            _ins("customer", [self._customer_row(w, d, c)]),
        ]

    def _delivery(self) -> list[str]:
        s = self.scale
        w = 1 + self._rand(s.warehouses)
        carrier = 1 + self._rand(10)
        sql: list[str] = []
        for d in range(1, s.districts_per_w + 1):
            queue = self.undelivered[(w, d)]
            if not queue:
                continue
            o_id = queue.pop(0)
            sql.append(_del("new_order", [(w, d, o_id)]))
            old_order = self._order_row(w, d, o_id)
            self.orders[(w, d, o_id)][2] = carrier
            sql.append(_del("orders", [old_order]))
            sql.append(_ins("orders", [self._order_row(w, d, o_id)]))
            old_lines = self.order_lines[(w, d, o_id)]
            new_lines = [
                ln[:6] + (self.clock,) + ln[7:] for ln in old_lines
            ]
            self.order_lines[(w, d, o_id)] = new_lines
            sql.append(_del("order_line", old_lines))
            sql.append(_ins("order_line", new_lines))
            c = self.orders[(w, d, o_id)][0]
            old_c = self._customer_row(w, d, c)
            cust = self.customer[(w, d, c)]
            cust[0] += sum(ln[8] for ln in new_lines)
            cust[3] += 1
            sql.append(_del("customer", [old_c]))
            sql.append(_ins("customer", [self._customer_row(w, d, c)]))
        return sql

    def sql_stream(self, n_txns: int) -> list[str]:
        """Flat SQL list for n transactions (determinism probes)."""
        out: list[str] = []
        for _ in range(n_txns):
            out.extend(self.next_transaction()[1])
        return out
