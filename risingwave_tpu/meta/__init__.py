"""Control plane: catalog, job management.

Reference counterpart: ``src/meta`` (SURVEY.md §2.4) — collapsed to a
single-process control plane in round 1: the catalog is in-memory, the
barrier scheduler is the engine's run loop, and recovery restores jobs
from their checkpoint snapshots.
"""

from risingwave_tpu.meta.catalog import Catalog, CatalogEntry

__all__ = ["Catalog", "CatalogEntry"]
