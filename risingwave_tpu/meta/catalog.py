"""In-memory catalog of sources and materialized views.

Reference counterpart: ``src/meta/src/controller/catalog/`` (sea-orm
backed) + the frontend's catalog cache — collapsed into one in-process
registry for the single-node round.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from risingwave_tpu.common.types import Schema


@dataclass
class CatalogEntry:
    name: str
    kind: str                  # "source" | "mview"
    schema: Schema
    #: source: factory (split_id, num_splits) -> reader
    reader_factory: Callable | None = None
    #: source: watermark (col_idx, delay_us)
    watermark: tuple[int, int] | None = None
    #: source: True when the stream never retracts
    append_only: bool = True
    #: mview: the running job + its materialize executor handle
    job: Any = None
    mv_executor: Any = None
    mv_state_index: Any = None  # index path to the MV state in job.states
    #: DML-fed tables: the TableDmlManager feeding all readers
    dml: Any = None
    #: mview/sink on a DagJob: the node ids this entry contributed
    #: (removed together on DROP)
    dag_nodes: Any = None
    #: source names this entry attached to a shared DagJob (detached on
    #: DROP so dropped MVs' private readers stop being pulled)
    dag_sources: Any = None
    #: mview: pk column positions in ``schema`` (the stream key exposed
    #: to downstream cascaded plans); None for append-only ring MVs
    stream_key: Any = None
    #: secondary-index MV: (upstream mv name, indexed column names) —
    #: the entry itself is a plain "mview" maintained through the
    #: MV-on-MV path; only its EXPORT key order differs (see export_pk)
    index_on: Any = None
    #: storage-export pk override: column positions whose memcomparable
    #: encoding forms the ``m:<name>\0<pk>`` key (defaults to the
    #: materialize executor's pk_indices) — index MVs sort by
    #: (indexed cols..., upstream pk) so equality probes are one
    #: contiguous byte range
    export_pk: Any = None
    #: mview: (leading export-pk column name, retention in that
    #: column's units) from WITH (ttl = '<n>') — the pushdown plane
    #: derives the expiry horizon from it at export time
    ttl: Any = None
    definition: str = ""


class Catalog:
    def __init__(self):
        self._entries: dict[str, CatalogEntry] = {}

    def create(self, entry: CatalogEntry, if_not_exists: bool = False) -> bool:
        if entry.name in self._entries:
            if if_not_exists:
                return False
            raise ValueError(f"{entry.name!r} already exists")
        self._entries[entry.name] = entry
        return True

    def drop(self, name: str, if_exists: bool = False) -> None:
        if name not in self._entries:
            if if_exists:
                return
            raise KeyError(name)
        del self._entries[name]

    def get(self, name: str) -> CatalogEntry:
        if name not in self._entries:
            raise KeyError(f"relation {name!r} does not exist")
        return self._entries[name]

    def list(self, kind: str | None = None) -> list[CatalogEntry]:
        return [e for e in self._entries.values()
                if kind is None or e.kind == kind]

    def __contains__(self, name: str) -> bool:
        return name in self._entries
