"""Durable metadata store: DDL log + DML write-ahead log.

Reference counterpart: the meta node's SQL metastore (sea-orm entities
over SQLite/PG, src/meta/model/) + ``DdlController`` recovery
(src/meta/src/rpc/ddl_controller.rs:1096): a fresh process reloads the
catalog and rebuilds every streaming job from persisted metadata, then
resumes from the last committed epoch.

TPU-first simplification: metadata volume is tiny and totally ordered
by the single control loop, so the store is two append-only JSONL logs
under ``data_dir``:

- ``catalog.jsonl`` — every applied DDL statement's raw SQL, in
  order (CREATE/DROP/ALTER/SET).  Replaying the log against a fresh
  Engine reconstructs the catalog AND the streaming jobs, because DDL
  is the single source of plan shape.
- ``dml/<table>.jsonl`` — committed INSERT batches per DML table (the
  reference's DML goes through the upstream table's durable state;
  here the table history IS that state, so it must survive restarts
  for source cursors to replay against).

Atomicity: lines are appended with a trailing newline and fsync'd;
a torn final line (crash mid-append) is detected and dropped at read
time.
"""

from __future__ import annotations

import json
import os


class MetaStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._ddl_path = os.path.join(root, "catalog.jsonl")
        self._dml_dir = os.path.join(root, "dml")
        os.makedirs(self._dml_dir, exist_ok=True)

    # -- append ---------------------------------------------------------
    def _append(self, path: str, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":")) + "\n"
        with open(path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())

    def append_ddl(self, sql: str) -> None:
        self._append(self._ddl_path, {"sql": sql})

    def append_dml(self, table: str, rows: list) -> None:
        self._append(
            os.path.join(self._dml_dir, f"{table}.jsonl"),
            {"rows": [list(r) for r in rows]},
        )

    # -- read -----------------------------------------------------------
    @staticmethod
    def _lines(path: str) -> list[dict]:
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                if not line.endswith("\n"):
                    break  # torn tail from a crash mid-append
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    break
        return out

    def ddl_log(self) -> list[str]:
        return [e["sql"] for e in self._lines(self._ddl_path)]

    def dml_rows(self, table: str) -> list[tuple]:
        rows: list[tuple] = []
        for e in self._lines(os.path.join(self._dml_dir,
                                          f"{table}.jsonl")):
            rows.extend(tuple(r) for r in e["rows"])
        return rows

    def truncate_dml(self, table: str) -> None:
        """DROP TABLE discards the table's history; a later same-named
        CREATE TABLE must not resurrect pre-drop rows at replay."""
        p = os.path.join(self._dml_dir, f"{table}.jsonl")
        if os.path.exists(p):
            os.remove(p)

    def has_catalog(self) -> bool:
        return os.path.exists(self._ddl_path)
