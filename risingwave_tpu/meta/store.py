"""Durable metadata store: DDL log + DML write-ahead log.

Reference counterpart: the meta node's SQL metastore (sea-orm entities
over SQLite/PG, src/meta/model/) + ``DdlController`` recovery
(src/meta/src/rpc/ddl_controller.rs:1096): a fresh process reloads the
catalog and rebuilds every streaming job from persisted metadata, then
resumes from the last committed epoch.

TPU-first simplification: metadata volume is tiny and totally ordered
by the single control loop, so the store is two append-only JSONL logs
under ``data_dir``:

- ``catalog.jsonl`` — every applied DDL statement's raw SQL, in
  order (CREATE/DROP/ALTER/SET).  Replaying the log against a fresh
  Engine reconstructs the catalog AND the streaming jobs, because DDL
  is the single source of plan shape.
- ``dml/<table>.jsonl`` — committed INSERT batches per DML table (the
  reference's DML goes through the upstream table's durable state;
  here the table history IS that state, so it must survive restarts
  for source cursors to replay against).

Atomicity: lines are appended with a trailing newline and fsync'd;
a torn final line (crash mid-append) is detected and dropped at read
time.
"""

from __future__ import annotations

import json
import logging
import os

log = logging.getLogger(__name__)


class MetaStoreCorruption(RuntimeError):
    """A NON-tail log line failed to decode: the log is damaged beyond
    the crash-mid-append case and silently truncating it would drop
    acknowledged DDL/DML — recovery must stop loudly instead."""


class MetaStore:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._ddl_path = os.path.join(root, "catalog.jsonl")
        self._dml_dir = os.path.join(root, "dml")
        os.makedirs(self._dml_dir, exist_ok=True)

    # -- append ---------------------------------------------------------
    def _append(self, path: str, obj: dict) -> None:
        # flush + fsync BEFORE returning: an append is acknowledged
        # (DDL applied, INSERT accepted) only once it is durable — a
        # worker SIGKILLed right after this call replays the line; one
        # killed mid-write leaves a torn tail ``_lines`` drops
        line = json.dumps(obj, separators=(",", ":")) + "\n"
        with open(path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())

    def append_ddl(self, sql: str) -> None:
        self._append(self._ddl_path, {"sql": sql})

    def append_dml(self, table: str, rows: list) -> None:
        self._append(
            os.path.join(self._dml_dir, f"{table}.jsonl"),
            {"rows": [list(r) for r in rows]},
        )

    def append_dml_sql(self, sql: str) -> None:
        """Cluster mode: the meta durably logs forwarded DML statements
        (the per-table row logs stay the single-node representation)."""
        self._append(os.path.join(self.root, "dml_sql.jsonl"),
                     {"sql": sql})

    def dml_sql_log(self) -> list[str]:
        return [e["sql"] for e in self._lines(
            os.path.join(self.root, "dml_sql.jsonl")
        )]

    def append_cluster_commit(self, round_: int, epoch: int,
                              seals: dict) -> None:
        """Cluster mode: one line per COMMITTED global round — the
        round number, the manifest epoch stamp, and every job's sealed
        epoch value.  A restarted meta replays the tail entry to
        recover its round position and per-job seal log (the manifest
        alone records epoch VALUES, not round indices).  Appended
        AFTER the manifest delta commits: a crash in between leaves
        the manifest one round ahead, which recovery re-commits
        idempotently (empty delta, same epoch stamp)."""
        self._append(os.path.join(self.root, "cluster_log.jsonl"),
                     {"round": int(round_), "epoch": int(epoch),
                      "seals": {k: int(v) for k, v in seals.items()}})

    def append_scale_event(self, event: dict) -> None:
        """Scale plane: one line per layout change — the vnode map,
        the active worker set, and every partitioned job's checkpoint
        lineages.  A restarted meta replays the TAIL event and
        re-adopts each lineage from the shared store."""
        self._append(os.path.join(self.root, "scale_log.jsonl"), event)

    def last_scale_event(self) -> dict | None:
        entries = self._lines(os.path.join(self.root,
                                           "scale_log.jsonl"))
        return entries[-1] if entries else None

    def last_cluster_commit(self) -> dict | None:
        """The newest committed-round record (None = nothing durable).
        Only the tail matters for recovery; earlier lines are history
        the log keeps for operators (lines are tiny)."""
        entries = self._lines(os.path.join(self.root,
                                           "cluster_log.jsonl"))
        return entries[-1] if entries else None

    # -- read -----------------------------------------------------------
    @staticmethod
    def _lines(path: str) -> list[dict]:
        """Replay one JSONL log.  A torn TAIL line (crash mid-append:
        missing newline and/or truncated JSON) is dropped with a
        warning — it was never acknowledged.  A damaged line anywhere
        ELSE raises ``MetaStoreCorruption``: silently truncating there
        would drop acknowledged history after it."""
        if not os.path.exists(path):
            return []
        with open(path) as f:
            lines = f.readlines()
        out = []
        for i, line in enumerate(lines):
            last = i == len(lines) - 1
            torn = not line.endswith("\n")
            if torn and not last:
                raise MetaStoreCorruption(
                    f"{path}:{i + 1}: embedded unterminated line"
                )
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                if last:
                    log.warning(
                        "%s: dropping torn trailing line %d "
                        "(crash mid-append): %s", path, i + 1, e,
                    )
                    break
                raise MetaStoreCorruption(
                    f"{path}:{i + 1}: undecodable line mid-log"
                ) from e
            if torn:
                # parses but the newline never landed: the fsync that
                # acknowledges the append covers the newline, so this
                # write was still in flight — not acknowledged, drop it
                log.warning(
                    "%s: dropping unterminated trailing line %d "
                    "(crash mid-append)", path, i + 1,
                )
                break
            out.append(obj)
        return out

    def ddl_log(self) -> list[str]:
        return [e["sql"] for e in self._lines(self._ddl_path)]

    def dml_rows(self, table: str) -> list[tuple]:
        rows: list[tuple] = []
        for e in self._lines(os.path.join(self._dml_dir,
                                          f"{table}.jsonl")):
            rows.extend(tuple(r) for r in e["rows"])
        return rows

    def truncate_dml(self, table: str) -> None:
        """DROP TABLE discards the table's history; a later same-named
        CREATE TABLE must not resurrect pre-drop rows at replay."""
        p = os.path.join(self._dml_dir, f"{table}.jsonl")
        if os.path.exists(p):
            os.remove(p)

    def has_catalog(self) -> bool:
        return os.path.exists(self._ddl_path)
