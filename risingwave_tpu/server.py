"""Node entrypoint: single-binary, meta, compute, or serving process.

Reference counterparts: the single-binary mode (``src/cmd_all/src/
single_node.rs``) bundling frontend + meta + compute into one process,
and the per-role binaries (``src/cmd/src/bin/{meta,compute}_node.rs``;
the frontend/serving node) the multi-process deployment launches.

    # everything in one process (the default)
    python -m risingwave_tpu.server --port 4566 --data-dir ./data

    # a 1-meta + 2-compute cluster over one shared data_dir
    python -m risingwave_tpu.server --role meta --port 4566 \
        --rpc-port 4600 --data-dir ./data
    python -m risingwave_tpu.server --role compute \
        --meta 127.0.0.1:4600 --data-dir ./data   # run twice

    # N stateless serving replicas (ENGINE-FREE: the process never
    # imports jax — it reads MV rows straight from shared SSTs at the
    # meta's pinned epoch)
    python -m risingwave_tpu.server --role serving \
        --meta 127.0.0.1:4600 --data-dir ./data   # run N times

The meta process hosts the pgwire front door: DDL places streaming
jobs on workers, SELECTs route round-robin across live serving
replicas pinned at the last cluster-committed epoch (falling back to
the owning worker — cluster/meta_service.py).

Engine imports stay INSIDE the non-serving paths: ``--role serving``
must boot without jax (the package __init__ skips the jax import for
that role; see risingwave_tpu/__init__.py).
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def _start_metrics_http(render, host: str, port: int):
    """Per-role stdlib ``/metrics`` endpoint (the unified metrics
    plane's per-process scrape surface — the meta's ``ctl cluster
    metrics`` aggregates the same text over RPC, so a Prometheus
    deployment can scrape either each process or just the meta)."""
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.split("?")[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # per-scrape stderr spam
            pass

    httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(target=httpd.serve_forever,
                     name="metrics-http", daemon=True).start()
    return httpd


class SingleNode:
    def __init__(self, config=None, data_dir: str | None = None):
        from risingwave_tpu.sql.engine import Engine

        self.engine = Engine(config, data_dir=data_dir)
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- barrier loop ---------------------------------------------------
    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            interval = int(
                self.engine.system_params.get("barrier_interval_ms")
            ) / 1000.0
            t0 = time.monotonic()
            with self._lock:
                if self.engine.jobs:
                    self.engine.tick(barriers=1)
            elapsed = time.monotonic() - t0
            self._stop.wait(max(interval - elapsed, 0.0))

    def start(self, host: str = "127.0.0.1", port: int = 4566,
              ticker: bool = True):
        from risingwave_tpu.pgwire import pg_serve

        if ticker:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True
            )
            self._ticker.start()
        # durable nodes run the storage service's background compactor
        # (the fourth node role, embedded single-binary style)
        self.engine.start_storage_service()
        # pgwire statements and the ticker share the engine lock
        server = pg_serve(self.engine, host, port, engine_lock=self._lock)
        return server

    def tick(self, barriers: int = 1,
             chunks_per_barrier: int | None = None) -> None:
        """Deterministic manual ticks (tests/FLUSH); lock-coordinated
        with the background ticker."""
        with self._lock:
            self.engine.tick(barriers, chunks_per_barrier)

    def stop(self) -> None:
        """Orderly shutdown: stop the ticker, then seal + commit ONE
        final barrier before the compactor/pgwire go away — every
        acked write (chunks processed since the last barrier) lands in
        a committed checkpoint instead of dying with the process."""
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
            self._ticker = None
        try:
            with self._lock:
                if self.engine.jobs:
                    # chunks_per_barrier=0: flush/commit what already
                    # flowed, pull nothing new on the way out (tick's
                    # batch boundary also drains the upload queue)
                    self.engine.tick(barriers=1, chunks_per_barrier=0)
        finally:
            try:
                self.engine.drain_uploads()
            finally:
                self.engine.stop_storage_service()


def _run_meta(args) -> None:
    from risingwave_tpu.cluster import MetaFrontend, MetaService
    from risingwave_tpu.pgwire import pg_serve

    meta = MetaService(
        args.data_dir or "./data",
        heartbeat_timeout_s=args.heartbeat_timeout,
        n_vnodes=args.n_vnodes,
        scale_partitioning=args.scale_partitioning,
        shuffle_ingest=not args.no_shuffle_ingest,
        scrub_interval_s=args.scrub_interval,
        serve_retry_timeout_s=args.serve_retry_timeout,
    ).start(args.host, args.rpc_port,
            scrubber=args.scrub_interval > 0)
    front = MetaFrontend(meta)
    server = pg_serve(front, args.host, args.port)
    if args.metrics_port:
        _start_metrics_http(meta.metrics.render_prometheus,
                            args.host, args.metrics_port)
    print(json.dumps({
        "role": "meta", "pgwire_port": args.port,
        "rpc_port": meta.rpc_port,
        "metrics_port": args.metrics_port or None,
    }), flush=True)

    stop = threading.Event()

    def tick_loop():
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                meta.tick()
            except Exception:
                pass  # incomplete rounds retry next interval
            elapsed = time.monotonic() - t0
            stop.wait(max(args.barrier_interval_ms / 1000.0 - elapsed,
                          0.0))

    # --barrier-interval-ms 0: NO self-ticker — an external driver
    # owns the round cadence through ``rpc_tick`` (the deterministic
    # mode the chaos campaign uses to count committed rounds exactly)
    if args.barrier_interval_ms > 0:
        threading.Thread(target=tick_loop, daemon=True).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stop.set()
        meta.stop()
        server.shutdown()


def _run_compute(args) -> None:
    from risingwave_tpu.cluster import ComputeWorker
    from risingwave_tpu.common.config import RwConfig

    config = RwConfig.from_dict(json.loads(args.config_json)) \
        if args.config_json else None
    worker = ComputeWorker(
        args.meta, args.data_dir or "./data", config=config,
        host=args.host, port=args.rpc_port,
        heartbeat_interval_s=args.heartbeat_interval,
    ).start()
    if args.metrics_port:
        _start_metrics_http(worker.engine.metrics.render_prometheus,
                            args.host, args.metrics_port)
    print(json.dumps({
        "role": "compute", "worker_id": worker.worker_id,
        "port": worker.port,
        "metrics_port": args.metrics_port or None,
    }), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        worker.stop()


def _run_serving(args) -> None:
    import sys

    from risingwave_tpu.serve import ServingWorker

    replica = ServingWorker(
        args.meta, args.data_dir or "./data",
        host=args.host, port=args.rpc_port,
        heartbeat_interval_s=args.heartbeat_interval,
        cache_blocks=args.serving_cache_blocks,
        result_cache_bytes=args.serving_result_cache_bytes,
        negative_cache_keys=args.serving_negative_cache_keys,
        warmup_keys=args.serving_warmup_keys,
    ).start()
    if args.metrics_port:
        _start_metrics_http(replica.metrics.render_prometheus,
                            args.host, args.metrics_port)
    print(json.dumps({
        "role": "serving", "replica_id": replica.replica_id,
        "port": replica.port,
        "metrics_port": args.metrics_port or None,
        # the engine-free contract, surfaced at the handshake: tests
        # parse this line and assert jax never loaded
        "jax_loaded": "jax" in sys.modules,
    }), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        replica.stop()


def main() -> None:
    p = argparse.ArgumentParser(description="risingwave_tpu node")
    p.add_argument("--role",
                   choices=["single", "meta", "compute", "serving"],
                   default="single")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=4566,
                   help="pgwire port (single/meta roles)")
    p.add_argument("--rpc-port", type=int, default=0,
                   help="control RPC port (meta/compute; 0 = ephemeral)")
    p.add_argument("--meta", default="127.0.0.1:4600",
                   help="meta RPC address (compute/serving roles)")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--config-json", default=None,
                   help="RwConfig overrides as JSON (compute role)")
    p.add_argument("--heartbeat-interval", type=float, default=0.5)
    p.add_argument("--heartbeat-timeout", type=float, default=3.0)
    p.add_argument("--barrier-interval-ms", type=int, default=1000)
    p.add_argument("--scrub-interval", type=float, default=30.0,
                   help="seconds between background integrity-scrub "
                        "cycles on the meta (0 = disabled)")
    p.add_argument("--serve-retry-timeout", type=float, default=60.0,
                   help="how long a serving read waits through "
                        "failover/repair windows before erroring")
    p.add_argument("--serving-cache-blocks", type=int, default=1024,
                   help="serving block-cache capacity (serving role)")
    p.add_argument("--serving-result-cache-bytes", type=int,
                   default=32 << 20,
                   help="serving result-cache budget in bytes "
                        "(serving role; 0 disables)")
    p.add_argument("--serving-negative-cache-keys", type=int,
                   default=65536,
                   help="serving per-vid negative-cache capacity "
                        "(known-missing pks; 0 disables)")
    p.add_argument("--serving-warmup-keys", type=int, default=8,
                   help="hottest sqls replayed against each fresh "
                        "lease grant (result-cache warmup; "
                        "0 disables)")
    p.add_argument("--n-vnodes", type=int, default=64,
                   help="scale plane: vnode ring size (meta role)")
    p.add_argument("--scale-partitioning", action="store_true",
                   help="scale plane: partition eligible jobs over "
                        "the vnode map (meta role); `ctl cluster "
                        "scale N` then moves only vnodes")
    p.add_argument("--no-shuffle-ingest", action="store_true",
                   help="exchange plane: disable sliced ingest "
                        "(meta role) — DML batches replicate to "
                        "every partition host and the VnodeGate "
                        "filters (the PR-7 baseline)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="HTTP /metrics port for this process "
                        "(0 = disabled); the unified plane also "
                        "aggregates over RPC via `ctl cluster "
                        "metrics`")
    p.add_argument("--trace-sample-n", type=int, default=1,
                   help="trace-lite sampling: 0 disables tracing "
                        "entirely; N>=1 records every control-plane "
                        "span and 1-in-N data-plane spans")
    p.add_argument("--trace-buffer-spans", type=int, default=4096,
                   help="per-process span flight-recorder capacity")
    args = p.parse_args()

    # trace-lite identity + sampling, wired BEFORE any role boots so
    # even registration RPCs carry (or drop) trace context uniformly.
    # A compute --config-json may override via ClusterConfig.
    from risingwave_tpu.common.trace import GLOBAL_TRACE

    sample_n, capacity = args.trace_sample_n, args.trace_buffer_spans
    if args.config_json:
        try:
            cj = json.loads(args.config_json).get("cluster") or {}
            sample_n = int(cj.get("trace_sample_n", sample_n))
            capacity = int(cj.get("trace_buffer_spans", capacity))
        except (ValueError, TypeError, AttributeError):
            pass
    GLOBAL_TRACE.configure(role=args.role, sample_n=sample_n,
                           capacity=capacity)

    if args.role == "meta":
        _run_meta(args)
        return
    if args.role == "compute":
        _run_compute(args)
        return
    if args.role == "serving":
        _run_serving(args)
        return
    node = SingleNode(data_dir=args.data_dir)
    server = node.start(args.host, args.port)
    if args.metrics_port:
        _start_metrics_http(node.engine.metrics.render_prometheus,
                            args.host, args.metrics_port)
    print(f"listening on {args.host}:{args.port} (psql -h {args.host} "
          f"-p {args.port} any_db)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
        server.shutdown()


if __name__ == "__main__":
    main()
