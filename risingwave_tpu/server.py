"""Single-node server: engine + barrier ticker + pgwire front door.

Reference counterpart: the single-binary modes (``src/cmd_all/src/
single_node.rs``) that bundle frontend + meta + compute into one
process.  Here: one Engine, a background barrier loop paced by the
``barrier_interval_ms`` system param, and the wire server.

    python -m risingwave_tpu.server --port 4566 --data-dir ./data
"""

from __future__ import annotations

import argparse
import threading
import time

from risingwave_tpu.sql.engine import Engine
from risingwave_tpu.sql.planner import PlannerConfig


class SingleNode:
    def __init__(self, config: PlannerConfig | None = None,
                 data_dir: str | None = None):
        self.engine = Engine(config, data_dir=data_dir)
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- barrier loop ---------------------------------------------------
    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            interval = int(
                self.engine.system_params.get("barrier_interval_ms")
            ) / 1000.0
            t0 = time.monotonic()
            with self._lock:
                if self.engine.jobs:
                    self.engine.tick(barriers=1)
            elapsed = time.monotonic() - t0
            self._stop.wait(max(interval - elapsed, 0.0))

    def start(self, host: str = "127.0.0.1", port: int = 4566,
              ticker: bool = True):
        from risingwave_tpu.pgwire import pg_serve

        if ticker:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True
            )
            self._ticker.start()
        # durable nodes run the storage service's background compactor
        # (the fourth node role, embedded single-binary style)
        self.engine.start_storage_service()
        # pgwire statements and the ticker share the engine lock
        server = pg_serve(self.engine, host, port, engine_lock=self._lock)
        return server

    def tick(self, barriers: int = 1,
             chunks_per_barrier: int | None = None) -> None:
        """Deterministic manual ticks (tests/FLUSH); lock-coordinated
        with the background ticker."""
        with self._lock:
            self.engine.tick(barriers, chunks_per_barrier)

    def stop(self) -> None:
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5)
        self.engine.stop_storage_service()


def main() -> None:
    p = argparse.ArgumentParser(description="risingwave_tpu single node")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=4566)
    p.add_argument("--data-dir", default=None)
    args = p.parse_args()
    node = SingleNode(data_dir=args.data_dir)
    server = node.start(args.host, args.port)
    print(f"listening on {args.host}:{args.port} (psql -h {args.host} "
          f"-p {args.port} any_db)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
        server.shutdown()


if __name__ == "__main__":
    main()
