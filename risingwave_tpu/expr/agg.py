"""Aggregate functions with retraction semantics.

Reference counterpart: ``AggregateFunction`` (src/expr/core/src/aggregate/
mod.rs:49) and impls in src/expr/impl/src/aggregate/.

TPU-first design
----------------
An aggregate is decomposed into one or more *primitive scatter states*,
each updatable with a single vectorized scatter op over a slot index
vector — this is what lets a whole chunk's worth of updates for
thousands of groups land in one XLA scatter instead of a per-group loop
(the reference's ``AggGroup::apply_chunk`` per-group path, hash_agg.rs:332,
becomes a ``state.at[slots].add/min/max(contrib)``):

- ``add`` states: count / sum / sum0 / avg-numerator — fully retractable
  via the changelog sign vector (insert=+1, delete=-1).
- ``min``/``max`` states: monotone monoids — exact for append-only
  inputs.  Retractable min/max requires a materialized-input state (the
  reference's ``minput.rs``); until that lands, executors flag deletes
  hitting a min/max state (consistency check, like the reference's
  consistency_error!).

``output`` combines the primitive states into the SQL result (e.g.
avg = sum / count) and is evaluated only at barrier emit time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.types import DataType, Field
from risingwave_tpu.expr.node import Expr


@dataclass(frozen=True)
class PrimState:
    """One scatter-updatable state array of a (possibly composite) agg."""

    mode: str  # "add" | "min" | "max"
    #: dtype of the state array given the input column dtype
    dtype: Callable[[jnp.dtype], jnp.dtype]
    #: identity element
    init: Callable[[jnp.dtype], jnp.ndarray]
    #: (value_col, signs) -> per-row contribution (same len as chunk)
    lift: Callable


def _i64(_):
    return jnp.int64


def _same(d):
    return d


_ADD_COUNT = PrimState(
    "add", _i64, lambda d: jnp.zeros((), jnp.int64),
    lambda col, signs: signs.astype(jnp.int64),
)


def _sum_dtype(d):
    # sum of int16/int32 widens to int64 (SQL sum semantics)
    if jnp.issubdtype(d, jnp.integer):
        return jnp.int64
    return d


_ADD_SUM = PrimState(
    "add", _sum_dtype, lambda d: jnp.zeros((), d),
    lambda col, signs: col.astype(_sum_dtype(col.dtype)) * signs.astype(_sum_dtype(col.dtype)),
)


def _minmax_init(mode):
    def init(d):
        if jnp.issubdtype(d, jnp.floating):
            v = jnp.inf if mode == "min" else -jnp.inf
            return jnp.asarray(v, d)
        info = jnp.iinfo(d)
        return jnp.asarray(info.max if mode == "min" else info.min, d)

    return init


def _minmax_lift(mode):
    def lift(col, signs):
        # deletes must not feed min/max; executor checks this invariant
        neutral = _minmax_init(mode)(col.dtype)
        return jnp.where(signs > 0, col, neutral)

    return lift


_MIN = PrimState("min", _same, _minmax_init("min"), _minmax_lift("min"))
_MAX = PrimState("max", _same, _minmax_init("max"), _minmax_lift("max"))


# -- min/max over short strings: order-preserving uint64 packing ------------
#
# A fixed-width string of <= 8 bytes packs big-endian into a uint64 whose
# unsigned order IS the byte-lexicographic order (zero-padding sorts
# shorter prefixes first, matching SQL collation on ASCII).  Biasing the
# sign bit maps that to SIGNED int64 order, so the scatter-min/max
# machinery works unchanged.  (Ref: memcomparable key encoding,
# src/common/src/util/memcmp_encoding — same trick, one word wide.)

_STR8_BIAS = np.uint64(1 << 63)


def _pack_str8(col) -> jnp.ndarray:
    data, lens = col.data, col.lens  # [cap, w<=8], [cap]
    cap, w = data.shape
    j = np.arange(w)
    shifts = jnp.asarray(((7 - j) * 8), jnp.uint64)
    in_str = j[None, :] < lens[:, None]
    b = jnp.where(in_str, data, 0).astype(jnp.uint64)
    packed = jnp.sum(b << shifts[None, :], axis=1)
    import jax
    return jax.lax.bitcast_convert_type(
        packed ^ _STR8_BIAS, jnp.int64
    )


def _minmax_str_lift(mode):
    def lift(col, signs):
        packed = _pack_str8(col)
        neutral = _minmax_init(mode)(jnp.int64)
        return jnp.where(signs > 0, packed, neutral)

    return lift


def _out_minmax_str(states, count, out_field):
    import jax
    v = jax.lax.bitcast_convert_type(states[0], jnp.uint64) ^ _STR8_BIAS
    w = 8
    j = np.arange(w)
    shifts = jnp.asarray(((7 - j) * 8), jnp.uint64)
    bytes_ = ((v[:, None] >> shifts[None, :])
              & jnp.uint64(0xFF)).astype(jnp.uint8)
    nz = bytes_ != 0
    lens = jnp.where(
        jnp.any(nz, axis=1),
        w - jnp.argmax(nz[:, ::-1], axis=1), 0
    ).astype(jnp.int32)
    from risingwave_tpu.common.chunk import StrCol
    return StrCol(bytes_, lens)


_MIN_STR = PrimState(
    "min", lambda d: jnp.int64, _minmax_init("min"), _minmax_str_lift("min")
)
_MAX_STR = PrimState(
    "max", lambda d: jnp.int64, _minmax_init("max"), _minmax_str_lift("max")
)


@dataclass(frozen=True)
class AggSpec:
    """A SQL aggregate = primitive states + an output combiner."""

    name: str
    states: tuple[PrimState, ...]
    #: (state_cols, group_count, out_field) -> output column
    output: Callable
    #: whether deletes are handled exactly
    retractable: bool
    #: return type given input type (None input for count(*))
    return_type: Callable[[DataType | None], DataType]

    def needs_input(self) -> bool:
        return self.name != "count_star"


def _out_first(states, count, out_field):
    return states[0]


def _out_count(states, count, out_field):
    return states[0]


def _out_avg(states, count, out_field):
    s, c = states
    if out_field.data_type == DataType.DECIMAL:
        # truncate toward zero (floor division biases negative sums)
        safe_c = jnp.where(c == 0, 1, c)
        q = jnp.sign(s) * (jnp.abs(s) // safe_c)
        return jnp.where(c != 0, q, 0)
    return jnp.where(
        c != 0, s / jnp.where(c == 0, 1, c).astype(jnp.float64), 0.0
    )


def _avg_type(t):
    if t == DataType.DECIMAL:
        return DataType.DECIMAL
    return DataType.FLOAT64


AGG_REGISTRY: dict[str, AggSpec] = {
    "count": AggSpec("count", (_ADD_COUNT,), _out_count, True, lambda t: DataType.INT64),
    "count_star": AggSpec(
        "count_star", (_ADD_COUNT,), _out_count, True, lambda t: DataType.INT64
    ),
    "sum": AggSpec(
        "sum", (_ADD_SUM,), _out_first, True,
        lambda t: DataType.INT64 if t in (DataType.INT16, DataType.INT32) else t,
    ),
    "sum0": AggSpec(  # sum that starts at 0 instead of NULL (internal, 2-phase)
        "sum0", (_ADD_SUM,), _out_first, True,
        lambda t: DataType.INT64 if t in (DataType.INT16, DataType.INT32) else t,
    ),
    "avg": AggSpec("avg", (_ADD_SUM, _ADD_COUNT), _out_avg, True, _avg_type),
    "min": AggSpec("min", (_MIN,), _out_first, False, lambda t: t),
    "max": AggSpec("max", (_MAX,), _out_first, False, lambda t: t),
    # min/max over strings (<= 8 device bytes; planner rewrite)
    "min_str": AggSpec("min_str", (_MIN_STR,), _out_minmax_str, False,
                       lambda t: DataType.VARCHAR),
    "max_str": AggSpec("max_str", (_MAX_STR,), _out_minmax_str, False,
                       lambda t: DataType.VARCHAR),
}


@dataclass(frozen=True)
class AggCall:
    """One aggregate call in a plan: kind + input expression.

    Ref: ``AggCall`` (src/expr/core/src/aggregate/mod.rs).  ``distinct``
    is handled by the planner as a dedup-before-agg rewrite (the
    reference's distinct dedup tables) — append-only inputs only this
    round.
    """

    kind: str
    arg: Expr | None = None
    alias: str | None = None
    distinct: bool = False
    #: FILTER (WHERE <cond>): rows failing the predicate contribute
    #: nothing to THIS call (ref: agg filter in agg_group.rs — per-call
    #: visibility; here the call's contribution signs zero out)
    filter: Expr | None = None

    def spec(self) -> AggSpec:
        return AGG_REGISTRY[self.kind]

    def out_field(self, input_schema) -> Field:
        spec = self.spec()
        if self.arg is None:
            in_t = None
            scale = 6
            nullable = False
        else:
            f = self.arg.return_field(input_schema)
            in_t, scale = f.data_type, f.decimal_scale
            # sum/min/max/avg over a nullable argument are NULL when
            # every argument row in the group is NULL (or when a FILTER
            # excludes every row); count never is
            nullable = (f.nullable or self.filter is not None) \
                and self.kind not in ("count", "count_star")
        t = spec.return_type(in_t)
        kw = {}
        if t.is_string:
            # packed-string min/max emits a fixed 8-byte column
            kw["str_width"] = 8
        return Field(self.alias or self.kind, t, decimal_scale=scale,
                     nullable=nullable, **kw)


def count_star(alias: str = "count") -> AggCall:
    return AggCall("count_star", None, alias)


def agg(kind: str, arg: Expr, alias: str | None = None) -> AggCall:
    return AggCall(kind, arg, alias)
