"""Expression AST.

Reference counterpart: ``src/expr/core/src/expr/mod.rs`` (``Expression``
trait, ``InputRefExpression``, ``LiteralExpression``,
``FuncCallExpression``).  Unlike the reference's boxed-trait interpreter,
evaluation here *traces*: ``Expr.eval(chunk)`` returns a jnp column and
the whole tree collapses into the surrounding jitted program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, StrCol, encode_strings
from risingwave_tpu.common.types import (
    DEFAULT_DECIMAL_SCALE,
    DEFAULT_STR_WIDTH,
    DataType,
    Field,
    Schema,
)


class Expr:
    """Base expression node; subclasses are immutable."""

    # -- interface ------------------------------------------------------
    def return_field(self, schema: Schema) -> Field:
        raise NotImplementedError

    def eval(self, chunk: Chunk):
        """Evaluate to a device column ([cap] array or StrCol)."""
        raise NotImplementedError

    def return_type(self, schema: Schema) -> DataType:
        return self.return_field(schema).data_type

    # -- builder sugar --------------------------------------------------
    def _f(self, name: str, *others: "Expr | Any") -> "FuncCall":
        return FuncCall(name, (self, *[as_expr(o) for o in others]))

    def __add__(self, o):
        return self._f("add", o)

    def __sub__(self, o):
        return self._f("subtract", o)

    def __mul__(self, o):
        return self._f("multiply", o)

    def __truediv__(self, o):
        return self._f("divide", o)

    def __mod__(self, o):
        return self._f("modulus", o)

    def __neg__(self):
        return self._f("neg")

    def __eq__(self, o):  # type: ignore[override]
        return self._f("equal", o)

    def __ne__(self, o):  # type: ignore[override]
        return self._f("not_equal", o)

    def __lt__(self, o):
        return self._f("less_than", o)

    def __le__(self, o):
        return self._f("less_than_or_equal", o)

    def __gt__(self, o):
        return self._f("greater_than", o)

    def __ge__(self, o):
        return self._f("greater_than_or_equal", o)

    def __and__(self, o):
        return self._f("and", o)

    def __or__(self, o):
        return self._f("or", o)

    def __invert__(self):
        return self._f("not")

    def __hash__(self):
        return object.__hash__(self)

    def cast(self, t: DataType) -> "FuncCall":
        return FuncCall(f"cast_{t.name.lower()}", (self,))

    def is_in(self, values: Sequence[Any]) -> "Expr":
        """`x IN (v1, v2, ...)` — or-chain of equalities (small lists)."""
        out: Expr | None = None
        for v in values:
            eq = self._f("equal", v)
            out = eq if out is None else out | eq
        if out is None:
            raise ValueError("empty IN list")
        return out


@dataclass(frozen=True, eq=False)
class InputRef(Expr):
    """Column reference by position (ref InputRefExpression)."""

    index: int

    def return_field(self, schema: Schema) -> Field:
        return schema[self.index]

    def eval(self, chunk: Chunk):
        return chunk.column(self.index)

    def __repr__(self):
        return f"${self.index}"


@dataclass(frozen=True, eq=False)
class NamedRef(Expr):
    """Column reference by name, resolved against the chunk's schema."""

    name: str

    def return_field(self, schema: Schema) -> Field:
        return schema[schema.index_of(self.name)]

    def eval(self, chunk: Chunk):
        return chunk.column_by_name(self.name)

    def __repr__(self):
        return self.name


@dataclass(frozen=True, eq=False)
class Literal(Expr):
    """Constant (ref LiteralExpression). Broadcast to the chunk capacity."""

    value: Any
    data_type: DataType

    def return_field(self, schema: Schema) -> Field:
        return Field("?const", self.data_type, nullable=self.value is None)

    def eval(self, chunk: Chunk):
        cap = chunk.capacity
        t = self.data_type
        if self.value is None:
            # NULL literal: zero payload, all-null mask
            from risingwave_tpu.common.chunk import NCol
            if t.is_string:
                data = StrCol(
                    jnp.zeros((cap, DEFAULT_STR_WIDTH), jnp.uint8),
                    jnp.zeros((cap,), jnp.int32),
                )
            else:
                data = jnp.zeros((cap,), t.physical_dtype)
            return NCol(data, jnp.ones((cap,), jnp.bool_))
        if t.is_string:
            data, lens = encode_strings([self.value], DEFAULT_STR_WIDTH)
            return StrCol(
                jnp.broadcast_to(jnp.asarray(data[0]), (cap, data.shape[1])),
                jnp.broadcast_to(jnp.asarray(lens[0]), (cap,)),
            )
        if t == DataType.DECIMAL:
            v = int(round(float(self.value) * 10**DEFAULT_DECIMAL_SCALE))
            return jnp.full((cap,), v, jnp.int64)
        return jnp.full((cap,), self.value, t.physical_dtype)

    def __repr__(self):
        return f"{self.value}:{self.data_type.name.lower()}"


@dataclass(frozen=True, eq=False)
class FuncCall(Expr):
    """Scalar function application, resolved via FUNCTION_REGISTRY."""

    name: str
    args: tuple[Expr, ...]

    def _resolve(self, schema: Schema):
        from risingwave_tpu.expr.registry import FUNCTION_REGISTRY

        arg_fields = [a.return_field(schema) for a in self.args]
        return FUNCTION_REGISTRY.resolve(self.name, arg_fields), arg_fields

    def return_field(self, schema: Schema) -> Field:
        sig, arg_fields = self._resolve(schema)
        return sig.return_field(arg_fields)

    def eval(self, chunk: Chunk):
        sig, arg_fields = self._resolve(chunk.schema)
        cols = [a.eval(chunk) for a in self.args]
        return sig.call(cols, arg_fields)

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


def as_expr(v: Any) -> Expr:
    """Coerce python values to Literal exprs (builder convenience)."""
    if isinstance(v, Expr):
        return v
    if isinstance(v, bool):
        return Literal(v, DataType.BOOLEAN)
    if isinstance(v, int):
        return Literal(v, DataType.INT64 if abs(v) > 2**31 - 1 else DataType.INT32)
    if isinstance(v, float):
        return Literal(v, DataType.FLOAT64)
    if isinstance(v, str):
        return Literal(v, DataType.VARCHAR)
    if isinstance(v, (np.integer,)):
        return as_expr(int(v))
    if isinstance(v, (np.floating,)):
        return as_expr(float(v))
    raise TypeError(f"cannot coerce {v!r} to Expr")


def col(name: str) -> NamedRef:
    return NamedRef(name)


def input_ref(i: int) -> InputRef:
    return InputRef(i)


def lit(v: Any, t: DataType | None = None) -> Literal:
    e = as_expr(v)
    if t is not None:
        return Literal(v, t)
    assert isinstance(e, Literal)
    return e


def case(cond: Expr, then: Expr | Any, otherwise: Expr | Any) -> FuncCall:
    """CASE WHEN cond THEN a ELSE b END."""
    return FuncCall("case", (cond, as_expr(then), as_expr(otherwise)))
