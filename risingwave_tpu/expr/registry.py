"""Scalar function registry with signature dispatch.

Reference counterpart: ``FUNCTION_REGISTRY`` (src/expr/core/src/sig/mod.rs:39)
populated by the ``#[function("add(int,int)->int")]`` proc-macro
(src/expr/macro/src/lib.rs).  Here the same idea is a decorator::

    @function("add(int64, int64) -> int64")
    def add_i64(a, b): return a + b

Signatures use SQL type names plus the families ``intlike`` (int16/32/64,
serial), ``floatlike`` (float32/64), ``numeric`` (ints+floats+decimal),
``timelike`` (date/time/timestamp/timestamptz/interval), ``any``.
Resolution prefers exact matches over family matches and, like the
reference's casting rules, auto-promotes mixed numeric widths.
"""

from __future__ import annotations

import inspect
import re
from dataclasses import dataclass
from typing import Callable, Sequence

from risingwave_tpu.common.types import (
    DEFAULT_STR_WIDTH,
    DataType,
    Field,
)

_FAMILIES: dict[str, tuple[DataType, ...]] = {
    "intlike": (DataType.INT16, DataType.INT32, DataType.INT64, DataType.SERIAL),
    "floatlike": (DataType.FLOAT32, DataType.FLOAT64),
    "numeric": (
        DataType.INT16,
        DataType.INT32,
        DataType.INT64,
        DataType.SERIAL,
        DataType.FLOAT32,
        DataType.FLOAT64,
        DataType.DECIMAL,
    ),
    "timelike": (
        DataType.DATE,
        DataType.TIME,
        DataType.TIMESTAMP,
        DataType.TIMESTAMPTZ,
        DataType.INTERVAL,
    ),
    "stringlike": (DataType.VARCHAR, DataType.BYTEA),
    "any": tuple(DataType),
}

#: pseudo return types computed from the argument types
_AUTO_RETURNS = ("auto", "same")


def _parse_type(tok: str) -> tuple[str, tuple[DataType, ...]]:
    tok = tok.strip().lower()
    if tok in _FAMILIES:
        return tok, _FAMILIES[tok]
    t = DataType.from_sql(tok) if tok not in ("auto", "same", "boolean") else None
    if tok == "boolean":
        t = DataType.BOOLEAN
    if t is None:
        raise ValueError(f"unknown type {tok!r}")
    return tok, (t,)


_NUMERIC_ORDER = [
    DataType.INT16,
    DataType.INT32,
    DataType.INT64,
    DataType.SERIAL,
    DataType.DECIMAL,
    DataType.FLOAT32,
    DataType.FLOAT64,
]


def promote_numeric(types: Sequence[DataType]) -> DataType:
    """SQL-ish numeric promotion: widest wins; decimal beats ints,
    floats beat decimal (matching the reference's cast lattice)."""
    best = -1
    for t in types:
        if t not in _NUMERIC_ORDER:
            return types[0]
        best = max(best, _NUMERIC_ORDER.index(t))
    return _NUMERIC_ORDER[best]


@dataclass(frozen=True)
class FuncSig:
    name: str
    arg_matchers: tuple[tuple[str, tuple[DataType, ...]], ...]
    ret: str  # sql type name or "auto"/"same"/"same_branch"
    impl: Callable
    #: impl declares a trailing ``fields`` kwarg for logical-type context
    takes_fields: bool = False
    #: impl handles NULL masks itself (receives NCol args as-is):
    #: Kleene AND/OR, IS NULL, COALESCE, CASE
    null_aware: bool = False
    #: result can never be NULL regardless of inputs (IS NULL, count)
    never_null: bool = False

    def call(self, cols: Sequence, arg_fields: Sequence[Field]):
        """Evaluate with SQL null semantics.

        Strict functions (the default, matching the reference's
        #[function] strictness) see only payloads; the result's null
        mask is the OR of the argument masks — one fused ``where``-free
        mask op, so non-nullable plans pay nothing."""
        from risingwave_tpu.common.chunk import make_col, split_col

        if self.null_aware:
            if self.takes_fields:
                return self.impl(*cols, fields=list(arg_fields))
            return self.impl(*cols)
        datas = []
        null = None
        for c in cols:
            d, n = split_col(c)
            datas.append(d)
            if n is not None:
                null = n if null is None else (null | n)
        if self.takes_fields:
            out = self.impl(*datas, fields=list(arg_fields))
        else:
            out = self.impl(*datas)
        if self.never_null:
            return out
        return make_col(out, null)

    def matches(self, arg_fields: Sequence[Field]) -> int:
        """Score the match: -1 no match; higher = more specific."""
        if len(arg_fields) != len(self.arg_matchers):
            return -1
        score = 0
        for f, (tok, accepted) in zip(arg_fields, self.arg_matchers):
            if f.data_type not in accepted:
                return -1
            score += 2 if len(accepted) == 1 else (1 if tok != "any" else 0)
        return score

    def return_field(self, arg_fields: Sequence[Field]) -> Field:
        base = self._base_return_field(arg_fields)
        if self.never_null:
            return base.with_nullable(False) if base.nullable else base
        if any(f.nullable for f in arg_fields) and not base.nullable:
            return base.with_nullable()
        return base

    def _base_return_field(self, arg_fields: Sequence[Field]) -> Field:
        if self.ret == "same":
            return Field("?expr", arg_fields[0].data_type,
                         str_width=arg_fields[0].str_width,
                         decimal_scale=arg_fields[0].decimal_scale)
        if self.ret == "same_branch":  # CASE: type of the THEN/ELSE branches
            b = arg_fields[1:]
            if all(f.data_type == b[0].data_type for f in b):
                return Field("?expr", b[0].data_type,
                             str_width=max(f.str_width for f in b),
                             decimal_scale=b[0].decimal_scale)
            return Field("?expr", promote_numeric([f.data_type for f in b]))
        if self.ret == "auto":
            return Field("?expr", promote_numeric([f.data_type for f in arg_fields]))
        _, accepted = _parse_type(self.ret)
        t = accepted[0]
        if t in (DataType.VARCHAR, DataType.BYTEA):
            # device width of a produced string: concat sums its inputs;
            # everything else is bounded by the widest string argument
            str_widths = [f.str_width for f in arg_fields
                          if f.data_type in (DataType.VARCHAR,
                                             DataType.BYTEA)]
            if self.name == "concat":
                width = sum(str_widths)
            else:
                width = max(str_widths, default=DEFAULT_STR_WIDTH)
            return Field("?expr", t, str_width=width)
        return Field("?expr", t)


_SIG_RE = re.compile(r"^\s*(\w+)\s*\(([^)]*)\)\s*->\s*([\w ]+)\s*$")


class _Registry:
    def __init__(self):
        self._by_name: dict[str, list[FuncSig]] = {}

    def register(self, spec: str, impl: Callable,
                 null_aware: bool = False,
                 never_null: bool = False) -> FuncSig:
        m = _SIG_RE.match(spec)
        if not m:
            raise ValueError(f"bad signature {spec!r}")
        name, args, ret = m.group(1), m.group(2), m.group(3)
        matchers = tuple(
            _parse_type(tok) for tok in args.split(",") if tok.strip()
        )
        takes_fields = "fields" in inspect.signature(impl).parameters
        sig = FuncSig(name, matchers, ret.strip().lower(), impl,
                      takes_fields, null_aware, never_null)
        self._by_name.setdefault(name, []).append(sig)
        return sig

    def resolve(self, name: str, arg_fields: Sequence[Field]) -> FuncSig:
        cands = self._by_name.get(name)
        if not cands:
            raise KeyError(f"no function named {name!r}")
        best: FuncSig | None = None
        best_score = -1
        for sig in cands:
            s = sig.matches(arg_fields)
            if s > best_score:
                best, best_score = sig, s
        if best is None or best_score < 0:
            types = [f.data_type.name for f in arg_fields]
            raise KeyError(f"no overload {name}({', '.join(types)})")
        return best

    def names(self) -> list[str]:
        return sorted(self._by_name)

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_name.values())


FUNCTION_REGISTRY = _Registry()


def function(spec: str, null_aware: bool = False, never_null: bool = False):
    """Decorator mirroring the reference's ``#[function(...)]`` macro.

    ``null_aware`` impls receive NCol arguments and own their null
    semantics; ``never_null`` marks results that cannot be NULL."""

    def deco(fn: Callable) -> Callable:
        FUNCTION_REGISTRY.register(spec, fn, null_aware, never_null)
        return fn

    return deco
