"""Vectorized expression + aggregate engine.

Reference counterpart: ``src/expr`` — ``Expression::eval(&DataChunk)``
(src/expr/core/src/expr/mod.rs:88), the ``FUNCTION_REGISTRY``
(src/expr/core/src/sig/mod.rs:39) and ``AggregateFunction``
(src/expr/core/src/aggregate/mod.rs:49).

TPU-first design: an expression tree evaluates to a whole device column
per chunk in one traced program — there is no per-row interpreter.  The
executor jits the *fragment* step, so expression trees fuse with their
consumers (filter masks, agg updates) into a single XLA computation.
"""

from risingwave_tpu.expr.node import (  # noqa: F401
    Expr,
    InputRef,
    Literal,
    FuncCall,
    col,
    lit,
    input_ref,
)
from risingwave_tpu.expr.registry import FUNCTION_REGISTRY, function  # noqa: F401
from risingwave_tpu.expr import scalar  # noqa: F401  (populates the registry)
from risingwave_tpu.expr.agg import (  # noqa: F401
    AGG_REGISTRY,
    AggCall,
    AggSpec,
)
