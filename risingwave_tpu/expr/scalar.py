"""Scalar function implementations (vectorized, trace-friendly).

Reference counterpart: ``src/expr/impl/src/scalar/`` (90 files of
``#[function]`` impls).  Coverage here targets the benchmark SQL surface
(Nexmark q0-q10, TPC-H arithmetic/predicates) and grows with the planner.

All impls take and return whole device columns.  Mixed numeric arg types
are promoted via implicit casts inserted at resolution time (the impls
that need logical-type context declare a trailing ``fields`` kwarg).
"""

from __future__ import annotations

import re
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import NCol, StrCol, make_col, split_col
from risingwave_tpu.common.types import (
    DEFAULT_DECIMAL_SCALE,
    DataType,
    Field,
)
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.expr.registry import function, promote_numeric

_SCALE = 10**DEFAULT_DECIMAL_SCALE

# ---------------------------------------------------------------------------
# casts / coercion


def coerce(col, field: Field, target: DataType):
    """Cast a device column from its logical type to ``target``."""
    t = field.data_type
    if t == target and not (
        t == DataType.DECIMAL and field.decimal_scale != DEFAULT_DECIMAL_SCALE
    ):
        return col
    if isinstance(col, StrCol):
        raise TypeError(f"cannot cast string column to {target}")
    if t == DataType.DECIMAL:
        if target == DataType.DECIMAL:
            # rescale a non-default-scale column to the engine scale so
            # downstream arithmetic (which assumes _SCALE) is correct
            diff = DEFAULT_DECIMAL_SCALE - field.decimal_scale
            if diff > 0:
                return col * (10**diff)
            return col // (10 ** (-diff))
        if target in (DataType.FLOAT32, DataType.FLOAT64):
            return (col.astype(target.physical_dtype)) / np.float64(
                10**field.decimal_scale
            ).astype(target.physical_dtype)
        if target.is_integral and target != DataType.DECIMAL:
            return (col // (10**field.decimal_scale)).astype(target.physical_dtype)
        raise TypeError(f"decimal -> {target}?")
    if target == DataType.DECIMAL:
        if t.is_integral:
            return col.astype(jnp.int64) * _SCALE
        # float -> decimal: round at the default scale
        return jnp.round(col.astype(jnp.float64) * _SCALE).astype(jnp.int64)
    if target == DataType.BOOLEAN:
        return col != 0
    _US_PER_DAY = 86_400_000_000
    if t == DataType.DATE and target in (DataType.TIMESTAMP,
                                         DataType.TIMESTAMPTZ):
        # DATE is i32 days since epoch; timestamps are i64 microseconds
        return col.astype(jnp.int64) * _US_PER_DAY
    if t in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ) \
            and target == DataType.DATE:
        return (col // _US_PER_DAY).astype(jnp.int32)
    return col.astype(target.physical_dtype)


for _t in (
    DataType.INT16,
    DataType.INT32,
    DataType.INT64,
    DataType.FLOAT32,
    DataType.FLOAT64,
    DataType.DECIMAL,
    DataType.BOOLEAN,
    DataType.TIMESTAMP,
    DataType.TIMESTAMPTZ,
    DataType.DATE,
):

    def _mk_cast(target: DataType):
        def _cast(a, fields: Sequence[Field]):
            return coerce(a, fields[0], target)

        return _cast

    function(f"cast_{_t.name.lower()}(any) -> {_t.value}")(_mk_cast(_t))


def _promote_args(cols, fields: Sequence[Field]) -> tuple[list, DataType]:
    target = promote_numeric([f.data_type for f in fields])
    return [coerce(c, f, target) for c, f in zip(cols, fields)], target


# ---------------------------------------------------------------------------
# arithmetic (decimal-aware)


@function("add(numeric, numeric) -> auto")
def _add(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return a + b


@function("subtract(numeric, numeric) -> auto")
def _sub(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return a - b


@function("subtract(timelike, timelike) -> interval")
def _sub_time(a, b):
    return (a - b).astype(jnp.int64)


@function("add(timestamp, interval) -> timestamp")
@function("add(timestamptz, interval) -> timestamptz")
def _add_ts_iv(a, b):
    return a + b


@function("subtract(timestamp, interval) -> timestamp")
@function("subtract(timestamptz, interval) -> timestamptz")
def _sub_ts_iv(a, b):
    return a - b


@function("multiply(numeric, numeric) -> auto")
def _mul(a, b, fields: Sequence[Field]):
    (a, b), t = _promote_args((a, b), fields)
    if t == DataType.DECIMAL:
        # via float64: raw int64 products overflow for realistic
        # magnitudes (scaled 10^6 operands); float64 keeps ~15-16
        # significant digits, which covers the SQL numeric surface here
        prod = a.astype(jnp.float64) * b.astype(jnp.float64) / _SCALE
        return jnp.round(prod).astype(jnp.int64)
    return a * b


@function("divide(numeric, numeric) -> auto")
def _div(a, b, fields: Sequence[Field]):
    (a, b), t = _promote_args((a, b), fields)
    if t == DataType.DECIMAL:
        q = a.astype(jnp.float64) / jnp.where(b == 0, 1, b).astype(jnp.float64)
        return jnp.where(
            b != 0, jnp.round(q * _SCALE).astype(jnp.int64), 0
        )
    if jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.where(b != 0, a // jnp.where(b == 0, 1, b), 0)
    return a / b


@function("modulus(numeric, numeric) -> auto")
def _mod(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0)


@function("neg(numeric) -> same")
def _neg(a):
    return -a


@function("abs(numeric) -> same")
def _abs(a):
    return jnp.abs(a)


@function("round(floatlike) -> same")
def _round(a):
    return jnp.round(a)


@function("round(numeric) -> same")
def _round_dec(a, fields: Sequence[Field]):
    if fields[0].data_type == DataType.DECIMAL:
        s = 10**fields[0].decimal_scale
        # half-away-from-zero (floor division alone biases negatives)
        mag = (jnp.abs(a) + s // 2) // s * s
        return jnp.sign(a) * mag
    return jnp.round(a)


@function("round(numeric, int) -> same")
@function("round(numeric, bigint) -> same")
def _round_dec_n(a, n, fields: Sequence[Field]):
    """round(x, n): n decimal places (ref round_digits.rs).  DECIMAL
    keeps its storage scale with the value rounded to n places; floats
    round via scaling."""
    if fields[0].data_type == DataType.DECIMAL:
        scale = fields[0].decimal_scale
        # n is almost always a literal; device-side we support the
        # whole column form with a per-row power
        shift = jnp.maximum(scale - n.astype(jnp.int64), 0)
        p = 10 ** shift
        mag = (jnp.abs(a) + p // 2) // p * p
        return jnp.sign(a) * mag
    if jnp.issubdtype(a.dtype, jnp.integer):
        return a  # rounding an integer to >=0 places is the identity
    p = 10.0 ** n.astype(jnp.float64)
    return jnp.round(a * p) / p


# ---------------------------------------------------------------------------
# comparison


def _cmp_strs(a: StrCol, b: StrCol):
    """Return (first-diff a byte, first-diff b byte) as int16 with -1 EOS."""
    wa, wb = a.data.shape[1], b.data.shape[1]
    w = max(wa, wb)
    ad = jnp.pad(a.data, ((0, 0), (0, w - wa))).astype(jnp.int16)
    bd = jnp.pad(b.data, ((0, 0), (0, w - wb))).astype(jnp.int16)
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    av = jnp.where(idx < a.lens[:, None], ad, jnp.int16(-1))
    bv = jnp.where(idx < b.lens[:, None], bd, jnp.int16(-1))
    return av, bv


def _make_cmp(name: str, op, str_op):
    @function(f"{name}(numeric, numeric) -> boolean")
    def _cmp(a, b, fields: Sequence[Field]):
        (a, b), _ = _promote_args((a, b), fields)
        return op(a, b)

    @function(f"{name}(timelike, timelike) -> boolean")
    @function(f"{name}(boolean, boolean) -> boolean")
    def _cmp_t(a, b):
        return op(a, b)

    @function(f"{name}(stringlike, stringlike) -> boolean")
    def _cmp_s(a: StrCol, b: StrCol):
        av, bv = _cmp_strs(a, b)
        if str_op == "eq":
            return jnp.all(av == bv, axis=1)
        if str_op == "ne":
            return jnp.any(av != bv, axis=1)
        neq = av != bv
        any_neq = jnp.any(neq, axis=1)
        first = jnp.argmax(neq, axis=1)
        fa = jnp.take_along_axis(av, first[:, None], axis=1)[:, 0]
        fb = jnp.take_along_axis(bv, first[:, None], axis=1)[:, 0]
        lt = fa < fb
        if str_op == "lt":
            return any_neq & lt
        if str_op == "le":
            return ~any_neq | lt
        if str_op == "gt":
            return any_neq & ~lt
        return ~any_neq | ~lt  # ge

    return _cmp


_make_cmp("equal", lambda a, b: a == b, "eq")
_make_cmp("not_equal", lambda a, b: a != b, "ne")
_make_cmp("less_than", lambda a, b: a < b, "lt")
_make_cmp("less_than_or_equal", lambda a, b: a <= b, "le")
_make_cmp("greater_than", lambda a, b: a > b, "gt")
_make_cmp("greater_than_or_equal", lambda a, b: a >= b, "ge")


# ---------------------------------------------------------------------------
# logical


@function("and(boolean, boolean) -> boolean", null_aware=True)
def _and(a, b):
    """Kleene AND: FALSE dominates NULL (ref three-valued logic)."""
    ad, an = split_col(a)
    bd, bn = split_col(b)
    if an is None and bn is None:
        return ad & bd
    a_known_false = (~ad) & (~an if an is not None else True)
    b_known_false = (~bd) & (~bn if bn is not None else True)
    some_null = (an if an is not None else False) | (
        bn if bn is not None else False
    )
    # NULL iff neither side is definitively FALSE and either is NULL
    null = some_null & ~a_known_false & ~b_known_false
    return NCol(ad & bd & ~null, null)


@function("or(boolean, boolean) -> boolean", null_aware=True)
def _or(a, b):
    """Kleene OR: TRUE dominates NULL."""
    ad, an = split_col(a)
    bd, bn = split_col(b)
    if an is None and bn is None:
        return ad | bd
    a_known_true = ad & (~an if an is not None else True)
    b_known_true = bd & (~bn if bn is not None else True)
    some_null = (an if an is not None else False) | (
        bn if bn is not None else False
    )
    null = some_null & ~a_known_true & ~b_known_true
    return NCol((a_known_true | b_known_true) & ~null, null)


@function("not(boolean) -> boolean")
def _not(a):
    return ~a


@function("is_null(any) -> boolean", null_aware=True, never_null=True)
def _is_null(a):
    d, n = split_col(a)
    if n is None:
        ref = d.lens if isinstance(d, StrCol) else d
        return jnp.zeros(ref.shape[:1], jnp.bool_)
    return n


@function("is_not_null(any) -> boolean", null_aware=True, never_null=True)
def _is_not_null(a):
    d, n = split_col(a)
    if n is None:
        ref = d.lens if isinstance(d, StrCol) else d
        return jnp.ones(ref.shape[:1], jnp.bool_)
    return ~n


@function("coalesce(any, any) -> same", null_aware=True)
def _coalesce(a, b):
    ad, an = split_col(a)
    bd, bn = split_col(b)
    if an is None:
        return a
    if isinstance(ad, StrCol):
        w = max(ad.data.shape[1], bd.data.shape[1])
        add = jnp.pad(ad.data, ((0, 0), (0, w - ad.data.shape[1])))
        bdd = jnp.pad(bd.data, ((0, 0), (0, w - bd.data.shape[1])))
        data = StrCol(
            jnp.where(an[:, None], bdd, add),
            jnp.where(an, bd.lens, ad.lens),
        )
    else:
        data = jnp.where(an, bd, ad)
    null = (an & bn) if bn is not None else None
    return make_col(data, null)


@function("case(boolean, any, any) -> same_branch",
          null_aware=True)  # CASE WHEN c THEN t ELSE e
def _case(c, t, e, fields: Sequence[Field]):
    """NULL condition selects the ELSE branch (SQL: WHEN does not
    match); branch NULLs flow through to the chosen side."""
    cd, cn = split_col(c)
    take_then = cd if cn is None else (cd & ~cn)
    td, tn = split_col(t)
    ed, en = split_col(e)
    if isinstance(td, StrCol):
        w = max(td.data.shape[1], ed.data.shape[1])
        tdd = jnp.pad(td.data, ((0, 0), (0, w - td.data.shape[1])))
        edd = jnp.pad(ed.data, ((0, 0), (0, w - ed.data.shape[1])))
        data = StrCol(
            jnp.where(take_then[:, None], tdd, edd),
            jnp.where(take_then, td.lens, ed.lens),
        )
    else:
        if fields[1].data_type != fields[2].data_type:
            target = promote_numeric(
                [fields[1].data_type, fields[2].data_type]
            )
            td = coerce(td, fields[1], target)
            ed = coerce(ed, fields[2], target)
        data = jnp.where(take_then, td, ed)
    if tn is None and en is None:
        return data
    zeros = jnp.zeros_like(take_then)
    null = jnp.where(
        take_then,
        tn if tn is not None else zeros,
        en if en is not None else zeros,
    )
    return NCol(data, null)


# ---------------------------------------------------------------------------
# temporal

_US = {"second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000,
       "day": 86_400_000_000}


# microsecond-based temporal fns: registered for the microsecond-backed
# types only (DATE is i32 days and must not match these overloads)
@function("extract_epoch(timestamp) -> bigint")
@function("extract_epoch(timestamptz) -> bigint")
def _extract_epoch(a):
    return a // 1_000_000


@function("extract_epoch(date) -> bigint")
def _extract_epoch_date(a):
    return a.astype(jnp.int64) * 86_400


def _us_trunc(unit: str):
    def impl(a):
        return a - a % _US[unit]

    return impl


for _unit in ("second", "minute", "hour", "day"):
    _impl = _us_trunc(_unit)
    function(f"date_trunc_{_unit}(timestamp) -> same")(_impl)
    function(f"date_trunc_{_unit}(timestamptz) -> same")(_impl)


@function("tumble_start(timestamp, interval) -> same")
@function("tumble_start(timestamptz, interval) -> same")
def _tumble_start(ts, size):
    return ts - ts % size


# ---------------------------------------------------------------------------
# string


@function("char_length(stringlike) -> int")
def _char_length(a: StrCol):
    # note: byte length; full utf-8 codepoint counting is a host fallback
    return a.lens


@function("lower(stringlike) -> same")
def _lower(a: StrCol):
    up = (a.data >= ord("A")) & (a.data <= ord("Z"))
    return StrCol(jnp.where(up, a.data + 32, a.data), a.lens)


@function("upper(stringlike) -> same")
def _upper(a: StrCol):
    lo = (a.data >= ord("a")) & (a.data <= ord("z"))
    return StrCol(jnp.where(lo, a.data - 32, a.data), a.lens)


# ---------------------------------------------------------------------------
# math

@function("sqrt(numeric) -> double precision")
def _sqrt(a, fields: Sequence[Field]):
    return jnp.sqrt(coerce(a, fields[0], DataType.FLOAT64))


@function("power(numeric, numeric) -> double precision")
def _power(a, b, fields: Sequence[Field]):
    return jnp.power(coerce(a, fields[0], DataType.FLOAT64),
                     coerce(b, fields[1], DataType.FLOAT64))


@function("exp(numeric) -> double precision")
def _exp(a, fields: Sequence[Field]):
    return jnp.exp(coerce(a, fields[0], DataType.FLOAT64))


@function("ln(numeric) -> double precision")
def _ln(a, fields: Sequence[Field]):
    return jnp.log(coerce(a, fields[0], DataType.FLOAT64))


@function("log10(numeric) -> double precision")
def _log10(a, fields: Sequence[Field]):
    return jnp.log10(coerce(a, fields[0], DataType.FLOAT64))


@function("floor(floatlike) -> same")
def _floor(a):
    return jnp.floor(a)


@function("ceil(floatlike) -> same")
def _ceil(a):
    return jnp.ceil(a)


@function("sign(numeric) -> int")
def _sign(a):
    return jnp.sign(a).astype(jnp.int32)


@function("greatest(numeric, numeric) -> auto")
def _greatest(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return jnp.maximum(a, b)


@function("least(numeric, numeric) -> auto")
def _least(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return jnp.minimum(a, b)


# ---------------------------------------------------------------------------
# strings (fixed-width byte kernels; ref src/expr/impl/src/scalar/)

@function("concat(stringlike, stringlike) -> character varying")
def _concat(a: StrCol, b: StrCol):
    wa, wb = a.data.shape[1], b.data.shape[1]
    w = wa + wb
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    # bytes: a's first len_a bytes, then b's bytes shifted to len_a
    from_a = idx < a.lens[:, None]
    b_pos = jnp.clip(idx - a.lens[:, None], 0, wb - 1)
    a_pos = jnp.clip(idx, 0, wa - 1)
    data = jnp.where(
        from_a,
        jnp.take_along_axis(a.data, a_pos, axis=1),
        jnp.take_along_axis(b.data, b_pos, axis=1),
    )
    lens = a.lens + b.lens
    in_range = idx < lens[:, None]
    return StrCol(jnp.where(in_range, data, 0).astype(jnp.uint8), lens)


def _substr_window(a: StrCol, start, count=None):
    """Postgres window semantics: the count window starts at the GIVEN
    (possibly <=0) position; e.g. substr('hello', -1, 3) = 'h'."""
    w = a.data.shape[1]
    idx = jnp.arange(w, dtype=jnp.int64)[None, :]
    s0 = start.astype(jnp.int64) - 1                      # 0-based, may be <0
    if count is None:
        end = jnp.full_like(s0, w)
    else:
        end = s0 + jnp.maximum(count.astype(jnp.int64), 0)
    lo = jnp.maximum(s0, 0)
    hi = jnp.minimum(end, a.lens.astype(jnp.int64))
    lens = jnp.maximum(hi - lo, 0).astype(jnp.int32)
    src = jnp.clip(idx + lo[:, None], 0, w - 1)
    data = jnp.take_along_axis(a.data, src.astype(jnp.int32), axis=1)
    keep = idx < lens[:, None]
    return StrCol(jnp.where(keep, data, 0).astype(jnp.uint8), lens)


@function("substr(stringlike, int) -> same")
@function("substr(stringlike, bigint) -> same")
def _substr2(a: StrCol, start):
    return _substr_window(a, start)


@function("substr(stringlike, int, int) -> same")
@function("substr(stringlike, bigint, bigint) -> same")
def _substr3(a: StrCol, start, count):
    return _substr_window(a, start, count)


def _trim_side(a: StrCol, left: bool, right: bool) -> StrCol:
    w = a.data.shape[1]
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    in_str = idx < a.lens[:, None]
    is_sp = (a.data == ord(" ")) & in_str
    nonsp = in_str & ~is_sp
    any_nonsp = jnp.any(nonsp, axis=1)
    first = jnp.argmax(nonsp, axis=1).astype(jnp.int32)
    last = (w - 1 - jnp.argmax(nonsp[:, ::-1], axis=1)).astype(jnp.int32)
    s0 = jnp.where(any_nonsp, first if left else 0, 0)
    e0 = jnp.where(any_nonsp, (last + 1) if right else a.lens, 0)
    lens = jnp.maximum(e0 - s0, 0)
    src = jnp.clip(idx + s0[:, None], 0, w - 1)
    data = jnp.take_along_axis(a.data, src, axis=1)
    return StrCol(
        jnp.where(idx < lens[:, None], data, 0).astype(jnp.uint8), lens
    )


@function("trim(stringlike) -> same")
def _trim(a: StrCol):
    return _trim_side(a, True, True)


@function("ltrim(stringlike) -> same")
def _ltrim(a: StrCol):
    return _trim_side(a, True, False)


@function("rtrim(stringlike) -> same")
def _rtrim(a: StrCol):
    return _trim_side(a, False, True)


def _match_at(a: StrCol, pat: StrCol, offsets: jnp.ndarray) -> jnp.ndarray:
    """[cap, n_off] bool: pattern matches a at each byte offset."""
    wa, wp = a.data.shape[1], pat.data.shape[1]
    j = jnp.arange(wp, dtype=jnp.int32)
    src = offsets[:, :, None] + j[None, None, :]          # [cap, off, wp]
    src_c = jnp.clip(src, 0, wa - 1)
    got = jnp.take_along_axis(
        a.data[:, None, :], src_c, axis=2
    )                                                     # [cap, off, wp]
    want = pat.data[:, None, :]
    in_pat = j[None, None, :] < pat.lens[:, None, None]
    in_str = src < a.lens[:, None, None]
    ok = jnp.where(in_pat, (got == want) & in_str, True)
    return jnp.all(ok, axis=2)


@function("starts_with(stringlike, stringlike) -> boolean")
def _starts_with(a: StrCol, p: StrCol):
    return _match_at(a, p, jnp.zeros((a.data.shape[0], 1), jnp.int32))[:, 0] \
        & (p.lens <= a.lens)


@function("ends_with(stringlike, stringlike) -> boolean")
def _ends_with(a: StrCol, p: StrCol):
    off = (a.lens - p.lens)[:, None]
    ok = _match_at(a, p, jnp.maximum(off, 0))[:, 0]
    return ok & (p.lens <= a.lens)


@function("contains(stringlike, stringlike) -> boolean")
def _contains(a: StrCol, p: StrCol):
    wa = a.data.shape[1]
    offs = jnp.broadcast_to(
        jnp.arange(wa, dtype=jnp.int32)[None, :], (a.data.shape[0], wa)
    )
    hits = _match_at(a, p, offs)
    valid_off = offs <= (a.lens - p.lens)[:, None]
    return jnp.any(hits & valid_off, axis=1) & (p.lens <= a.lens)


@function("octet_length(stringlike) -> int")
def _octet_length(a: StrCol):
    return a.lens


# ---------------------------------------------------------------------------
# calendar (proleptic Gregorian; Howard Hinnant's civil_from_days,
# vectorized over int64 microsecond timestamps)

def _civil_from_ts(us: jnp.ndarray):
    days = us // 86_400_000_000
    z = days + 719468
    era = z // 146097  # // floors, so no negative-z correction needed
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


for _part in ("year", "month", "day", "hour", "minute", "second",
              "dow", "doy"):

    def _mk_extract(part):
        def impl(ts):
            if part in ("year", "month", "day", "dow", "doy"):
                y, m, d = _civil_from_ts(ts)
                if part == "year":
                    return y
                if part == "month":
                    return m
                if part == "day":
                    return d
                days = ts // 86_400_000_000
                if part == "dow":
                    return (days + 4) % 7  # 1970-01-01 was a Thursday
                # day-of-year = date - Jan1 + 1; Jan 1 via the inverse
                # civil mapping
                yy = y - 1
                days_jan1 = (
                    yy * 365 + yy // 4 - yy // 100 + yy // 400
                ) - 719162
                return (days - days_jan1 + 1).astype(jnp.int64)
            us_in_day = ts % 86_400_000_000
            if part == "hour":
                return us_in_day // 3_600_000_000
            if part == "minute":
                return (us_in_day // 60_000_000) % 60
            return (us_in_day // 1_000_000) % 60

        return impl

    function(f"extract_{_part}(timestamp) -> bigint")(_mk_extract(_part))
    function(f"extract_{_part}(timestamptz) -> bigint")(_mk_extract(_part))

    def _mk_extract_date(part):
        inner = _mk_extract(part)

        def impl(d):
            # DATE is i32 days since epoch; reuse the civil mapping
            return inner(d.astype(jnp.int64) * 86_400_000_000)

        return impl

    function(f"extract_{_part}(date) -> bigint")(_mk_extract_date(_part))


@function("length(stringlike) -> int")
def _length(a: StrCol):
    # byte length (see char_length note)
    return a.lens


def _greedy_starts(a: StrCol, p: StrCol) -> jnp.ndarray:
    """[cap, wa] bool: non-overlapping leftmost-first match starts of
    ``p`` in ``a`` (the scan PG string functions use: after a match the
    cursor jumps past it)."""
    import jax

    cap, wa = a.data.shape
    offs = jnp.broadcast_to(jnp.arange(wa, dtype=jnp.int32)[None, :],
                            (cap, wa))
    hits = _match_at(a, p, offs)
    hits = hits & (offs <= (a.lens - p.lens)[:, None]) & (p.lens > 0)[:, None]

    def step(next_ok, hit_b):
        b, hit = hit_b
        sel = hit & (b >= next_ok)
        return jnp.where(sel, b + p.lens, next_ok), sel

    _, sels = jax.lax.scan(
        step,
        jnp.zeros((cap,), jnp.int32),
        (jnp.arange(wa, dtype=jnp.int32), hits.T),
    )
    return sels.T


def _cover_mask(sel: jnp.ndarray, span_lens: jnp.ndarray) -> jnp.ndarray:
    """[cap, wa] bool: bytes covered by [start, start+len) spans."""
    cap, wa = sel.shape
    rows = jnp.broadcast_to(jnp.arange(cap)[:, None], (cap, wa))
    cols = jnp.broadcast_to(jnp.arange(wa)[None, :], (cap, wa))
    delta = jnp.zeros((cap, wa + 1), jnp.int32)
    delta = delta.at[rows, cols].add(sel.astype(jnp.int32))
    ends = jnp.clip(cols + span_lens[:, None], 0, wa)
    delta = delta.at[rows, ends].add(jnp.where(sel, -1, 0))
    return jnp.cumsum(delta[:, :wa], axis=1) > 0


@function("split_part(stringlike, stringlike, int) -> same")
@function("split_part(stringlike, stringlike, bigint) -> same")
def _split_part(a: StrCol, delim: StrCol, n):
    """Ref: src/expr/impl/src/scalar/split_part.rs (1-based; negative
    counts from the end; out-of-range -> empty)."""
    cap, wa = a.data.shape
    sel = _greedy_starts(a, delim)
    in_delim = _cover_mask(sel, delim.lens)
    cols = jnp.broadcast_to(jnp.arange(wa, dtype=jnp.int32)[None, :],
                            (cap, wa))
    # part index of each byte = delimiters fully ended at or before it
    part_id = jnp.cumsum(sel.astype(jnp.int32), axis=1) - sel
    n_parts = jnp.sum(sel.astype(jnp.int32), axis=1) + 1
    n = n.astype(jnp.int32)
    target = jnp.where(n > 0, n - 1, n_parts + n)
    keep = (part_id == target[:, None]) & ~in_delim \
        & (cols < a.lens[:, None])
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - keep
    rows = jnp.broadcast_to(jnp.arange(cap)[:, None], (cap, wa))
    out = jnp.zeros((cap, wa), jnp.uint8)
    out = out.at[rows, jnp.where(keep, pos, wa)].set(a.data, mode="drop")
    return StrCol(out, jnp.sum(keep, axis=1).astype(jnp.int32))


@function("replace(stringlike, stringlike, stringlike) -> same")
def _replace(a: StrCol, frm: StrCol, to: StrCol):
    """Ref: src/expr/impl/src/scalar/replace.rs.  Output is clamped to
    the input column's device width: growth past it (replacement longer
    than the match at a near-full string) truncates — benchmark usage
    (char removal / same-length swaps) is exact."""
    cap, wa = a.data.shape
    sel = _greedy_starts(a, frm)
    in_from = _cover_mask(sel, frm.lens)
    cols = jnp.broadcast_to(jnp.arange(wa, dtype=jnp.int32)[None, :],
                            (cap, wa))
    rows = jnp.broadcast_to(jnp.arange(cap)[:, None], (cap, wa))
    in_str = cols < a.lens[:, None]
    emit = jnp.where(sel, to.lens[:, None],
                     jnp.where(in_from | ~in_str, 0, 1)).astype(jnp.int32)
    start = jnp.cumsum(emit, axis=1) - emit      # exclusive prefix
    out_len = jnp.minimum(start[:, -1] + emit[:, -1], wa)
    out = jnp.zeros((cap, wa), jnp.uint8)
    # pass-through bytes: one scatter
    normal = in_str & ~in_from
    out = out.at[rows, jnp.where(normal, start, wa)].set(
        a.data, mode="drop"
    )
    # replacement spans: output-space cover + forward-filled span base
    out_sel = jnp.zeros((cap, wa), jnp.bool_)
    out_sel = out_sel.at[rows, jnp.where(sel, start, wa)].set(
        True, mode="drop"
    )
    # base of the covering span / its to-length, forward-filled
    import jax
    base = jax.lax.cummax(jnp.where(out_sel, cols, -1), axis=1)
    in_to = _cover_mask(
        out_sel,
        # per-row constant to-length applies at every span start
        to.lens,
    )
    off = jnp.clip(cols - base, 0, to.data.shape[1] - 1)
    to_bytes = jnp.take_along_axis(to.data, off, axis=1)
    out = jnp.where(in_to & (base >= 0), to_bytes, out)
    return StrCol(jnp.where(cols < out_len[:, None], out, 0), out_len)


# ---------------------------------------------------------------------------
# to_char (ref src/expr/impl/src/scalar/to_char.rs: PG patterns compiled
# once per literal format — here at BIND time, so the kernel is a pure
# fixed-width byte construction and the output StrCol width is static)

_TO_CHAR_FIELDS = {
    # pattern -> (component, digit width); longest-first matching
    "HH24": ("hour24", 2), "hh24": ("hour24", 2),
    "HH12": ("hour12", 2), "hh12": ("hour12", 2),
    "YYYY": ("year", 4), "yyyy": ("year", 4),
    "AM": ("meridiem_upper", 2), "PM": ("meridiem_upper", 2),
    "am": ("meridiem_lower", 2), "pm": ("meridiem_lower", 2),
    "HH": ("hour12", 2), "hh": ("hour12", 2),
    "MI": ("minute", 2), "mi": ("minute", 2),
    "SS": ("second", 2), "ss": ("second", 2),
    "YY": ("year2", 2), "yy": ("year2", 2),
    "MM": ("month", 2), "mm": ("month", 2),
    "DD": ("day", 2), "dd": ("day", 2),
    "MS": ("milli", 3), "ms": ("milli", 3),
    "US": ("micro", 6), "us": ("micro", 6),
}


def compile_to_char_pattern(fmt: str) -> list:
    """[(kind, payload)]: ("lit", bytes) | ("field", (component, width))."""
    segs: list = []
    i = 0
    keys = sorted(_TO_CHAR_FIELDS, key=len, reverse=True)
    lit: list[int] = []
    while i < len(fmt):
        hit = next((k for k in keys if fmt.startswith(k, i)), None)
        if hit is None:
            lit.extend(fmt[i].encode("utf-8"))
            i += 1
            continue
        if lit:
            segs.append(("lit", bytes(lit)))
            lit = []
        segs.append(("field", _TO_CHAR_FIELDS[hit]))
        i += len(hit)
    if lit:
        segs.append(("lit", bytes(lit)))
    return segs


def eval_to_char(ts: jnp.ndarray, segs: list) -> StrCol:
    """Format int64-us timestamps by a compiled pattern; fixed width."""
    cap = ts.shape[0]
    y, m, d = _civil_from_ts(ts)
    us_in_day = ts % 86_400_000_000
    comp = {
        "year": y, "year2": y % 100, "month": m, "day": d,
        "hour24": us_in_day // 3_600_000_000,
        "minute": (us_in_day // 60_000_000) % 60,
        "second": (us_in_day // 1_000_000) % 60,
        "milli": (us_in_day // 1_000) % 1000,
        "micro": us_in_day % 1_000_000,
    }
    comp["hour12"] = (comp["hour24"] + 11) % 12 + 1
    parts = []
    width = 0
    for kind, payload in segs:
        if kind == "lit":
            arr = jnp.broadcast_to(
                jnp.asarray(np.frombuffer(payload, np.uint8)),
                (cap, len(payload)),
            )
            parts.append(arr)
            width += len(payload)
            continue
        name, w = payload
        if name.startswith("meridiem"):
            is_pm = comp["hour24"] >= 12
            a, p = (b"AM", b"PM") if name.endswith("upper") \
                else (b"am", b"pm")
            arr = jnp.where(
                is_pm[:, None],
                jnp.asarray(np.frombuffer(p, np.uint8))[None, :],
                jnp.asarray(np.frombuffer(a, np.uint8))[None, :],
            )
            parts.append(jnp.broadcast_to(arr, (cap, 2)))
            width += 2
            continue
        v = comp[name].astype(jnp.int64)
        digits = [
            (v // (10 ** (w - 1 - j))) % 10 + np.uint8(ord("0"))
            for j in range(w)
        ]
        parts.append(jnp.stack(digits, axis=1).astype(jnp.uint8))
        width += w
    return StrCol(
        jnp.concatenate(parts, axis=1),
        jnp.full((cap,), width, jnp.int32),
    )


class LikePattern(Expr):
    """General %-wildcard LIKE, compiled at bind time.

    Ref: src/expr/impl/src/scalar/like.rs — the reference walks a
    byte-DP; for '%'-only patterns leftmost-greedy sequential segment
    search is equivalent and vectorizes: each literal segment takes one
    ``_match_at`` scan over all offsets, with the running cursor
    enforcing order.  '_' wildcards remain unsupported (parser/binder
    reject them)."""

    def __init__(self, arg: Expr, pattern: str):
        if "_" in pattern:
            raise ValueError("LIKE '_' wildcards not supported")
        self.arg = arg
        self.pattern = pattern
        self.segs = [s for s in pattern.split("%") if s != ""]
        self.anchor_start = not pattern.startswith("%")
        self.anchor_end = not pattern.endswith("%")

    def return_field(self, schema) -> Field:
        f = self.arg.return_field(schema)
        return Field("like", DataType.BOOLEAN, nullable=f.nullable)

    def return_type(self, schema):
        return DataType.BOOLEAN

    def _const(self, seg: str, cap: int) -> StrCol:
        from risingwave_tpu.common.chunk import encode_strings
        b = seg.encode("utf-8")
        data, lens = encode_strings([seg], max(len(b), 1))
        return StrCol(
            jnp.broadcast_to(jnp.asarray(data[0]), (cap, data.shape[1])),
            jnp.broadcast_to(jnp.asarray(lens[0]), (cap,)),
        )

    def eval(self, chunk):
        a, null = split_col(self.arg.eval(chunk))
        cap, wa = a.data.shape
        segs = self.segs
        if not segs:  # '%', '%%', ... — everything matches
            return make_col(jnp.ones((cap,), jnp.bool_), null)
        if len(segs) == 1 and self.anchor_start and self.anchor_end:
            pat = self._const(segs[0], cap)
            ok = _match_at(
                a, pat, jnp.zeros((cap, 1), jnp.int32)
            )[:, 0] & (a.lens == pat.lens)
            return make_col(ok, null)
        ok = jnp.ones((cap,), jnp.bool_)
        pos = jnp.zeros((cap,), jnp.int32)
        offs_all = jnp.broadcast_to(
            jnp.arange(wa, dtype=jnp.int32)[None, :], (cap, wa)
        )
        for k, seg in enumerate(segs):
            pat = self._const(seg, cap)
            if k == 0 and self.anchor_start:
                ok &= _match_at(
                    a, pat, jnp.zeros((cap, 1), jnp.int32)
                )[:, 0] & (pat.lens <= a.lens)
                pos = pat.lens.astype(jnp.int32)
                continue
            if k == len(segs) - 1 and self.anchor_end:
                off = a.lens - pat.lens
                ok &= _match_at(
                    a, pat, jnp.maximum(off, 0)[:, None]
                )[:, 0] & (off >= pos)
                continue
            hits = _match_at(a, pat, offs_all) \
                & (offs_all >= pos[:, None]) \
                & (offs_all <= (a.lens - pat.lens)[:, None])
            ok &= jnp.any(hits, axis=1)
            first = jnp.argmax(hits, axis=1).astype(jnp.int32)
            pos = first + pat.lens
        return make_col(ok, null)

    def __repr__(self):
        return f"like({self.arg!r}, {self.pattern!r})"


class ToChar(Expr):
    """Bound to_char(ts, 'literal fmt') expression node."""

    def __init__(self, arg: Expr, fmt: str):
        self.arg = arg
        self.fmt = fmt
        self.segs = compile_to_char_pattern(fmt)
        self.width = sum(
            len(p) if k == "lit" else p[1] for k, p in self.segs
        )

    def return_field(self, schema) -> Field:
        f = self.arg.return_field(schema)
        return Field("to_char", DataType.VARCHAR,
                     str_width=max(self.width, 1), nullable=f.nullable)

    def return_type(self, schema):
        return DataType.VARCHAR

    def eval(self, chunk):
        col, null = split_col(self.arg.eval(chunk))
        out = eval_to_char(col, self.segs)
        return make_col(out, null)

    def __repr__(self):
        return f"to_char({self.arg!r}, {self.fmt!r})"


# ---------------------------------------------------------------------------
# regexp_match (restricted pattern family, compiled at bind time)

_RX_FAMILY = re.compile(
    # (&|^) prefix-guard, a literal, then a ([^X]*) capture
    r"^(?:\((?P<guard>[^)|])\|\^\)|\(\^\|(?P<guard2>[^)|])\))?"
    r"(?P<lit>[A-Za-z0-9_=:/.\-]+)"
    r"\(\[\^(?P<stop>.)\]\*\)$"
)


class RegexpGroup(Expr):
    """``(regexp_match(s, 'pat'))[n]`` for the benchmark pattern family
    ``(&|^)literal([^X]*)``: the n-th capture (n=2 → the [^X]* run
    after the literal, anchored at start or after the guard char).

    Ref: src/expr/impl/src/scalar/regexp.rs — full regexes run a
    backtracking engine; this subset compiles to fixed-width byte
    kernels (match scan + bounded take), NULL when unmatched."""

    def __init__(self, arg: Expr, pattern: str, group: int):
        m = _RX_FAMILY.match(pattern)
        if m is None:
            raise ValueError(
                f"regexp_match pattern {pattern!r} outside the "
                "supported (&|^)literal([^X]*) family"
            )
        if group != 2:
            raise ValueError("only capture group [2] is supported")
        self.arg = arg
        self.pattern = pattern
        self.guard = m.group("guard") or m.group("guard2")
        self.lit = m.group("lit")
        self.stop = m.group("stop")

    def return_field(self, schema) -> Field:
        f = self.arg.return_field(schema)
        return Field("regexp_match", DataType.VARCHAR,
                     str_width=f.str_width, nullable=True)

    def return_type(self, schema):
        return DataType.VARCHAR

    def eval(self, chunk):
        from risingwave_tpu.common.chunk import NCol, encode_strings

        s, s_null = split_col(self.arg.eval(chunk))
        cap, w = s.data.shape
        ld, ll = encode_strings([self.lit], max(len(self.lit), 1))
        lit = StrCol(
            jnp.broadcast_to(jnp.asarray(ld[0]), (cap, ld.shape[1])),
            jnp.broadcast_to(jnp.asarray(ll[0]), (cap,)),
        )
        offs = jnp.broadcast_to(
            jnp.arange(w, dtype=jnp.int32)[None, :], (cap, w)
        )
        hits = _match_at(s, lit, offs) & (
            offs <= (s.lens - len(self.lit))[:, None]
        )
        if self.guard is not None:
            prev_idx = jnp.clip(offs - 1, 0, w - 1)
            prev = jnp.take_along_axis(s.data, prev_idx, axis=1)
            guarded = (offs == 0) | (prev == ord(self.guard))
            hits = hits & guarded
        found = jnp.any(hits, axis=1)
        first = jnp.argmax(hits, axis=1).astype(jnp.int32)
        start = first + len(self.lit)
        # capture runs until the stop char (or end of string)
        idx = jnp.arange(w, dtype=jnp.int32)[None, :]
        src = jnp.clip(idx + start[:, None], 0, w - 1)
        shifted = jnp.take_along_axis(s.data, src, axis=1)
        in_str = (idx + start[:, None]) < s.lens[:, None]
        is_stop = (shifted == ord(self.stop)) & in_str
        # length = first stop position (or remaining length)
        any_stop = jnp.any(is_stop, axis=1)
        stop_at = jnp.argmax(is_stop, axis=1).astype(jnp.int32)
        lens = jnp.where(
            any_stop, stop_at,
            jnp.maximum(s.lens - start, 0),
        )
        lens = jnp.where(found, jnp.maximum(lens, 0), 0)
        data = jnp.where(idx < lens[:, None], shifted, 0).astype(jnp.uint8)
        null = ~found if s_null is None else (~found | s_null)
        return NCol(StrCol(data, lens), null)

    def __repr__(self):
        return f"regexp_match({self.arg!r}, {self.pattern!r})[2]"
