"""Scalar function implementations (vectorized, trace-friendly).

Reference counterpart: ``src/expr/impl/src/scalar/`` (90 files of
``#[function]`` impls).  Coverage here targets the benchmark SQL surface
(Nexmark q0-q10, TPC-H arithmetic/predicates) and grows with the planner.

All impls take and return whole device columns.  Mixed numeric arg types
are promoted via implicit casts inserted at resolution time (the impls
that need logical-type context declare a trailing ``fields`` kwarg).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import StrCol
from risingwave_tpu.common.types import (
    DEFAULT_DECIMAL_SCALE,
    DataType,
    Field,
)
from risingwave_tpu.expr.registry import function, promote_numeric

_SCALE = 10**DEFAULT_DECIMAL_SCALE

# ---------------------------------------------------------------------------
# casts / coercion


def coerce(col, field: Field, target: DataType):
    """Cast a device column from its logical type to ``target``."""
    t = field.data_type
    if t == target and not (
        t == DataType.DECIMAL and field.decimal_scale != DEFAULT_DECIMAL_SCALE
    ):
        return col
    if isinstance(col, StrCol):
        raise TypeError(f"cannot cast string column to {target}")
    if t == DataType.DECIMAL:
        if target == DataType.DECIMAL:
            # rescale a non-default-scale column to the engine scale so
            # downstream arithmetic (which assumes _SCALE) is correct
            diff = DEFAULT_DECIMAL_SCALE - field.decimal_scale
            if diff > 0:
                return col * (10**diff)
            return col // (10 ** (-diff))
        if target in (DataType.FLOAT32, DataType.FLOAT64):
            return (col.astype(target.physical_dtype)) / np.float64(
                10**field.decimal_scale
            ).astype(target.physical_dtype)
        if target.is_integral and target != DataType.DECIMAL:
            return (col // (10**field.decimal_scale)).astype(target.physical_dtype)
        raise TypeError(f"decimal -> {target}?")
    if target == DataType.DECIMAL:
        if t.is_integral:
            return col.astype(jnp.int64) * _SCALE
        # float -> decimal: round at the default scale
        return jnp.round(col.astype(jnp.float64) * _SCALE).astype(jnp.int64)
    if target == DataType.BOOLEAN:
        return col != 0
    return col.astype(target.physical_dtype)


for _t in (
    DataType.INT16,
    DataType.INT32,
    DataType.INT64,
    DataType.FLOAT32,
    DataType.FLOAT64,
    DataType.DECIMAL,
    DataType.BOOLEAN,
    DataType.TIMESTAMP,
    DataType.TIMESTAMPTZ,
    DataType.DATE,
):

    def _mk_cast(target: DataType):
        def _cast(a, fields: Sequence[Field]):
            return coerce(a, fields[0], target)

        return _cast

    function(f"cast_{_t.name.lower()}(any) -> {_t.value}")(_mk_cast(_t))


def _promote_args(cols, fields: Sequence[Field]) -> tuple[list, DataType]:
    target = promote_numeric([f.data_type for f in fields])
    return [coerce(c, f, target) for c, f in zip(cols, fields)], target


# ---------------------------------------------------------------------------
# arithmetic (decimal-aware)


@function("add(numeric, numeric) -> auto")
def _add(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return a + b


@function("subtract(numeric, numeric) -> auto")
def _sub(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return a - b


@function("subtract(timelike, timelike) -> interval")
def _sub_time(a, b):
    return (a - b).astype(jnp.int64)


@function("add(timestamp, interval) -> timestamp")
@function("add(timestamptz, interval) -> timestamptz")
def _add_ts_iv(a, b):
    return a + b


@function("subtract(timestamp, interval) -> timestamp")
@function("subtract(timestamptz, interval) -> timestamptz")
def _sub_ts_iv(a, b):
    return a - b


@function("multiply(numeric, numeric) -> auto")
def _mul(a, b, fields: Sequence[Field]):
    (a, b), t = _promote_args((a, b), fields)
    if t == DataType.DECIMAL:
        # via float64: raw int64 products overflow for realistic
        # magnitudes (scaled 10^6 operands); float64 keeps ~15-16
        # significant digits, which covers the SQL numeric surface here
        prod = a.astype(jnp.float64) * b.astype(jnp.float64) / _SCALE
        return jnp.round(prod).astype(jnp.int64)
    return a * b


@function("divide(numeric, numeric) -> auto")
def _div(a, b, fields: Sequence[Field]):
    (a, b), t = _promote_args((a, b), fields)
    if t == DataType.DECIMAL:
        q = a.astype(jnp.float64) / jnp.where(b == 0, 1, b).astype(jnp.float64)
        return jnp.where(
            b != 0, jnp.round(q * _SCALE).astype(jnp.int64), 0
        )
    if jnp.issubdtype(a.dtype, jnp.integer):
        return jnp.where(b != 0, a // jnp.where(b == 0, 1, b), 0)
    return a / b


@function("modulus(numeric, numeric) -> auto")
def _mod(a, b, fields: Sequence[Field]):
    (a, b), _ = _promote_args((a, b), fields)
    return jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0)


@function("neg(numeric) -> same")
def _neg(a):
    return -a


@function("abs(numeric) -> same")
def _abs(a):
    return jnp.abs(a)


@function("round(floatlike) -> same")
def _round(a):
    return jnp.round(a)


@function("round(numeric) -> same")
def _round_dec(a, fields: Sequence[Field]):
    if fields[0].data_type == DataType.DECIMAL:
        s = 10**fields[0].decimal_scale
        return (a + s // 2) // s * s
    return jnp.round(a)


# ---------------------------------------------------------------------------
# comparison


def _cmp_strs(a: StrCol, b: StrCol):
    """Return (first-diff a byte, first-diff b byte) as int16 with -1 EOS."""
    wa, wb = a.data.shape[1], b.data.shape[1]
    w = max(wa, wb)
    ad = jnp.pad(a.data, ((0, 0), (0, w - wa))).astype(jnp.int16)
    bd = jnp.pad(b.data, ((0, 0), (0, w - wb))).astype(jnp.int16)
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    av = jnp.where(idx < a.lens[:, None], ad, jnp.int16(-1))
    bv = jnp.where(idx < b.lens[:, None], bd, jnp.int16(-1))
    return av, bv


def _make_cmp(name: str, op, str_op):
    @function(f"{name}(numeric, numeric) -> boolean")
    def _cmp(a, b, fields: Sequence[Field]):
        (a, b), _ = _promote_args((a, b), fields)
        return op(a, b)

    @function(f"{name}(timelike, timelike) -> boolean")
    @function(f"{name}(boolean, boolean) -> boolean")
    def _cmp_t(a, b):
        return op(a, b)

    @function(f"{name}(stringlike, stringlike) -> boolean")
    def _cmp_s(a: StrCol, b: StrCol):
        av, bv = _cmp_strs(a, b)
        if str_op == "eq":
            return jnp.all(av == bv, axis=1)
        if str_op == "ne":
            return jnp.any(av != bv, axis=1)
        neq = av != bv
        any_neq = jnp.any(neq, axis=1)
        first = jnp.argmax(neq, axis=1)
        fa = jnp.take_along_axis(av, first[:, None], axis=1)[:, 0]
        fb = jnp.take_along_axis(bv, first[:, None], axis=1)[:, 0]
        lt = fa < fb
        if str_op == "lt":
            return any_neq & lt
        if str_op == "le":
            return ~any_neq | lt
        if str_op == "gt":
            return any_neq & ~lt
        return ~any_neq | ~lt  # ge

    return _cmp


_make_cmp("equal", lambda a, b: a == b, "eq")
_make_cmp("not_equal", lambda a, b: a != b, "ne")
_make_cmp("less_than", lambda a, b: a < b, "lt")
_make_cmp("less_than_or_equal", lambda a, b: a <= b, "le")
_make_cmp("greater_than", lambda a, b: a > b, "gt")
_make_cmp("greater_than_or_equal", lambda a, b: a >= b, "ge")


# ---------------------------------------------------------------------------
# logical


@function("and(boolean, boolean) -> boolean")
def _and(a, b):
    return a & b


@function("or(boolean, boolean) -> boolean")
def _or(a, b):
    return a | b


@function("not(boolean) -> boolean")
def _not(a):
    return ~a


@function("case(boolean, any, any) -> same_branch")  # CASE WHEN c THEN t ELSE e
def _case(c, t, e, fields: Sequence[Field]):
    if isinstance(t, StrCol):
        w = max(t.data.shape[1], e.data.shape[1])
        td = jnp.pad(t.data, ((0, 0), (0, w - t.data.shape[1])))
        ed = jnp.pad(e.data, ((0, 0), (0, w - e.data.shape[1])))
        return StrCol(
            jnp.where(c[:, None], td, ed), jnp.where(c, t.lens, e.lens)
        )
    if fields[1].data_type != fields[2].data_type:
        target = promote_numeric([fields[1].data_type, fields[2].data_type])
        t = coerce(t, fields[1], target)
        e = coerce(e, fields[2], target)
    return jnp.where(c, t, e)


# ---------------------------------------------------------------------------
# temporal

_US = {"second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000,
       "day": 86_400_000_000}


# microsecond-based temporal fns: registered for the microsecond-backed
# types only (DATE is i32 days and must not match these overloads)
@function("extract_epoch(timestamp) -> bigint")
@function("extract_epoch(timestamptz) -> bigint")
def _extract_epoch(a):
    return a // 1_000_000


@function("extract_epoch(date) -> bigint")
def _extract_epoch_date(a):
    return a.astype(jnp.int64) * 86_400


def _us_trunc(unit: str):
    def impl(a):
        return a - a % _US[unit]

    return impl


for _unit in ("second", "minute", "hour", "day"):
    _impl = _us_trunc(_unit)
    function(f"date_trunc_{_unit}(timestamp) -> same")(_impl)
    function(f"date_trunc_{_unit}(timestamptz) -> same")(_impl)


@function("tumble_start(timestamp, interval) -> same")
@function("tumble_start(timestamptz, interval) -> same")
def _tumble_start(ts, size):
    return ts - ts % size


# ---------------------------------------------------------------------------
# string


@function("char_length(stringlike) -> int")
def _char_length(a: StrCol):
    # note: byte length; full utf-8 codepoint counting is a host fallback
    return a.lens


@function("lower(stringlike) -> same")
def _lower(a: StrCol):
    up = (a.data >= ord("A")) & (a.data <= ord("Z"))
    return StrCol(jnp.where(up, a.data + 32, a.data), a.lens)


@function("upper(stringlike) -> same")
def _upper(a: StrCol):
    lo = (a.data >= ord("a")) & (a.data <= ord("z"))
    return StrCol(jnp.where(lo, a.data - 32, a.data), a.lens)
