"""Trace-lite: epoch-scoped distributed tracing flight recorder.

Reference counterpart: ``await-tree`` / the embedded tracing RisingWave
ships for barrier attribution (``src/common/src/util/epoch.rs`` epochs
plus the meta dashboard's per-actor traces), scaled down to the same
stdlib-only discipline as ``common/metrics.py``: every process keeps a
bounded ring buffer of completed spans (a *flight recorder* — old
spans fall off, nothing blocks, nothing is ever written on the hot
path unless tracing is on), and the meta assembles the cluster-wide
view on demand by pulling each peer's buffer over ``rpc_trace_dump``.

Model
-----
- A **trace** is one cluster round: ``trace_id = "round-<N>"`` where
  ``N`` is the global cluster epoch the round commits.  The meta opens
  the root span; the context ``(trace_id, span_id)`` rides RPC frames
  (a top-level ``"trace"`` key, outside ``params``) so worker/uploader
  /serving spans parent correctly across processes.
- A **span** is a finished interval: dict with ``trace_id``,
  ``span_id`` (``"<role>:<n>"`` — unique cluster-wide without
  coordination), ``parent_id``, ``role``, ``name``, ``ts`` (wall
  seconds), ``dur`` (seconds), ``attrs``, ``thread``.  Only completed
  spans enter the ring: a SIGKILL loses at most the spans in flight,
  and the survivors still parse (satellite: truncated-but-parseable).
- **Overhead contract**: ``sample_n == 0`` disables tracing — `span()`
  returns a module-level null singleton (zero allocations, no clock
  reads) and ``sampled_span()`` likewise.  ``sample_n >= 1`` records
  every control-plane span (rounds are low-rate) and 1-in-N
  data-plane spans (serving reads, compact/scrub cycles).  Nothing in
  here touches jax or a device: timing is wall-clock only, so a span
  around a dispatch measures the host-side call, never forces a sync.
- **Determinism under retries**: spans are recorded where the work
  runs.  A round-tagged barrier retry that answers from the worker's
  round cache re-runs no chunks and records no spans — one span tree
  per round by construction (the meta-side barrier-unit span carries
  an ``attempts`` attr instead).
"""

from __future__ import annotations

import itertools
import threading
import time


class _NullSpan:
    """Tracing disabled / unsampled: a shared, allocation-free no-op.
    Also what ``span()`` hands out mid-tree when the recorder is off,
    so call sites never branch."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self

    @property
    def ctx(self):
        return None


NULL_SPAN = _NullSpan()


class _Span:
    """One in-flight span; records itself into the ring on exit."""

    __slots__ = ("_rec", "trace_id", "span_id", "parent_id", "name",
                 "attrs", "_t0", "_ts", "_pushed")

    def __init__(self, rec, trace_id, span_id, parent_id, name, attrs):
        self._rec = rec
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._ts = 0.0
        self._pushed = False

    @property
    def ctx(self) -> tuple:
        """The (trace_id, span_id) pair to hand to children — RPC
        frames, cross-thread closures, UploadTask fields."""
        return (self.trace_id, self.span_id)

    def set(self, **attrs) -> "_Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_Span":
        self._ts = time.time()
        self._t0 = time.perf_counter()
        stack = self._rec._stack()
        stack.append((self.trace_id, self.span_id))
        self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        if self._pushed:
            stack = self._rec._stack()
            if stack and stack[-1] == (self.trace_id, self.span_id):
                stack.pop()
            self._pushed = False
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._rec._record(self, dur)
        return False


class SpanRecorder:
    """Per-process bounded span ring + thread-local trace context."""

    def __init__(self, role: str = "proc", sample_n: int = 1,
                 capacity: int = 4096):
        self.role = role
        self.sample_n = sample_n
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: list = []
        self._head = 0
        self._ids = itertools.count(1)
        self._sample_ctr = itertools.count()
        self._tls = threading.local()

    def configure(self, role: str | None = None,
                  sample_n: int | None = None,
                  capacity: int | None = None) -> "SpanRecorder":
        if role is not None:
            self.role = role
        if sample_n is not None:
            self.sample_n = sample_n
        if capacity is not None and capacity != self.capacity:
            with self._lock:
                self.capacity = capacity
                self._ring = self._snapshot_locked()[-capacity:]
                self._head = 0
        return self

    @property
    def enabled(self) -> bool:
        return self.sample_n > 0

    # -- context ---------------------------------------------------------
    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def current(self) -> tuple | None:
        """The active (trace_id, span_id) on THIS thread, or None."""
        s = getattr(self._tls, "stack", None)
        return s[-1] if s else None

    def activate(self, ctx) -> "_CtxGuard | _NullSpan":
        """Adopt a remote context (an RPC frame's ``trace`` key) for
        the current thread.  No span is recorded — children attach."""
        if not self.enabled or not ctx:
            return NULL_SPAN
        return _CtxGuard(self, (ctx[0], ctx[1]))

    # -- span creation ---------------------------------------------------
    def span(self, name: str, ctx: tuple | None = None,
             trace_id: str | None = None, **attrs):
        """Open a control-plane span.  Parent resolution: explicit
        ``ctx`` (cross-thread/cross-process) > the thread's active
        span > root (``trace_id`` names a fresh trace)."""
        if self.sample_n <= 0:
            return NULL_SPAN
        if ctx is not None:
            tid, parent = ctx[0], ctx[1]
        else:
            cur = self.current()
            if cur is not None:
                tid, parent = cur
            elif trace_id is not None:
                tid, parent = trace_id, None
            else:
                return NULL_SPAN  # no trace active: nothing to attach to
        if trace_id is not None:
            tid = trace_id
        span_id = f"{self.role}:{next(self._ids)}"
        return _Span(self, tid, span_id, parent, name, attrs)

    def sampled_span(self, name: str, trace_id: str | None = None,
                     ctx: tuple | None = None, **attrs):
        """Data-plane span recorded 1-in-``sample_n`` (serving reads,
        compaction/scrub cycles).  Off or unsampled = the null span.
        ``ctx`` parents the sampled span into an existing trace (a
        serving replica tags reads with the last committed round's
        root ctx); otherwise it roots a ``sampled-<role>`` trace."""
        n = self.sample_n
        if n <= 0:
            return NULL_SPAN
        if next(self._sample_ctr) % n:
            return NULL_SPAN
        if ctx is not None:
            return self.span(name, ctx=ctx, **attrs)
        tid = trace_id if trace_id is not None \
            else f"sampled-{self.role}"
        return self.span(name, trace_id=tid, **attrs)

    # -- the ring --------------------------------------------------------
    def _record(self, span: _Span, dur: float) -> None:
        entry = {
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "role": self.role,
            "name": span.name,
            "ts": span._ts,
            "dur": dur,
            "attrs": span.attrs,
            "thread": threading.current_thread().name,
        }
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(entry)
            else:
                self._ring[self._head] = entry
                self._head = (self._head + 1) % self.capacity
        return None

    def _snapshot_locked(self) -> list:
        return self._ring[self._head:] + self._ring[:self._head]

    def dump(self, trace_id: str | None = None) -> list[dict]:
        """Snapshot the ring, oldest first (the ``rpc_trace_dump``
        payload — plain dicts, JSON-clean)."""
        with self._lock:
            spans = self._snapshot_locked()
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._head = 0


class _CtxGuard:
    __slots__ = ("_rec", "_ctx", "_pushed")

    def __init__(self, rec: SpanRecorder, ctx: tuple):
        self._rec = rec
        self._ctx = ctx
        self._pushed = False

    def __enter__(self):
        self._rec._stack().append(self._ctx)
        self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            stack = self._rec._stack()
            if stack and stack[-1] == self._ctx:
                stack.pop()
            self._pushed = False
        return False


# ---------------------------------------------------------------------------
# assembly (meta-side / ctl-side): merge per-process dumps into round
# trees and export Chrome trace_event JSON


def merge_dumps(dumps: list[list[dict]]) -> list[dict]:
    """Concatenate per-process dumps, dedup by span_id (a dump pulled
    twice must not double spans), order by start time."""
    seen: set[str] = set()
    out: list[dict] = []
    for d in dumps:
        for s in d or ():
            sid = s.get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            out.append(s)
    out.sort(key=lambda s: s.get("ts", 0.0))
    return out


def round_ids(spans: list[dict]) -> list[int]:
    """The committed-round numbers present in a merged dump."""
    out = set()
    for s in spans:
        t = s.get("trace_id", "")
        if t.startswith("round-"):
            try:
                out.add(int(t[len("round-"):]))
            except ValueError:
                pass
    return sorted(out)


def spans_for_round(spans: list[dict], round_no: int) -> list[dict]:
    want = f"round-{round_no}"
    return [s for s in spans if s.get("trace_id") == want]


def tree_check(spans: list[dict]) -> dict:
    """Structural audit of one trace's spans: exactly one root, every
    parent resolvable, and the root's interval covers every child.
    Truncated dumps (dead worker, ring wrap) stay *parseable*: orphan
    spans are reported, not fatal."""
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if s.get("parent_id") is None]
    orphans = [s for s in spans
               if s.get("parent_id") is not None
               and s["parent_id"] not in by_id]
    covered = True
    if len(roots) == 1:
        r = roots[0]
        r0, r1 = r["ts"], r["ts"] + r["dur"]
        slack = 0.25  # wall clocks across processes wobble
        # coverage applies to the BARRIER PATH only: checkpoint
        # uploads are async by contract and sampled serving reads
        # attach to an already-committed round — both legitimately
        # outlive the root span
        async_ok = {"ckpt_prepare", "ckpt_commit", "serving_read"}
        for s in spans:
            if s is r or s["name"] in async_ok:
                continue
            if s["ts"] < r0 - slack or s["ts"] + s["dur"] > r1 + slack:
                covered = False
    return {
        "spans": len(spans),
        "roots": [s["span_id"] for s in roots],
        "orphans": [s["span_id"] for s in orphans],
        "complete": len(roots) == 1 and not orphans,
        "root_covers": covered,
        "roles": sorted({s["role"] for s in spans}),
        "names": sorted({s["name"] for s in spans}),
    }


def to_chrome_trace(spans: list[dict]) -> dict:
    """Chrome ``trace_event`` JSON (object format) for
    chrome://tracing / Perfetto: one pid per role, one tid per
    (role, thread), complete ``"X"`` events in microseconds."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    for s in spans:
        role = s.get("role", "?")
        if role not in pids:
            pids[role] = len(pids) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pids[role],
                "tid": 0, "args": {"name": role},
            })
        tkey = (role, s.get("thread", ""))
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pids[role],
                "tid": tids[tkey], "args": {"name": tkey[1] or "main"},
            })
        args = dict(s.get("attrs") or {})
        args["span_id"] = s["span_id"]
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        args["trace_id"] = s.get("trace_id")
        events.append({
            "ph": "X",
            "name": s["name"],
            "cat": s.get("trace_id", "trace"),
            "pid": pids[role],
            "tid": tids[tkey],
            "ts": s["ts"] * 1e6,
            "dur": max(s["dur"], 0.0) * 1e6,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: process-wide recorder (mirrors GLOBAL_METRICS) — the server wires
#: role + sample_n at boot; library code just imports and records
GLOBAL_TRACE = SpanRecorder()
