"""Fixed-capacity columnar stream chunks.

Reference counterparts:
- ``DataChunk``   — src/common/src/array/data_chunk.rs:65 (columns + visibility Bitmap)
- ``StreamChunk`` — src/common/src/array/stream_chunk.rs:45 (DataChunk + per-row Op)

TPU-first design
----------------
The reference's visibility ``Bitmap`` ("mask rows without copying") is
adopted as the *universal* mechanism: a ``Chunk`` always has a static
``capacity`` (its leading array dimension) and a boolean ``valid`` mask.
Every kernel is therefore shape-static and jit-friendly — filtering,
dispatch partitioning and selective emission all just rewrite the mask.

A chunk is a JAX pytree whose leaves are device arrays:

- ``columns``: one leaf per column — a plain ``[cap]`` (or ``[cap, w]``
  u8 for strings) array;
- ``ops``: ``int8 [cap]`` changelog op per row (Insert/Delete/UpdateDelete/
  UpdateInsert, ref stream_chunk.rs Op enum);
- ``valid``: ``bool [cap]`` visibility.

The ``schema`` travels as static pytree aux data, so tracing specializes
on it (this mirrors how the reference's executors know their schema at
build time).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.types import DataType, Field, Schema

# Changelog ops. Sign: +1 for *Insert, -1 for *Delete — all retraction
# arithmetic (counts, sums) works uniformly on the sign vector.
# (ref: src/common/src/array/stream_chunk.rs:45 `Op`)
OP_INSERT = np.int8(0)
OP_DELETE = np.int8(1)
OP_UPDATE_DELETE = np.int8(2)
OP_UPDATE_INSERT = np.int8(3)

_OP_PRETTY = {0: "+", 1: "-", 2: "U-", 3: "U+"}
_PRETTY_OP = {"+": 0, "-": 1, "u-": 2, "u+": 3}


class StrCol(NamedTuple):
    """A fixed-width device string column: utf-8 bytes + logical lengths."""

    data: jnp.ndarray  # [cap, width] uint8, zero-padded
    lens: jnp.ndarray  # [cap] int32


class NCol(NamedTuple):
    """A nullable column: payload + per-row null mask (True = NULL).

    The reference gives EVERY array a null bitmap
    (src/common/src/array/mod.rs:279); here nullability is static per
    column — columns that cannot hold NULLs stay bare arrays/StrCols and
    compile to exactly the pre-null programs.  ``data`` at null rows is
    unspecified (kernels mask it out)."""

    data: Any          # [cap] array or StrCol
    null: jnp.ndarray  # bool [cap], True = NULL


def split_col(col):
    """(payload, null-mask-or-None) view of any column value."""
    if isinstance(col, NCol):
        return col.data, col.null
    return col, None


def make_col(data, null):
    """Wrap payload + optional mask back into a column value."""
    if null is None:
        return data
    return NCol(data, null)


def conform_col(col, nullable: bool, cap: int):
    """Make a column's runtime representation match its STATIC field
    nullability (state tables fix their pytree structure at creation,
    so a nullable field must always arrive as an NCol)."""
    if nullable and not isinstance(col, NCol):
        return NCol(col, jnp.zeros((cap,), jnp.bool_))
    if not nullable and isinstance(col, NCol):
        # statically non-nullable: the mask is provably all-false
        return col.data
    return col


def _leaf_shape_cap(col) -> int:
    if isinstance(col, NCol):
        col = col.data
    return (col.data if isinstance(col, StrCol) else col).shape[0]


@jax.tree_util.register_pytree_node_class
class Chunk:
    """A fixed-capacity changelog batch of rows (SoA layout)."""

    __slots__ = ("columns", "ops", "valid", "schema")

    def __init__(
        self,
        columns: Sequence[Any],
        ops: jnp.ndarray,
        valid: jnp.ndarray,
        schema: Schema,
    ):
        self.columns = tuple(columns)
        self.ops = ops
        self.valid = valid
        self.schema = schema

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.columns, self.ops, self.valid), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, ops, valid = children
        return cls(columns, ops, valid, schema)

    # -- basic properties ----------------------------------------------
    @property
    def capacity(self) -> int:
        return _leaf_shape_cap(self.ops if len(self.columns) == 0 else self.columns[0])

    def cardinality(self) -> jnp.ndarray:
        """Number of visible rows (traced value)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def signs(self) -> jnp.ndarray:
        """Per-row +1/-1/0 changelog sign (0 for invisible rows)."""
        insert_like = (self.ops == OP_INSERT) | (self.ops == OP_UPDATE_INSERT)
        s = jnp.where(insert_like, jnp.int32(1), jnp.int32(-1))
        return jnp.where(self.valid, s, jnp.int32(0))

    def column(self, i: int):
        return self.columns[i]

    def column_by_name(self, name: str):
        return self.columns[self.schema.index_of(name)]

    # -- functional updates ---------------------------------------------
    def with_valid(self, valid: jnp.ndarray) -> "Chunk":
        return Chunk(self.columns, self.ops, valid, self.schema)

    def mask(self, keep: jnp.ndarray) -> "Chunk":
        """Narrow visibility (ref DataChunk::with_visibility)."""
        return self.with_valid(self.valid & keep)

    def with_columns(self, columns: Sequence[Any], schema: Schema) -> "Chunk":
        return Chunk(columns, self.ops, self.valid, schema)

    def project(self, indices: Sequence[int]) -> "Chunk":
        """Column projection without copying (ref DataChunk::project)."""
        return Chunk(
            [self.columns[i] for i in indices],
            self.ops,
            self.valid,
            self.schema.select(list(indices)),
        )

    # -- host-side conversion (test / serving surface) -------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Sequence[np.ndarray],
        ops: np.ndarray | None = None,
        capacity: int | None = None,
    ) -> "Chunk":
        """Build a chunk from host arrays, padding to ``capacity``.

        String columns are passed as 1-D object/str arrays and encoded to
        fixed-width bytes here (the host↔device boundary).
        """
        if len(arrays) != len(schema.fields):
            raise ValueError(
                f"{len(arrays)} arrays for {len(schema.fields)}-field schema"
            )
        n = len(arrays[0]) if arrays else (len(ops) if ops is not None else 0)
        cap = capacity or max(n, 1)
        if n > cap:
            raise ValueError(f"{n} rows > capacity {cap}")
        if ops is None:
            ops = np.full(n, OP_INSERT, np.int8)
        cols = []
        for f, arr in zip(schema.fields, arrays):
            cols.append(_encode_column(f, np.asarray(arr), cap))
        ops_full = np.zeros(cap, np.int8)
        ops_full[:n] = ops
        valid = np.zeros(cap, np.bool_)
        valid[:n] = True
        return Chunk(
            cols, jnp.asarray(ops_full), jnp.asarray(valid), schema
        )

    def to_host(self) -> tuple[np.ndarray, list[np.ndarray], np.ndarray]:
        """Return (ops, columns-as-python-values, valid) compacted to visible rows."""
        valid = np.asarray(self.valid)
        ops = np.asarray(self.ops)[valid]
        out_cols: list[np.ndarray] = []
        for f, col in zip(self.schema.fields, self.columns):
            out_cols.append(_decode_column(f, col, valid))
        return ops, out_cols, valid

    def to_rows(self) -> list[tuple]:
        """Visible rows as ((op, values...)) tuples — test helper."""
        ops, cols, _ = self.to_host()
        return [
            (int(ops[i]), *(c[i] for c in cols)) for i in range(len(ops))
        ]

    # -- pretty DSL (test enabler; ref StreamChunk::from_pretty) ---------
    @staticmethod
    def from_pretty(
        text: str,
        capacity: int | None = None,
        names: Sequence[str] | None = None,
    ) -> "Chunk":
        """Parse the reference's chunk text DSL.

        Example::

            i I F
            +  1 10 1.5
            -  2 20 2.5
            U- 3 30 0.0
            U+ 3 31 0.0

        Header letters: ``b`` bool, ``s`` int16, ``i`` int32, ``I`` int64,
        ``f`` float32, ``F`` float64, ``d`` decimal, ``D`` date,
        ``t`` timestamp, ``T`` varchar, ``S`` serial.
        """
        lines = [ln for ln in (l.strip() for l in text.splitlines()) if ln]
        header = lines[0].split()
        fields = tuple(
            Field(
                names[idx] if names else f"c{idx}", _PRETTY_TYPES[tok]
            )
            for idx, tok in enumerate(header)
        )
        schema = Schema(fields)
        ops_l: list[int] = []
        rows: list[list[str]] = []
        for ln in lines[1:]:
            parts = ln.split()
            ops_l.append(_PRETTY_OP[parts[0].lower()])
            if len(parts) - 1 != len(fields):
                raise ValueError(f"row {ln!r} arity != {len(fields)}")
            rows.append(parts[1:])
        arrays: list[np.ndarray] = []
        final_fields = list(fields)
        for ci, f in enumerate(fields):
            raw = [r[ci] for r in rows]
            arr = _parse_pretty_col(f, raw)
            if arr.dtype == object and any(v is None for v in arr):
                final_fields[ci] = f.with_nullable()
            arrays.append(arr)
        return Chunk.from_numpy(
            Schema(tuple(final_fields)), arrays,
            np.asarray(ops_l, np.int8), capacity=capacity,
        )

    def to_pretty(self) -> str:
        ops, cols, _ = self.to_host()
        out = []
        for i in range(len(ops)):
            vals = " ".join(
                "." if c[i] is None else str(c[i]) for c in cols
            )
            out.append(f"{_OP_PRETTY[int(ops[i])]:>2} {vals}")
        return "\n".join(out)

    def __repr__(self) -> str:
        return (
            f"Chunk(cap={self.capacity}, schema={list(self.schema.fields)})"
        )


_PRETTY_TYPES = {
    "b": DataType.BOOLEAN,
    "s": DataType.INT16,
    "i": DataType.INT32,
    "I": DataType.INT64,
    "f": DataType.FLOAT32,
    "F": DataType.FLOAT64,
    "d": DataType.DECIMAL,
    "D": DataType.DATE,
    "t": DataType.TIMESTAMP,
    "T": DataType.VARCHAR,
    "S": DataType.SERIAL,
}


def _parse_pretty_col(f: Field, raw: list[str]) -> np.ndarray:
    """Parse one pretty-DSL column; ``.`` (ref from_pretty) or ``NULL``
    denote SQL NULL and yield an object array with None entries."""
    t = f.data_type

    def scalar(v: str):
        if v == "." or v.lower() == "null":
            return None
        if t.is_string:
            return v
        if t == DataType.BOOLEAN:
            return v in ("t", "true", "1")
        if t == DataType.DECIMAL or t in (DataType.FLOAT32, DataType.FLOAT64):
            return float(v)
        return int(v)

    vals = [scalar(v) for v in raw]
    if any(v is None for v in vals) or t.is_string:
        return np.asarray(vals, object)
    return np.asarray(vals)


def encode_strings(values: Sequence, width: int) -> tuple[np.ndarray, np.ndarray]:
    """Encode python strings/bytes to fixed-width (bytes, lens) arrays."""
    n = len(values)
    data = np.zeros((n, width), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, v in enumerate(values):
        b = v if isinstance(v, (bytes, bytearray)) else str(v).encode("utf-8")
        b = b[:width]
        data[i, : len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return data, lens


def apply_null_mask(out: np.ndarray, nulls: np.ndarray | None) -> np.ndarray:
    """Replace masked entries of a decoded host column with None."""
    if nulls is None or not nulls.any():
        return out
    out = np.asarray(list(out), object)
    out[nulls] = None
    return out


def decode_strings(data: np.ndarray, lens: np.ndarray) -> np.ndarray:
    out = np.empty(len(lens), object)
    for i in range(len(lens)):
        out[i] = bytes(data[i, : lens[i]]).decode("utf-8", "replace")
    return out


def _encode_column(f: Field, arr: np.ndarray, cap: int):
    t = f.data_type
    # None entries (SQL NULL) in object arrays become an NCol mask
    null_mask = None
    if arr.dtype == object:
        nulls = np.asarray([v is None for v in arr], np.bool_)
        if nulls.any():
            if not f.nullable:
                raise ValueError(
                    f"NULL value for NOT NULL column {f.name!r} "
                    "(declare the column `NULL` to allow NULLs)"
                )
            null_mask = np.zeros(cap, np.bool_)
            null_mask[: len(arr)] = nulls
            fill = "" if t.is_string else 0
            repl = [fill if v is None else v for v in arr]
            arr = np.asarray(repl, object) if t.is_string \
                else np.asarray(repl)
        elif not t.is_string:
            arr = np.asarray(list(arr))
    if t.is_string:
        data, lens = encode_strings(list(arr), f.str_width)
        full = np.zeros((cap, f.str_width), np.uint8)
        full[: len(arr)] = data
        lfull = np.zeros(cap, np.int32)
        lfull[: len(arr)] = lens
        col = StrCol(jnp.asarray(full), jnp.asarray(lfull))
    else:
        dtype = np.dtype(t.physical_dtype)
        if t == DataType.DECIMAL:
            # logical values; device representation is scaled int64
            arr = np.round(
                arr.astype(np.float64) * 10**f.decimal_scale
            ).astype(np.int64)
        full = np.zeros(cap, dtype)
        full[: len(arr)] = arr.astype(dtype)
        col = jnp.asarray(full)
    if null_mask is not None or f.nullable:
        mask = null_mask if null_mask is not None else np.zeros(cap, np.bool_)
        return NCol(col, jnp.asarray(mask))
    return col


def _decode_column(f: Field, col, valid: np.ndarray) -> np.ndarray:
    t = f.data_type
    col, null = split_col(col)
    if isinstance(col, StrCol):
        data = np.asarray(col.data)[valid]
        lens = np.asarray(col.lens)[valid]
        out = decode_strings(data, lens)
    else:
        arr = np.asarray(col)[valid]
        if t == DataType.DECIMAL:
            out = arr.astype(np.float64) / 10**f.decimal_scale
        elif t == DataType.BOOLEAN:
            out = arr.astype(bool)
        else:
            out = arr
    if null is not None:
        out = apply_null_mask(out, np.asarray(null)[valid])
    return out


def concat_chunks(chunks: Sequence[Chunk], capacity: int) -> list[Chunk]:
    """Host-side re-batching of visible rows into fixed-capacity chunks."""
    if not chunks:
        return []
    schema = chunks[0].schema
    all_rows: list[tuple] = []
    for c in chunks:
        ops, cols, _ = c.to_host()
        for i in range(len(ops)):
            all_rows.append((ops[i], tuple(col[i] for col in cols)))
    out = []
    for start in range(0, len(all_rows), capacity):
        batch = all_rows[start : start + capacity]
        ops = np.asarray([r[0] for r in batch], np.int8)
        arrays = [
            np.asarray([r[1][ci] for r in batch])
            for ci in range(len(schema))
        ]
        out.append(Chunk.from_numpy(schema, arrays, ops, capacity=capacity))
    return out
