"""Fixed-capacity columnar stream chunks.

Reference counterparts:
- ``DataChunk``   — src/common/src/array/data_chunk.rs:65 (columns + visibility Bitmap)
- ``StreamChunk`` — src/common/src/array/stream_chunk.rs:45 (DataChunk + per-row Op)

TPU-first design
----------------
The reference's visibility ``Bitmap`` ("mask rows without copying") is
adopted as the *universal* mechanism: a ``Chunk`` always has a static
``capacity`` (its leading array dimension) and a boolean ``valid`` mask.
Every kernel is therefore shape-static and jit-friendly — filtering,
dispatch partitioning and selective emission all just rewrite the mask.

A chunk is a JAX pytree whose leaves are device arrays:

- ``columns``: one leaf per column — a plain ``[cap]`` (or ``[cap, w]``
  u8 for strings) array;
- ``ops``: ``int8 [cap]`` changelog op per row (Insert/Delete/UpdateDelete/
  UpdateInsert, ref stream_chunk.rs Op enum);
- ``valid``: ``bool [cap]`` visibility.

The ``schema`` travels as static pytree aux data, so tracing specializes
on it (this mirrors how the reference's executors know their schema at
build time).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.types import DataType, Field, Schema

# Changelog ops. Sign: +1 for *Insert, -1 for *Delete — all retraction
# arithmetic (counts, sums) works uniformly on the sign vector.
# (ref: src/common/src/array/stream_chunk.rs:45 `Op`)
OP_INSERT = np.int8(0)
OP_DELETE = np.int8(1)
OP_UPDATE_DELETE = np.int8(2)
OP_UPDATE_INSERT = np.int8(3)

_OP_PRETTY = {0: "+", 1: "-", 2: "U-", 3: "U+"}
_PRETTY_OP = {"+": 0, "-": 1, "u-": 2, "u+": 3}


class StrCol(NamedTuple):
    """A fixed-width device string column: utf-8 bytes + logical lengths."""

    data: jnp.ndarray  # [cap, width] uint8, zero-padded
    lens: jnp.ndarray  # [cap] int32


def _leaf_shape_cap(col) -> int:
    return (col.data if isinstance(col, StrCol) else col).shape[0]


@jax.tree_util.register_pytree_node_class
class Chunk:
    """A fixed-capacity changelog batch of rows (SoA layout)."""

    __slots__ = ("columns", "ops", "valid", "schema")

    def __init__(
        self,
        columns: Sequence[Any],
        ops: jnp.ndarray,
        valid: jnp.ndarray,
        schema: Schema,
    ):
        self.columns = tuple(columns)
        self.ops = ops
        self.valid = valid
        self.schema = schema

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.columns, self.ops, self.valid), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, ops, valid = children
        return cls(columns, ops, valid, schema)

    # -- basic properties ----------------------------------------------
    @property
    def capacity(self) -> int:
        return _leaf_shape_cap(self.ops if len(self.columns) == 0 else self.columns[0])

    def cardinality(self) -> jnp.ndarray:
        """Number of visible rows (traced value)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def signs(self) -> jnp.ndarray:
        """Per-row +1/-1/0 changelog sign (0 for invisible rows)."""
        insert_like = (self.ops == OP_INSERT) | (self.ops == OP_UPDATE_INSERT)
        s = jnp.where(insert_like, jnp.int32(1), jnp.int32(-1))
        return jnp.where(self.valid, s, jnp.int32(0))

    def column(self, i: int):
        return self.columns[i]

    def column_by_name(self, name: str):
        return self.columns[self.schema.index_of(name)]

    # -- functional updates ---------------------------------------------
    def with_valid(self, valid: jnp.ndarray) -> "Chunk":
        return Chunk(self.columns, self.ops, valid, self.schema)

    def mask(self, keep: jnp.ndarray) -> "Chunk":
        """Narrow visibility (ref DataChunk::with_visibility)."""
        return self.with_valid(self.valid & keep)

    def with_columns(self, columns: Sequence[Any], schema: Schema) -> "Chunk":
        return Chunk(columns, self.ops, self.valid, schema)

    def project(self, indices: Sequence[int]) -> "Chunk":
        """Column projection without copying (ref DataChunk::project)."""
        return Chunk(
            [self.columns[i] for i in indices],
            self.ops,
            self.valid,
            self.schema.select(list(indices)),
        )

    # -- host-side conversion (test / serving surface) -------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Sequence[np.ndarray],
        ops: np.ndarray | None = None,
        capacity: int | None = None,
    ) -> "Chunk":
        """Build a chunk from host arrays, padding to ``capacity``.

        String columns are passed as 1-D object/str arrays and encoded to
        fixed-width bytes here (the host↔device boundary).
        """
        if len(arrays) != len(schema.fields):
            raise ValueError(
                f"{len(arrays)} arrays for {len(schema.fields)}-field schema"
            )
        n = len(arrays[0]) if arrays else (len(ops) if ops is not None else 0)
        cap = capacity or max(n, 1)
        if n > cap:
            raise ValueError(f"{n} rows > capacity {cap}")
        if ops is None:
            ops = np.full(n, OP_INSERT, np.int8)
        cols = []
        for f, arr in zip(schema.fields, arrays):
            cols.append(_encode_column(f, np.asarray(arr), cap))
        ops_full = np.zeros(cap, np.int8)
        ops_full[:n] = ops
        valid = np.zeros(cap, np.bool_)
        valid[:n] = True
        return Chunk(
            cols, jnp.asarray(ops_full), jnp.asarray(valid), schema
        )

    def to_host(self) -> tuple[np.ndarray, list[np.ndarray], np.ndarray]:
        """Return (ops, columns-as-python-values, valid) compacted to visible rows."""
        valid = np.asarray(self.valid)
        ops = np.asarray(self.ops)[valid]
        out_cols: list[np.ndarray] = []
        for f, col in zip(self.schema.fields, self.columns):
            out_cols.append(_decode_column(f, col, valid))
        return ops, out_cols, valid

    def to_rows(self) -> list[tuple]:
        """Visible rows as ((op, values...)) tuples — test helper."""
        ops, cols, _ = self.to_host()
        return [
            (int(ops[i]), *(c[i] for c in cols)) for i in range(len(ops))
        ]

    # -- pretty DSL (test enabler; ref StreamChunk::from_pretty) ---------
    @staticmethod
    def from_pretty(
        text: str,
        capacity: int | None = None,
        names: Sequence[str] | None = None,
    ) -> "Chunk":
        """Parse the reference's chunk text DSL.

        Example::

            i I F
            +  1 10 1.5
            -  2 20 2.5
            U- 3 30 0.0
            U+ 3 31 0.0

        Header letters: ``b`` bool, ``s`` int16, ``i`` int32, ``I`` int64,
        ``f`` float32, ``F`` float64, ``d`` decimal, ``D`` date,
        ``t`` timestamp, ``T`` varchar, ``S`` serial.
        """
        lines = [ln for ln in (l.strip() for l in text.splitlines()) if ln]
        header = lines[0].split()
        fields = tuple(
            Field(
                names[idx] if names else f"c{idx}", _PRETTY_TYPES[tok]
            )
            for idx, tok in enumerate(header)
        )
        schema = Schema(fields)
        ops_l: list[int] = []
        rows: list[list[str]] = []
        for ln in lines[1:]:
            parts = ln.split()
            ops_l.append(_PRETTY_OP[parts[0].lower()])
            if len(parts) - 1 != len(fields):
                raise ValueError(f"row {ln!r} arity != {len(fields)}")
            rows.append(parts[1:])
        arrays: list[np.ndarray] = []
        for ci, f in enumerate(fields):
            raw = [r[ci] for r in rows]
            arrays.append(_parse_pretty_col(f, raw))
        return Chunk.from_numpy(
            schema, arrays, np.asarray(ops_l, np.int8), capacity=capacity
        )

    def to_pretty(self) -> str:
        ops, cols, _ = self.to_host()
        out = []
        for i in range(len(ops)):
            vals = " ".join(str(c[i]) for c in cols)
            out.append(f"{_OP_PRETTY[int(ops[i])]:>2} {vals}")
        return "\n".join(out)

    def __repr__(self) -> str:
        return (
            f"Chunk(cap={self.capacity}, schema={list(self.schema.fields)})"
        )


_PRETTY_TYPES = {
    "b": DataType.BOOLEAN,
    "s": DataType.INT16,
    "i": DataType.INT32,
    "I": DataType.INT64,
    "f": DataType.FLOAT32,
    "F": DataType.FLOAT64,
    "d": DataType.DECIMAL,
    "D": DataType.DATE,
    "t": DataType.TIMESTAMP,
    "T": DataType.VARCHAR,
    "S": DataType.SERIAL,
}


def _parse_pretty_col(f: Field, raw: list[str]) -> np.ndarray:
    t = f.data_type
    if t.is_string:
        return np.asarray(raw, object)
    if t == DataType.BOOLEAN:
        return np.asarray([v in ("t", "true", "1") for v in raw])
    if t == DataType.DECIMAL:
        return np.asarray([float(v) for v in raw])
    if t in (DataType.FLOAT32, DataType.FLOAT64):
        return np.asarray([float(v) for v in raw])
    return np.asarray([int(v) for v in raw])


def encode_strings(values: Sequence, width: int) -> tuple[np.ndarray, np.ndarray]:
    """Encode python strings/bytes to fixed-width (bytes, lens) arrays."""
    n = len(values)
    data = np.zeros((n, width), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, v in enumerate(values):
        b = v if isinstance(v, (bytes, bytearray)) else str(v).encode("utf-8")
        b = b[:width]
        data[i, : len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return data, lens


def decode_strings(data: np.ndarray, lens: np.ndarray) -> np.ndarray:
    out = np.empty(len(lens), object)
    for i in range(len(lens)):
        out[i] = bytes(data[i, : lens[i]]).decode("utf-8", "replace")
    return out


def _encode_column(f: Field, arr: np.ndarray, cap: int):
    t = f.data_type
    if t.is_string:
        data, lens = encode_strings(list(arr), f.str_width)
        full = np.zeros((cap, f.str_width), np.uint8)
        full[: len(arr)] = data
        lfull = np.zeros(cap, np.int32)
        lfull[: len(arr)] = lens
        return StrCol(jnp.asarray(full), jnp.asarray(lfull))
    dtype = np.dtype(t.physical_dtype)
    if t == DataType.DECIMAL:
        # inputs are logical values; the device representation is scaled int64
        arr = np.round(arr.astype(np.float64) * 10**f.decimal_scale).astype(np.int64)
    full = np.zeros(cap, dtype)
    full[: len(arr)] = arr.astype(dtype)
    return jnp.asarray(full)


def _decode_column(f: Field, col, valid: np.ndarray) -> np.ndarray:
    t = f.data_type
    if isinstance(col, StrCol):
        data = np.asarray(col.data)[valid]
        lens = np.asarray(col.lens)[valid]
        return decode_strings(data, lens)
    arr = np.asarray(col)[valid]
    if t == DataType.DECIMAL:
        return arr.astype(np.float64) / 10**f.decimal_scale
    if t == DataType.BOOLEAN:
        return arr.astype(bool)
    return arr


def concat_chunks(chunks: Sequence[Chunk], capacity: int) -> list[Chunk]:
    """Host-side re-batching of visible rows into fixed-capacity chunks."""
    if not chunks:
        return []
    schema = chunks[0].schema
    all_rows: list[tuple] = []
    for c in chunks:
        ops, cols, _ = c.to_host()
        for i in range(len(ops)):
            all_rows.append((ops[i], tuple(col[i] for col in cols)))
    out = []
    for start in range(0, len(all_rows), capacity):
        batch = all_rows[start : start + capacity]
        ops = np.asarray([r[0] for r in batch], np.int8)
        arrays = [
            np.asarray([r[1][ci] for r in batch])
            for ci in range(len(schema))
        ]
        out.append(Chunk.from_numpy(schema, arrays, ops, capacity=capacity))
    return out
