"""Logical data types and schemas.

Reference counterpart: ``src/common/src/types/mod.rs:154-219`` (the
``DataType`` enum) and ``src/common/src/catalog/`` (``Field``/``Schema``).

TPU-first design notes
----------------------
Every logical type maps to a *fixed-width* physical representation so that
chunks are shape-static XLA values:

- integers/floats/bool map 1:1 onto jnp dtypes;
- ``DECIMAL`` is a scaled ``int64`` (value * 10^scale).  The reference uses
  a 128-bit decimal; 64-bit scaled covers the benchmark surface (prices,
  amounts) and overflow is checked host-side on ingest;
- temporal types are integer epochs (days / micros);
- ``VARCHAR`` is a (bytes[cap, max_len] u8, len[cap] i32) pair — fixed
  max width on device.  Comparisons/equality/hashing are vectorized over
  the byte dimension; unbounded string ops fall back to host;
- composite types (STRUCT/LIST/MAP) exist at the planner level and are
  flattened to multiple physical columns before reaching the device.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    """Logical column types (subset of reference types/mod.rs:154)."""

    BOOLEAN = "boolean"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "real"
    FLOAT64 = "double precision"
    DECIMAL = "numeric"          # scaled int64, scale fixed per column
    DATE = "date"                # i32 days since unix epoch
    TIME = "time"                # i64 microseconds since midnight
    TIMESTAMP = "timestamp"      # i64 microseconds since unix epoch (naive)
    TIMESTAMPTZ = "timestamptz"  # i64 microseconds since unix epoch (UTC)
    INTERVAL = "interval"        # i64 microseconds (simplified; ref has months/days/usecs)
    VARCHAR = "character varying"
    BYTEA = "bytea"
    SERIAL = "serial"            # i64 row-id

    # ------------------------------------------------------------------
    @property
    def physical_dtype(self) -> jnp.dtype:
        """The jnp dtype of the device column (bytes column for strings)."""
        return _PHYSICAL[self]

    @property
    def is_string(self) -> bool:
        return self in (DataType.VARCHAR, DataType.BYTEA)

    @property
    def is_numeric(self) -> bool:
        return self in (
            DataType.INT16,
            DataType.INT32,
            DataType.INT64,
            DataType.FLOAT32,
            DataType.FLOAT64,
            DataType.DECIMAL,
        )

    @property
    def is_integral(self) -> bool:
        return self in (
            DataType.INT16,
            DataType.INT32,
            DataType.INT64,
            DataType.SERIAL,
            DataType.DATE,
            DataType.TIME,
            DataType.TIMESTAMP,
            DataType.TIMESTAMPTZ,
            DataType.INTERVAL,
            DataType.DECIMAL,
        )

    @property
    def byte_width(self) -> int:
        """Width of the memcomparable/hash key encoding of one value."""
        if self.is_string:
            raise ValueError("strings have no fixed byte width")
        return np.dtype(self.physical_dtype).itemsize

    @classmethod
    def from_sql(cls, name: str) -> "DataType":
        return parse_sql_type(name)[0]


_PHYSICAL: dict[DataType, jnp.dtype] = {
    DataType.BOOLEAN: jnp.bool_,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FLOAT32: jnp.float32,
    DataType.FLOAT64: jnp.float64,
    DataType.DECIMAL: jnp.int64,
    DataType.DATE: jnp.int32,
    DataType.TIME: jnp.int64,
    DataType.TIMESTAMP: jnp.int64,
    DataType.TIMESTAMPTZ: jnp.int64,
    DataType.INTERVAL: jnp.int64,
    DataType.VARCHAR: jnp.uint8,
    DataType.BYTEA: jnp.uint8,
    DataType.SERIAL: jnp.int64,
}

_SQL_NAMES: dict[str, DataType] = {}
for _t in DataType:
    _SQL_NAMES[_t.value] = _t
_SQL_NAMES.update(
    {
        "bool": DataType.BOOLEAN,
        "int2": DataType.INT16,
        "smallint": DataType.INT16,
        "int4": DataType.INT32,
        "integer": DataType.INT32,
        "int8": DataType.INT64,
        "bigint": DataType.INT64,
        "float4": DataType.FLOAT32,
        "real": DataType.FLOAT32,
        "float8": DataType.FLOAT64,
        "double": DataType.FLOAT64,
        "decimal": DataType.DECIMAL,
        "varchar": DataType.VARCHAR,
        "string": DataType.VARCHAR,
        "text": DataType.VARCHAR,
        "char": DataType.VARCHAR,
        "character": DataType.VARCHAR,
        "timestamp without time zone": DataType.TIMESTAMP,
        "timestamp with time zone": DataType.TIMESTAMPTZ,
    }
)

def parse_sql_type(name: str):
    """``(DataType, declared-width-or-None, declared-scale-or-None)``.

    Accepts parameterized SQL spellings — ``VARCHAR(100)`` (device byte
    width), ``NUMERIC(p, s)`` (scale) — alongside the bare names.  The
    reference parses type parameters in its sqlparser
    (src/sqlparser/src/ast/data_type.rs); here the declared VARCHAR
    length doubles as the device column width."""
    s = name.strip().lower()
    width = scale = None
    if "(" in s:
        base, _, rest = s.partition("(")
        args = rest.rstrip(") ").split(",")
        base = base.strip()
        t = _SQL_NAMES[base]
        if t.is_string:
            width = int(args[0])
        elif t == DataType.DECIMAL and len(args) > 1:
            scale = int(args[1])
        return t, width, scale
    return _SQL_NAMES[s], None, None


# Default device width (bytes) for VARCHAR columns unless the schema
# declares one.  Nexmark's longest generated strings (extra/url) fit well
# within this.
DEFAULT_STR_WIDTH = 64

# Default decimal scale: micro-units, enough for currency math in the
# benchmark suite (ref nexmark uses f64-backed "price" semantics).
DEFAULT_DECIMAL_SCALE = 6


@dataclass(frozen=True)
class Field:
    """A named, typed column (ref: src/common/src/catalog/schema.rs Field)."""

    name: str
    data_type: DataType
    #: device byte width for string columns
    str_width: int = DEFAULT_STR_WIDTH
    #: power-of-ten scale for DECIMAL columns
    decimal_scale: int = DEFAULT_DECIMAL_SCALE
    #: column may contain NULLs (ref: every reference array carries a
    #: null bitmap, src/common/src/array/mod.rs:279; here nullability is
    #: STATIC per column so non-nullable plans compile with zero masks)
    nullable: bool = False

    def with_nullable(self, nullable: bool = True) -> "Field":
        from dataclasses import replace
        return replace(self, nullable=nullable)

    def __repr__(self) -> str:  # compact for plan display
        mark = "?" if self.nullable else ""
        return f"{self.name}:{self.data_type.name.lower()}{mark}"


@dataclass(frozen=True)
class Schema:
    """An ordered list of fields (ref: src/common/src/catalog/schema.rs)."""

    fields: tuple[Field, ...] = field(default_factory=tuple)

    def __post_init__(self) -> None:
        object.__setattr__(self, "fields", tuple(self.fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def data_types(self) -> list[DataType]:
        return [f.data_type for f in self.fields]

    def select(self, indices: list[int]) -> "Schema":
        return Schema(tuple(self.fields[i] for i in indices))

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)

    @staticmethod
    def of(*cols: tuple[str, DataType]) -> "Schema":
        return Schema(tuple(Field(n, t) for n, t in cols))
