"""Layered configuration system.

Reference counterpart (SURVEY.md §5.6): the reference layers
1. per-node TOML config (``RwConfig``, src/common/src/config/mod.rs:81)
2. cluster-wide runtime-mutable system params
   (src/common/src/system_param/mod.rs:84)
3. per-session ``SET`` variables (src/common/src/session_config/)
4. WITH options on sources/sinks (handled by the SQL layer).

Here: dataclass sections mirroring (1), a ``SystemParams`` registry with
mutability flags mirroring (2) (``ALTER SYSTEM SET`` in the engine), and
``SessionConfig`` for (3).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class StreamingConfig:
    """ref config streaming section (src/common/src/config/streaming.rs)."""

    chunk_size: int = 4096           # ref default 256; TPU chunks are larger
    in_flight_barrier_nums: int = 1  # host loop is synchronous this round
    exchange_vnode_count: int = 256


@dataclass
class StorageConfig:
    """ref config storage section."""

    data_directory: str | None = None   # None = in-memory checkpoints only
    checkpoint_keep_epochs: int = 2
    sst_block_size_bytes: int = 64 * 1024


@dataclass
class StateConfig:
    """capacity knobs for device state tables (planner defaults)."""

    agg_table_size: int = 1 << 16
    agg_emit_capacity: int = 4096
    join_table_size: int = 1 << 14
    join_bucket_cap: int = 64
    join_out_capacity: int = 1 << 15
    topn_pool_size: int = 4096
    topn_emit_capacity: int = 1024
    mv_table_size: int = 1 << 16
    mv_ring_size: int = 1 << 20


@dataclass
class ClusterConfig:
    """Control-plane knobs (ref meta config: heartbeat/barrier
    sections of src/common/src/config/mod.rs)."""

    meta_host: str = "127.0.0.1"
    meta_rpc_port: int = 4600
    #: worker → meta liveness cadence
    heartbeat_interval_s: float = 0.5
    #: silence after which meta declares a worker dead and fails over
    heartbeat_timeout_s: float = 3.0
    #: how long a serving read waits for a reassigned owner before
    #: erroring (covers adopt + recover + first compile on a survivor)
    serve_retry_timeout_s: float = 60.0
    #: meta → worker control RPC deadline (barrier rounds include
    #: first-compile latency on fresh workers)
    rpc_timeout_s: float = 180.0
    #: serving replica → meta lease cadence (each heartbeat acks the
    #: held manifest vid and receives the next epoch-pin grant)
    serving_heartbeat_interval_s: float = 0.5
    #: serving replica block-cache capacity (decoded SST blocks)
    serving_cache_blocks: int = 1024
    #: serving replica result-cache budget (bytes of cached rows):
    #: completed reads keyed by (normalized sql, manifest vid) — an
    #: epoch advance re-keys every entry, so hits can never be stale
    serving_result_cache_bytes: int = 32 << 20
    #: pushdown plane: per-vid negative-cache capacity (pks proven
    #: absent at the pinned version; cleared wholesale on every vid
    #: advance, so a stale negative can never mask a fresh row).
    #: 0 disables.
    serving_negative_cache_keys: int = 65536
    #: pushdown plane: hottest normalized-sql keys replayed against
    #: the new vid on each lease grant (result-cache warmup).
    #: 0 disables.
    serving_warmup_keys: int = 8
    #: scale plane: vnode ring size (the consistent-hash keyspace
    #: jobs partition over; ref VirtualNode::COUNT)
    n_vnodes: int = 64
    #: scale plane: place ELIGIBLE jobs as vnode partitions over the
    #: active worker set (``ctl cluster scale N`` then moves only
    #: vnodes + the state behind them).  Off = whole-job placement.
    scale_partitioning: bool = False
    #: Exchange-lite sliced ingest (default ON): the ingest leader
    #: hash-partitions each DML batch ONCE and ships each worker only
    #: its owned slice; the VnodeGate becomes a correctness assert.
    #: Off = the PR-7 replicate-everything fan-out (the A/B baseline
    #: and field escape hatch).
    shuffle_ingest: bool = True
    #: integrity scrubber (meta-owned): seconds between background
    #: scrub cycles over pinned-version SSTs + checkpoint lineages
    #: (0 disables the background thread; ``ctl cluster scrub`` still
    #: drives cycles on demand)
    scrub_interval_s: float = 30.0
    #: unified control-RPC retry budget (common/faults.RetryPolicy):
    #: total attempts per idempotent/epoch-guarded call before the
    #: failure surfaces (1 = no retries, the pre-chaos behavior)
    rpc_retry_max_attempts: int = 4
    #: first backoff delay; doubles per retry (deterministic jitter)
    rpc_retry_base_delay_s: float = 0.05
    #: backoff cap
    rpc_retry_max_delay_s: float = 0.5
    #: trace-lite sampling (common/trace.py): 0 disables tracing
    #: entirely (span() hands out a shared null singleton — zero
    #: allocations on the chunk path); N >= 1 records every
    #: control-plane span (round/barrier/phase/upload) and 1-in-N
    #: data-plane spans (serving reads, compact/scrub cycles)
    trace_sample_n: int = 1
    #: per-process span flight-recorder capacity (bounded ring;
    #: oldest spans fall off — a dump is always the recent window)
    trace_buffer_spans: int = 4096


@dataclass
class RwConfig:
    """Top-level node config (ref RwConfig, config/mod.rs:81)."""

    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    state: StateConfig = field(default_factory=StateConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)

    @staticmethod
    def from_dict(d: dict) -> "RwConfig":
        cfg = RwConfig()
        for section_name, section in d.items():
            target = getattr(cfg, section_name)
            for k, v in section.items():
                if not hasattr(target, k):
                    raise KeyError(f"unknown config {section_name}.{k}")
                setattr(target, k, v)
        return cfg


# ---------------------------------------------------------------------------
# system params: cluster-wide, runtime mutable, persisted with checkpoints
# (ref system_param/mod.rs:84 — declared with defaults + mutability)

_SYSTEM_PARAM_DEFS = {
    # name: (default, mutable)
    "barrier_interval_ms": (1000, True),   # ref :84
    "checkpoint_frequency": (1, True),     # ref :85
    "chunks_per_barrier": (1, True),       # TPU batch knob (no ref analog)
    "max_concurrent_creating_streaming_jobs": (1, True),
    #: checkpoints between state-maintenance passes (rehash + counter
    #: checks); >1 amortizes the per-barrier device syncs
    "maintenance_interval_checkpoints": (1, True),
    #: checkpoints between in-memory snapshots; >1 amortizes the
    #: incremental shadow-snapshot dispatch (recovery falls back up to
    #: N-1 extra epochs)
    "snapshot_interval_checkpoints": (1, True),
    #: max sealed-but-not-yet-durable epochs in the async checkpoint
    #: uploader before the barrier loop write-stalls (the checkpoint
    #: analog of the storage L0-depth stall)
    "checkpoint_upload_window": (4, True),
    "pause_on_next_bootstrap": (False, True),
}




def _coerce(default, value):
    """Type-safe coercion for param writes (bool('false') is True...)."""
    if isinstance(default, bool):
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            low = value.strip().lower()
            if low in ("true", "t", "on", "1"):
                return True
            if low in ("false", "f", "off", "0"):
                return False
            raise ValueError(f"not a boolean: {value!r}")
        return bool(value)
    if isinstance(default, int):
        if isinstance(value, float) and value != int(value):
            raise ValueError(f"not an integer: {value!r}")
        return int(value)
    if isinstance(default, float):
        return float(value)
    return type(default)(value)


class SystemParams:
    def __init__(self, overrides: dict | None = None):
        self._values = {k: v for k, (v, _) in _SYSTEM_PARAM_DEFS.items()}
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def get(self, name: str):
        if name not in self._values:
            raise KeyError(f"unknown system param {name!r}")
        return self._values[name]

    def set(self, name: str, value) -> None:
        if name not in _SYSTEM_PARAM_DEFS:
            raise KeyError(f"unknown system param {name!r}")
        default, mutable = _SYSTEM_PARAM_DEFS[name]
        if not mutable:
            raise ValueError(f"system param {name!r} is immutable")
        self._values[name] = _coerce(default, value)

    def to_dict(self) -> dict:
        return dict(self._values)


# ---------------------------------------------------------------------------
# session config (ref session_config/mod.rs — SET-able per session)

_SESSION_DEFS = {
    "query_epoch": (0, "read at a specific committed epoch (0 = latest)"),
    "streaming_parallelism": (
        1, "1 = linear; 0 = adaptive (all devices); N = N shards"
    ),
    "timezone": ("UTC", "display timezone"),
    "batch_row_limit": (1_000_000, "serving scan cap"),
}


class SessionConfig:
    def __init__(self):
        self._values = {k: v for k, (v, _) in _SESSION_DEFS.items()}

    def get(self, name: str):
        if name not in self._values:
            raise KeyError(f"unknown session variable {name!r}")
        return self._values[name]

    def set(self, name: str, value) -> None:
        if name not in _SESSION_DEFS:
            raise KeyError(f"unknown session variable {name!r}")
        default, _ = _SESSION_DEFS[name]
        self._values[name] = _coerce(default, value)

    def show_all(self) -> list[tuple[str, str, str]]:
        return [
            (k, str(self._values[k]), _SESSION_DEFS[k][1])
            for k in sorted(self._values)
        ]
