"""Data-plane primitives: types, chunks, hashing, epochs.

Reference counterpart: ``src/common`` (see SURVEY.md §2.2).

Exports resolve lazily (PEP 562): ``common.types``/``common.chunk``
import jax, but jax-free processes (the engine-free serving tier) need
``common.metrics`` and must be able to import the package without
paying — or even having — jax.
"""

_LAZY = {
    "DataType": ("risingwave_tpu.common.types", "DataType"),
    "Field": ("risingwave_tpu.common.types", "Field"),
    "Schema": ("risingwave_tpu.common.types", "Schema"),
    "Chunk": ("risingwave_tpu.common.chunk", "Chunk"),
    "StrCol": ("risingwave_tpu.common.chunk", "StrCol"),
    "OP_INSERT": ("risingwave_tpu.common.chunk", "OP_INSERT"),
    "OP_DELETE": ("risingwave_tpu.common.chunk", "OP_DELETE"),
    "OP_UPDATE_DELETE": ("risingwave_tpu.common.chunk",
                         "OP_UPDATE_DELETE"),
    "OP_UPDATE_INSERT": ("risingwave_tpu.common.chunk",
                         "OP_UPDATE_INSERT"),
    "Epoch": ("risingwave_tpu.common.epoch", "Epoch"),
    "EpochPair": ("risingwave_tpu.common.epoch", "EpochPair"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value
    return value
