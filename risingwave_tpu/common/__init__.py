"""Data-plane primitives: types, chunks, hashing, epochs.

Reference counterpart: ``src/common`` (see SURVEY.md §2.2).
"""

from risingwave_tpu.common.types import (  # noqa: F401
    DataType,
    Field,
    Schema,
)
from risingwave_tpu.common.chunk import (  # noqa: F401
    Chunk,
    StrCol,
    OP_INSERT,
    OP_DELETE,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
)
from risingwave_tpu.common.epoch import Epoch, EpochPair  # noqa: F401
