"""Mask compaction primitives, backend-adaptive.

The same logical op has opposite cost profiles per backend (all
measured, see ARCHITECTURE.md perf notes):

- ``jnp.nonzero(mask, size=k)`` lowers to a cumsum + full-size
  scatter: ~18.6ms on TPU for a 2^18 mask (the single hottest op in
  barrier flush) but only ~2.7ms on CPU.
- ``lax.top_k`` is a tuned TPU primitive (~0.02ms for the same shape)
  but on CPU costs ~34ms (it lowers to a full variadic sort per call).

Round 2 switched everything to top_k and silently made the CPU path
~6x slower (the round-2 q7 "4x regression"); the strategy is now
selected once per process from ``jax.default_backend()`` — a
trace-time Python branch, so each backend compiles only its fast op.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.cache
def accel_tuned() -> bool:
    """True when compiling for an accelerator (TPU tunings apply)."""
    return jax.default_backend() != "cpu"


def mask_indices(mask: jnp.ndarray, k: int, fill) -> jnp.ndarray:
    """Indices of up to ``k`` set bits of ``mask`` (ascending), ``fill``
    for the rest.

    TPU: ``lax.top_k`` (tie-break = ascending index, a drop-in for
    nonzero's order).  CPU: ``jnp.nonzero`` (top_k is ~13x slower
    there)."""
    if accel_tuned():
        vals, idx = jax.lax.top_k(mask.astype(jnp.int32), k)
        return jnp.where(vals > 0, idx, jnp.asarray(fill, idx.dtype))
    (idx,) = jnp.nonzero(mask, size=k, fill_value=fill)
    return idx.astype(jnp.int32)


def segment_starts(sorted_neq: jnp.ndarray) -> jnp.ndarray:
    """[n-1] adjacent-inequality -> [n] is-segment-start mask."""
    return jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_neq]
    )


def segment_start_positions(starts: jnp.ndarray) -> jnp.ndarray:
    """Running index of each row's segment start (int32 [n]).

    One ``cummax`` — the building block for the cheap segmented
    reductions below.  (``associative_scan`` would unroll to ~8 ops per
    level × log2(n) levels; at TPU's per-op launch floor that costs
    milliseconds, while cumsum/cummax lower to single reduce-window
    ops.)"""
    idx = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(starts, idx, 0))


def segmented_sum(values: jnp.ndarray, start_pos: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented running sum; the value at each segment's END
    is the segment total.  cumsum + gather-of-prefix — 4 ops total."""
    c = jnp.cumsum(values, axis=0, dtype=values.dtype)
    prev = jnp.maximum(start_pos - 1, 0)
    base = jnp.where(start_pos > 0, c[prev], jnp.zeros((), values.dtype))
    return c - base


def segmented_minmax_at_ends(seg_id: jnp.ndarray, values: jnp.ndarray,
                             start_pos: jnp.ndarray, mode: str):
    """Per-segment min or max of ``values``, available at every row of
    the segment (in particular its END, where the representative row
    lives).

    One secondary sort by (segment id, value): the segment's min lands
    on its start row and its max on its end row.  ``mode`` selects
    which to return ("min" | "max")."""
    _, sorted_v = jax.lax.sort((seg_id, values), num_keys=2)
    if mode == "min":
        return sorted_v[start_pos]    # value at segment start = min
    if mode == "max":
        return sorted_v               # value at own row; at END = max
    raise ValueError(mode)
