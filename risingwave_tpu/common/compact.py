"""Mask compaction primitives tuned for TPU.

``jnp.nonzero(mask, size=k)`` lowers to a cumsum + full-size scatter,
which on TPU costs ~milliseconds for table-sized masks (measured 18.6ms
for 2^18 — the single hottest op in barrier flush).  ``lax.top_k`` is a
tuned TPU primitive (~0.02ms for the same shape), and its tie-breaking
(equal values ordered by ascending index) makes it a drop-in
replacement for nonzero's ascending index order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mask_indices(mask: jnp.ndarray, k: int, fill) -> jnp.ndarray:
    """Indices of up to ``k`` set bits of ``mask`` (ascending), ``fill``
    for the rest — the fast equivalent of
    ``jnp.nonzero(mask, size=k, fill_value=fill)[0]``."""
    vals, idx = jax.lax.top_k(mask.astype(jnp.int32), k)
    return jnp.where(vals > 0, idx, jnp.asarray(fill, idx.dtype))


def segment_starts(sorted_neq: jnp.ndarray) -> jnp.ndarray:
    """[n-1] adjacent-inequality -> [n] is-segment-start mask."""
    return jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_neq]
    )


def segment_start_positions(starts: jnp.ndarray) -> jnp.ndarray:
    """Running index of each row's segment start (int32 [n]).

    One ``cummax`` — the building block for the cheap segmented
    reductions below.  (``associative_scan`` would unroll to ~8 ops per
    level × log2(n) levels; at TPU's per-op launch floor that costs
    milliseconds, while cumsum/cummax lower to single reduce-window
    ops.)"""
    idx = jnp.arange(starts.shape[0], dtype=jnp.int32)
    return jax.lax.cummax(jnp.where(starts, idx, 0))


def segmented_sum(values: jnp.ndarray, start_pos: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented running sum; the value at each segment's END
    is the segment total.  cumsum + gather-of-prefix — 4 ops total."""
    c = jnp.cumsum(values, axis=0, dtype=values.dtype)
    prev = jnp.maximum(start_pos - 1, 0)
    base = jnp.where(start_pos > 0, c[prev], jnp.zeros((), values.dtype))
    return c - base


def segmented_minmax_at_ends(seg_id: jnp.ndarray, values: jnp.ndarray,
                             start_pos: jnp.ndarray, mode: str):
    """Per-segment min or max of ``values``, available at every row of
    the segment (in particular its END, where the representative row
    lives).

    One secondary sort by (segment id, value): the segment's min lands
    on its start row and its max on its end row.  ``mode`` selects
    which to return ("min" | "max")."""
    _, sorted_v = jax.lax.sort((seg_id, values), num_keys=2)
    if mode == "min":
        return sorted_v[start_pos]    # value at segment start = min
    if mode == "max":
        return sorted_v               # value at own row; at END = max
    raise ValueError(mode)
