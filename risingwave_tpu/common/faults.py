"""Deterministic cluster-wide fault fabric + unified retry policy.

Reference counterpart: the madsim deterministic simulation
(src/tests/simulation) — every network partition, dropped packet and
crashed node in a reference chaos run is produced by a SEEDED
deterministic scheduler, so a failing run replays exactly.  This repo
cannot intercept the OS scheduler, but it owns every cross-process
seam (the JSON-RPC transport in ``cluster/rpc.py``, the object store
under storage/checkpoints), so the same property holds where it
matters: **identical seed ⇒ identical injected-fault sequence**.

The fabric generalizes the counter-addressed ``StoreFaults`` pattern
(storage/hummock/object_store.py): a rule fires on the Nth matching
operation — never on a random draw — and deterministic "randomness"
(schedule expansion, retry jitter) comes from splitmix64 over
``(seed, counter)``, a pure function with no hidden state.

Injection points:

- ``rpc`` ops at the CLIENT transport (cluster/rpc.py):
  ``drop``             the request never leaves (ConnectionError);
  ``delay``            sleep ``delay_s`` before sending;
  ``error_after_send`` the peer receives AND executes the call but the
                       response is lost (ConnectionError) — the probe
                       for non-idempotent handlers;
  one-way partitions select on the ``src>dst`` peer label, so meta→A
  can be dark while A→meta flows.
- ``put``/``get``/``delete`` at every ObjectStore (the global fabric
  is consulted next to each store's own ``StoreFaults``), with the
  same before/after (lost vs durable-then-error) split — plus PAYLOAD
  corruption modes ``bit_flip``/``truncate`` on put/get: the Nth
  matching operation's bytes are deterministically damaged (one bit
  chosen by splitmix64, or the tail cut) instead of erroring, the
  corruption-storm primitive the integrity layer's detect/quarantine/
  repair pipeline is proven against.  Corrupted keys are recorded
  (``corrupted_keys``) so a chaos harness can assert every planted
  corruption was detected.

Processes: the fabric is process-global (``install``/``get_fabric``)
and boots from the ``RWT_FAULTS`` env var — a JSON schedule — so a
chaos harness arms identical deterministic schedules inside spawned
worker/serving/meta subprocesses without any code in between.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field


def splitmix64(x: int) -> int:
    """Pure 64-bit mix (the digest scheme's position mixer): the
    fabric's only source of "randomness" — a function, not a stream."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


class FaultInjected(ConnectionError):
    """An injected transport fault (subclasses ConnectionError so every
    peer-unreachable code path handles it identically)."""


#: store-rule modes that damage payload bytes instead of erroring
CORRUPT_MODES = ("bit_flip", "truncate")


def corrupt_payload(data: bytes, mode: str, seed: int,
                    counter: int) -> bytes:
    """Deterministically damage one payload: flip the splitmix64-chosen
    bit, or cut the object to half its length.  Pure function of
    (bytes, mode, seed, counter) — the corruption-storm replay
    contract."""
    if not data:
        return data
    if mode == "truncate":
        return data[:max(1, len(data) // 2)]
    pos = splitmix64((seed << 8) ^ counter) % (len(data) * 8)
    out = bytearray(data)
    out[pos >> 3] ^= 1 << (pos & 7)
    return bytes(out)


@dataclass
class FabricRule:
    """One counter-addressed fault: fires on matching ops number
    ``after`` .. ``after + times - 1`` (0-based), then retires."""

    op: str                 # "rpc" | "put" | "get" | "delete"
    substr: str = ""        # matches "src>dst/method" (rpc) or the key
    after: int = 0
    mode: str = "drop"      # rpc: drop|delay|error_after_send
    #                       # store: before|after (StoreFaults split)
    times: int = 1
    delay_s: float = 0.0
    hits: int = 0
    seen: int = 0

    def to_json(self) -> dict:
        return {"op": self.op, "substr": self.substr,
                "after": self.after, "mode": self.mode,
                "times": self.times, "delay_s": self.delay_s}

    @staticmethod
    def from_json(d: dict) -> "FabricRule":
        return FabricRule(
            op=d["op"], substr=d.get("substr", ""),
            after=int(d.get("after", 0)), mode=d.get("mode", "drop"),
            times=int(d.get("times", 1)),
            delay_s=float(d.get("delay_s", 0.0)),
        )


class FaultFabric:
    """A deterministic fault schedule shared by every seam in one
    process.  Thread-safe: rule matching mutates per-rule counters
    under a lock, so concurrent RPC clients observe one global op
    order per rule (the order itself is the caller's schedule — tests
    that need total determinism drive ops single-threaded)."""

    def __init__(self, seed: int = 0,
                 rules: "list[FabricRule] | None" = None):
        self.seed = int(seed)
        self.rules: list[FabricRule] = list(rules or [])
        self._lock = threading.Lock()
        #: totals for assertions/metrics ({op: count})
        self.injected: dict[str, int] = {}
        self.delays: int = 0
        #: object keys whose payloads a corrupt-mode rule damaged —
        #: the chaos harness' "every planted corruption detected"
        #: ground truth
        self.corrupted_keys: list[str] = []

    # -- arming -----------------------------------------------------------
    def fail_rpc(self, substr: str = "", after: int = 0,
                 mode: str = "drop", times: int = 1,
                 delay_s: float = 0.0) -> None:
        assert mode in ("drop", "delay", "error_after_send"), mode
        self.rules.append(FabricRule("rpc", substr, after, mode, times,
                                     delay_s))

    def fail_store(self, op: str, substr: str = "", after: int = 0,
                   mode: str = "before", times: int = 1) -> None:
        assert op in ("put", "get", "delete") \
            and mode in ("before", "after") + CORRUPT_MODES
        assert not (mode in CORRUPT_MODES and op == "delete")
        self.rules.append(FabricRule(op, substr, after, mode, times))

    def partition(self, src: str, dst: str, times: int = 1 << 30,
                  after: int = 0) -> FabricRule:
        """One-way partition: every RPC labeled ``src>dst`` drops until
        ``heal()`` (the label carries direction — the reverse path
        stays up).  Returns the rule so the caller can heal it."""
        rule = FabricRule("rpc", f"{src}>{dst}/", after, "drop", times)
        self.rules.append(rule)
        return rule

    @staticmethod
    def heal(rule: FabricRule) -> None:
        rule.times = rule.hits  # retires without rewriting history

    # -- matching (called by the seams) -----------------------------------
    def _match(self, op: str, label: str) -> "FabricRule | None":
        with self._lock:
            for r in self.rules:
                if r.op != op or r.substr not in label \
                        or r.hits >= r.times:
                    continue
                r.seen += 1
                if r.seen > r.after:
                    r.hits += 1
                    self.injected[op] = self.injected.get(op, 0) + 1
                    return r
            return None

    def rpc_before_send(self, label: str) -> "FabricRule | None":
        """Consulted by RpcClient before writing the request.  Raises
        ``FaultInjected`` for drops; sleeps for delays; returns the
        rule for ``error_after_send`` so the client can lose the
        response after delivery."""
        r = self._match("rpc", label)
        if r is None:
            return None
        if r.mode == "delay":
            with self._lock:
                self.delays += 1
                self.injected[r.op] -= 1  # a delay is not an error
            time.sleep(r.delay_s)
            return None
        if r.mode == "drop":
            raise FaultInjected(f"injected rpc drop: {label}")
        return r  # error_after_send: caller delivers, then severs

    def store_before(self, op: str, key: str) -> "FabricRule | None":
        r = self._match(op, key)
        if r is not None and r.mode == "before":
            from risingwave_tpu.storage.hummock.object_store import (
                ObjectError,
            )
            raise ObjectError(f"injected {op} fault (lost): {key}")
        return r

    def store_after(self, rule: "FabricRule | None", op: str,
                    key: str) -> None:
        if rule is not None and rule.mode == "after":
            from risingwave_tpu.storage.hummock.object_store import (
                ObjectError,
            )
            raise ObjectError(f"injected {op} fault (durable): {key}")

    def store_corrupt(self, rule: "FabricRule | None", key: str,
                      data: bytes) -> bytes:
        """Apply a matched corrupt-mode rule to one payload (consulted
        by the stores between ``store_before`` and the actual I/O)."""
        if rule is None or rule.mode not in CORRUPT_MODES:
            return data
        with self._lock:
            self.corrupted_keys.append(key)
        return corrupt_payload(data, rule.mode, self.seed, rule.hits)

    # -- introspection -----------------------------------------------------
    def injected_total(self) -> int:
        with self._lock:
            return sum(self.injected.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": len(self.rules),
                "armed": sum(1 for r in self.rules if r.hits < r.times),
                "injected": dict(self.injected),
                "injected_total": sum(self.injected.values()),
                "delays": self.delays,
                "corrupted_keys": list(self.corrupted_keys),
            }

    # -- (de)serialization -------------------------------------------------
    def to_json(self) -> dict:
        return {"seed": self.seed,
                "rules": [r.to_json() for r in self.rules]}

    @staticmethod
    def from_json(d: dict) -> "FaultFabric":
        return FaultFabric(
            seed=int(d.get("seed", 0)),
            rules=[FabricRule.from_json(r) for r in d.get("rules", [])],
        )

    # -- seeded schedule expansion ----------------------------------------
    @staticmethod
    def storm(seed: int, op: str = "rpc", substr: str = "",
              n: int = 8, span: int = 64, modes: tuple = (),
              ) -> "FaultFabric":
        """Expand ``seed`` into ``n`` single-shot faults whose trigger
        offsets (0..span) and modes are pure functions of the seed —
        the deterministic storm generator every chaos schedule uses.
        Same seed, same storm; there is no RNG to drift."""
        if not modes:
            modes = ("drop",) if op == "rpc" else ("before",)
        fab = FaultFabric(seed=seed)
        for i in range(n):
            h = splitmix64((seed << 16) ^ i)
            after = h % max(span, 1)
            mode = modes[(h >> 32) % len(modes)]
            if op == "rpc":
                fab.fail_rpc(substr=substr, after=after, mode=mode)
            else:
                fab.fail_store(op, substr=substr, after=after,
                               mode=mode)
        return fab


# ---------------------------------------------------------------------------
# process-global fabric (the seam every transport/store consults)

_FABRIC: FaultFabric | None = None
_ENV_CHECKED = False
ENV_VAR = "RWT_FAULTS"


def install(fabric: "FaultFabric | None") -> "FaultFabric | None":
    """Install (or clear, with None) the process-global fabric."""
    global _FABRIC, _ENV_CHECKED
    _FABRIC = fabric
    _ENV_CHECKED = True
    return fabric


def get_fabric() -> "FaultFabric | None":
    """The process-global fabric; on first call, boots from the
    ``RWT_FAULTS`` env var (JSON — see FaultFabric.to_json) so
    subprocesses inherit the harness' schedule."""
    global _FABRIC, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            _FABRIC = FaultFabric.from_json(json.loads(spec))
    return _FABRIC


# ---------------------------------------------------------------------------
# unified retry policy (capped exponential backoff, deterministic jitter)


@dataclass
class RetryPolicy:
    """Retry transient failures with capped exponential backoff.

    Jitter is DETERMINISTIC — ``splitmix64(seed, attempt)`` scales the
    delay within ``[1 - jitter_frac, 1]`` — so a seeded chaos run
    replays its exact retry timeline.  Retries are only safe for
    idempotent or epoch-guarded calls; the caller picks the exception
    set (``ConnectionError``/``OSError`` by default: the peer never
    answered — ``RpcError`` means the peer REFUSED, which no retry
    fixes, so it is never retried here).
    """

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter_frac: float = 0.25
    seed: int = 0
    #: metrics label + registry (counters: rpc_retries_total,
    #: rpc_retry_gave_up_total)
    metrics: object = None
    op: str = "rpc"
    #: cumulative counters (introspection without a registry)
    retries: int = 0
    gave_up: int = 0
    sleeper: object = field(default=time.sleep, repr=False)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.base_delay_s * (2 ** (attempt - 1)),
                self.max_delay_s)
        if self.jitter_frac > 0.0:
            h = splitmix64((self.seed << 20) ^ attempt)
            frac = (h & 0xFFFFFFFF) / 0xFFFFFFFF
            d *= 1.0 - self.jitter_frac * frac
        return d

    def run(self, fn, retry_on: tuple = (ConnectionError, OSError),
            label: str = ""):
        """Call ``fn()``; on a retryable exception back off and retry
        up to ``max_attempts`` total calls, then re-raise."""
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                attempt += 1
                if attempt >= self.max_attempts:
                    self.gave_up += 1
                    if self.metrics is not None:
                        self.metrics.inc("rpc_retry_gave_up_total",
                                         op=label or self.op)
                    raise
                self.retries += 1
                if self.metrics is not None:
                    self.metrics.inc("rpc_retries_total",
                                     op=label or self.op)
                self.sleeper(self.delay(attempt))

    def call(self, client, method: str, **params):
        """Retrying ``RpcClient.call`` (the one-liner every control
        loop uses for idempotent/epoch-guarded calls)."""
        return self.run(lambda: client.call(method, **params),
                        label=method)
