"""Lightweight metrics registry (counters / gauges / histograms).

Reference counterpart (SURVEY.md §5.5): guarded Prometheus metrics
(src/common/metrics/src/guarded_metrics.rs) with per-subsystem
registries (``StreamingMetrics`` etc.).  Here: an in-process registry
with labeled series and a Prometheus-text exporter, feeding the
``rw_catalog``-style introspection the engine exposes.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict


class _Series:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _HistSeries:
    __slots__ = ("buckets", "counts", "total", "sum")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float):
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += 1
        self.sum += v


_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, _Series] = defaultdict(_Series)
        self._gauges: dict[tuple, _Series] = defaultdict(_Series)
        self._hists: dict[tuple, _HistSeries] = {}

    # ------------------------------------------------------------------
    def counter(self, name: str, **labels) -> None:
        raise TypeError("use inc()")

    def inc(self, name: str, amount: float = 1.0, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key].value += amount

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key].value = value

    def observe(self, name: str, value: float, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if key not in self._hists:
                self._hists[key] = _HistSeries(_DEFAULT_BUCKETS)
            self._hists[key].observe(value)

    def timer(self, name: str, **labels):
        """Context manager observing elapsed seconds into a histogram
        (the guarded-metrics ``start_timer`` analog) — used by the
        storage service for compaction/vacuum durations."""
        import time

        class _Timer:
            def __enter__(s):
                s.t0 = time.perf_counter()
                return s

            def __exit__(s, *exc):
                self.observe(name, time.perf_counter() - s.t0, **labels)

        return _Timer()

    def remove_series(self, name: str, **labels) -> None:
        """Drop one labeled series (counter/gauge/histogram).  The
        control plane retires a dead worker's per-worker gauges so the
        scrape surface reflects the live membership, not tombstones."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters.pop(key, None)
            self._gauges.pop(key, None)
            self._hists.pop(key, None)

    # ------------------------------------------------------------------
    def get(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        if key in self._counters:
            return self._counters[key].value
        if key in self._gauges:
            return self._gauges[key].value
        raise KeyError(name)

    def quantile(self, name: str, q: float, **labels) -> float:
        """Approximate quantile from histogram buckets (upper bound)."""
        key = (name, tuple(sorted(labels.items())))
        h = self._hists[key]
        target = q * h.total
        seen = 0
        for i, c in enumerate(h.counts):
            seen += c
            if seen >= target:
                return h.buckets[i] if i < len(h.buckets) else float("inf")
        return float("inf")

    def render_prometheus(self) -> str:
        """Prometheus text exposition (the scrape surface)."""
        out = []

        def fmt_labels(labels):
            if not labels:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            return "{" + inner + "}"

        with self._lock:
            for (name, labels), s in sorted(self._counters.items()):
                out.append(f"{name}{fmt_labels(labels)} {s.value}")
            for (name, labels), s in sorted(self._gauges.items()):
                out.append(f"{name}{fmt_labels(labels)} {s.value}")
            for (name, labels), h in sorted(self._hists.items()):
                acc = 0
                for i, b in enumerate(h.buckets):
                    acc += h.counts[i]
                    lb = dict(labels)
                    lb["le"] = b
                    out.append(
                        f"{name}_bucket{fmt_labels(sorted(lb.items()))} {acc}"
                    )
                lb = dict(labels)
                lb["le"] = "+Inf"
                out.append(
                    f"{name}_bucket{fmt_labels(sorted(lb.items()))} "
                    f"{h.total}"
                )
                out.append(f"{name}_count{fmt_labels(labels)} {h.total}")
                out.append(f"{name}_sum{fmt_labels(labels)} {h.sum}")
        return "\n".join(out) + "\n"


#: process-wide default registry (subsystems may make their own)
GLOBAL_METRICS = MetricsRegistry()
