"""Lightweight metrics registry (counters / gauges / histograms).

Reference counterpart (SURVEY.md §5.5): guarded Prometheus metrics
(src/common/metrics/src/guarded_metrics.rs) with per-subsystem
registries (``StreamingMetrics`` etc.).  Here: an in-process registry
with labeled series and a Prometheus-text exporter, feeding the
``rw_catalog``-style introspection the engine exposes.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict


class _Series:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _HistSeries:
    __slots__ = ("buckets", "counts", "total", "sum")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float):
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += 1
        self.sum += v


_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

#: wide-range duration grid for coarse control-plane phases (barrier
#: commits, replays): the default grid tops out at 10s, pushing any
#: slower observation into +Inf — useless for a bounded p99 gate on a
#: 1-core box where a compile-heavy round legitimately takes minutes
WIDE_SECONDS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
    300.0, 600.0,
)


def _fmt_le(b: float) -> str:
    """Prometheus exposition-format bound: ``0.005``, ``1``, ``2.5``
    — decimal notation, no trailing ``.0``, never an exponent repr."""
    s = f"{b:.10f}".rstrip("0").rstrip(".")
    return s if s else "0"


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, _Series] = defaultdict(_Series)
        self._gauges: dict[tuple, _Series] = defaultdict(_Series)
        self._hists: dict[tuple, _HistSeries] = {}

    # ------------------------------------------------------------------
    def counter(self, name: str, **labels) -> None:
        raise TypeError("use inc()")

    def inc(self, name: str, amount: float = 1.0, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key].value += amount

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key].value = value

    def observe(self, name: str, value: float, buckets=None,
                **labels) -> None:
        """``buckets`` picks the grid at series CREATION (first
        observe wins; later values are ignored — one series, one
        grid)."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if key not in self._hists:
                self._hists[key] = _HistSeries(
                    tuple(buckets) if buckets else _DEFAULT_BUCKETS)
            self._hists[key].observe(value)

    def timer(self, name: str, **labels):
        """Context manager observing elapsed seconds into a histogram
        (the guarded-metrics ``start_timer`` analog) — used by the
        storage service for compaction/vacuum durations."""

        class _Timer:
            def __enter__(s):
                s.t0 = time.perf_counter()
                return s

            def __exit__(s, *exc):
                self.observe(name, time.perf_counter() - s.t0, **labels)

        return _Timer()

    def remove_series(self, name: str, **labels) -> None:
        """Drop one labeled series (counter/gauge/histogram).  The
        control plane retires a dead worker's per-worker gauges so the
        scrape surface reflects the live membership, not tombstones."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters.pop(key, None)
            self._gauges.pop(key, None)
            self._hists.pop(key, None)

    def remove_where(self, name: str | None = None, **labels) -> None:
        """Bulk companion of ``remove_series``: drop EVERY series
        whose label set contains the given key/values (optionally
        restricted to one metric name).  ``DROP MATERIALIZED VIEW``
        retires a job's whole scrape footprint this way — the
        job-labeled families carry extra labels (``node``/``side``/
        ``phase``) the caller cannot enumerate."""
        want = tuple(labels.items())

        def match(key) -> bool:
            n, lbls = key
            if name is not None and n != name:
                return False
            d = dict(lbls)
            return all(d.get(k) == v for k, v in want)

        with self._lock:
            for store in (self._counters, self._gauges, self._hists):
                for k in [k for k in store if match(k)]:
                    del store[k]

    # ------------------------------------------------------------------
    def get(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        if key in self._counters:
            return self._counters[key].value
        if key in self._gauges:
            return self._gauges[key].value
        raise KeyError(name)

    def quantile(self, name: str, q: float, **labels) -> float:
        """Approximate quantile from histogram buckets.

        Always returns a bucket UPPER BOUND: the least bucket boundary
        ``b`` such that at least ``q`` of the observations are ``<= b``
        (``+inf`` when the quantile falls in the overflow bucket, and
        ``0.0`` for an empty histogram).  Consumers that form ratios of
        two quantiles — the ``barrier_spike_ratio`` gauge divides
        p99 by p50 — therefore compare like with like: both sides are
        boundaries of the same fixed bucket grid, never interpolated.
        """
        key = (name, tuple(sorted(labels.items())))
        h = self._hists[key]
        if h.total == 0:
            return 0.0
        target = q * h.total
        seen = 0
        for i, c in enumerate(h.counts):
            seen += c
            if seen >= target:
                return h.buckets[i] if i < len(h.buckets) else float("inf")
        return float("inf")

    def hist_counts(self, name: str, **labels) -> list[int]:
        """Bucket-count snapshot of one histogram series (empty list
        when the series does not exist yet).  Pair with
        ``quantile_delta`` for warmup-excluding tail gates."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            return list(h.counts) if h else []

    def quantile_delta(self, name: str, q: float, baseline,
                       **labels) -> float:
        """``quantile`` over only the observations made since
        ``baseline`` (a ``hist_counts`` snapshot) — how SLO gates
        exclude compile-heavy warmup rounds from a tail ceiling.
        Returns 0.0 when nothing was observed since the snapshot."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                return 0.0
            base = list(baseline) + [0] * (len(h.counts) - len(baseline))
            counts = [c - b for c, b in zip(h.counts, base)]
        total = sum(counts)
        if total <= 0:
            return 0.0
        target = q * total
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return h.buckets[i] if i < len(h.buckets) \
                    else float("inf")
        return float("inf")

    def render_prometheus(self) -> str:
        """Prometheus text exposition (the scrape surface): samples
        grouped per metric under one ``# TYPE`` line, ``le`` bucket
        labels in exposition-format convention (``0.005``, ``1``,
        ``+Inf`` — never ``1.0`` or an exponent repr)."""
        out = []

        def fmt_labels(labels):
            if not labels:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            return "{" + inner + "}"

        seen: set[str] = set()

        def type_line(name, kind):
            if name not in seen:
                seen.add(name)
                out.append(f"# TYPE {name} {kind}")

        with self._lock:
            for (name, labels), s in sorted(self._counters.items()):
                type_line(name, "counter")
                out.append(f"{name}{fmt_labels(labels)} {s.value}")
            for (name, labels), s in sorted(self._gauges.items()):
                type_line(name, "gauge")
                out.append(f"{name}{fmt_labels(labels)} {s.value}")
            for (name, labels), h in sorted(self._hists.items()):
                type_line(name, "histogram")
                acc = 0
                for i, b in enumerate(h.buckets):
                    acc += h.counts[i]
                    lb = dict(labels)
                    lb["le"] = _fmt_le(b)
                    out.append(
                        f"{name}_bucket{fmt_labels(sorted(lb.items()))} {acc}"
                    )
                lb = dict(labels)
                lb["le"] = "+Inf"
                out.append(
                    f"{name}_bucket{fmt_labels(sorted(lb.items()))} "
                    f"{h.total}"
                )
                out.append(f"{name}_count{fmt_labels(labels)} {h.total}")
                out.append(f"{name}_sum{fmt_labels(labels)} {h.sum}")
        return "\n".join(out) + "\n"


def merge_prometheus(scrapes: list[tuple[dict, str]]) -> str:
    """Merge per-process scrapes into ONE cluster exposition: each
    ``(identity_labels, text)`` section's sample lines gain the
    identity labels (``role=...``/``worker=...``), ``# TYPE`` lines
    are deduplicated and hoisted to the top (the format requires a
    family's TYPE before its first sample), and everything else
    passes through.  The meta's ``ctl cluster metrics`` surface."""
    type_lines: dict[str, str] = {}
    samples: list[str] = []
    for labels, text in scrapes:
        extra = ",".join(f'{k}="{v}"' for k, v in labels.items())
        for line in (text or "").splitlines():
            if not line.strip():
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 3:
                    type_lines.setdefault(parts[2], line)
                continue
            if line.startswith("#"):
                continue
            head, _, value = line.rpartition(" ")
            if not head:
                continue
            if "{" in head and head.endswith("}"):
                name = head[:head.index("{")]
                inner = head[head.index("{") + 1:-1]
                merged = f"{inner},{extra}" if extra else inner
            else:
                name = head
                merged = extra
            samples.append(
                f"{name}{{{merged}}} {value}" if merged else line
            )
    out = [type_lines[n] for n in sorted(type_lines)]
    out += samples
    return "\n".join(out) + "\n"


#: process-wide default registry (subsystems may make their own)
GLOBAL_METRICS = MetricsRegistry()
