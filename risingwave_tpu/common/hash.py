"""Vectorized hashing: vnode assignment and hash-table key hashing.

Reference counterparts:
- ``VirtualNode::compute_chunk`` — src/common/src/hash/consistent_hash/vnode.rs:151
  (vnode = crc32(dist-key bytes) % vnode_count, vectorized over a chunk)
- ``HashKey`` vectorized build  — src/common/src/hash/key_v2.rs:221
- crc32 hasher                  — src/common/src/util/hash_util.rs:25

TPU-first design
----------------
The crc32 inner loop is a table lookup per byte.  On device this is a
``[256]`` u32 gather per byte position, unrolled over the (static) key
byte width — entirely vectorized over the chunk's row dimension, so a
whole chunk's vnodes are computed in one fused XLA program (the
reference's `compute_chunk` is the same idea on CPU SIMD).

For open-addressing state tables we also provide a 64-bit mix hash
(`hash64_columns`) — cheaper than crc for wide probes and with better
avalanche for slot distribution.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import NCol, StrCol

def normalize_null_col(col) -> list:
    """Flatten a possibly-nullable column into hashable plain columns.

    An ``NCol`` becomes [payload-with-nulls-zeroed, null-flag]: equal
    values (including NULL==NULL, the *grouping* equality the reference
    uses for GROUP BY/DISTINCT keys) produce equal words, regardless of
    whatever garbage the payload held at null rows."""
    if not isinstance(col, NCol):
        return [col]
    data, null = col.data, col.null
    if isinstance(data, StrCol):
        zeroed = StrCol(
            jnp.where(null[:, None], jnp.uint8(0), data.data),
            jnp.where(null, 0, data.lens),
        )
    else:
        zeroed = jnp.where(null, jnp.zeros((), data.dtype), data)
    return [zeroed, null]

#: Default number of virtual nodes (ref vnode.rs:62 COUNT_FOR_COMPAT).
VNODE_COUNT = 256


@lru_cache(maxsize=1)
def _crc32_table() -> np.ndarray:
    poly = np.uint32(0xEDB88320)
    table = np.zeros(256, np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = np.where(c & 1, poly ^ (c >> np.uint32(1)), c >> np.uint32(1))
        table[i] = c
    return table


def _crc_step(state: jnp.ndarray, byte: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    idx = (state ^ byte.astype(jnp.uint32)) & jnp.uint32(0xFF)
    return (state >> jnp.uint32(8)) ^ table[idx]


def _key_words(col) -> list[jnp.ndarray]:
    """Decompose one fixed-width key column into unsigned integer words.

    SQL-equal values must produce equal words: floats are canonicalized
    (-0.0 → +0.0, all NaNs → one NaN) before bit extraction.  float64 is
    split double-double style into two float32 words because the TPU x64
    rewrite does not implement 64-bit bitcasts from floats.
    """
    if col.dtype == jnp.bool_:
        col = col.astype(jnp.int64)
    if jnp.issubdtype(col.dtype, jnp.floating):
        zero = jnp.zeros((), col.dtype)
        col = jnp.where(col == 0, zero, col)           # -0.0 == 0.0 in SQL
        col = jnp.where(jnp.isnan(col), jnp.full((), jnp.nan, col.dtype), col)
        if col.dtype == jnp.float64:
            hi = col.astype(jnp.float32)
            lo = (col - hi.astype(jnp.float64)).astype(jnp.float32)
            return [hi.view(jnp.uint32), lo.view(jnp.uint32)]
        return [col.view(jnp.uint32)]
    return [col.view(_unsigned_view(col.dtype))]


def crc32_columns(columns: Sequence, init: int = 0xFFFFFFFF) -> jnp.ndarray:
    """crc32 over the little-endian bytes of each row's key columns.

    ``columns`` are ``[cap]`` integer arrays and/or ``StrCol``s; returns
    ``uint32 [cap]``.  String padding bytes beyond ``lens`` are skipped so
    equal strings hash equally regardless of column width.
    """
    table = jnp.asarray(_crc32_table())
    state = None
    for col in columns:
        if isinstance(col, StrCol):
            cap, width = col.data.shape
            if state is None:
                state = jnp.full((cap,), init, jnp.uint32)
            for k in range(width):
                b = col.data[:, k]
                stepped = _crc_step(state, b, table)
                state = jnp.where(k < col.lens, stepped, state)
        else:
            for u in _key_words(col):
                nbytes = np.dtype(u.dtype).itemsize
                if state is None:
                    state = jnp.full(u.shape, init, jnp.uint32)
                for k in range(nbytes):
                    b = ((u >> (8 * k)) & 0xFF).astype(jnp.uint32)
                    state = _crc_step(state, b, table)
    if state is None:
        raise ValueError("no key columns")
    return ~state  # final xor, standard crc32


def _unsigned_view(dtype) -> jnp.dtype:
    return {
        jnp.dtype(jnp.int16): jnp.uint16,
        jnp.dtype(jnp.int32): jnp.uint32,
        jnp.dtype(jnp.int64): jnp.uint64,
        jnp.dtype(jnp.uint8): jnp.uint8,
        jnp.dtype(jnp.uint16): jnp.uint16,
        jnp.dtype(jnp.uint32): jnp.uint32,
        jnp.dtype(jnp.uint64): jnp.uint64,
    }[jnp.dtype(dtype)]


def compute_vnodes(
    key_columns: Sequence, vnode_count: int = VNODE_COUNT
) -> jnp.ndarray:
    """Vectorized vnode assignment for a chunk (ref vnode.rs:151).

    vnode = crc32(dist key) % vnode_count, returned as ``int32 [cap]``.

    Nullable (``NCol``) keys route by grouping equality: NULLs hash as
    a zeroed payload + null flag, so all NULL keys land on one vnode —
    exactly the reference's NULL-is-one-group GROUP BY routing.
    """
    flat: list = []
    for c in key_columns:
        flat.extend(normalize_null_col(c))
    h = crc32_columns(flat)
    return (h % jnp.uint32(vnode_count)).astype(jnp.int32)


_MIX_K1 = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio multiplier
_MIX_K2 = np.uint64(0xBF58476D1CE4E5B9)  # splitmix64 constants
_MIX_K3 = np.uint64(0x94D049BB133111EB)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _MIX_K2
    x = (x ^ (x >> np.uint64(27))) * _MIX_K3
    return x ^ (x >> np.uint64(31))


def hash64_i64_host(vals) -> np.ndarray:
    """Pure-numpy ``hash64_columns([int64 col])`` — bit-identical to
    the device path for a single NOT NULL int64 column (asserted by
    tests/test_exchange.py).  The Exchange-lite host paths (ingest
    leader batch slicing, reader-side vnode filters) hash thousands of
    tiny batches; eager jnp dispatch per batch costs more than the
    hash itself, so the host plane runs this numpy twin instead."""
    with np.errstate(over="ignore"):
        u = np.asarray(vals, np.int64).view(np.uint64)
        state = np.full(u.shape, _MIX_K1, np.uint64)  # seed 0 ^ K1
        x = state ^ (u * _MIX_K1)
        x = (x ^ (x >> np.uint64(30))) * _MIX_K2
        x = (x ^ (x >> np.uint64(27))) * _MIX_K3
        x = x ^ (x >> np.uint64(31))
    return np.where(x == ~np.uint64(0), ~np.uint64(1), x)


def hash64_columns(columns: Sequence, seed: int = 0) -> jnp.ndarray:
    """64-bit mix hash of key columns, ``uint64 [cap]``.

    Used for open-addressing state-table slot selection (the analog of
    the reference's ``HashKey`` + hasher in hash_join/hash_agg).

    The all-ones value is never returned (remapped to ~1): callers use
    ~0 as an "invalid row" sort sentinel, and the remap here keeps that
    convention consistent between chunk pre-aggregation sorts and the
    hash table's own probe hashing.
    """
    state = None
    for raw in columns:
        for col in normalize_null_col(raw):
            state = _hash64_one(col, state, seed)
    if state is None:
        raise ValueError("no key columns")
    return jnp.where(state == ~np.uint64(0), ~np.uint64(1), state)


def hash64_partial(columns: Sequence, seed: int = 0) -> jnp.ndarray:
    """Unfinalized mix state after folding ``columns``.

    Split-hash support for probes that re-derive a compound hash per
    iteration (the fused (hash, rank) join probe): fold the expensive
    prefix once, then ``hash64_extend`` the varying suffix column and
    ``hash64_finish`` per probe round.  The composition
    ``hash64_finish(hash64_extend(hash64_partial([a]), b))`` is EXACTLY
    ``hash64_columns([a, b])`` — entries placed by one are found by the
    other."""
    state = None
    for raw in columns:
        for col in normalize_null_col(raw):
            state = _hash64_one(col, state, seed)
    if state is None:
        raise ValueError("no key columns")
    return state


def hash64_extend(state: jnp.ndarray, col) -> jnp.ndarray:
    """Fold one more column into a ``hash64_partial`` state."""
    out = state
    for c in normalize_null_col(col):
        out = _hash64_one(c, out, 0)
    return out


def hash64_finish(state: jnp.ndarray) -> jnp.ndarray:
    """Finalize a partial state (the sentinel remap of hash64_columns)."""
    return jnp.where(state == ~np.uint64(0), ~np.uint64(1), state)


def _hash64_one(col, state, seed):
    if isinstance(col, StrCol):
        cap, width = col.data.shape
        if state is None:
            state = jnp.full((cap,), np.uint64(seed) ^ _MIX_K1, jnp.uint64)
        # fold 8-byte words; bytes at/after lens are masked to zero so
        # slot reuse with stale padding can never split equal strings
        words = width // 8 + (1 if width % 8 else 0)
        padded = jnp.pad(col.data, ((0, 0), (0, words * 8 - width)))
        byte_idx = jnp.arange(words * 8, dtype=jnp.int32)
        masked = jnp.where(byte_idx[None, :] < col.lens[:, None], padded, 0)
        w64 = masked.reshape(cap, words, 8).astype(jnp.uint64)
        shifts = (np.arange(8, dtype=np.uint64) * 8)
        folded = jnp.sum(w64 << shifts[None, None, :], axis=-1, dtype=jnp.uint64)
        for k in range(words):
            state = _mix64(state ^ folded[:, k] * _MIX_K1)
        state = _mix64(state ^ col.lens.astype(jnp.uint64))
    else:
        for w in _key_words(col):
            u = w.astype(jnp.uint64)
            if state is None:
                state = jnp.full(u.shape, np.uint64(seed) ^ _MIX_K1, jnp.uint64)
            state = _mix64(state ^ u * _MIX_K1)
    return state
