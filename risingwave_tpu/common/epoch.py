"""Epochs — the unit of consistency.

Reference counterpart: ``src/common/src/util/epoch.rs:31,36,156``.
An epoch is ``physical-ms-since-2021-04-01 << 16``; the low 16 bits are a
sequence number so multiple epochs can share a wall-clock millisecond.
Every barrier carries an ``EpochPair {curr, prev}``; state commits are
tagged with the epoch they seal.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

#: 2021-04-01T00:00:00Z in unix millis (ref epoch.rs UNIX_RISINGWAVE_DATE_EPOCH)
_EPOCH_BASE_MS = 1_617_235_200_000
EPOCH_PHYSICAL_SHIFT = 16


@dataclass(frozen=True, order=True)
class Epoch:
    value: int

    @staticmethod
    def now(prev: "Epoch | None" = None) -> "Epoch":
        phys = max(int(time.time() * 1000) - _EPOCH_BASE_MS, 0)
        e = phys << EPOCH_PHYSICAL_SHIFT
        if prev is not None and e <= prev.value:
            e = prev.value + 1  # monotonicity under clock skew / same-ms ticks
        return Epoch(e)

    @property
    def physical_ms(self) -> int:
        return self.value >> EPOCH_PHYSICAL_SHIFT

    def next(self) -> "Epoch":
        return Epoch.now(prev=self)

    def __repr__(self) -> str:
        return f"Epoch({self.value})"


INVALID_EPOCH = Epoch(0)


@dataclass(frozen=True)
class EpochPair:
    """(curr, prev) carried by every barrier (ref epoch.rs:156)."""

    curr: Epoch
    prev: Epoch

    @staticmethod
    def first() -> "EpochPair":
        return EpochPair(Epoch.now(), INVALID_EPOCH)

    def bump(self) -> "EpochPair":
        return EpochPair(self.curr.next(), self.curr)
