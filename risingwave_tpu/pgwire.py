"""Postgres wire-protocol server (simple query protocol, text format).

Reference counterpart: ``src/utils/pgwire`` (``pg_serve()``,
pg_server.rs:338) — the reference implements the full simple+extended
protocol with SSL and auth; this round covers the simple-query flow that
``psql`` and most drivers use for DDL + ad-hoc reads:

    StartupMessage → AuthenticationOk → ParameterStatus* →
    BackendKeyData → ReadyForQuery → (Query → RowDescription →
    DataRow* → CommandComplete → ReadyForQuery)*

Extended protocol (parse/bind/execute), SASL auth and SSL land in later
rounds; SSLRequest is answered with 'N' so clients fall back cleanly.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

from risingwave_tpu.common.types import DataType

# pg type OIDs for the text protocol
_OID = {
    DataType.BOOLEAN: 16,
    DataType.INT16: 21,
    DataType.INT32: 23,
    DataType.INT64: 20,
    DataType.FLOAT32: 700,
    DataType.FLOAT64: 701,
    DataType.DECIMAL: 1700,
    DataType.VARCHAR: 1043,
    DataType.BYTEA: 17,
    DataType.DATE: 1082,
    DataType.TIME: 1083,
    DataType.TIMESTAMP: 1114,
    DataType.TIMESTAMPTZ: 1184,
    DataType.INTERVAL: 1186,
    DataType.SERIAL: 20,
}

PROTOCOL_VERSION = 196608       # 3.0
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 — the protocol state machine
        sock: socket.socket = self.request
        engine = self.server.engine
        lock = self.server.engine_lock
        f = sock.makefile("rwb")
        try:
            if not self._startup(f):
                return
            self._ready(f)
            while True:
                header = f.read(5)
                if len(header) < 5:
                    return
                tag, length = header[:1], struct.unpack("!I", header[1:])[0]
                body = f.read(length - 4)
                if tag == b"X":  # Terminate
                    return
                if tag != b"Q":  # only simple queries this round
                    self._error(f, f"unsupported message {tag!r}")
                    self._ready(f)
                    continue
                sql = body.rstrip(b"\x00").decode()
                try:
                    with lock:
                        cols, rows = engine.query(sql)
                    self._results(f, sql, cols, rows)
                except Exception as e:  # surface as pg error, keep session
                    self._error(f, str(e))
                self._ready(f)
        finally:
            f.close()

    # -- protocol pieces -------------------------------------------------
    def _startup(self, f) -> bool:
        while True:
            raw = f.read(4)
            if len(raw) < 4:
                return False
            length = struct.unpack("!I", raw)[0]
            body = f.read(length - 4)
            code = struct.unpack("!I", body[:4])[0]
            if code == SSL_REQUEST:
                f.write(b"N")
                f.flush()
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_VERSION:
                self._error(f, f"unsupported protocol {code}")
                return False
            break
        f.write(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "13.0 (risingwave_tpu 0.1)"),
            ("server_encoding", "UTF8"),
            ("client_encoding", "UTF8"),
        ):
            f.write(_msg(b"S", _cstr(k) + _cstr(v)))
        f.write(_msg(b"K", struct.pack("!II", 0, 0)))  # BackendKeyData
        f.flush()
        return True

    def _ready(self, f) -> None:
        f.write(_msg(b"Z", b"I"))
        f.flush()

    def _error(self, f, message: str) -> None:
        payload = b"SERROR\x00" + b"CXX000\x00" + b"M" + _cstr(message) + \
            b"\x00"
        f.write(_msg(b"E", payload))
        f.flush()

    def _results(self, f, sql: str, cols, rows) -> None:
        verb = sql.strip().split()[0].upper() if sql.strip() else "QUERY"
        if cols:
            desc = struct.pack("!H", len(cols))
            for name in cols:
                # text protocol: report every column as TEXT (oid 25);
                # typed OIDs (_OID) arrive with the extended protocol
                desc += _cstr(str(name)) + struct.pack(
                    "!IHIhiH", 0, 0, 25, -1, -1, 0
                )
            f.write(_msg(b"T", desc))
            for row in rows:
                data = struct.pack("!H", len(row))
                for v in row:
                    text = _pg_text(v)
                    data += struct.pack("!i", len(text)) + text
                f.write(_msg(b"D", data))
            tagline = f"SELECT {len(rows)}"
        else:
            tagline = {"CREATE": "CREATE", "DROP": "DROP",
                       "FLUSH": "FLUSH", "SET": "SET",
                       "ALTER": "ALTER SYSTEM"}.get(verb, verb)
        f.write(_msg(b"C", _cstr(tagline)))
        f.flush()


def _pg_text(v) -> bytes:
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return v
    if isinstance(v, float):
        return repr(v).encode()
    return str(v).encode()


class PgServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 4566,
                 engine_lock: threading.Lock | None = None):
        super().__init__((host, port), _Handler)
        self.engine = engine
        # the engine is single-threaded; serialize statements across
        # connections (the reference runs per-session tokio tasks over a
        # shared catalog — same effective serialization for DDL).  The
        # lock must be installed BEFORE accepting: callers sharing it
        # with a barrier ticker pass it here
        self.engine_lock = engine_lock or threading.Lock()


class SimpleClient:
    """Minimal simple-query-protocol client (text format).

    Used by ``risingwave_tpu.ctl`` and the protocol tests; real
    deployments use psql/any postgres driver."""

    def __init__(self, host: str, port: int, user: str = "tpu",
                 database: str = "dev"):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.f = self.sock.makefile("rwb")
        params = _cstr("user") + _cstr(user) + _cstr("database") + \
            _cstr(database) + b"\x00"
        body = struct.pack("!I", PROTOCOL_VERSION) + params
        self.f.write(struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        while self._read_msg()[0] != b"Z":
            pass

    def _read_msg(self):
        header = self.f.read(5)
        if len(header) < 5:
            raise ConnectionError("connection closed")
        return header[:1], self.f.read(
            struct.unpack("!I", header[1:])[0] - 4
        )

    def query(self, sql: str):
        body = sql.encode() + b"\x00"
        self.f.write(b"Q" + struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        cols, rows, error = [], [], None
        while True:
            tag, payload = self._read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                error = payload.decode(errors="replace")
            elif tag == b"Z":
                if error:
                    raise RuntimeError(error)
                return cols, rows

    def close(self) -> None:
        self.f.write(b"X" + struct.pack("!I", 4))
        self.f.flush()
        self.sock.close()


def pg_serve(engine, host: str = "127.0.0.1", port: int = 4566,
             engine_lock: threading.Lock | None = None) -> PgServer:
    """Start serving in a background thread; returns the server handle
    (ref pg_serve, pg_server.rs:338)."""
    server = PgServer(engine, host, port, engine_lock)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
