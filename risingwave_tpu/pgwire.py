"""Postgres wire-protocol server (simple + extended protocol, text
format, optional cleartext-password auth).

Reference counterpart: ``src/utils/pgwire`` (``pg_serve()``,
pg_server.rs:338; extended-protocol state machine pg_protocol.rs:340).

Simple flow:
    StartupMessage → [AuthenticationCleartextPassword → Password] →
    AuthenticationOk → ParameterStatus* → BackendKeyData →
    ReadyForQuery → (Query → RowDescription → DataRow* →
    CommandComplete → ReadyForQuery)*

Extended flow (what psycopg/JDBC default to):
    Parse → Bind → Describe → Execute → Sync
Parameters are text-format; ``$n`` placeholders substitute as SQL
literals at Bind time (the engine plans per-execution, so there is no
plan cache to parameterize — the reference's prepared-statement reuse
is a latency optimization this engine gets from its jit cache
instead).  Describe(portal) runs the query eagerly and caches rows so
RowDescription can be answered exactly; Execute drains the cache.

SASL/md5 auth and SSL stay unsupported; SSLRequest is answered 'N' so
clients fall back cleanly.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

from risingwave_tpu.common.types import DataType

# pg type OIDs for the text protocol
_OID = {
    DataType.BOOLEAN: 16,
    DataType.INT16: 21,
    DataType.INT32: 23,
    DataType.INT64: 20,
    DataType.FLOAT32: 700,
    DataType.FLOAT64: 701,
    DataType.DECIMAL: 1700,
    DataType.VARCHAR: 1043,
    DataType.BYTEA: 17,
    DataType.DATE: 1082,
    DataType.TIME: 1083,
    DataType.TIMESTAMP: 1114,
    DataType.TIMESTAMPTZ: 1184,
    DataType.INTERVAL: 1186,
    DataType.SERIAL: 20,
}

PROTOCOL_VERSION = 196608       # 3.0
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


#: pg text-type oids whose params must stay quoted even when the value
#: looks numeric ('007' as varchar must not become integer 7)
_TEXT_OIDS = {25, 1043, 18, 19, 1042}


def _substitute_params(sql: str, params: list,
                       oids: "list[int] | None" = None) -> str:
    """Inline text-format parameter values as SQL literals at their
    ``$n`` sites (outside string literals).  A param whose Parse-time
    oid names a text type always quotes; otherwise numbers inline
    bare, everything else single-quotes with '' escaping; None →
    NULL."""
    import re as _re

    def lit(idx: int, v) -> str:
        if v is None:
            return "NULL"
        s = v.decode() if isinstance(v, bytes) else str(v)
        oid = oids[idx] if oids and idx < len(oids) else 0
        if oid not in _TEXT_OIDS \
                and _re.fullmatch(r"-?\d+(\.\d+)?", s):
            return s
        return "'" + s.replace("'", "''") + "'"

    out: list[str] = []
    i, n = 0, len(sql)
    in_str = False
    while i < n:
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                in_str = False
            i += 1
            continue
        if ch == "'":
            in_str = True
            out.append(ch)
            i += 1
            continue
        if ch == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            idx = int(sql[i + 1:j]) - 1
            if idx < 0 or idx >= len(params):
                raise ValueError(f"parameter ${idx + 1} not bound")
            out.append(lit(idx, params[idx]))
            i = j
            continue
        out.append(ch)
        i += 1
    return "".join(out)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 — the protocol state machine
        sock: socket.socket = self.request
        engine = self.server.engine
        lock = self.server.engine_lock
        f = sock.makefile("rwb")
        #: extended-protocol session state
        stmts: dict[str, str] = {}           # name -> sql
        portals: dict[str, dict] = {}        # name -> {sql, cols?, rows?}
        in_error = False                     # skip-until-Sync
        try:
            if not self._startup(f):
                return
            self._ready(f)
            while True:
                header = f.read(5)
                if len(header) < 5:
                    return
                tag, length = header[:1], struct.unpack("!I", header[1:])[0]
                body = f.read(length - 4)
                if tag == b"X":  # Terminate
                    return
                if tag == b"S":  # Sync — ends an extended batch
                    in_error = False
                    self._ready(f)
                    continue
                if in_error and tag in (b"P", b"B", b"D", b"E", b"C",
                                        b"H"):
                    continue  # discard until Sync (pg_protocol.rs:340)
                if tag == b"Q":
                    sql = body.rstrip(b"\x00").decode()
                    try:
                        with lock:
                            cols, rows = engine.query(sql)
                        self._results(f, sql, cols, rows,
                                      with_desc=True)
                    except Exception as e:
                        self._error(f, str(e))
                    self._ready(f)
                    continue
                try:
                    if tag == b"P":  # Parse
                        name, off = self._take_cstr(body, 0)
                        sql, off = self._take_cstr(body, off)
                        noids = struct.unpack_from("!H", body, off)[0]
                        off += 2
                        oids = [
                            struct.unpack_from("!I", body,
                                               off + 4 * k)[0]
                            for k in range(noids)
                        ]
                        stmts[name] = (sql, oids)
                        f.write(_msg(b"1", b""))  # ParseComplete
                    elif tag == b"B":  # Bind
                        portal, off = self._take_cstr(body, 0)
                        sname, off = self._take_cstr(body, off)
                        nfmt = struct.unpack_from("!H", body, off)[0]
                        off += 2 + 2 * nfmt
                        nparams = struct.unpack_from("!H", body, off)[0]
                        off += 2
                        params: list = []
                        for _ in range(nparams):
                            ln = struct.unpack_from("!i", body, off)[0]
                            off += 4
                            if ln < 0:
                                params.append(None)
                            else:
                                params.append(body[off:off + ln])
                                off += ln
                        if sname not in stmts:
                            raise ValueError(
                                f"unknown prepared statement {sname!r}"
                            )
                        psql, poids = stmts[sname]
                        portals[portal] = {
                            "sql": _substitute_params(
                                psql, params, poids
                            ),
                        }
                        f.write(_msg(b"2", b""))  # BindComplete
                    elif tag == b"D":  # Describe
                        kind = body[:1]
                        name, _ = self._take_cstr(body, 1)
                        if kind == b"S":
                            if name not in stmts:
                                raise ValueError(
                                    f"unknown prepared statement "
                                    f"{name!r}"
                                )
                            dsql, doids = stmts[name]
                            nparams = max(self._count_params(dsql),
                                          len(doids))
                            pd = struct.pack("!H", nparams)
                            for k in range(nparams):
                                pd += struct.pack(
                                    "!I",
                                    doids[k] if k < len(doids) else 0,
                                )
                            f.write(_msg(b"t", pd))
                            # RowDescription for read-only statements:
                            # drivers on the describe-statement path
                            # (pgjdbc) need columns before Execute.
                            # Evaluated with NULL params — SELECTs have
                            # no side effects
                            verb = dsql.lstrip()[:8].lower()
                            if verb.startswith(("select", "show",
                                                "describe")):
                                trial = _substitute_params(
                                    dsql, [None] * nparams, doids
                                )
                                with lock:
                                    cols, _ = engine.query(trial)
                                if cols:
                                    self._row_description(f, cols)
                                else:
                                    f.write(_msg(b"n", b""))
                            else:
                                f.write(_msg(b"n", b""))  # NoData
                        else:
                            p = portals.get(name)
                            if p is None:
                                raise ValueError(
                                    f"unknown portal {name!r}"
                                )
                            # eager execution so RowDescription is
                            # exact; Execute drains the cache
                            with lock:
                                cols, rows = engine.query(p["sql"])
                            p["cols"], p["rows"] = cols, rows
                            if cols:
                                self._row_description(f, cols)
                            else:
                                f.write(_msg(b"n", b""))
                    elif tag == b"E":  # Execute
                        name, _ = self._take_cstr(body, 0)
                        p = portals.get(name)
                        if p is None:
                            raise ValueError(f"unknown portal {name!r}")
                        if "rows" not in p:
                            with lock:
                                p["cols"], p["rows"] = engine.query(
                                    p["sql"]
                                )
                        self._results(f, p["sql"], p["cols"],
                                      p["rows"], with_desc=False)
                    elif tag == b"C":  # Close
                        kind = body[:1]
                        name, _ = self._take_cstr(body, 1)
                        (stmts if kind == b"S" else portals).pop(
                            name, None
                        )
                        f.write(_msg(b"3", b""))  # CloseComplete
                    elif tag == b"H":  # Flush
                        pass
                    else:
                        raise ValueError(
                            f"unsupported message {tag!r}"
                        )
                    f.flush()
                except Exception as e:
                    self._error(f, str(e))
                    in_error = True
        finally:
            f.close()

    @staticmethod
    def _take_cstr(body: bytes, off: int) -> tuple[str, int]:
        end = body.index(b"\x00", off)
        return body[off:end].decode(), end + 1

    @staticmethod
    def _count_params(sql: str) -> int:
        import re as _re
        best = 0
        # the quoted-string alternative consumes literals first, so
        # $n inside strings never matches
        for m in _re.finditer(r"'[^']*'|\$(\d+)", sql):
            if m.group(1):
                best = max(best, int(m.group(1)))
        return best

    # -- protocol pieces -------------------------------------------------
    def _startup(self, f) -> bool:
        while True:
            raw = f.read(4)
            if len(raw) < 4:
                return False
            length = struct.unpack("!I", raw)[0]
            body = f.read(length - 4)
            code = struct.unpack("!I", body[:4])[0]
            if code == SSL_REQUEST:
                f.write(b"N")
                f.flush()
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_VERSION:
                self._error(f, f"unsupported protocol {code}")
                return False
            break
        password = getattr(self.server, "password", None)
        if password is not None:
            # AuthenticationCleartextPassword (ref pg_protocol auth;
            # the reference also speaks md5/SASL — cleartext is the
            # interoperable floor every driver supports)
            f.write(_msg(b"R", struct.pack("!I", 3)))
            f.flush()
            header = f.read(5)
            if len(header) < 5 or header[:1] != b"p":
                return False
            length = struct.unpack("!I", header[1:])[0]
            got = f.read(length - 4).rstrip(b"\x00").decode()
            if got != password:
                payload = b"SFATAL\x00" + b"C28P01\x00" + b"M" + _cstr(
                    "password authentication failed"
                ) + b"\x00"
                f.write(_msg(b"E", payload))
                f.flush()
                return False
        f.write(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "13.0 (risingwave_tpu 0.1)"),
            ("server_encoding", "UTF8"),
            ("client_encoding", "UTF8"),
        ):
            f.write(_msg(b"S", _cstr(k) + _cstr(v)))
        f.write(_msg(b"K", struct.pack("!II", 0, 0)))  # BackendKeyData
        f.flush()
        return True

    def _ready(self, f) -> None:
        f.write(_msg(b"Z", b"I"))
        f.flush()

    def _error(self, f, message: str) -> None:
        payload = b"SERROR\x00" + b"CXX000\x00" + b"M" + _cstr(message) + \
            b"\x00"
        f.write(_msg(b"E", payload))
        f.flush()

    def _row_description(self, f, cols) -> None:
        desc = struct.pack("!H", len(cols))
        for name in cols:
            # text protocol: report every column as TEXT (oid 25);
            # typed OIDs (_OID) would need the binder's fields here
            desc += _cstr(str(name)) + struct.pack(
                "!IHIhiH", 0, 0, 25, -1, -1, 0
            )
        f.write(_msg(b"T", desc))

    def _results(self, f, sql: str, cols, rows,
                 with_desc: bool = True) -> None:
        verb = sql.strip().split()[0].upper() if sql.strip() else "QUERY"
        if cols:
            if with_desc:
                self._row_description(f, cols)
            for row in rows:
                data = struct.pack("!H", len(row))
                for v in row:
                    text = _pg_text(v)
                    data += struct.pack("!i", len(text)) + text
                f.write(_msg(b"D", data))
            tagline = f"SELECT {len(rows)}"
        else:
            tagline = {"CREATE": "CREATE", "DROP": "DROP",
                       "FLUSH": "FLUSH", "SET": "SET",
                       "ALTER": "ALTER SYSTEM"}.get(verb, verb)
        f.write(_msg(b"C", _cstr(tagline)))
        f.flush()


def _pg_text(v) -> bytes:
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return v
    if isinstance(v, float):
        return repr(v).encode()
    return str(v).encode()


class PgServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 4566,
                 engine_lock: threading.Lock | None = None,
                 password: str | None = None):
        super().__init__((host, port), _Handler)
        self.engine = engine
        #: non-None enables cleartext-password auth at startup
        self.password = password
        # the engine is single-threaded; serialize statements across
        # connections (the reference runs per-session tokio tasks over a
        # shared catalog — same effective serialization for DDL).  The
        # lock must be installed BEFORE accepting: callers sharing it
        # with a barrier ticker pass it here
        self.engine_lock = engine_lock or threading.Lock()


class SimpleClient:
    """Minimal simple-query-protocol client (text format).

    Used by ``risingwave_tpu.ctl`` and the protocol tests; real
    deployments use psql/any postgres driver."""

    def __init__(self, host: str, port: int, user: str = "tpu",
                 database: str = "dev", password: str | None = None):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.f = self.sock.makefile("rwb")
        params = _cstr("user") + _cstr(user) + _cstr("database") + \
            _cstr(database) + b"\x00"
        body = struct.pack("!I", PROTOCOL_VERSION) + params
        self.f.write(struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        while True:
            tag, payload = self._read_msg()
            if tag == b"R" and len(payload) >= 4 \
                    and struct.unpack("!I", payload[:4])[0] == 3:
                pw = _cstr(password or "")
                self.f.write(b"p" + struct.pack("!I", len(pw) + 4) + pw)
                self.f.flush()
            elif tag == b"E":
                raise RuntimeError(payload.decode(errors="replace"))
            elif tag == b"Z":
                break

    def _read_msg(self):
        header = self.f.read(5)
        if len(header) < 5:
            raise ConnectionError("connection closed")
        return header[:1], self.f.read(
            struct.unpack("!I", header[1:])[0] - 4
        )

    def query(self, sql: str):
        body = sql.encode() + b"\x00"
        self.f.write(b"Q" + struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        cols, rows, error = [], [], None
        while True:
            tag, payload = self._read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                error = payload.decode(errors="replace")
            elif tag == b"Z":
                if error:
                    raise RuntimeError(error)
                return cols, rows

    def close(self) -> None:
        self.f.write(b"X" + struct.pack("!I", 4))
        self.f.flush()
        self.sock.close()

    # -- extended protocol (Parse/Bind/Describe/Execute/Sync) -----------
    def execute_prepared(self, sql: str, params=(), name: str = ""):
        """One extended-protocol round trip with text-format params.

        Returns (cols, rows) like query(); exercises the same message
        sequence psycopg/JDBC drivers emit by default."""
        def send(tag: bytes, payload: bytes) -> None:
            self.f.write(tag + struct.pack("!I", len(payload) + 4)
                         + payload)

        send(b"P", _cstr(name) + _cstr(sql) + struct.pack("!H", 0))
        bind = _cstr("") + _cstr(name) + struct.pack("!H", 0) \
            + struct.pack("!H", len(params))
        for v in params:
            if v is None:
                bind += struct.pack("!i", -1)
            else:
                b = str(v).encode()
                bind += struct.pack("!i", len(b)) + b
        bind += struct.pack("!H", 0)
        send(b"B", bind)
        send(b"D", b"P" + _cstr(""))
        send(b"E", _cstr("") + struct.pack("!I", 0))
        send(b"S", b"")
        self.f.flush()

        cols, rows, error = [], [], None
        saw = set()
        while True:
            tag, payload = self._read_msg()
            saw.add(tag)
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                error = payload.decode(errors="replace")
            elif tag == b"Z":
                if error:
                    raise RuntimeError(error)
                assert b"1" in saw and b"2" in saw, \
                    "Parse/Bind not acknowledged"
                return cols, rows


def pg_serve(engine, host: str = "127.0.0.1", port: int = 4566,
             engine_lock: threading.Lock | None = None,
             password: str | None = None) -> PgServer:
    """Start serving in a background thread; returns the server handle
    (ref pg_serve, pg_server.rs:338)."""
    server = PgServer(engine, host, port, engine_lock, password)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
