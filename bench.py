"""Benchmark driver: Nexmark q7-shaped streaming throughput per chip.

Pipeline: on-device bid generation → window projection → hash
aggregation (max price + count per 10s tumble), with a barrier flush
every ``CHUNKS_PER_BARRIER`` chunks — the BASELINE.md q5/q7 windowed-agg
configuration at the reference's default freshness envelope
(barrier_interval work-equivalent; see BASELINE.md).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured-TPU / measured-CPU-single-thread-equivalent
(the reference publishes no absolute numbers — BASELINE.md; the north
star is >=5x vs CPU rows/sec at equal freshness).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import risingwave_tpu  # noqa: F401  (platform/x64 config before backend init)

import jax
import jax.numpy as jnp

from __graft_entry__ import _q7_executors
from risingwave_tpu.stream.fragment import Fragment

CHUNK_CAP = 8192
CHUNKS = 64
CHUNKS_PER_BARRIER = 8
TABLE_SIZE = 1 << 16
EMIT_CAP = 4096


def measure_rows_per_sec() -> float:
    gen, project, agg = _q7_executors(TABLE_SIZE, EMIT_CAP)
    frag = Fragment([project, agg], name="nexmark_q7_bench")
    states = frag.init_states()

    # one fused program: generate + project + aggregate
    @jax.jit
    def fused_step(states, k0):
        chunk = gen._bids_impl(k0, CHUNK_CAP)
        states, _ = frag._step_impl(states, chunk)
        return states

    # warmup / compile
    states = fused_step(states, jnp.int64(0))
    states, _ = frag.flush(states, 0)
    jax.block_until_ready(states)

    t0 = time.perf_counter()
    k = 0
    for b in range(CHUNKS // CHUNKS_PER_BARRIER):
        for _ in range(CHUNKS_PER_BARRIER):
            states = fused_step(states, jnp.int64((k + 1) * CHUNK_CAP))
            k += 1
        states, _ = frag.flush(states, b)
    jax.block_until_ready(states)
    dt = time.perf_counter() - t0
    return CHUNKS * CHUNK_CAP / dt


def _cpu_baseline() -> float:
    """Same workload on one CPU device, in a subprocess."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RWT_BENCH_RAW"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.splitlines():
        if line.startswith("RAW "):
            return float(line.split()[1])
    raise RuntimeError(f"cpu baseline failed: {out.stderr[-500:]}")


def main() -> None:
    rows_per_sec = measure_rows_per_sec()
    if os.environ.get("RWT_BENCH_RAW"):
        print(f"RAW {rows_per_sec}")
        return
    try:
        cpu = _cpu_baseline()
        vs = rows_per_sec / cpu
    except Exception as e:
        print(f"warning: cpu baseline failed, vs_baseline=0: {e}",
              file=sys.stderr)
        vs = 0.0
    print(json.dumps({
        "metric": "nexmark_q7_windowed_agg_throughput",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
