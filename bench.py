"""Benchmark driver: Nexmark streaming throughput per chip via SQL.

Runs the BASELINE.md configurations end-to-end through the SQL engine
(source generation on device → jitted fragment steps → device MV), at
the reference's default freshness envelope (checkpoint every barrier).

- q1: stateless project over the bid stream
- q5: sliding-window (hop) bid counts per auction  (windowed hash agg)
- q7: tumbling-window max price                    (windowed hash agg)
- q8: windowed person × auction join

Prints ONE json line {"metric", "value", "unit", "vs_baseline"} for the
headline metric (q7; override with RWT_BENCH_QUERY=q1|q5|q7|q8|all —
"all" reports q7 as the json line and the rest on stderr).
``vs_baseline`` is measured-TPU / measured-CPU on the identical workload
(the reference publishes no absolute numbers — BASELINE.md; north star
is >=5x vs CPU at equal freshness).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import risingwave_tpu  # noqa: F401  (platform/x64 config before backend init)

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig

CHUNK_CAP = 8192
# warmup must cover one snapshot barrier (interval 8) so the snapshot
# copy's compile stays out of the measured window; the consistency
# audit compiles after the window (see measure())
WARMUP_BARRIERS = 9
BARRIERS = 32
CHUNKS_PER_BARRIER = 8

# q8 uses a lower event rate + 1s windows: per-(window, hot-seller)
# auction counts must fit the join's bucket depth this round
# (degree-adaptive join storage is queued for the next round)
SOURCES = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid',
        nexmark.event.rate = '{rate}');
CREATE SOURCE person (
    id BIGINT, name VARCHAR, date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'person',
        nexmark.event.rate = '{rate}');
CREATE SOURCE auction (
    id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
    date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'auction',
        nexmark.event.rate = '{rate}');
"""

RATES = {"q8": "2000"}

QUERIES = {
    "q1": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, bidder, 0.908 * price AS price, date_time
        FROM bid;
    """,
    "q5": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, window_start, count(*) AS bids
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY auction, window_start;
    """,
    "q7": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT window_start, max(price) AS max_price, count(*) AS bids
        FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
        GROUP BY window_start;
    """,
    "q8": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT p.id AS id, p.name AS name, a.reserve AS reserve
        FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
        JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
        ON p.id = a.seller AND p.window_start = a.window_start;
    """,
}


def measure(query: str) -> float:
    eng = Engine(PlannerConfig(
        chunk_capacity=CHUNK_CAP,
        agg_table_size=1 << 18,
        agg_emit_capacity=4096,
        join_table_size=1 << 13,
        join_bucket_cap=64,
        join_out_capacity=1 << 18,
        # q8: persons are (window, id)-unique — many keys, depth 4;
        # auctions concentrate on hot sellers — fewer keys, depth 128
        join_left_table_size=1 << 18,
        join_left_bucket_cap=4,
        join_right_table_size=1 << 14,
        join_right_bucket_cap=128,
        mv_table_size=1 << 18,
        # q1/q8 materialize every output row; the ring must hold the
        # whole warmup+measured window (the lap counter voids lossy runs)
        mv_ring_size=1 << 23 if query in ("q1", "q8") else 1 << 21,
        topn_pool_size=1 << 14,
    ))
    eng.execute(SOURCES.format(rate=RATES.get(query, "1000000")))
    eng.execute(QUERIES[query])
    # snapshots (the durability/freshness envelope) stay at every 8
    # checkpoints — they are pure device-side copies.  The consistency
    # AUDIT does a device→host counter read, and on the tunneled chip
    # ONE such read permanently degrades async dispatch ~50x, so it
    # runs once AFTER the measured window instead of on a cadence.
    eng.execute(
        "ALTER SYSTEM SET maintenance_interval_checkpoints = 1000000"
    )
    eng.execute("ALTER SYSTEM SET snapshot_interval_checkpoints = 8")
    eng.tick(barriers=WARMUP_BARRIERS,
             chunks_per_barrier=CHUNKS_PER_BARRIER)  # compile + warm state
    import jax
    jax.block_until_ready(eng.jobs[0].states)

    t0 = time.perf_counter()
    eng.tick(barriers=BARRIERS, chunks_per_barrier=CHUNKS_PER_BARRIER)
    jax.block_until_ready(eng.jobs[0].states)
    dt = time.perf_counter() - t0
    rows = eng.metrics.get("stream_rows_total", job="bench_mv") \
        - WARMUP_BARRIERS * CHUNKS_PER_BARRIER * CHUNK_CAP * (
            2 if query == "q8" else 1)
    # post-window consistency audit: overflow/inconsistency in the
    # measured stream would raise here and void the result
    eng.execute("ALTER SYSTEM SET maintenance_interval_checkpoints = 1")
    eng.tick(barriers=1, chunks_per_barrier=0)
    return rows / dt


def _subprocess_measure(query: str, cpu: bool) -> float:
    """Measure one query in a fresh process.

    Each query gets its own process even on the accelerator: the
    post-window consistency audit performs a device readback, and on the
    tunneled chip one readback permanently degrades async dispatch for
    the remainder of the process (~50x) — a second query measured in the
    same process reports the degraded number, not its own."""
    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    env["RWT_BENCH_RAW"] = "1"
    env["RWT_BENCH_QUERY"] = query
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=2000,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if not cpu and "accelerator unavailable" in out.stderr:
        # the child fell back to CPU — its number is NOT a device
        # number; surface loudly so a degraded tunnel can't masquerade
        # as a TPU result
        print(f"warning: {query} device subprocess fell back to CPU",
              file=sys.stderr)
    for line in out.stdout.splitlines():
        if line.startswith("RAW "):
            return float(line.split()[1])
    raise RuntimeError(
        f"{'cpu' if cpu else 'device'} measure failed: {out.stderr[-500:]}"
    )


def _cpu_baseline(query: str) -> float:
    return _subprocess_measure(query, cpu=True)


def _ensure_backend(timeout_s: float = 240.0) -> None:
    """Fall back to CPU if the accelerator backend cannot initialize.

    A dead TPU tunnel HANGS inside ``jax.devices()`` rather than
    raising, so the probe runs in a watchdog thread; on timeout (or
    error) the process re-execs itself with ``JAX_PLATFORMS=cpu`` —
    the driver must always get its JSON line, labeled via stderr."""
    if os.environ.get("RWT_BENCH_NO_PROBE"):
        return
    import threading

    result: dict = {}

    def probe():
        try:
            import jax

            jax.devices()
            result["ok"] = True
        except Exception as e:  # init error: also fall back
            result["err"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result.get("ok"):
        return
    why = result.get("err", f"backend init hung > {timeout_s:.0f}s")
    print(f"warning: accelerator unavailable ({why}); "
          "re-executing on CPU", file=sys.stderr)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RWT_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    query = os.environ.get("RWT_BENCH_QUERY", "q7")
    if os.environ.get("RWT_BENCH_RAW"):
        _ensure_backend()
        print(f"RAW {measure(query)}")
        return
    queries = list(QUERIES) if query == "all" else [query]
    results = {}
    if query != "all":
        _ensure_backend()
    # "all" isolates each query in a subprocess (a post-window device
    # readback degrades async dispatch for the rest of a process on the
    # tunneled chip) and the PARENT never claims the accelerator — a
    # parent claim could starve the children's claims on a one-chip
    # tunnel
    for q in queries:
        results[q] = _subprocess_measure(q, cpu=False) \
            if query == "all" else measure(q)
        if q != "q7" or query != "all":
            print(f"# {q}: {results[q]:,.0f} rows/s", file=sys.stderr)
    headline = "q7" if query == "all" else query
    try:
        cpu = _cpu_baseline(headline)
        vs = results[headline] / cpu
        print(f"# cpu baseline {headline}: {cpu:,.0f} rows/s",
              file=sys.stderr)
    except Exception as e:
        print(f"warning: cpu baseline failed, vs_baseline=0: {e}",
              file=sys.stderr)
        vs = 0.0
    print(json.dumps({
        "metric": f"nexmark_{headline}_throughput",
        "value": round(results[headline], 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
