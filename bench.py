"""Benchmark driver: Nexmark streaming throughput per chip via SQL.

Runs the BASELINE.md configurations end-to-end through the SQL engine
(source generation on device → jitted fragment steps → device MV), at
the reference's default freshness envelope (checkpoint every barrier).

- q1: stateless project over the bid stream
- q5: sliding-window (hop) bid counts per auction  (windowed hash agg)
- q7: tumbling-window max price                    (windowed hash agg)
- q8: windowed person × auction join

Prints ONE json line for the headline metric (q7), with every query's
number embedded under "queries" (override with
RWT_BENCH_QUERY=q1|q5|q7|q8|all; default "all" so the driver artifact
records all four).  ``vs_baseline`` is measured-device / measured-CPU
on the identical workload (the reference publishes no absolute numbers
— BASELINE.md; north star is >=5x vs CPU at equal freshness).

Accelerator forensics: the parent probes the backend ONCE in a
throwaway subprocess (a dead tunnel HANGS in jax.devices(), it never
raises).  On failure the children run on CPU directly and the json
line carries a "blocker" record — what hung, for how long, plus the
round's probe history from TPU_PROBE_LOG.jsonl — so a degraded tunnel
can't masquerade as a TPU result or a silent fallback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import risingwave_tpu  # noqa: F401  (platform/x64 config before backend init)

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig

CHUNK_CAP = 8192
# warmup must cover one snapshot barrier (interval 8) so the snapshot
# copy's compile stays out of the measured window; the consistency
# audit compiles after the window (see measure())
WARMUP_BARRIERS = 9
BARRIERS = 32
CHUNKS_PER_BARRIER = 8

SOURCES = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'bid',
        nexmark.event.rate = '{rate}');
CREATE SOURCE person (
    id BIGINT, name VARCHAR, date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'person',
        nexmark.event.rate = '{rate}');
CREATE SOURCE auction (
    id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
    date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'auction',
        nexmark.event.rate = '{rate}');
"""

#: per-query event-rate overrides (none: the degree-adaptive pool join
#: runs q8 at the same full rate as every other query)
RATES: dict = {}

QUERIES = {
    "q1": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, bidder, 0.908 * price AS price, date_time
        FROM bid;
    """,
    "q5": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, window_start, count(*) AS bids
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY auction, window_start;
    """,
    "q7": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT window_start, max(price) AS max_price, count(*) AS bids
        FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
        GROUP BY window_start;
    """,
    "q8": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT p.id AS id, p.name AS name, a.reserve AS reserve
        FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
        JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
        ON p.id = a.seller AND p.window_start = a.window_start;
    """,
}


def measure(query: str) -> float:
    eng = Engine(PlannerConfig(
        chunk_capacity=CHUNK_CAP,
        agg_table_size=1 << 18,
        agg_emit_capacity=4096,
        # q8 state is rate x live-window-span rows per side (~2.7M in
        # the measured window before the watermark closes anything):
        # the shared pool holds them with NO per-key cap — hot sellers
        # need no hand-tuned bucket depths and no rate limiting
        join_left_table_size=1 << 22,
        join_right_table_size=1 << 18,
        join_pool_size=1 << 22,
        # out_capacity sizes every emission window chunk; oversizing
        # it taxes every chunk with dead rows (measured 3.6x on q8)
        join_out_capacity=1 << 12,
        mv_table_size=1 << 18,
        # q1/q8 materialize every output row; the ring must hold the
        # whole warmup+measured window (the lap counter voids lossy runs)
        mv_ring_size=1 << 23 if query in ("q1", "q8") else 1 << 21,
        topn_pool_size=1 << 14,
    ))
    eng.execute(SOURCES.format(rate=RATES.get(query, "1000000")))
    eng.execute(QUERIES[query])
    # snapshots (the durability/freshness envelope) stay at every 8
    # checkpoints — they are pure device-side copies.  The consistency
    # AUDIT does a device→host counter read, and on the tunneled chip
    # ONE such read permanently degrades async dispatch ~50x, so it
    # runs once AFTER the measured window instead of on a cadence.
    eng.execute(
        "ALTER SYSTEM SET maintenance_interval_checkpoints = 1000000"
    )
    eng.execute("ALTER SYSTEM SET snapshot_interval_checkpoints = 8")
    eng.tick(barriers=WARMUP_BARRIERS,
             chunks_per_barrier=CHUNKS_PER_BARRIER)  # compile + warm state
    import jax
    jax.block_until_ready(eng.jobs[0].states)

    t0 = time.perf_counter()
    eng.tick(barriers=BARRIERS, chunks_per_barrier=CHUNKS_PER_BARRIER)
    jax.block_until_ready(eng.jobs[0].states)
    dt = time.perf_counter() - t0
    rows = eng.metrics.get("stream_rows_total", job="bench_mv") \
        - WARMUP_BARRIERS * CHUNKS_PER_BARRIER * CHUNK_CAP * (
            2 if query == "q8" else 1)
    # post-window consistency audit: overflow/inconsistency in the
    # measured stream would raise here and void the result
    eng.execute("ALTER SYSTEM SET maintenance_interval_checkpoints = 1")
    eng.tick(barriers=1, chunks_per_barrier=0)
    return rows / dt


def _subprocess_measure(query: str, cpu: bool) -> float:
    """Measure one query in a fresh process.

    Each query gets its own process even on the accelerator: the
    post-window consistency audit performs a device readback, and on the
    tunneled chip one readback permanently degrades async dispatch for
    the remainder of the process (~50x) — a second query measured in the
    same process reports the degraded number, not its own."""
    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["RWT_BENCH_NO_PROBE"] = "1"
    env["RWT_BENCH_RAW"] = "1"
    env["RWT_BENCH_QUERY"] = query
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=2400,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if not cpu and "accelerator unavailable" in out.stderr:
        # the child fell back to CPU — its number is NOT a device
        # number; surface loudly so a degraded tunnel can't masquerade
        # as a TPU result
        print(f"warning: {query} device subprocess fell back to CPU",
              file=sys.stderr)
    for line in out.stdout.splitlines():
        if line.startswith("RAW "):
            return float(line.split()[1])
    raise RuntimeError(
        f"{'cpu' if cpu else 'device'} measure failed: {out.stderr[-500:]}"
    )


def _probe_device(timeout_s: float = 300.0) -> dict:
    """One throwaway-subprocess probe of the accelerator backend.

    The child claims the backend, runs a sanity matmul, and EXITS
    (releasing the chip for the measurement children).  Returns the
    probe record; appends it to TPU_PROBE_LOG.jsonl."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    from tpu_probe import LOG, probe
    rec = probe(timeout_s)
    rec["note"] = "bench.py parent probe"
    try:
        with open(LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return rec


def _probe_history(window_s: float = 12 * 3600) -> list:
    """Probe records from the last ``window_s`` (one round), tolerating
    torn lines (the probe loop appends concurrently)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_PROBE_LOG.jsonl")
    cutoff = time.strftime(
        "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - window_s))
    out = []
    try:
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn concurrent append
                if rec.get("t", "") >= cutoff:
                    out.append(rec)
    except OSError:
        pass
    return out


def _ensure_backend(timeout_s: float = 240.0) -> None:
    """Fall back to CPU if the accelerator backend cannot initialize.

    A dead TPU tunnel HANGS inside ``jax.devices()`` rather than
    raising, so the probe runs in a watchdog thread; on timeout (or
    error) the process re-execs itself with ``JAX_PLATFORMS=cpu`` —
    the driver must always get its JSON line, labeled via stderr."""
    if os.environ.get("RWT_BENCH_NO_PROBE"):
        return
    import threading

    result: dict = {}

    def probe():
        try:
            import jax

            jax.devices()
            result["ok"] = True
        except Exception as e:  # init error: also fall back
            result["err"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result.get("ok"):
        return
    why = result.get("err", f"backend init hung > {timeout_s:.0f}s")
    print(f"warning: accelerator unavailable ({why}); "
          "re-executing on CPU", file=sys.stderr)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RWT_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)], env)


def main() -> None:
    query = os.environ.get("RWT_BENCH_QUERY", "all")
    if os.environ.get("RWT_BENCH_RAW"):
        _ensure_backend()
        print(f"RAW {measure(query)}")
        return
    queries = list(QUERIES) if query == "all" else [query]

    # fast-fail: when EVERY probe attempt of the last 12 h failed (a
    # dead tunnel burns a full watchdog timeout per probe — observed
    # 72/72 failures x 300 s in one round), skip the probe and go
    # straight to the CPU fallback.  RWT_BENCH_FORCE_PROBE=1 overrides
    # (e.g. right after a tunnel repair).
    history = _probe_history()
    history_fails = [a for a in history if not a.get("ok")]
    skip_probe = (
        not os.environ.get("RWT_BENCH_FORCE_PROBE")
        and history
        and len(history_fails) == len(history)
    )
    if skip_probe:
        probe_rec = {
            "ok": False,
            "error": (
                f"probe skipped: {len(history_fails)}/{len(history)} "
                "attempts failed in the last 12 h "
                "(RWT_BENCH_FORCE_PROBE=1 overrides)"
            ),
        }
    else:
        # ONE parent-side probe decides the backend for every child: a
        # dead tunnel would otherwise cost each child its full watchdog
        # timeout.  The probe subprocess exits before the children
        # start, so the parent never holds the one-chip tunnel while a
        # child needs it.
        probe_rec = _probe_device(
            float(os.environ.get("RWT_PROBE_TIMEOUT", "300")))
    dev_ok = bool(probe_rec.get("ok"))
    blocker = None
    if not dev_ok:
        attempts = _probe_history()
        fails = [a for a in attempts if not a.get("ok")]
        blocker = {
            "this_run": probe_rec.get("error", "unknown"),
            "probe_skipped": bool(skip_probe),
            "attempts_last_12h": len(attempts),
            "failed_attempts_last_12h": len(fails),
            "history": "TPU_PROBE_LOG.jsonl",
        }
        print(f"warning: accelerator unavailable "
              f"({probe_rec.get('error', 'unknown')}); "
              f"{len(fails)}/{len(attempts)} probe attempts failed this "
              "round — measuring on CPU", file=sys.stderr)
    else:
        print(f"# device up: {probe_rec.get('devices')} "
              f"(init {probe_rec.get('init_seconds')}s, 4k matmul "
              f"{probe_rec.get('matmul_4k_ms_steady')}ms)",
              file=sys.stderr)

    results: dict = {}
    cpu_results: dict = {}
    errors: dict = {}
    for q in queries:
        # one query failing must not discard the others' measurements —
        # the driver needs its JSON line either way.  EVERY query gets
        # a fresh-process CPU baseline (not just the q7 headline): on a
        # device run vs_baseline is device/cpu; on the CPU fallback it
        # is a run-to-run noise ratio — either way the per-query
        # trajectory (q1/q5/q8 included) is recorded, never null.
        try:
            results[q] = _subprocess_measure(q, cpu=not dev_ok)
            cpu_results[q] = _subprocess_measure(q, cpu=True)
        except Exception as e:
            errors[q] = repr(e)[:300]
            print(f"warning: {q} failed: {e}", file=sys.stderr)
            continue
        print(f"# {q}: {results[q]:,.0f} rows/s"
              + (f" (cpu {cpu_results[q]:,.0f}, "
                 f"{results[q] / cpu_results[q]:.2f}x)" if dev_ok else
                 f" (cpu; baseline rerun {cpu_results[q]:,.0f})"),
              file=sys.stderr)
    headline = "q7" if query == "all" else query
    qrec = {}
    for q in results:
        cb = cpu_results.get(q)
        qrec[q] = {
            "value": round(results[q], 1),
            "cpu_baseline": round(cb, 1) if cb else None,
            "vs_baseline": round(results[q] / cb, 3) if cb else None,
        }
    head_val = results.get(headline, 0.0)
    head_cpu = cpu_results.get(headline)
    print(json.dumps({
        "metric": f"nexmark_{headline}_throughput",
        "value": round(head_val, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(head_val / head_cpu, 3) if head_cpu else 0.0,
        "backend": (probe_rec.get("platform", "device") if dev_ok
                    else "cpu-fallback"),
        "queries": qrec,
        "errors": errors or None,
        "blocker": blocker,
    }))


if __name__ == "__main__":
    main()
