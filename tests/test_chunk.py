"""Chunk / type-system tests (ref: data_chunk.rs, stream_chunk.rs tests)."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import (
    Chunk,
    DataType,
    Field,
    Schema,
    OP_INSERT,
    OP_DELETE,
)
from risingwave_tpu.common.chunk import StrCol, concat_chunks


def test_from_pretty_roundtrip():
    c = Chunk.from_pretty(
        """
        i I F
        +  1 10 1.5
        -  2 20 2.5
        U- 3 30 0.5
        U+ 3 31 0.5
        """
    )
    assert c.capacity == 4
    assert int(c.cardinality()) == 4
    rows = c.to_rows()
    assert rows[0] == (0, 1, 10, 1.5)
    assert rows[1] == (1, 2, 20, 2.5)
    assert rows[2][0] == 2 and rows[3][0] == 3
    signs = np.asarray(c.signs())
    assert signs.tolist() == [1, -1, -1, 1]


def test_padding_and_mask():
    c = Chunk.from_pretty(
        """
        i
        + 1
        + 2
        + 3
        """,
        capacity=8,
    )
    assert c.capacity == 8
    assert int(c.cardinality()) == 3
    keep = jnp.asarray([True, False, True, True, True, True, True, True])
    c2 = c.mask(keep)
    assert int(c2.cardinality()) == 2
    assert [r[1] for r in c2.to_rows()] == [1, 3]
    # signs are zero for invisible rows
    assert np.asarray(c2.signs()).tolist()[:3] == [1, 0, 1]


def test_string_columns():
    schema = Schema.of(("name", DataType.VARCHAR), ("v", DataType.INT64))
    c = Chunk.from_numpy(
        schema,
        [np.asarray(["alice", "bob", "charlie"], object), np.asarray([1, 2, 3])],
        capacity=4,
    )
    col = c.column_by_name("name")
    assert isinstance(col, StrCol)
    _, cols, _ = c.to_host()
    assert cols[0].tolist() == ["alice", "bob", "charlie"]
    assert cols[1].tolist() == [1, 2, 3]


def test_decimal_scaling():
    schema = Schema(
        (Field("price", DataType.DECIMAL, decimal_scale=2),)
    )
    c = Chunk.from_numpy(schema, [np.asarray([1.25, 3.5])])
    # stored as scaled ints on device
    assert c.column(0).dtype == jnp.int64
    assert np.asarray(c.column(0)).tolist() == [125, 350]
    _, cols, _ = c.to_host()
    assert cols[0].tolist() == [1.25, 3.5]


def test_project():
    c = Chunk.from_pretty(
        """
        i I F
        + 1 2 3.0
        """
    )
    p = c.project([2, 0])
    assert p.schema.data_types() == [DataType.FLOAT64, DataType.INT32]
    assert p.to_rows() == [(0, 3.0, 1)]


def test_concat_chunks_rebatch():
    a = Chunk.from_pretty("i\n+ 1\n+ 2", capacity=4)
    b = Chunk.from_pretty("i\n- 3\n+ 4\n+ 5", capacity=4)
    out = concat_chunks([a, b], capacity=2)
    assert [len(c.to_rows()) for c in out] == [2, 2, 1]
    flat = [r for c in out for r in c.to_rows()]
    assert flat == [(0, 1), (0, 2), (1, 3), (0, 4), (0, 5)]


def test_ops_constants():
    assert OP_INSERT == 0 and OP_DELETE == 1
