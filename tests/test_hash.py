"""Vnode / hashing tests (ref: vnode.rs, hash_util.rs tests)."""

import binascii

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import Chunk, DataType, Schema
from risingwave_tpu.common.hash import (
    VNODE_COUNT,
    compute_vnodes,
    crc32_columns,
    hash64_columns,
)


def test_crc32_matches_zlib_for_int64_le_bytes():
    vals = np.asarray([0, 1, 42, -1, 2**40, -(2**40)], np.int64)
    col = jnp.asarray(vals)
    got = np.asarray(crc32_columns([col]))
    for i, v in enumerate(vals):
        expect = binascii.crc32(int(v).to_bytes(8, "little", signed=True))
        assert int(got[i]) == expect, (v, hex(int(got[i])), hex(expect))


def test_crc32_string_column_matches_zlib():
    schema = Schema.of(("s", DataType.VARCHAR))
    c = Chunk.from_numpy(schema, [np.asarray(["", "a", "hello world"], object)])
    got = np.asarray(crc32_columns([c.column(0)]))[:3]
    for i, s in enumerate(["", "a", "hello world"]):
        assert int(got[i]) == binascii.crc32(s.encode())


def test_vnode_range_and_determinism():
    keys = jnp.arange(10_000, dtype=jnp.int64)
    vn = np.asarray(compute_vnodes([keys]))
    assert vn.min() >= 0 and vn.max() < VNODE_COUNT
    # deterministic across jit / re-trace
    vn2 = np.asarray(jax.jit(lambda k: compute_vnodes([k]))(keys))
    assert (vn == vn2).all()
    # all vnodes hit for a large key space (uniformity smoke test)
    assert len(np.unique(vn)) == VNODE_COUNT


def test_hash64_no_trivial_collisions():
    keys = jnp.arange(100_000, dtype=jnp.int64)
    h = np.asarray(hash64_columns([keys]))
    assert len(np.unique(h)) == len(keys)


def test_hash64_multi_column_differs_from_single():
    a = jnp.asarray([1, 2, 3], jnp.int64)
    b = jnp.asarray([3, 2, 1], jnp.int64)
    h_ab = np.asarray(hash64_columns([a, b]))
    h_ba = np.asarray(hash64_columns([b, a]))
    assert not (h_ab == h_ba).all()  # order-sensitive


def test_hash64_strings():
    schema = Schema.of(("s", DataType.VARCHAR))
    c = Chunk.from_numpy(
        schema, [np.asarray(["alice", "bob", "alice", "alicf"], object)]
    )
    h = np.asarray(hash64_columns([c.column(0)]))
    assert h[0] == h[2]
    assert h[0] != h[1] and h[0] != h[3]
