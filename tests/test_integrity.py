"""Integrity-lite: end-to-end corruption detection, quarantine, and
self-healing repair (typed IntegrityError taxonomy, checksum coverage
of SSTs / checkpoint objects / the manifest chain, the scrubber, and
the meta's repair pipeline)."""

import json
import os

import numpy as np
import pytest

from risingwave_tpu.storage import codec
from risingwave_tpu.storage.hummock import (
    HummockStorage,
    InMemObjectStore,
    LocalFsObjectStore,
    StoreFaults,
    VersionManager,
)
from risingwave_tpu.storage.integrity import (
    BlockCorruption,
    CheckpointCorruption,
    FooterCorruption,
    IntegrityError,
    ManifestCorruption,
    quarantine_list,
    verify_sst_object,
)
from risingwave_tpu.storage.sst import SstReader, build_sst_bytes


def _pairs(n=300):
    return ([f"k{i:05d}".encode() for i in range(n)],
            [f"v{i}".encode() * 3 for i in range(n)])


def _flip(data: bytes, pos: int) -> bytes:
    out = bytearray(data)
    out[pos] ^= 0x40
    return bytes(out)


# -- SST coverage: footer crc + typed block errors ----------------------
def test_sst_block_and_footer_corruption_typed():
    keys, vals = _pairs()
    data, _meta = build_sst_bytes(keys, vals, block_bytes=1024)
    store = InMemObjectStore()
    store._d["sst/ok"] = data
    assert verify_sst_object(store, "sst/ok") > 1  # multi-block

    # a flipped bit in a DATA block: open succeeds, the read raises
    store._d["sst/blk"] = _flip(data, 100)
    r = SstReader(store=store, key="sst/blk")
    with pytest.raises(BlockCorruption) as ei:
        list(r.scan())
    assert ei.value.key == "sst/blk"
    r.close()

    # a flipped bit in the INDEX region: the footer crc catches it at
    # open — the index/bloom bytes are covered end-to-end now
    store._d["sst/idx"] = _flip(data, len(data) - 40)
    with pytest.raises(FooterCorruption):
        SstReader(store=store, key="sst/idx")

    # a truncated object: typed, never a struct/json crash
    store._d["sst/trunc"] = data[:len(data) // 2]
    with pytest.raises(FooterCorruption):
        SstReader(store=store, key="sst/trunc")
    store._d["sst/tiny"] = b"xy"
    with pytest.raises(FooterCorruption):
        SstReader(store=store, key="sst/tiny")


# -- manifest hash chain ------------------------------------------------
def test_version_log_chain_detects_tamper():
    store = InMemObjectStore()
    vm = VersionManager(store, base_interval=100)
    from risingwave_tpu.storage.hummock.version import SstInfo

    for e in range(1, 5):
        vm.commit(e, adds={0: [SstInfo(
            key=f"sst/{e}", first_key=b"a", last_key=b"z",
            n_records=1, size=8)]}, removes={})
    # untampered log replays clean
    assert VersionManager(store).current.vid == 4

    key = "version/delta_000000000003.json"
    raw = store._d[key]
    # tamper INSIDE the delta body (change an SST key)
    store._d[key] = raw.replace(b"sst/3", b"sst/X")
    with pytest.raises(ManifestCorruption):
        VersionManager(store)

    # the serving-tier follower verifies the same chain
    from risingwave_tpu.serve.reader import ManifestFollower

    with pytest.raises(ManifestCorruption):
        ManifestFollower(store).refresh(None)
    store._d[key] = raw  # heal
    assert ManifestFollower(store).refresh(None).vid == 4


def test_version_log_chain_links_predecessors():
    """Each delta commits the hash of its predecessor: REPLACING one
    delta with a self-consistent but different entry still breaks the
    chain at the successor."""
    store = InMemObjectStore()
    vm = VersionManager(store, base_interval=100)
    from risingwave_tpu.storage.hummock.version import (
        SstInfo,
        VersionDelta,
        wrap_chain_doc,
    )

    for e in range(1, 4):
        vm.commit(e, adds={0: [SstInfo(
            key=f"sst/{e}", first_key=b"a", last_key=b"z",
            n_records=1, size=8)]}, removes={})
    # forge delta 2 wholesale (valid self-crc, wrong chain position)
    forged = VersionDelta(vid=2, epoch=2, adds={}, removes={})
    raw, _ = wrap_chain_doc("delta", forged.to_json(), 0xDEAD)
    store._d["version/delta_000000000002.json"] = raw
    with pytest.raises(ManifestCorruption):
        VersionManager(store)


# -- checkpoint objects: crc trailers + lineage self-heal ---------------
def _save_epochs(store, job, n):
    for e in range(1, n + 1):
        states = {"a": np.arange(64, dtype=np.int64) + e,
                  "b": np.full(16, e, dtype=np.int64)}
        store.save(job, e, states, {"offset": e * 10})


def test_checkpoint_crc_recorded_and_verified(tmp_path):
    from risingwave_tpu.common.metrics import MetricsRegistry
    from risingwave_tpu.storage.checkpoint_store import CheckpointStore

    m = MetricsRegistry()
    store = CheckpointStore(str(tmp_path), keep_epochs=8,
                            metrics=m)
    _save_epochs(store, "j", 3)
    manifest = json.loads(store.store.get("MANIFEST.json"))
    crcs = manifest["jobs"]["j"]["crc"]
    assert set(crcs) == {"1", "2", "3"}
    for e in ("1", "2", "3"):
        data = store.store.get(f"j/epoch_{e}.npz")
        assert codec.crc32c(data) == crcs[e]["npz"]
    assert store.verify_job("j")["corrupt"] == []

    # flip one stored bit in the NEWEST epoch object
    path = os.path.join(str(tmp_path), "j", "epoch_3.npz")
    with open(path, "r+b") as f:
        f.seek(30)
        b = f.read(1)
        f.seek(30)
        f.write(bytes([b[0] ^ 1]))
    assert [e for e, _ in store.verify_job("j")["corrupt"]] == [3]

    # explicit-epoch load (time travel / handover slice) must be exact
    with pytest.raises(CheckpointCorruption):
        store.load("j", 3)

    # latest-epoch load SELF-HEALS: quarantine + rewind to epoch 2
    epoch, states, src = store.load("j")
    assert epoch == 2
    assert src == {"offset": 20}
    assert int(np.asarray(states["a"])[0]) == 2 + 0
    notes = quarantine_list(store.store)
    assert any("epoch_3" in n["key"] for n in notes)
    assert m.get("integrity_errors_total", kind="checkpoint") >= 1
    assert m.get("integrity_repairs_total",
                 kind="checkpoint_rewind") >= 1
    # the corrupt epoch left the manifest; a later save moves forward
    assert store.epochs("j") == [1, 2]
    _save_epochs(store, "j", 4)  # re-saves 1..4 (4 is new)
    assert store.load("j")[0] == 4


def test_checkpoint_repair_lineage_truncates(tmp_path):
    from risingwave_tpu.storage.checkpoint_store import CheckpointStore

    store = CheckpointStore(str(tmp_path), keep_epochs=8)
    _save_epochs(store, "j", 3)
    path = os.path.join(str(tmp_path), "j", "epoch_2.meta")
    with open(path, "r+b") as f:
        f.write(b"\x00\x01\x02")
    rep = store.repair_lineage("j")
    assert rep["corrupt"] == ["j/epoch_2.meta"]
    # epoch 2 dropped; 3 is a FULL here (default interval) so it stays
    assert 2 not in store.epochs("j")
    assert store.load("j")[0] == 3


# -- deterministic corruption faults ------------------------------------
def test_store_faults_bit_flip_and_truncate_deterministic():
    def run():
        faults = StoreFaults(seed=11)
        faults.fail("put", substr="sst/", mode="bit_flip", times=1)
        faults.fail("get", substr="blob", mode="truncate", times=1)
        store = InMemObjectStore(faults=faults)
        store.put("sst/a", b"A" * 64)
        store.put("other", b"B" * 64)  # no match: intact
        store.put("blob1", b"C" * 64)
        return (store._d["sst/a"], store._d["other"],
                store.get("blob1"), faults.injected_corruptions)

    a1, o1, g1, n1 = run()
    a2, o2, g2, n2 = run()
    assert a1 == a2 and g1 == g2 and n1 == n2 == 2
    assert a1 != b"A" * 64 and len(a1) == 64      # one bit flipped
    assert o1 == b"B" * 64                         # rule retired
    assert g1 == b"C" * 32                         # truncated read


def test_fabric_corruption_records_keys():
    from risingwave_tpu.common import faults as F

    fab = F.FaultFabric(seed=5)
    fab.fail_store("put", substr="sst/", mode="bit_flip", times=2)
    F.install(fab)
    try:
        store = InMemObjectStore()
        store.put("sst/x", b"x" * 32)
        store.put("sst/y", b"y" * 32)
        store.put("sst/z", b"z" * 32)  # rule exhausted
    finally:
        F.install(None)
    assert fab.corrupted_keys == ["sst/x", "sst/y"]
    assert store._d["sst/x"] != b"x" * 32
    assert store._d["sst/z"] == b"z" * 32
    assert fab.stats()["corrupted_keys"] == ["sst/x", "sst/y"]


# -- scrubber -----------------------------------------------------------
def test_scrubber_walks_and_reports(tmp_path):
    from risingwave_tpu.common.metrics import MetricsRegistry
    from risingwave_tpu.storage.hummock.scrubber import ScrubberService

    m = MetricsRegistry()
    storage = HummockStorage(
        LocalFsObjectStore(str(tmp_path / "hummock")), metrics=m)
    keys, vals = _pairs(200)
    storage.write_batch(list(zip(keys, vals)), epoch=1)
    storage.write_batch([(b"zz" + k, v)
                         for k, v in zip(keys, vals)], epoch=2)

    hits = []
    scrub = ScrubberService(storage, metrics=m, pace_s=0.0,
                            on_corruption=lambda k, key, ctx:
                            hits.append((k, key)))
    rep = scrub.run_once()
    assert rep["ssts_verified"] == 2 and not rep["corrupt"]
    assert m.get("scrub_objects_verified_total") == 2
    assert m.get("scrub_cursor_age_s") >= 0.0
    # durable cursor written
    assert storage.store.exists("scrub/CURSOR.json")

    # plant a bit flip in one SST: next cycle detects + reports
    sst_key = sorted(storage.versions.current.all_keys())[0]
    path = os.path.join(str(tmp_path / "hummock"), sst_key)
    with open(path, "r+b") as f:
        f.seek(64)
        b = f.read(1)
        f.seek(64)
        f.write(bytes([b[0] ^ 8]))
    rep = scrub.run_once()
    assert ("sst", sst_key) in rep["corrupt"]
    assert hits == [("sst", sst_key)]
    assert m.get("scrub_corruptions_total", kind="sst") == 1


# -- compaction as a detection point ------------------------------------
def test_compaction_detects_quarantines_and_continues(tmp_path):
    storage = HummockStorage(
        LocalFsObjectStore(str(tmp_path)), l0_trigger=2)
    keys, vals = _pairs(100)
    storage.write_batch(list(zip(keys, vals)), epoch=1)
    storage.write_batch(list(zip(keys, vals)), epoch=2)
    bad = storage.versions.current.levels[0][0].key
    path = os.path.join(str(tmp_path), bad)
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    seen = []
    storage.on_corruption = lambda k, key, ctx: seen.append(key)
    # the merge reads the corrupt input: abort + quarantine, no crash
    assert storage.compact_once() is False
    assert seen == [bad]
    assert any(bad in n["key"] for n in quarantine_list(storage.store))
    # the poisoned task released its level locks (no wedge)
    assert storage._busy_levels == set()


# -- in-process meta repair: corrupt export SST re-exported -------------
def test_meta_repairs_corrupt_export_sst(tmp_path):
    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 64},
        "state": {"agg_table_size": 256, "agg_emit_capacity": 64,
                  "mv_table_size": 256, "mv_ring_size": 512},
    })
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=30.0)
    meta.start(port=0, monitor=False, compactor=False,
               scrubber=False)
    w = ComputeWorker(f"127.0.0.1:{meta.rpc_port}", str(tmp_path),
                      config=cfg).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
            "CREATE MATERIALIZED VIEW iv AS "
            "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
        )
        for _ in range(2):
            assert meta.tick(1)["committed"]
        _, before = meta.serve("SELECT g, n FROM iv")

        # corrupt the newest committed export SST on disk
        v = meta.hummock.versions.current
        bad = v.levels[0][0].key
        path = os.path.join(str(tmp_path), "hummock", bad)
        with open(path, "r+b") as f:
            f.seek(16)
            f.write(b"\x55\xaa")
        with pytest.raises(IntegrityError):
            verify_sst_object(meta.hummock.store, bad)

        # the full pipeline: quarantine + re-export + atomic replace
        res = meta.report_corruption(bad, kind="sst_block",
                                     reason="test plant", sync=True)
        assert res["repair"] == "done"
        assert bad not in meta.hummock.versions.current.all_keys()
        assert any(bad in n["key"]
                   for n in quarantine_list(meta.hummock.store))
        assert meta.repairs["sst"] == 1

        # every remaining object verifies; rows byte-identical
        rep = meta.cluster_scrub()
        assert rep["corrupt"] == []
        _, after = meta.serve("SELECT g, n FROM iv")
        assert sorted(after) == sorted(before)

        # rounds keep committing after the repair
        assert meta.tick(1)["committed"]
    finally:
        w.stop()
        meta.stop()
