"""SQL frontend tests: parse → plan → run → serve, nexmark-flavored.

Mirrors the reference's e2e sqllogictest style (SURVEY.md §4): DDL +
streaming MVs + serving SELECTs in one session.
"""

import numpy as np
import pytest

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.parser import ParseError, parse
from risingwave_tpu.sql import ast


# -- parser ----------------------------------------------------------------

def test_parse_select_shapes():
    (s,) = parse("""
        SELECT auction, bidder, 0.908 * price AS price_eur
        FROM bid WHERE price > 100 AND bidder <> 5
    """)
    assert isinstance(s, ast.Select)
    assert len(s.items) == 3
    assert s.items[2].alias == "price_eur"
    assert isinstance(s.where, ast.BinaryOp) and s.where.op == "and"


def test_parse_create_source_with_watermark():
    (s,) = parse("""
        CREATE SOURCE bid (
            auction BIGINT, price BIGINT, date_time TIMESTAMP,
            WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
        ) WITH (connector = 'nexmark', nexmark.table = 'bid')
    """)
    assert isinstance(s, ast.CreateSource)
    assert s.watermark.column == "date_time"
    assert s.watermark.delay.micros == 4_000_000
    assert s.with_options["connector"] == "nexmark"


def test_parse_tumble_group_by():
    (s,) = parse("""
        SELECT window_start, max(price), count(*)
        FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
        GROUP BY window_start
    """)
    assert isinstance(s.from_, ast.Tumble)
    assert s.from_.size.micros == 10_000_000


def test_parse_join_and_case():
    (s,) = parse("""
        SELECT p.name, CASE WHEN a.reserve > 100 THEN 1 ELSE 0 END
        FROM person AS p JOIN auction AS a ON p.id = a.seller
    """)
    assert isinstance(s.from_, ast.Join)
    assert isinstance(s.items[1].expr, ast.Case)


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("SELEC x FROM y")
    with pytest.raises(ParseError):
        parse("SELECT x FROM y WHERE")


# -- end-to-end engine -----------------------------------------------------

NEXMARK_DDL = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid',
        nexmark.event.rate = '100000');
"""


def _engine(cap=512):
    from risingwave_tpu.sql.planner import PlannerConfig
    return Engine(PlannerConfig(
        chunk_capacity=cap, agg_table_size=1 << 10,
        agg_emit_capacity=256, mv_table_size=1 << 10,
        mv_ring_size=1 << 12, topn_pool_size=512, topn_emit_capacity=128,
        join_table_size=1 << 12, join_bucket_cap=1024,
        join_out_capacity=1 << 12,
    ))


def test_engine_q1_stateless():
    eng = _engine()
    eng.execute(NEXMARK_DDL)
    eng.execute("""
        CREATE MATERIALIZED VIEW q1 AS
        SELECT auction, bidder, 0.908 * price AS price, date_time
        FROM bid;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT auction, price FROM q1 LIMIT 5")
    assert len(rows) == 5

    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    want = gen.gen_bids(0, 1024)
    _, cols, _ = want.to_host()
    got_all = eng.execute("SELECT price FROM q1")
    np.testing.assert_allclose(
        sorted(r[0] for r in got_all),
        sorted(cols[2].astype(np.float64) * 0.908),
        rtol=1e-9,
    )


def test_engine_q7_windowed_agg():
    eng = _engine()
    eng.execute(NEXMARK_DDL)
    eng.execute("""
        CREATE MATERIALIZED VIEW q7 AS
        SELECT window_start, max(price) AS max_price, count(*) AS bids
        FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
        GROUP BY window_start;
    """)
    eng.tick(barriers=3, chunks_per_barrier=1)
    rows = eng.execute("SELECT window_start, max_price, bids FROM q7")
    got = {int(r[0]): (int(r[1]), int(r[2])) for r in rows}

    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    bids = gen.gen_bids(0, 3 * 512)
    _, cols, _ = bids.to_host()
    price, ts = cols[2], cols[5]
    w = ts - ts % 1_000_000
    want = {}
    for wv in np.unique(w):
        m = w == wv
        want[int(wv)] = (int(price[m].max()), int(m.sum()))
    assert got == want


def test_engine_filter_and_topn():
    eng = _engine()
    eng.execute(NEXMARK_DDL)
    eng.execute("""
        CREATE MATERIALIZED VIEW top_bids AS
        SELECT price, auction FROM bid
        WHERE price > 1000
        ORDER BY price DESC LIMIT 10;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT price, auction FROM top_bids")

    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    bids = gen.gen_bids(0, 2 * 512)
    _, cols, _ = bids.to_host()
    price = cols[2]
    want = sorted(price[price > 1000], reverse=True)[:10]
    assert sorted((int(r[0]) for r in rows), reverse=True) == [
        int(x) for x in want
    ]


def test_engine_join():
    eng = _engine()
    eng.execute("""
        CREATE SOURCE person (
            id BIGINT, name VARCHAR, date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'person');
        CREATE SOURCE auction (
            id BIGINT, seller BIGINT, reserve BIGINT, date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'auction');
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW sellers AS
        SELECT p.name AS name, a.reserve AS reserve
        FROM person p JOIN auction a ON p.id = a.seller;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT name, reserve FROM sellers")
    assert len(rows) > 0

    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    # event-time pacing pulls 3 auction chunks per person chunk
    p = gen.gen_persons(0, 2 * 512)
    a = gen.gen_auctions(0, 6 * 512)
    _, pc, _ = p.to_host()
    _, ac, _ = a.to_host()
    n_match = sum(
        int((pc[0] == s).sum()) for s in ac[7]
    )
    assert len(rows) == n_match


def test_engine_show_and_drop():
    eng = _engine()
    eng.execute(NEXMARK_DDL)
    assert eng.execute("SHOW SOURCES") == [("bid",)]
    eng.execute("CREATE MATERIALIZED VIEW v AS SELECT auction FROM bid")
    assert eng.execute("SHOW MATERIALIZED VIEWS") == [("v",)]
    eng.execute("DROP MATERIALIZED VIEW v")
    assert eng.execute("SHOW MATERIALIZED VIEWS") == []
    assert len(eng.jobs) == 0


def test_engine_datagen_group_by():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT)
        WITH (connector = 'datagen');
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW agg AS
        SELECT k % 4 AS bucket, count(*) AS n, sum(v) AS s
        FROM t GROUP BY k % 4;
    """)
    eng.tick(barriers=2, chunks_per_barrier=2)
    rows = eng.execute("SELECT bucket, n, s FROM agg")
    got = {int(r[0]): (int(r[1]), int(r[2])) for r in rows}
    ks = np.arange(4 * 64, dtype=np.int64)
    want = {
        int(b): (int((ks % 4 == b).sum()), int(ks[ks % 4 == b].sum()))
        for b in range(4)
    }
    assert got == want


def test_string_functions_and_like():
    eng = _engine()
    eng.execute(NEXMARK_DDL)
    eng.execute("""
        CREATE MATERIALIZED VIEW ch AS
        SELECT substr(channel, 1, 3) AS pre, channel || url AS cu, auction
        FROM bid
        WHERE channel LIKE 'Goo%' AND price BETWEEN 10 AND 1000000
              AND auction IN (1000, 1001, 1002, 2000, 2500)
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = eng.execute("SELECT pre, cu, auction FROM ch")
    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    _, cols, _ = gen.gen_bids(0, 512).to_host()
    want = [
        (c[:3], c + u, int(a))
        for a, c, u, p in zip(cols[0], cols[3], cols[4], cols[2])
        if c.startswith("Goo") and 10 <= p <= 1000000
        and int(a) in (1000, 1001, 1002, 2000, 2500)
    ]
    assert sorted(rows) == sorted(want)
    assert len(want) > 0


def test_extract_and_math():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS
        SELECT k, sqrt(v::DOUBLE PRECISION) AS r,
               extract(year FROM (v * 86400000000)::TIMESTAMP) AS y
        FROM t;
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = eng.execute("SELECT k, r, y FROM m")
    import math, datetime
    for k, r, y in rows[:20]:
        v = int(k)  # datagen v == k
        assert abs(r - math.sqrt(v)) < 1e-9
        want_y = datetime.datetime.fromtimestamp(
            v * 86400, datetime.timezone.utc
        ).year
        assert int(y) == want_y


def test_create_sink_file_and_blackhole(tmp_path):
    import json as _json

    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
    """)
    path = str(tmp_path / "out.jsonl")
    eng.execute(f"""
        CREATE SINK f AS SELECT k, v FROM t WHERE k < 5
        WITH (connector = 'file', path = '{path}');
        CREATE SINK b AS
        SELECT k % 2 AS g, count(*) AS n FROM t GROUP BY k % 2
        WITH (connector = 'blackhole');
    """)
    assert eng.execute("SHOW SINKS") == [("f",), ("b",)]
    eng.tick(barriers=2, chunks_per_barrier=1)
    recs = [_json.loads(l) for l in open(path)]
    data = [r for r in recs if r["op"] == "insert"]
    commits = [r for r in recs if r["op"] == "commit"]
    assert [(r["k"], r["v"]) for r in data] == [(i, i) for i in range(5)]
    assert len(commits) == 2  # one per checkpoint barrier
    # blackhole sink saw the agg changelog
    bh = eng.catalog.get("b").mv_executor.sink
    assert bh.rows_written > 0 and bh.commits == 2
    eng.execute("DROP SINK f")
    assert eng.execute("SHOW SINKS") == [("b",)]


def test_create_table_insert_tpch_style():
    """DML tables + a TPC-H q1-shaped pricing summary MV."""
    eng = _engine(cap=64)
    eng.execute("""
        CREATE TABLE lineitem (
            l_quantity BIGINT, l_extendedprice DOUBLE PRECISION,
            l_discount DOUBLE PRECISION, l_returnflag BIGINT
        );
        CREATE MATERIALIZED VIEW pricing AS
        SELECT l_returnflag,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice * (1 - l_discount)) AS revenue,
               count(*) AS n
        FROM lineitem GROUP BY l_returnflag;
    """)
    eng.execute("""
        INSERT INTO lineitem VALUES
            (10, 100.0, 0.1, 0),
            (5, 50.0, 0.0, 0),
            (7, 70.0, 0.5, 1);
        INSERT INTO lineitem (l_returnflag, l_quantity, l_extendedprice,
                              l_discount)
        VALUES (1, 3, 30.0, 0.0);
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = {int(r[0]): (int(r[1]), round(r[2], 6), int(r[3]))
            for r in eng.execute(
                "SELECT l_returnflag, sum_qty, revenue, n FROM pricing")}
    assert rows == {
        0: (15, 100.0 * 0.9 + 50.0, 2),
        1: (10, 70.0 * 0.5 + 30.0, 2),
    }
    # a second epoch of inserts updates the MV incrementally
    eng.execute("INSERT INTO lineitem VALUES (1, 10.0, 0.0, 0)")
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = {int(r[0]): int(r[1]) for r in eng.execute(
        "SELECT l_returnflag, n FROM pricing")}
    assert rows == {0: 3, 1: 2}


def test_agg_over_join_q4_style():
    """q4-shape: aggregate over the joined stream (join -> hash agg)."""
    eng = _engine()
    eng.execute("""
        CREATE SOURCE person (
            id BIGINT, date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'person');
        CREATE SOURCE auction (
            id BIGINT, seller BIGINT, reserve BIGINT, category BIGINT,
            date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'auction');
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW cat_stats AS
        SELECT a.category AS category, count(*) AS n,
               sum(a.reserve) AS total_reserve
        FROM person p JOIN auction a ON p.id = a.seller
        GROUP BY a.category;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT category, n, total_reserve FROM cat_stats")
    got = {int(r[0]): (int(r[1]), int(r[2])) for r in rows}

    from collections import defaultdict
    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    _, pc, _ = gen.gen_persons(0, 2 * 512).to_host()
    _, ac, _ = gen.gen_auctions(0, 6 * 512).to_host()
    person_count = defaultdict(int)
    for pid in pc[0]:
        person_count[int(pid)] += 1
    want = defaultdict(lambda: [0, 0])
    for i in range(len(ac[0])):
        seller, reserve, cat = int(ac[7][i]), int(ac[4][i]), int(ac[8][i])
        m = person_count.get(seller, 0)
        if m:
            want[cat][0] += m
            want[cat][1] += m * reserve
    assert got == {k: tuple(v) for k, v in want.items()}
    assert len(got) > 0


def test_emit_on_window_close():
    """EOWC MV: windows appear once, final, append-only, after closing."""
    eng = _engine()
    eng.execute("""
        CREATE SOURCE bid2 (
            auction BIGINT, bidder BIGINT, price BIGINT,
            channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
            WATERMARK FOR date_time AS date_time
        ) WITH (connector = 'nexmark', nexmark.table = 'bid',
                nexmark.event.rate = '1000');
        CREATE MATERIALIZED VIEW w AS
        SELECT window_start, max(price) AS hi, count(*) AS n
        FROM TUMBLE(bid2, date_time, INTERVAL '1' SECOND)
        GROUP BY window_start
        EMIT ON WINDOW CLOSE;
    """)
    eng.tick(barriers=3, chunks_per_barrier=1)
    rows = eng.execute("SELECT window_start, hi, n FROM w")
    got = {int(r[0]): (int(r[1]), int(r[2])) for r in rows}

    import numpy as np
    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=1000))
    _, cols, _ = gen.gen_bids(0, 3 * 512).to_host()
    price, ts = cols[2], cols[5]
    wm = ts.max()  # watermark after all processed rows
    w = ts - ts % 1_000_000
    want = {}
    for wv in np.unique(w):
        if wv + 1_000_000 <= wm:  # only CLOSED windows are in the MV
            m = w == wv
            want[int(wv)] = (int(price[m].max()), int(m.sum()))
    assert got == want
    assert 0 < len(got)
    # open windows must NOT be present
    open_windows = {int(wv) for wv in np.unique(w)
                    if wv + 1_000_000 > wm}
    assert not (set(got) & open_windows)


def test_join_agg_with_topn():
    """ORDER BY/LIMIT over join aggregates keeps only the top groups."""
    eng = _engine()
    eng.execute("""
        CREATE SOURCE person (
            id BIGINT, date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'person');
        CREATE SOURCE auction (
            id BIGINT, seller BIGINT, reserve BIGINT, category BIGINT,
            date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'auction');
        CREATE MATERIALIZED VIEW top_cats AS
        SELECT a.category AS category, count(*) AS n
        FROM person p JOIN auction a ON p.id = a.seller
        GROUP BY a.category
        ORDER BY n DESC LIMIT 2;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT category, n FROM top_cats")
    assert len(rows) <= 2

    from collections import defaultdict
    from risingwave_tpu.connector.nexmark import NexmarkConfig, NexmarkGenerator
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=10))
    _, pc, _ = gen.gen_persons(0, 2 * 512).to_host()
    _, ac, _ = gen.gen_auctions(0, 6 * 512).to_host()
    person_count = defaultdict(int)
    for pid in pc[0]:
        person_count[int(pid)] += 1
    want = defaultdict(int)
    for i in range(len(ac[0])):
        m = person_count.get(int(ac[7][i]), 0)
        if m:
            want[int(ac[8][i])] += m
    top2 = sorted(want.items(), key=lambda kv: -kv[1])[:2]
    assert sorted((int(r[0]), int(r[1])) for r in rows) == sorted(top2)


def test_eowc_without_agg_rejected():
    import pytest as _pytest
    from risingwave_tpu.sql.planner import PlanError

    eng = _engine()
    eng.execute(NEXMARK_DDL)
    with _pytest.raises(PlanError):
        eng.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction FROM bid "
            "EMIT ON WINDOW CLOSE"
        )


def test_serving_aggregates_over_mv():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t;
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    (row,) = eng.execute(
        "SELECT count(*), sum(v), min(v), max(v), avg(v) FROM m "
        "WHERE v < 32"
    )
    assert row == (32, sum(range(32)), 0, 31, sum(range(32)) / 32)


def test_count_distinct_streaming():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW d AS
        SELECT k % 4 AS g, count(DISTINCT v % 10) AS u FROM t
        GROUP BY k % 4;
    """)
    eng.tick(barriers=2, chunks_per_barrier=2)
    rows = {int(r[0]): int(r[1]) for r in eng.execute("SELECT g, u FROM d")}
    import numpy as np
    ks = np.arange(4 * 64, dtype=np.int64)
    want = {
        int(g): len({int(v % 10) for v in ks[ks % 4 == g]})
        for g in range(4)
    }
    assert rows == want


def test_window_functions_over_clause():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW w AS
        SELECT k, v,
               row_number() OVER (PARTITION BY k % 4 ORDER BY v) AS rn,
               sum(v) OVER (PARTITION BY k % 4 ORDER BY v) AS rsum
        FROM t;
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = sorted(eng.execute("SELECT k, v, rn, rsum FROM w"))
    assert len(rows) == 64
    # per-partition ground truth
    from collections import defaultdict
    parts = defaultdict(list)
    for k in range(64):
        parts[k % 4].append(k)  # v == k for datagen
    want = []
    for p, vs in parts.items():
        run = 0
        for i, v in enumerate(sorted(vs)):
            run += v
            want.append((v, v, i + 1, run))
    assert rows == sorted(want)


def test_serving_group_by_over_mv():
    eng = _engine(cap=64)
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t;
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = eng.execute(
        "SELECT k % 4 AS g, count(*) AS n, sum(v) AS s FROM m "
        "GROUP BY k % 4 ORDER BY g"
    )
    import numpy as np
    ks = np.arange(64)
    want = [
        (g, int((ks % 4 == g).sum()), int(ks[ks % 4 == g].sum()))
        for g in range(4)
    ]
    assert [(int(a), int(b), int(c)) for a, b, c in rows] == want

    top = eng.execute(
        "SELECT k % 4 AS g, sum(v) AS s FROM m GROUP BY k % 4 "
        "ORDER BY s DESC LIMIT 1"
    )
    assert int(top[0][0]) == 3


def test_time_travel_query_epoch(tmp_path):
    """SET query_epoch reads a retained historical checkpoint."""
    eng = Engine(
        __import__("risingwave_tpu.sql.planner",
                   fromlist=["PlannerConfig"]).PlannerConfig(
            chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
            mv_table_size=256, mv_ring_size=1024,
        ),
        data_dir=str(tmp_path),
    )
    eng.execute("""
        CREATE SOURCE t (k BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t;
    """)
    eng.tick(barriers=1, chunks_per_barrier=1)
    e1 = eng.jobs[0].committed_epoch
    eng.tick(barriers=1, chunks_per_barrier=1)
    e2 = eng.jobs[0].committed_epoch
    assert e2 > e1

    assert eng.execute("SELECT n FROM m") == [(128,)]
    eng.execute(f"SET query_epoch = {e1}")
    assert eng.execute("SELECT n FROM m") == [(64,)]  # the past
    eng.execute("SET query_epoch = 0")
    assert eng.execute("SELECT n FROM m") == [(128,)]

    # unretained epochs fail loudly
    import pytest as _p
    from risingwave_tpu.sql.planner import PlanError
    eng.execute("SET query_epoch = 12345")
    with _p.raises(PlanError):
        eng.execute("SELECT n FROM m")
