"""Slow wrapper over scripts/trace_report.py (the ISSUE 14 acceptance
harness), matching the cluster_stress pattern: a real 4-role
subprocess cluster must assemble one complete cross-role span tree
per committed round, and disabled tracing must cost < 2%."""

import pytest


def _import():
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        return importlib.import_module("trace_report")
    finally:
        sys.path.pop(0)


@pytest.mark.slow
def test_trace_report_cluster_rounds(tmp_path):
    tr = _import()
    chrome = str(tmp_path / "trace.chrome.json")
    summary = tr.run_cluster(rounds=3, workers=2, chrome=chrome,
                             data_dir=str(tmp_path))
    assert summary["failures"] == [], summary["failures"]
    assert len(summary["rounds_committed"]) == 3
    assert summary["serving_read_rounds"] >= 1
    assert summary["chrome_events"] > 0

    import json
    with open(chrome) as f:
        ct = json.load(f)
    assert any(e.get("ph") == "X" for e in ct["traceEvents"])


@pytest.mark.slow
def test_trace_overhead_under_budget():
    tr = _import()
    ov = tr.run_overhead(iters=6)
    # generous CI budget; the standalone --assert gate uses 2%
    assert ov["overhead_frac"] < 0.10, ov
