"""Serve-hot (ISSUE 10): epoch-keyed result cache, batched multi-get,
secondary-index MVs, and DROP-MV tombstoning — the fast in-process
guard for the memcached-class read path (the slow bench wrapper
asserts throughput/latency floors; here correctness only)."""

import time

import pytest

from risingwave_tpu.cluster import ComputeWorker, MetaService
from risingwave_tpu.common.config import RwConfig
from risingwave_tpu.serve import ServingWorker
from risingwave_tpu.serve.worker import (
    ResultCache,
    ServeUnsupported,
    plan_read,
)


def _cfg():
    return RwConfig.from_dict({
        "streaming": {"chunk_size": 128},
        "state": {"agg_table_size": 512, "agg_emit_capacity": 128,
                  "mv_table_size": 512, "mv_ring_size": 1024},
        "storage": {"checkpoint_keep_epochs": 4},
    })


def _rows(served):
    return sorted(tuple(r) for r in served[1])


# -- result cache (unit) -------------------------------------------------
def test_result_cache_lru_bytes_and_stale_sweep():
    rc = ResultCache(max_bytes=64 << 10)
    big = [(i, "x" * 64) for i in range(8)]
    rc.put(("q1", 1), (["a"], big, 7))
    assert rc.get(("q1", 1)) == (["a"], big, 7)
    assert rc.bytes > 0 and len(rc) == 1
    # a different vid is a different key: epoch advance re-keys
    assert rc.get(("q1", 2)) is None
    rc.put(("q1", 2), (["a"], big, 8))
    rc.evict_stale(2)  # sweeps every non-current-vid entry
    assert rc.get(("q1", 1)) is None and rc.get(("q1", 2)) is not None
    # byte budget evicts LRU-first
    for i in range(64):
        rc.put((f"q{i}", 2), (["a"], big, 8))
    assert rc.bytes <= rc.max_bytes
    # jumbo entries never enter (they would churn the whole LRU)
    jumbo = [(i, "y" * 64) for i in range(1000)]
    before = rc.bytes
    rc.put(("jumbo", 2), (["a"], jumbo, 8))
    assert rc.get(("jumbo", 2)) is None and rc.bytes == before
    assert 0.0 <= rc.hit_ratio() <= 1.0


# -- index rewrite (unit) ------------------------------------------------
def test_plan_read_index_rewrite():
    from risingwave_tpu.serve.reader import MvSchema
    from risingwave_tpu.sql import ast
    from risingwave_tpu.sql.parser import parse

    prim = MvSchema({
        "mv": "m",
        "columns": [
            {"name": "g", "kind": "int", "scale": 0, "hidden": False},
            {"name": "n", "kind": "int", "scale": 0, "hidden": False},
        ],
        "pk": [0],
        "indexes": [{"name": "m_n", "cols": ["n"]}],
    })
    ix = MvSchema({
        "mv": "m_n",
        "columns": [
            {"name": "n", "kind": "int", "scale": 0, "hidden": False},
            {"name": "g", "kind": "int", "scale": 0, "hidden": False},
        ],
        "pk": [0, 1],
        "index_of": "m", "index_width": 1, "since_epoch": 5,
    })
    schemas = {"m": prim, "m_n": ix}

    def plan(sql, at_epoch=10):
        (sel,) = parse(sql)
        assert isinstance(sel, ast.Select)
        return plan_read(sel, prim, schema_of=schemas.get,
                         at_epoch=at_epoch)

    p = plan("SELECT g FROM m WHERE n = 42")
    assert p.mode == "index" and p.index_mv == "m_n"
    assert p.index_width == 1 and p.lo.startswith(b"m:m_n\x00")
    assert p.hi is not None and p.hi > p.lo
    # pk predicates still take the point-get path, not the index
    assert plan("SELECT g FROM m WHERE g = 1").mode == "get"
    # a pin OLDER than the index's first export must not use it
    with pytest.raises(ServeUnsupported):
        plan("SELECT g FROM m WHERE n = 42", at_epoch=3)
    # index RANGE scan (Exchange-lite round): WHERE n > x bounds the
    # index byte range — the memcomparable encoding already sorts
    p = plan("SELECT g FROM m WHERE n > 42")
    assert p.mode == "index" and p.index_mv == "m_n"
    assert p.lo > b"m:m_n\x00" and p.hi is not None
    # the range predicate also rides as a residual (exactness guard)
    assert (1, "greater_than", 42) in (p.residual or [])
    p2 = plan("SELECT g FROM m WHERE n >= 10 AND n < 42")
    assert p2.mode == "index" and p2.lo < p.lo
    # composite predicate: index prefix + residual filter on a column
    # the index bytes cannot bound
    p3 = plan("SELECT g FROM m WHERE n = 42 AND g > 7")
    assert p3.mode == "index" and p3.index_mv == "m_n"
    assert (0, "greater_than", 7) in (p3.residual or [])
    # no schema_of (no index discovery): old behavior preserved
    (sel,) = parse("SELECT g FROM m WHERE n = 42")
    with pytest.raises(ServeUnsupported):
        plan_read(sel, prim)


# -- the in-process cluster smoke (tier-1 fast) --------------------------
def test_serve_hot_cluster_smoke(tmp_path):
    """One cluster boot guards the whole hot path: result-cache hits
    with epoch-advance invalidation (a write committed at e+1 is
    visible after the lease re-grant, byte-identical to the owning
    worker), serve_batch with per-item owner fallback, first-class
    multi-get, secondary-index reads byte-identical to the full scan,
    and DROP MATERIALIZED VIEW tombstoning the shared keyspace."""
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False, compactor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                      heartbeat_interval_s=0.5).start()
    meta.execute_ddl(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')"
    )
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW m1 AS "
        "SELECT k % 8 AS g, count(*) AS n FROM t GROUP BY k % 8"
    )
    meta.execute_ddl("CREATE INDEX m1_n ON m1(n)")
    for _ in range(3):
        assert meta.tick(1)["committed"]
    sv = ServingWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.1).start()
    try:
        # -- batched reads: point-gets share one multi-get pass;
        # engine-only shapes fall back per item to the owner
        res = meta.serve_batch([
            "SELECT n FROM m1 WHERE g = 3",
            "SELECT g, n FROM m1 WHERE g >= 2 AND g < 5",
            "SELECT count(*) FROM m1",
        ])
        assert _rows(res[0]) == [(48,)]
        assert _rows(res[1]) == [(g, 48) for g in (2, 3, 4)]
        assert _rows(res[2]) == [(8,)]
        # a final per-item error surfaces like the single-read path
        with pytest.raises(Exception, match="does not exist"):
            meta.serve_batch(["SELECT nope FROM m1"])

        # -- the repeat read HITS the result cache (same sql modulo
        # whitespace, same pinned vid) and stays byte-identical
        first = meta.serve_batch(["SELECT n FROM m1 WHERE g = 3"])[0]
        hits0 = sv.result_cache.hits
        again = meta.serve_batch(["SELECT  n  FROM m1 WHERE g = 3"])[0]
        assert again == first
        assert sv.result_cache.hits > hits0
        assert sv.metrics.get("serving_result_cache_hits") >= 1

        # -- epoch-advance invalidation: the next committed round
        # re-keys the cache; the SAME sql returns the NEW rows,
        # byte-identical to the owning worker
        for _ in range(2):
            assert meta.tick(1)["committed"]
        (cols, rows) = meta.serve_batch(
            ["SELECT n FROM m1 WHERE g = 3"]
        )[0]
        assert rows == [(80,)], rows
        with meta._lock:
            job = meta.jobs[meta._mv_to_job["m1"]]
            wk = meta.workers[job.worker_id]
            pin = job.pinned_epoch
        owner = wk.client.call(
            "serve", sql="SELECT n FROM m1 WHERE g = 3",
            query_epoch=pin,
        )
        assert rows == [tuple(r) for r in owner["rows"]]

        # -- first-class multi-get: rows in encoded-pk order, missing
        # pks omitted
        cols, rows = meta.serve_multi_get(
            "m1", [[5], [1], [99]], cols=["g", "n"]
        )
        assert cols == ["g", "n"] and rows == [(1, 80), (5, 80)]

        # -- secondary index: byte-identical to the full scan's
        # filtered rows, and actually exercised (metrics move)
        _, allr = meta.serve("SELECT g, n FROM m1")
        want = sorted(r for r in allr if r[1] == 80)
        assert _rows(meta.serve("SELECT g, n FROM m1 WHERE n = 80")) \
            == want
        assert sv.metrics.get("serving_index_lookups_total") >= 1

        # -- index RANGE scan over the memcomparable encoding
        # (Exchange-lite satellite): byte-identical to full scan +
        # filter, including the empty range
        want = sorted(r for r in allr if r[1] > 79)
        assert _rows(meta.serve(
            "SELECT g, n FROM m1 WHERE n > 79")) == want
        assert _rows(meta.serve(
            "SELECT g, n FROM m1 WHERE n > 80")) == []
        want = sorted(r for r in allr if 1 <= r[1] < 81)
        assert _rows(meta.serve(
            "SELECT g, n FROM m1 WHERE n >= 1 AND n < 81")) == want
        # composite: index prefix + residual filter on g
        want = sorted(r for r in allr if r[1] == 80 and r[0] > 3)
        assert _rows(meta.serve(
            "SELECT g, n FROM m1 WHERE n = 80 AND g > 3")) == want

        # -- DROP: protection first, then tombstones + "does not
        # exist" instead of stale rows
        with pytest.raises(Exception, match="depend on it"):
            meta.execute_ddl("DROP MATERIALIZED VIEW m1")
        meta.execute_ddl("DROP INDEX m1_n")
        meta.execute_ddl("DROP MATERIALIZED VIEW m1")
        with pytest.raises(ValueError, match="does not exist"):
            meta.serve("SELECT g, n FROM m1")
        sv._grant_refresh()
        assert sv.view.scan_mv("m1") == []
        assert sv.view.scan_mv("m1_n") == []
        assert sv.view.schema("m1") is None
    finally:
        sv.stop()
        w.stop()
        meta.stop()


# -- index maintenance through retraction churn (single node) ------------
def test_index_byte_identity_through_retraction_churn(tmp_path):
    """DML updates retract old index rows (the group's aggregate
    moves): after every export the index path answers byte-identical
    rows to the full scan, and entries for DEAD aggregate values are
    gone (no resurrection)."""
    from risingwave_tpu.sql import Engine

    eng = Engine(_cfg(), data_dir=str(tmp_path))
    eng.execute("CREATE TABLE pt (k BIGINT, v BIGINT)")
    eng.execute(
        "CREATE MATERIALIZED VIEW am AS "
        "SELECT k % 4 AS g, sum(v) AS s FROM pt GROUP BY k % 4"
    )
    eng.execute("CREATE INDEX am_s ON am(s)")
    sv = ServingWorker(None, str(tmp_path))
    started = False
    try:
        seen_s: set = set()
        for rnd in range(3):
            for k in range(8):
                eng.execute(
                    f"INSERT INTO pt VALUES ({k}, {10 * (rnd + 1)})"
                )
            eng.execute("FLUSH")
            eng.storage_export_mv("am")
            eng.storage_export_mv("am_s")
            if not started:
                sv.start()
                started = True
            else:
                sv.view.refresh(None)
            rows = eng.storage_serve_mv("am")
            scan = sorted(tuple(r) for r in rows)
            svals = sorted({r[1] for r in scan})
            assert len(svals) == 1  # every group moved together
            s_live = svals[0]
            _, got, _ = sv.read(f"SELECT g, s FROM am WHERE s = {s_live}")
            assert sorted(got) == scan
            # previous rounds' aggregate values retracted out of the
            # index: a probe for them returns NOTHING (not stale rows)
            for s_dead in seen_s:
                _, dead, _ = sv.read(
                    f"SELECT g, s FROM am WHERE s = {s_dead}"
                )
                assert dead == []
            seen_s.add(s_live)
        # drop the index: the upstream doc stops advertising it, so
        # the replica refuses (owner fallback) instead of answering
        # from tombstoned index rows
        eng.execute("DROP INDEX am_s")
        sv.view.refresh(None)
        with pytest.raises(ServeUnsupported):
            sv.read(f"SELECT g, s FROM am WHERE s = {max(seen_s)}")
    finally:
        if started:
            sv.stop()


# -- pushdown plane: negative cache + warmup + filtered scan -------------
def test_negative_cache_warmup_and_filtered_scan(tmp_path):
    """ISSUE 18: (1) a residual predicate on a NON-indexed, non-pk
    column runs inside the replica's block-walk evaluator,
    byte-identical to fetch-then-filter; (2) repeated missing-pk
    lookups are absorbed by the per-vid negative cache; (3) on epoch
    advance the negative fact is structurally invalidated (the
    materialized row appears — zero stale rows) and the hottest
    result-cache keys are re-warmed against the new vid with FRESH
    rows."""
    from risingwave_tpu.sql import Engine

    eng = Engine(_cfg(), data_dir=str(tmp_path))
    eng.execute("CREATE TABLE pt (k BIGINT, v BIGINT)")
    eng.execute(
        "CREATE MATERIALIZED VIEW pm AS "
        "SELECT k, sum(v) AS s FROM pt GROUP BY k"
    )
    for k in range(8):
        eng.execute(f"INSERT INTO pt VALUES ({k}, {k * 10})")
    eng.execute("FLUSH")
    eng.storage_export_mv("pm")
    sv = ServingWorker(None, str(tmp_path))
    sv.start()
    try:
        # -- filtered scan: no index on s, so the predicate runs as a
        # residual inside the merge scan (never an owner fallback)
        _, allr, _ = sv.read("SELECT k, s FROM pm")
        want = sorted(r for r in allr if r[1] >= 40)
        _, got, _ = sv.read("SELECT k, s FROM pm WHERE s >= 40")
        assert sorted(got) == want
        assert sv.metrics.get("pushdown_rows_elided_total",
                              where="replica") > 0

        # -- negative cache: the second miss for the same absent pk
        # is absorbed without another SstView pass
        _, rows, _ = sv.multi_get("pm", [[99]], cols=["k", "s"])
        assert rows == []
        h0 = sv.neg_cache.hits
        _, rows, _ = sv.multi_get("pm", [[99]], cols=["k", "s"])
        assert rows == [] and sv.neg_cache.hits > h0
        assert len(sv.neg_cache) >= 1
        assert sv.metrics.get("serving_negative_cache_entries") >= 1

        # heat one key so the re-grant has something to warm
        for _ in range(3):
            sv.read("SELECT s FROM pm WHERE k = 1")

        # -- epoch advance: pk 99 materializes and k=1 moves; the
        # re-grant must drop the negative fact AND re-warm the hot
        # key at the new vid with the NEW rows
        eng.execute("INSERT INTO pt VALUES (99, 7)")
        eng.execute("INSERT INTO pt VALUES (1, 5)")
        eng.execute("FLUSH")
        eng.storage_export_mv("pm")
        r0 = sv.warmup_replays
        sv._grant_refresh()
        assert sv.warmup_replays > r0
        vid = sv.view.version.vid
        assert sv.result_cache.contains(
            ("SELECT s FROM pm WHERE k = 1", vid)
        )
        _, rows, _ = sv.read("SELECT s FROM pm WHERE k = 1")
        assert rows == [(15,)], rows
        _, rows, _ = sv.multi_get("pm", [[99]], cols=["k", "s"])
        assert rows == [(99, 7)], rows  # zero stale rows
    finally:
        sv.stop()


# -- per-replica gauge retirement ---------------------------------------
def test_serving_replica_reap_retires_gauges(tmp_path):
    """ISSUE 10 satellite: a reaped (or deregistered) serving replica
    leaves NO frozen per-replica series on the meta's scrape surface,
    mirroring the PR-7 per-worker retirement."""
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=0.6)
    meta.start(port=0, monitor=False, compactor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    sv1 = ServingWorker(addr, str(tmp_path),
                        heartbeat_interval_s=0.1).start()
    sv2 = ServingWorker(addr, str(tmp_path),
                        heartbeat_interval_s=0.1).start()
    try:
        meta.check_heartbeats()
        m = meta.metrics
        for sv in (sv1, sv2):
            rid = str(sv.replica_id)
            assert m.get("cluster_serving_heartbeat_age_seconds",
                         replica=rid) >= 0.0
            assert m.get("cluster_serving_granted_vid",
                         replica=rid) >= 0
        # graceful deregistration retires the series
        r2 = sv2.replica_id
        sv2.stop()
        text = m.render_prometheus()
        assert f'replica="{r2}"' not in text
        assert f'replica="{sv1.replica_id}"' in text
        # hard death (no unregister): heartbeat expiry reaps + retires
        r1 = sv1.replica_id
        sv1._stop.set()
        sv1._server.stop()
        sv1._server = None
        deadline = time.monotonic() + 10
        while meta.state()["serving"]:
            meta.check_heartbeats()
            assert time.monotonic() < deadline, "lease never reaped"
            time.sleep(0.1)
        text = m.render_prometheus()
        assert f'replica="{r1}"' not in text
        assert meta.versions.pinned_count() == 0
    finally:
        sv1.stop()
        sv2.stop()
        meta.stop()
