"""LSM lifecycle: leveled compaction, block cache, serving-from-SST.

Ref: src/storage/src/hummock/compactor/compactor_runner.rs:70 (merge
compaction, tombstone handling), sstable_store.rs:208 (block cache),
and the compaction determinism test (src/tests/compaction_test/).
"""

import pickle
import struct

from risingwave_tpu.storage.sst import (
    TOMBSTONE,
    BlockCache,
    LsmTree,
    SstReader,
    write_sst,
)


def _k(i: int) -> bytes:
    return struct.pack(">I", i)


def test_lsm_compaction_preserves_view(tmp_path):
    """The merged view is identical before and after compaction, files
    shrink, and the bottommost output drops tombstones."""
    t = LsmTree(str(tmp_path), l0_trigger=100)  # no auto-compact yet
    # 6 overlapping batches: overwrites + deletes
    for gen in range(6):
        pairs = [(_k(i), f"g{gen}v{i}".encode())
                 for i in range(gen, 50, 2)]
        t.write_batch(pairs)
    t.delete_batch([_k(i) for i in range(0, 10)])

    before = list(t.scan())
    files_before = t.file_count()
    assert files_before == 7

    n = 0
    t.l0_trigger = 2
    n = t.maybe_compact()
    assert n >= 1
    after = list(t.scan())
    assert after == before
    assert t.file_count() < files_before
    # deleted keys stay gone, and the surviving run holds NO tombstones
    assert t.get(_k(0)) is None
    for level in t.m["levels"][1:]:
        for p in level:
            r = t._reader(p)
            assert all(v != TOMBSTONE for _, v in r.scan())
    # deterministic: replaying the same writes yields the same manifest
    t2 = LsmTree(str(tmp_path / "replay"), l0_trigger=100)
    for gen in range(6):
        t2.write_batch([(_k(i), f"g{gen}v{i}".encode())
                        for i in range(gen, 50, 2)])
    t2.delete_batch([_k(i) for i in range(0, 10)])
    t2.l0_trigger = 2
    t2.maybe_compact()
    assert t2.m["levels"] == t.m["levels"]
    assert list(t2.scan()) == after
    t.close()
    t2.close()


def test_lsm_auto_compaction_and_reopen(tmp_path):
    t = LsmTree(str(tmp_path), l0_trigger=3)
    for gen in range(10):
        t.write_batch([(_k(i), f"g{gen}".encode())
                       for i in range(gen * 5, gen * 5 + 20)])
    assert len(t.m["levels"][0]) < 3  # compactions kept L0 below trigger
    view = list(t.scan())
    t.close()
    # a fresh process reopens from the manifest
    t2 = LsmTree(str(tmp_path), l0_trigger=3)
    assert list(t2.scan()) == view
    assert t2.get(_k(7)) == b"g1"  # gen1 overwrote gen0's range [5,25)
    t2.close()


def test_block_cache_hits(tmp_path):
    path = str(tmp_path / "one.sst")
    pairs = [(_k(i), str(i).encode() * 10) for i in range(2000)]
    write_sst(path, [k for k, _ in pairs], [v for _, v in pairs],
              block_bytes=1 << 12)
    cache = BlockCache(capacity_blocks=64)
    r = SstReader(path, cache)
    assert r.get(_k(123)) == b"123" * 10
    m0 = cache.misses
    assert r.get(_k(123)) == b"123" * 10  # same block: cache hit
    assert cache.hits >= 1 and cache.misses == m0
    r.close()


def test_cold_serving_from_exported_mv_sst(tmp_path):
    """Engine-free serving read of an MV exported to SST: a fresh
    reader (no engine, no device state) scans the MV rows through the
    block cache — the BatchTable-over-Hummock pattern (SURVEY §3.4)."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    data = str(tmp_path / "data")
    eng = Engine(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=128,
        mv_table_size=512, mv_ring_size=1024,
    ), data_dir=data)
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    vals = ",".join(f"({i},{i * i})" for i in range(100))
    eng.execute(f"INSERT INTO t VALUES {vals}")
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS "
        "SELECT k, sum(v) AS s FROM t GROUP BY k"
    )
    eng.execute("FLUSH")
    want = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    entry = eng.catalog.get("mv")
    path = eng.checkpoint_store.export_mv_sst(
        "mv", eng.jobs[-1].committed_epoch, entry.mv_executor,
        eng.jobs[-1].states[entry.mv_state_index[0]]
        if len(entry.mv_state_index) == 1 else None,
    )
    del eng  # engine gone; read the SST cold
    cache = BlockCache()
    r = SstReader(path, cache)
    got = sorted(pickle.loads(v) for _, v in r.scan())
    assert [tuple(g) for g in got] == [tuple(w) for w in want]
    r.close()


def test_tombstone_survives_non_bottommost_compaction(tmp_path):
    """Deleted keys must NOT resurrect: a task-based (non-cascading)
    compaction of L0→L1 while L2 still holds the key's old value must
    KEEP the tombstone; it may drop only when compacting into the
    bottommost non-empty level (sst.output_is_bottommost — the rule a
    naive 'output is the deepest allocated level' check violates)."""
    t = LsmTree(str(tmp_path), l0_trigger=2, auto_compact=False)
    # push an old value of key 7 down to L2
    t.write_batch([(_k(i), b"old") for i in range(20)])
    t._compact_into(0)   # -> L1
    t._compact_into(1)   # -> L2
    assert t.m["levels"][2] and not t.m["levels"][1]
    # delete key 7, then make L0 due and run ONE task (L0 -> L1)
    t.delete_batch([_k(7)])
    t.write_batch([(_k(30), b"x")])
    assert t.pending_compaction() == 0
    assert t.compact_one()
    # the tombstone was preserved in the L1 output (L2 is non-empty)
    l1_values = [v for p in t.m["levels"][1]
                 for _, v in t._reader(p).scan()]
    assert TOMBSTONE in l1_values
    assert t.get(_k(7)) is None           # still deleted
    assert _k(7) not in dict(t.scan())
    # cascading to the bottom finally drops it — and the key STAYS gone
    while t.compact_one():
        pass
    assert t.get(_k(7)) is None
    assert all(v != TOMBSTONE for _, v in t.scan())
    t.close()


def test_external_compaction_mode_write_path_is_merge_free(tmp_path):
    """auto_compact=False: write_batch never merges (the hummock
    split); an external driver drains with compact_one."""
    t = LsmTree(str(tmp_path), l0_trigger=3, auto_compact=False)
    for gen in range(8):
        t.write_batch([(_k(i), f"g{gen}".encode())
                       for i in range(gen * 4, gen * 4 + 10)])
    assert t.compactions_run == 0          # ingest did no merge I/O
    assert t.l0_depth() == 8
    view = list(t.scan())
    n = 0
    while t.compact_one():
        n += 1
    assert n >= 1 and t.compactions_run == n
    assert t.l0_depth() < 3
    assert list(t.scan()) == view
    t.close()


def test_bloom_filter_skips_and_metrics(tmp_path):
    from risingwave_tpu.common.metrics import MetricsRegistry
    from risingwave_tpu.storage.sst import build_sst_bytes

    # reader-level: present keys always pass, absent keys mostly skip
    path = str(tmp_path / "b.sst")
    keys = [_k(i) for i in range(0, 4000, 2)]
    write_sst(path, keys, [b"v"] * len(keys), block_bytes=1 << 12)
    r = SstReader(path)
    assert all(r.may_contain(k) for k in keys[:200])
    absent = [_k(i) for i in range(1, 4000, 2)][:500]
    neg = sum(0 if r.may_contain(k) else 1 for k in absent)
    assert neg > 400            # ~1% fp rate at 10 bits/key
    assert r.bloom_negatives == neg
    # negative gets do NO block I/O
    cache = BlockCache()
    r2 = SstReader(path, cache)
    assert r2.get(_k(1)) is None
    assert cache.misses == 0
    r.close()
    r2.close()

    # tree-level: hit/miss/skip recorded in the metrics registry
    m = MetricsRegistry()
    t = LsmTree(str(tmp_path / "t"), l0_trigger=100, metrics=m)
    t.write_batch([(_k(i), b"a") for i in range(0, 100, 2)])
    t.write_batch([(_k(i), b"b") for i in range(100, 200, 2)])
    assert t.get(_k(102)) == b"b"
    # key 102 lives in the newer run; the probe never touches the
    # other SST's blocks (range/bloom skip)
    assert m.get("storage_bloom_filter_total", result="hit") == 1
    assert t.get(_k(3)) is None            # absent everywhere
    assert m.get("storage_bloom_filter_total", result="skip") >= 2
    t.close()

    # blooms survive the build_sst_bytes/object-store path too
    data, meta = build_sst_bytes([b"k1"], [b"v1"])
    assert meta.size == len(data)


def test_lsm_over_in_memory_object_store():
    """The whole LSM lifecycle against the InMem store: no local
    files, manifest + SSTs live behind the ObjectStore seam."""
    from risingwave_tpu.storage.hummock import InMemObjectStore

    store = InMemObjectStore()
    t = LsmTree("ignored-root", l0_trigger=3, store=store)
    for gen in range(7):
        t.write_batch([(_k(i), f"g{gen}".encode())
                       for i in range(gen * 3, gen * 3 + 9)])
    t.delete_batch([_k(0), _k(1)])
    view = list(t.scan())
    assert t.get(_k(0)) is None
    assert store.exists("LSM_MANIFEST.json")
    t.close()
    # reopen from the same store
    t2 = LsmTree("ignored-root", l0_trigger=3, store=store)
    assert list(t2.scan()) == view
    t2.close()
