"""End-to-end Nexmark pipelines on the streaming runtime (CPU).

Mirrors the reference's e2e nexmark suite (e2e_test/nexmark/) at small
scale: the same queries run as maintained MVs and their contents are
cross-checked against a numpy reimplementation of the query.
"""

import numpy as np

from risingwave_tpu.common.types import DataType
from risingwave_tpu.connector.nexmark import (
    NexmarkGenerator,
    NexmarkSplitReader,
)
from risingwave_tpu.expr.agg import AggCall, count_star
from risingwave_tpu.expr.node import FuncCall, col, lit
from risingwave_tpu.stream.executor import ProjectExecutor
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.hash_join import HashJoinExecutor
from risingwave_tpu.stream.materialize import (
    AppendOnlyMaterialize,
    MaterializeExecutor,
)
from risingwave_tpu.stream.dag import DagJob
from risingwave_tpu.stream.runtime import StreamingJob

WINDOW_US = 10_000_000


def test_q1_currency_conversion():
    """q1: SELECT auction, bidder, 0.908*price, date_time FROM bid."""
    src = NexmarkSplitReader("bid", chunk_capacity=256)
    proj = ProjectExecutor(src.schema, [
        ("auction", col("auction")),
        ("price_eur", col("price").cast(DataType.FLOAT64) * 0.908),
    ])
    mv = AppendOnlyMaterialize(proj.out_schema, ring_size=1024)
    job = StreamingJob(src, Fragment([proj, mv]))
    job.run(barriers=2, chunks_per_barrier=2)
    rows = mv.to_host(job.states[1])
    assert len(rows) == 1024

    want = NexmarkGenerator().gen_bids(0, 1024)
    _, cols, _ = want.to_host()
    np.testing.assert_allclose(
        [r[1] for r in rows], cols[2] * 0.908, rtol=1e-12
    )


def test_q7_style_windowed_max():
    """q7-ish: max price + bid count per 10s tumbling window."""
    cap = 512
    src = NexmarkSplitReader("bid", chunk_capacity=cap)
    proj = ProjectExecutor(src.schema, [
        ("w", FuncCall("tumble_start",
                       (col("date_time"), lit(WINDOW_US, DataType.INTERVAL)))),
        ("price", col("price")),
    ])
    agg = HashAggExecutor(
        proj.out_schema, [("w", col("w"))],
        [AggCall("max", col("price"), "max_price"), count_star("bids")],
        table_size=256, emit_capacity=64,
    )
    mv = MaterializeExecutor(agg.out_schema, pk_indices=[0], table_size=256)
    job = StreamingJob(src, Fragment([proj, agg, mv]))
    n_chunks = 4
    job.run(barriers=2, chunks_per_barrier=2)
    got = {int(w): (int(mx), int(n)) for w, mx, n in mv.to_host(job.states[2])}

    bids = NexmarkGenerator().gen_bids(0, n_chunks * cap)
    _, cols, _ = bids.to_host()
    price, ts = cols[2], cols[5]
    w = ts - ts % WINDOW_US
    want = {}
    for wv in np.unique(w):
        m = w == wv
        want[int(wv)] = (int(price[m].max()), int(m.sum()))
    assert got == want


def test_q8_style_windowed_join():
    """q8-ish: persons joined with auctions by seller in the same window."""
    cap = 256
    gen = NexmarkGenerator()
    persons = NexmarkSplitReader("person", gen, chunk_capacity=cap)
    auctions = NexmarkSplitReader("auction", gen, chunk_capacity=cap)

    p_proj = ProjectExecutor(persons.schema, [
        ("w", FuncCall("tumble_start",
                       (col("date_time"), lit(WINDOW_US, DataType.INTERVAL)))),
        ("id", col("id")),
        ("name", col("name")),
    ])
    a_proj = ProjectExecutor(auctions.schema, [
        ("w", FuncCall("tumble_start",
                       (col("date_time"), lit(WINDOW_US, DataType.INTERVAL)))),
        ("seller", col("seller")),
        ("reserve", col("reserve")),
    ])
    join = HashJoinExecutor(
        p_proj.out_schema, a_proj.out_schema,
        [col("w"), col("id")], [col("w"), col("seller")],
        table_size=1 << 12, out_capacity=1 << 15,
        left_bucket_cap=4,      # persons are unique per key
        right_bucket_cap=512,   # hot sellers concentrate auctions
    )
    mv = AppendOnlyMaterialize(join.out_schema, ring_size=1 << 15)
    job = DagJob.binary(persons, auctions, join, Fragment([mv]),
                    left_fragment=Fragment([p_proj]),
                    right_fragment=Fragment([a_proj]))
    job.run(barriers=2, chunks_per_barrier=1)
    rows = mv.to_host(job.states[3][0])

    # ground truth join in numpy (sides pace 1:3 by event time, so two
    # scheduling units pull 2 person chunks and 6 auction chunks)
    p = NexmarkGenerator().gen_persons(0, 2 * cap)
    a = NexmarkGenerator().gen_auctions(0, 6 * cap)
    _, pc, _ = p.to_host()
    _, ac, _ = a.to_host()
    p_w = pc[6] - pc[6] % WINDOW_US
    a_w = ac[5] - ac[5] % WINDOW_US
    want = set()
    from collections import Counter
    want = Counter()
    for i in range(len(pc[0])):
        for j in range(len(ac[0])):
            if pc[0][i] == ac[7][j] and p_w[i] == a_w[j]:
                want[(int(p_w[i]), int(pc[0][i]), int(ac[7][j]),
                      int(ac[4][j]))] += 1
    got = Counter(
        (int(r[0]), int(r[1]), int(r[4]), int(r[5])) for r in rows
    )
    assert got == want
    assert sum(want.values()) > 0  # the test actually joined something


def test_nexmark_splits_partition_the_stream():
    """N split readers cover the ordinal space disjointly (the
    reference's source split assignment, base.rs:222)."""
    gen = NexmarkGenerator()
    whole = NexmarkSplitReader("bid", gen, chunk_capacity=64)
    want = []
    for _ in range(4):
        _, cols, _ = whole.next_chunk().to_host()
        want.extend(zip(cols[0], cols[1], cols[5]))

    parts = [
        NexmarkSplitReader("bid", gen, chunk_capacity=64,
                           split_id=i, num_splits=2)
        for i in range(2)
    ]
    got = []
    for r in parts:
        for _ in range(2):
            _, cols, _ = r.next_chunk().to_host()
            got.extend(zip(cols[0], cols[1], cols[5]))
    assert sorted(got) == sorted(want)
    # offsets checkpoint per split
    assert parts[0].state() == {"table": "bid", "split_id": 0,
                                "offset": 128}
