"""TopN / dedup executor tests (changelog-diff semantics)."""

from collections import Counter

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.top_n import (
    AppendOnlyDedupExecutor,
    GroupTopNExecutor,
)

S = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))


def _chunk(text):
    return Chunk.from_pretty(text, names=["g", "v"])


def _fold(counter, out):
    for op, *vals in out.to_rows():
        if op in (0, 3):
            counter[tuple(vals)] += 1
        else:
            counter[tuple(vals)] -= 1
    return +counter


def test_plain_top2_asc():
    top = GroupTopNExecutor(
        S, group_by=[], order_by=[(col("v"), False)], limit=2,
        pool_size=16, emit_capacity=8,
    )
    frag = Fragment([top])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 0 30
        + 0 10
        + 0 20
    """))
    st, outs = frag.flush(st, 1)
    mv = _fold(Counter(), outs[0])
    assert mv == Counter({(0, 10): 1, (0, 20): 1})

    # a smaller value displaces 20
    st, _ = frag.step(st, _chunk("""
        I I
        + 0 5
    """))
    st, outs = frag.flush(st, 2)
    mv = _fold(mv, outs[0])
    assert mv == Counter({(0, 5): 1, (0, 10): 1})

    # delete 5 -> 20 re-enters from the pool (retraction within pool)
    st, _ = frag.step(st, _chunk("""
        I I
        - 0 5
    """))
    st, outs = frag.flush(st, 3)
    mv = _fold(mv, outs[0])
    assert mv == Counter({(0, 10): 1, (0, 20): 1})


def test_group_top1_desc():
    top = GroupTopNExecutor(
        S, group_by=[col("g")], order_by=[(col("v"), True)], limit=1,
        pool_size=16, emit_capacity=8,
    )
    frag = Fragment([top])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 10
        + 1 30
        + 2 7
    """))
    st, outs = frag.flush(st, 1)
    mv = _fold(Counter(), outs[0])
    assert mv == Counter({(1, 30): 1, (2, 7): 1})

    st, _ = frag.step(st, _chunk("""
        I I
        + 2 9
        + 1 20
    """))
    st, outs = frag.flush(st, 2)
    mv = _fold(mv, outs[0])
    assert mv == Counter({(1, 30): 1, (2, 9): 1})


def test_topn_offset():
    top = GroupTopNExecutor(
        S, group_by=[], order_by=[(col("v"), False)], limit=2, offset=1,
        pool_size=16, emit_capacity=8,
    )
    frag = Fragment([top])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 0 10
        + 0 20
        + 0 30
        + 0 40
    """))
    st, outs = frag.flush(st, 1)
    mv = _fold(Counter(), outs[0])
    assert mv == Counter({(0, 20): 1, (0, 30): 1})


def test_topn_duplicate_values():
    top = GroupTopNExecutor(
        S, group_by=[], order_by=[(col("v"), False)], limit=3,
        pool_size=16, emit_capacity=8,
    )
    frag = Fragment([top])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 0 10
        + 0 10
        + 0 20
        + 0 30
    """))
    st, outs = frag.flush(st, 1)
    mv = _fold(Counter(), outs[0])
    assert mv == Counter({(0, 10): 2, (0, 20): 1})

    # delete one duplicate: multiset diff emits exactly one delete
    st, _ = frag.step(st, _chunk("""
        I I
        - 0 10
    """))
    st, outs = frag.flush(st, 2)
    mv = _fold(mv, outs[0])
    assert mv == Counter({(0, 10): 1, (0, 20): 1, (0, 30): 1})


def test_append_only_dedup():
    dedup = AppendOnlyDedupExecutor(S, [col("g")], table_size=64)
    frag = Fragment([dedup])
    st = frag.init_states()
    st, out = frag.step(st, _chunk("""
        I I
        + 1 10
        + 1 11
        + 2 20
    """))
    assert sorted(out.to_rows()) == [(0, 1, 10), (0, 2, 20)]
    st, out = frag.step(st, _chunk("""
        I I
        + 1 12
        + 3 30
    """))
    assert sorted(out.to_rows()) == [(0, 3, 30)]
