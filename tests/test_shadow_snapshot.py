"""Incremental shadow snapshots + pipelined checkpoint upload (round 7).

The ISSUE 4 acceptance surface: the shadow restore must be
byte-identical to the full-copy path it replaced, the digest scheme
must be shared verbatim with the durable store, and the async uploader
must preserve the synchronous store's durable contents and crash
semantics.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.storage.checkpoint_store import CheckpointStore
from risingwave_tpu.stream.shadow import ShadowSnapshot


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        eq = np.array_equal(x, y, equal_nan=True) \
            if x.dtype.kind == "f" else np.array_equal(x, y)
        if not eq:
            return False
    return True


def _mixed_tree(rng):
    return {
        "big": jnp.asarray(
            rng.integers(0, 1 << 40, size=(1 << 14,), dtype=np.int64)
        ),
        "f64": jnp.asarray(rng.standard_normal((1 << 12,))),
        "f32": jnp.asarray(
            rng.standard_normal((257, 9)).astype(np.float32)
        ),
        "bytes": jnp.asarray(
            rng.integers(0, 256, size=(1 << 13, 24), dtype=np.uint8)
        ),
        "flags": jnp.asarray(rng.integers(0, 2, size=(77,)) > 0),
        "ctr": jnp.zeros((), jnp.int64),
    }


def test_shadow_restore_byte_identical_across_dtypes():
    rng = np.random.default_rng(7)
    tree = _mixed_tree(rng)
    sh = ShadowSnapshot(tree, block_elems=256)
    assert _leaves_equal(sh.restore(), tree)

    # sparse dirt, medium dirt, full dirt, and float specials — every
    # budget rung of the scatter ladder must reproduce live exactly
    cur = dict(tree)
    cur["big"] = cur["big"].at[3].set(-1).at[9000].set(5)
    cur["ctr"] = jnp.int64(2)
    sh.update(cur)
    assert _leaves_equal(sh.restore(), cur)

    big = np.asarray(cur["big"]).copy()
    big[:: 700] = 123  # ~ every-other-block dirt
    cur["big"] = jnp.asarray(big)
    sh.update(cur)
    assert _leaves_equal(sh.restore(), cur)

    cur = {
        k: (v + 1 if v.dtype not in (jnp.bool_,) else ~v)
        for k, v in cur.items()
    }
    sh.update(cur)
    assert _leaves_equal(sh.restore(), cur)

    f = np.asarray(cur["f64"]).copy()
    f[0], f[1], f[2] = np.nan, np.inf, -np.inf
    cur["f64"] = jnp.asarray(f)
    sh.update(cur)
    assert _leaves_equal(sh.restore(), cur)
    # clean re-update keeps it stable (digest invariant)
    sh.update(cur)
    assert _leaves_equal(sh.restore(), cur)


def test_shadow_restore_is_independent_copy():
    """restore() output must survive later shadow updates (recover
    hands it to donating step programs)."""
    tree = {"a": jnp.arange(1 << 12, dtype=jnp.int64)}
    sh = ShadowSnapshot(tree, block_elems=256)
    restored = sh.restore()
    sh.update({"a": tree["a"] + 7})
    assert np.array_equal(np.asarray(restored["a"]),
                          np.arange(1 << 12))


def test_shadow_dirty_ratio_tracks_activity():
    tree = {"a": jnp.zeros(1 << 14, jnp.int64)}
    sh = ShadowSnapshot(tree, block_elems=256)
    sh.update(tree)
    assert sh.dirty_ratio() == 0.0
    sh.update({"a": tree["a"].at[:256].set(1)})
    assert 0.0 < sh.dirty_ratio() < 0.1
    sh.update({"a": jnp.ones(1 << 14, jnp.int64) * 9})
    assert sh.dirty_ratio() > 0.9


def _job(store=None):
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.expr.node import col
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.materialize import MaterializeExecutor
    from risingwave_tpu.stream.runtime import StreamingJob

    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))

    class Src:
        def __init__(self):
            self.offset = 0

        def next_chunk(self):
            ar = [np.arange(8, dtype=np.int64) % 3,
                  np.full(8, self.offset, np.int64)]
            self.offset += 1
            return Chunk.from_numpy(schema, ar)

        def state(self):
            return {"offset": self.offset}

    agg = HashAggExecutor(
        schema, [("g", col("g"))], [count_star("n")],
        table_size=64, emit_capacity=16,
    )
    mv = MaterializeExecutor(agg.out_schema, [0], table_size=64)
    return StreamingJob(Src(), Fragment([agg, mv]), "sj",
                        checkpoint_store=store), mv


def test_job_recover_from_shadow_matches_live_state():
    """ISSUE 4 acceptance: restore from the incremental shadow snapshot
    is byte-identical to the state at the sealed epoch (the full-copy
    path's contract, without the full copy)."""
    job, mv = _job()
    job.run(barriers=3, chunks_per_barrier=2)
    live = jax.device_get(job.states)
    want = sorted(mv.to_host(job.states[1]))
    # progress past the snapshot, then rewind
    job.run_chunk()
    job.recover()
    assert _leaves_equal(job.states, live)
    assert sorted(mv.to_host(job.states[1])) == want
    assert job.source.offset == 6


def test_async_durable_checkpoint_matches_live_state(tmp_path):
    """The async-uploaded chain reconstructs the sealed state exactly
    (shared digest vector, dirty runs fetched from the shadow)."""
    store = CheckpointStore(str(tmp_path), keep_epochs=8)
    job, mv = _job(store)
    job.run(barriers=4, chunks_per_barrier=2)  # run() drains uploads
    live = jax.device_get(job.states)
    assert job.committed_epoch == job.sealed_epoch > 0
    assert store.committed_epoch("sj") == job.sealed_epoch
    epoch, states, src = store.load("sj")
    assert epoch == job.sealed_epoch
    assert _leaves_equal(states, live)
    assert src == {"offset": 8}
    # steady-state epochs persist as deltas, not fulls
    kinds = [store.checkpoint_kind("sj", e) for e in store.epochs("sj")]
    assert "delta" in kinds


def test_upload_failure_is_loud_and_recover_rewinds(tmp_path):
    """Crash-mid-upload (ISSUE 4 satellite): an injected failure
    between the object write and the manifest commit leaves durable
    state at the previous epoch; the error surfaces on the barrier
    loop; recover() rewinds, vacuums the orphan files, and invalidates
    the digest cache (next save re-bases FULL)."""
    from risingwave_tpu.storage.hummock.object_store import (
        LocalFsObjectStore,
        StoreFaults,
    )

    faults = StoreFaults()
    store = CheckpointStore(
        str(tmp_path),
        object_store=LocalFsObjectStore(str(tmp_path), faults=faults),
    )
    job, mv = _job(store)
    job.run(barriers=2, chunks_per_barrier=1)
    durable = job.committed_epoch
    assert durable > 0

    # persistent fault: the uploader's RetryPolicy (4 attempts) must
    # exhaust before the failure surfaces (ISSUE 6: transient faults
    # retry invisibly; only a dead store goes loud).  times=4 == the
    # budget, so the post-recovery save below succeeds again.
    faults.fail("put", substr="MANIFEST", mode="before", times=4)
    with pytest.raises(RuntimeError, match="upload failed"):
        job.run(barriers=1, chunks_per_barrier=1)
    sealed = job.sealed_epoch
    assert sealed > durable
    assert store.committed_epoch("sj") == durable
    assert job._uploader.retries_total >= 3
    # the failed epoch's npz was vacuumed WITH the failure (no orphan
    # lingers while the operator decides what to do)
    assert not store.store.exists(f"sj/epoch_{sealed}.npz")

    job.recover()
    assert job.committed_epoch == durable
    assert job.source.offset == 2
    # orphans vacuumed: every epoch file on disk is manifest-reachable
    known = {str(e) for e in store.epochs("sj")}
    for key in store.store.list("sj/"):
        stem = key.rsplit("/", 1)[-1]
        assert stem.startswith("epoch_")
        num = stem[len("epoch_"):].split(".")[0]
        assert num in known, f"orphan survived recovery: {key}"
    # digest cache invalidated: the replayed epoch re-bases FULL (a
    # delta against post-rewind live state would corrupt the chain)
    job.run(barriers=1, chunks_per_barrier=1)
    assert store.checkpoint_kind("sj", job.committed_epoch) == "full"
    # and the replay converges to the undisturbed result
    ref_job, ref_mv = _job()
    ref_job.run(barriers=3, chunks_per_barrier=1)
    assert sorted(mv.to_host(job.states[1])) \
        == sorted(ref_mv.to_host(ref_job.states[1]))


def test_store_accepts_shared_shadow_digests(tmp_path):
    """Digest sharing: a save fed the shadow's digest vector produces
    the same delta chain as one that computes digests itself."""
    rng = np.random.default_rng(3)
    tree = {"a": jnp.asarray(
        rng.integers(0, 99, size=(1 << 13,), dtype=np.int64)
    ), "b": jnp.zeros((), jnp.int64)}
    shared = CheckpointStore(str(tmp_path / "shared"), keep_epochs=8)
    own = CheckpointStore(str(tmp_path / "own"), keep_epochs=8)
    sh = ShadowSnapshot(tree, block_elems=shared.block_elems)

    leaves, treedef = jax.tree.flatten(tree)
    shapes = [np.shape(x) for x in leaves]
    shared.commit(shared.prepare(
        "j", 1, sh.leaves, sh.shapes, sh.treedef, {},
        digests=np.asarray(sh.digests),
    ))
    own.save("j", 1, tree, {})

    tree2 = dict(tree)
    tree2["a"] = tree["a"].at[100].set(-5)
    tree2["b"] = jnp.int64(1)
    digests2 = sh.update(tree2)
    shared.commit(shared.prepare(
        "j", 2, sh.leaves, sh.shapes, sh.treedef, {},
        digests=np.asarray(digests2),
    ))
    own.save("j", 2, tree2, {})

    for st in (shared, own):
        assert st.checkpoint_kind("j", 2) == "delta"
    # identical dirty detection → identical delta payload sizes
    assert shared.checkpoint_bytes("j", 2) == own.checkpoint_bytes("j", 2)
    for st in (shared, own):
        _, loaded, _ = st.load("j", 2)
        assert _leaves_equal(loaded, tree2)


def test_per_shard_digest_lanes_unit():
    """ISSUE 9: ``shard_rows`` mode digests mesh-stacked leaves in
    per-shard lanes — one shard's write dirties only its own lane's
    block, and restore stays byte-identical."""
    S = 8
    tree = {
        "big": jnp.arange(S * 2048, dtype=jnp.int64).reshape(S, 2048),
        "scalar": jnp.zeros((S,), jnp.int64),
    }
    sh = ShadowSnapshot(tree, block_elems=64, digest=True, shard_rows=S)
    # leaf order follows the flattened dict: big then scalar
    assert (S, 2048) in sh.lanes and (S, 1) in sh.lanes
    # 8 lanes x 32 blocks + 8 single-element lanes
    assert sh.total_blocks == S * 32 + S

    tree2 = dict(tree)
    tree2["big"] = tree["big"].at[3, 100].set(-1)
    sh.update(tree2)
    assert int(np.asarray(sh.dirty_blocks)) == 1  # ONE lane block
    restored = sh.restore()
    np.testing.assert_array_equal(
        np.asarray(restored["big"]), np.asarray(tree2["big"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored["scalar"]), np.asarray(tree2["scalar"])
    )


def test_checkpoint_store_lane_runs_do_not_cross_shards(tmp_path):
    """Lane-aware delta extraction: a dirty block in one shard's
    ragged tail uploads ONLY that lane's elements — the run never
    crosses into the next shard's row — and the delta chain loads
    byte-identical."""
    S, m = 4, 100  # 100 elems/lane, block 64 → blocks (0..64),(64..100)
    store = CheckpointStore(str(tmp_path), keep_epochs=8,
                            block_elems=64)
    tree = {"x": jnp.arange(S * m, dtype=jnp.int64).reshape(S, m)}
    sh = ShadowSnapshot(tree, block_elems=64, digest=True, shard_rows=S)
    store.commit(store.prepare(
        "j", 1, sh.leaves, sh.shapes, sh.treedef, {},
        digests=np.asarray(sh.digests), lanes=sh.lanes,
    ))
    tree2 = {"x": tree["x"].at[1, 90].set(-7)}  # lane 1, tail block
    digests2 = sh.update(tree2)
    store.commit(store.prepare(
        "j", 2, sh.leaves, sh.shapes, sh.treedef, {},
        digests=np.asarray(digests2), lanes=sh.lanes,
    ))
    assert store.checkpoint_kind("j", 2) == "delta"

    import io
    with np.load(io.BytesIO(store.store.get("j/epoch_2.npz"))) as z:
        keys = sorted(z.files)
        # lane 1 starts at flat 100; its tail block at 100+64=164 and
        # ends at the LANE boundary 200 — 36 elements, not 64
        assert keys == ["r_0_164"], keys
        assert z["r_0_164"].shape[0] == 36

    _, loaded, _ = store.load("j", 2)
    np.testing.assert_array_equal(
        np.asarray(loaded["x"]), np.asarray(tree2["x"])
    )
