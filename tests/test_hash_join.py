"""Streaming hash-join tests (inner join, retraction, multiset)."""

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_join import HashJoinExecutor
from risingwave_tpu.stream.materialize import AppendOnlyMaterialize
from risingwave_tpu.stream.dag import DagJob

L = Schema.of(("k", DataType.INT64), ("a", DataType.INT64))
R = Schema.of(("k", DataType.INT64), ("b", DataType.INT64))


def _join(**kw):
    return HashJoinExecutor(
        L, R, [col("k")], [col("k")],
        table_size=64, bucket_cap=4, out_capacity=64, **kw,
    )


def _lc(text):
    return Chunk.from_pretty(text, names=["k", "a"])


def _rc(text):
    return Chunk.from_pretty(text, names=["k", "b"])


def _apply(j, st, chunk, side):
    st, out = j.apply(st, chunk, side)
    return st, sorted(out.to_rows())


def test_inner_join_basic():
    j = _join()
    st = j.init_state()
    st, rows = _apply(j, st, _lc("""
        I I
        + 1 10
        + 2 20
    """), "left")
    assert rows == []  # right empty

    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
        + 1 101
        + 3 300
    """), "right")
    # right rows probe left: k=1 matches once each
    assert rows == [(0, 1, 10, 1, 100), (0, 1, 10, 1, 101)]

    st, rows = _apply(j, st, _lc("""
        I I
        + 1 11
    """), "left")
    # new left row matches both right k=1 rows
    assert rows == [(0, 1, 11, 1, 100), (0, 1, 11, 1, 101)]


def test_join_retraction():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
    """), "left")
    st, _ = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    # delete the left row: must retract the joined row
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
    """), "left")
    assert rows == [(1, 1, 10, 1, 100)]
    # left side now empty: new right row matches nothing
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 101
    """), "right")
    assert rows == []


def test_join_multiset_duplicates():
    j = _join()
    st = j.init_state()
    # two identical left rows — multiset semantics
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 1 10
    """), "left")
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    assert rows == [(0, 1, 10, 1, 100), (0, 1, 10, 1, 100)]
    # delete ONE copy
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
    """), "left")
    assert rows == [(1, 1, 10, 1, 100)]
    # one copy left
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 101
    """), "right")
    assert rows == [(0, 1, 10, 1, 101)]


def test_join_delete_then_insert_same_chunk_reuses_hole():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 1 11
        + 1 12
        + 1 13
    """), "left")  # bucket_cap=4: full
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
        + 1 14
    """), "left")
    assert int(st.left.overflow) == 0  # hole reused, no overflow
    assert int(st.left.count[np.argmax(st.left.count)]) == 4


def test_join_state_cleaning():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 5 50
    """), "left")
    st = j.clean_below(st, "left", 0, 3)  # drop keys < 3
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
        + 5 500
    """), "right")
    assert rows == [(0, 5, 50, 5, 500)]


def test_binary_job_end_to_end():
    class ListSource:
        def __init__(self, chunks):
            self.chunks = list(chunks)
            self.i = 0

        def next_chunk(self):
            c = self.chunks[self.i % len(self.chunks)]
            self.i += 1
            return c

    j = _join()
    mv = AppendOnlyMaterialize(j.out_schema, ring_size=256)
    job = DagJob.binary(
        ListSource([_lc("""
            I I
            + 1 10
        """), _lc("""
            I I
            + 2 20
        """)]),
        ListSource([_rc("""
            I I
            + 1 100
        """), _rc("""
            I I
            + 2 200
        """)]),
        j,
        Fragment([mv]),
    )
    job.run(barriers=1, chunks_per_barrier=2)
    # nodes: [join, post] — the post fragment holds the MV
    rows = mv.to_host(job.states[1][0])
    assert sorted(rows) == [(1, 10, 1, 100), (2, 20, 2, 200)]
    assert job.committed_epoch > 0


def test_join_insert_then_delete_same_chunk_annihilates():
    """Regression: [+row, -row] in ONE chunk must not ghost-insert."""
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        - 1 10
    """), "left")
    # left state must be empty: a new right row matches nothing
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    assert rows == []
    assert int(st.left.inconsistency) == 0


def test_join_delete_of_absent_key_no_ghost():
    """Regression: deletes must not insert ghost keys into the table."""
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        - 7 70
    """), "left")
    assert int(st.left.key_table.count()) == 0  # no ghost key slot
    assert int(st.left.inconsistency) == 1      # surfaced, not silent


def test_binary_job_recover():
    class ReplaySource:
        def __init__(self, chunks):
            self.chunks = list(chunks)
            self.offset = 0

        def next_chunk(self):
            c = self.chunks[self.offset % len(self.chunks)]
            self.offset += 1
            return c

        def state(self):
            return {"offset": self.offset}

    j = _join()
    mv = AppendOnlyMaterialize(j.out_schema, ring_size=256)
    job = DagJob.binary(
        ReplaySource([_lc("""
            I I
            + 1 10
        """)]),
        ReplaySource([_rc("""
            I I
            + 1 100
        """)]),
        j, Fragment([mv]),
    )
    job.run(barriers=1, chunks_per_barrier=1)
    committed = job.committed_epoch
    n_rows = len(mv.to_host(job.states[1][0]))
    # process more, then crash before the barrier
    job.run_chunk("left")
    job.recover()
    assert job.sources["left"].offset == 1
    assert len(mv.to_host(job.states[1][0])) == n_rows
    assert job.committed_epoch == committed


# -- degree-adaptive pool storage (round-3: shared row pool, no per-key
# -- cap; ref JoinHashMap's unbounded rows, hash_join.rs:169) ----------

def _pool_join(**kw):
    return HashJoinExecutor(
        L, R, [col("k")], [col("k")],
        table_size=64, out_capacity=64,
        left_storage="pool", right_storage="pool",
        left_pool_size=1024, right_pool_size=1024, **kw,
    )


def _brute_inner(lrows, rrows):
    return sorted(
        (0, lk, a, rk, b)
        for lk, a in lrows for rk, b in rrows if lk == rk
    )


def test_pool_join_hot_key_exceeds_any_bucket():
    """One key holding 200 rows (far past any dense bucket_cap) joins
    fully: the pool has no per-key depth limit."""
    import jax

    j = _pool_join()
    st = j.init_state()
    lrows = [(7, i) for i in range(200)] + [(1, 900), (2, 901)]
    rows_txt = "I I\n" + "\n".join(f"+ {k} {v}" for k, v in lrows)
    st, out = j.apply(st, Chunk.from_pretty(rows_txt, names=["k", "a"]),
                      "left")
    st, rows = _apply(j, st, _rc("""
        I I
        + 7 500
        + 2 600
    """), "right")
    want = _brute_inner(lrows, [(7, 500), (2, 600)])
    # out_capacity=64 < 201 matches: drain the remaining windows the
    # way the DAG runtime does
    assert int(st.left.overflow) == 0 and int(st.right.overflow) == 0
    assert len(rows) == 64  # first window full
    # full-match check via the windowed interface
    st2 = j.init_state()
    st2, _ = j.apply(st2, Chunk.from_pretty(rows_txt, names=["k", "a"]),
                     "left")
    chunk = _rc("""
        I I
        + 7 500
        + 2 600
    """)
    st2, pending = j.apply_begin(st2, chunk, "right")
    build = j.build_rows_of(st2, "right")
    got = []
    import jax.numpy as jnp
    w = 0
    while w * j.out_capacity < int(pending.total):
        got.extend(
            j.emit_window(build, pending, jnp.int32(w), "right")[0].to_rows()
        )
        w += 1
    assert sorted(got) == want


def test_pool_join_10x_skew_matches_brute_force():
    """10x hot-key skew across multiple chunks: exact results, zero
    overflow, no per-key tuning (round-2 verdict item 4 done-criterion)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    j = _pool_join()
    st = j.init_state()
    lrows, rrows = [], []
    got = []

    def drain(pending, side):
        build = j.build_rows_of(st, side)
        w = 0
        while w * j.out_capacity < int(pending.total):
            got.extend(j.emit_window(
                build, pending, jnp.int32(w), side)[0].to_rows())
            w += 1

    for step in range(6):
        # 90% of rows on key 7 (10x skew vs the other 9 keys)
        lk = np.where(rng.random(32) < 0.9, 7,
                      rng.integers(0, 9, 32)).astype(np.int64)
        la = rng.integers(0, 1000, 32).astype(np.int64)
        rk = np.where(rng.random(32) < 0.9, 7,
                      rng.integers(0, 9, 32)).astype(np.int64)
        rb = rng.integers(0, 1000, 32).astype(np.int64)
        lchunk = "I I\n" + "\n".join(
            f"+ {k} {v}" for k, v in zip(lk, la))
        rchunk = "I I\n" + "\n".join(
            f"+ {k} {v}" for k, v in zip(rk, rb))
        st, pending = j.apply_begin(
            st, Chunk.from_pretty(lchunk, names=["k", "a"]), "left")
        drain(pending, "left")
        lrows.extend(zip(lk.tolist(), la.tolist()))
        st, pending = j.apply_begin(
            st, Chunk.from_pretty(rchunk, names=["k", "b"]), "right")
        drain(pending, "right")
        rrows.extend(zip(rk.tolist(), rb.tolist()))

    assert int(st.left.overflow) == 0 and int(st.right.overflow) == 0
    assert sorted(got) == _brute_inner(lrows, rrows)


def test_pool_join_watermark_cleaning_bounds_state():
    """clean_below on a pool side evicts whole keys (all their fused
    (hash, rank) entries) in one mask; ranks stay consistent for
    survivors and compaction reclaims the dead pool rows."""
    import jax.numpy as jnp

    j = _pool_join()
    j.left_clean = (0, 0, 0)  # clean left keys below threshold
    st = j.init_state()
    lrows = [(k, 10 * k + i) for k in range(8) for i in range(5)]
    txt = "I I\n" + "\n".join(f"+ {k} {v}" for k, v in lrows)
    st, _ = j.apply(st, Chunk.from_pretty(txt, names=["k", "a"]), "left")
    assert int(st.left.table.count()) == 40
    assert int(st.left.pool_len) == 40

    st = j.clean_below(st, "left", 0, 5)  # drop keys 0..4
    assert int(st.left.table.count()) == 15  # 3 keys x 5 rows remain

    # survivors still join correctly (ranks intact)
    st, pending = j.apply_begin(st, _rc("""
        I I
        + 6 600
        + 2 200
    """), "right")
    build = j.build_rows_of(st, "right")
    got = []
    w = 0
    while w * j.out_capacity < int(pending.total):
        got.extend(j.emit_window(
            build, pending, jnp.int32(w), "right")[0].to_rows())
        w += 1
    want = _brute_inner([r for r in lrows if r[0] >= 5], [(6, 600)])
    assert sorted(got) == want


def test_pool_join_retraction_is_loud():
    """A delete reaching an append-only pool side surfaces as
    inconsistency, never silent corruption."""
    j = _pool_join()
    st = j.init_state()
    st, _ = j.apply(st, _lc("""
        I I
        + 1 10
    """), "left")
    st, _ = j.apply(st, _lc("""
        I I
        - 1 10
    """), "left")
    assert int(st.left.inconsistency) == 1
