"""Streaming hash-join tests (inner join, retraction, multiset)."""

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_join import HashJoinExecutor
from risingwave_tpu.stream.materialize import AppendOnlyMaterialize
from risingwave_tpu.stream.dag import DagJob

L = Schema.of(("k", DataType.INT64), ("a", DataType.INT64))
R = Schema.of(("k", DataType.INT64), ("b", DataType.INT64))


def _join(**kw):
    return HashJoinExecutor(
        L, R, [col("k")], [col("k")],
        table_size=64, bucket_cap=4, out_capacity=64, **kw,
    )


def _lc(text):
    return Chunk.from_pretty(text, names=["k", "a"])


def _rc(text):
    return Chunk.from_pretty(text, names=["k", "b"])


def _apply(j, st, chunk, side):
    st, out = j.apply(st, chunk, side)
    return st, sorted(out.to_rows())


def test_inner_join_basic():
    j = _join()
    st = j.init_state()
    st, rows = _apply(j, st, _lc("""
        I I
        + 1 10
        + 2 20
    """), "left")
    assert rows == []  # right empty

    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
        + 1 101
        + 3 300
    """), "right")
    # right rows probe left: k=1 matches once each
    assert rows == [(0, 1, 10, 1, 100), (0, 1, 10, 1, 101)]

    st, rows = _apply(j, st, _lc("""
        I I
        + 1 11
    """), "left")
    # new left row matches both right k=1 rows
    assert rows == [(0, 1, 11, 1, 100), (0, 1, 11, 1, 101)]


def test_join_retraction():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
    """), "left")
    st, _ = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    # delete the left row: must retract the joined row
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
    """), "left")
    assert rows == [(1, 1, 10, 1, 100)]
    # left side now empty: new right row matches nothing
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 101
    """), "right")
    assert rows == []


def test_join_multiset_duplicates():
    j = _join()
    st = j.init_state()
    # two identical left rows — multiset semantics
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 1 10
    """), "left")
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    assert rows == [(0, 1, 10, 1, 100), (0, 1, 10, 1, 100)]
    # delete ONE copy
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
    """), "left")
    assert rows == [(1, 1, 10, 1, 100)]
    # one copy left
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 101
    """), "right")
    assert rows == [(0, 1, 10, 1, 101)]


def test_join_delete_then_insert_same_chunk_reuses_hole():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 1 11
        + 1 12
        + 1 13
    """), "left")  # bucket_cap=4: full
    st, rows = _apply(j, st, _lc("""
        I I
        - 1 10
        + 1 14
    """), "left")
    assert int(st.left.overflow) == 0  # hole reused, no overflow
    assert int(st.left.count[np.argmax(st.left.count)]) == 4


def test_join_state_cleaning():
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        + 5 50
    """), "left")
    st = j.clean_below(st, "left", 0, 3)  # drop keys < 3
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
        + 5 500
    """), "right")
    assert rows == [(0, 5, 50, 5, 500)]


def test_binary_job_end_to_end():
    class ListSource:
        def __init__(self, chunks):
            self.chunks = list(chunks)
            self.i = 0

        def next_chunk(self):
            c = self.chunks[self.i % len(self.chunks)]
            self.i += 1
            return c

    j = _join()
    mv = AppendOnlyMaterialize(j.out_schema, ring_size=256)
    job = DagJob.binary(
        ListSource([_lc("""
            I I
            + 1 10
        """), _lc("""
            I I
            + 2 20
        """)]),
        ListSource([_rc("""
            I I
            + 1 100
        """), _rc("""
            I I
            + 2 200
        """)]),
        j,
        Fragment([mv]),
    )
    job.run(barriers=1, chunks_per_barrier=2)
    # nodes: [join, post] — the post fragment holds the MV
    rows = mv.to_host(job.states[1][0])
    assert sorted(rows) == [(1, 10, 1, 100), (2, 20, 2, 200)]
    assert job.committed_epoch > 0


def test_join_insert_then_delete_same_chunk_annihilates():
    """Regression: [+row, -row] in ONE chunk must not ghost-insert."""
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        + 1 10
        - 1 10
    """), "left")
    # left state must be empty: a new right row matches nothing
    st, rows = _apply(j, st, _rc("""
        I I
        + 1 100
    """), "right")
    assert rows == []
    assert int(st.left.inconsistency) == 0


def test_join_delete_of_absent_key_no_ghost():
    """Regression: deletes must not insert ghost keys into the table."""
    j = _join()
    st = j.init_state()
    st, _ = _apply(j, st, _lc("""
        I I
        - 7 70
    """), "left")
    assert int(st.left.key_table.count()) == 0  # no ghost key slot
    assert int(st.left.inconsistency) == 1      # surfaced, not silent


def test_binary_job_recover():
    class ReplaySource:
        def __init__(self, chunks):
            self.chunks = list(chunks)
            self.offset = 0

        def next_chunk(self):
            c = self.chunks[self.offset % len(self.chunks)]
            self.offset += 1
            return c

        def state(self):
            return {"offset": self.offset}

    j = _join()
    mv = AppendOnlyMaterialize(j.out_schema, ring_size=256)
    job = DagJob.binary(
        ReplaySource([_lc("""
            I I
            + 1 10
        """)]),
        ReplaySource([_rc("""
            I I
            + 1 100
        """)]),
        j, Fragment([mv]),
    )
    job.run(barriers=1, chunks_per_barrier=1)
    committed = job.committed_epoch
    n_rows = len(mv.to_host(job.states[1][0]))
    # process more, then crash before the barrier
    job.run_chunk("left")
    job.recover()
    assert job.sources["left"].offset == 1
    assert len(mv.to_host(job.states[1][0])) == n_rows
    assert job.committed_epoch == committed
