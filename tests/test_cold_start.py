"""Cold-start recovery: a fresh process rebuilds everything from
data_dir alone.

Ref: the meta node's durable metastore + DdlController recovery
(src/meta/model/, src/meta/src/rpc/ddl_controller.rs:1096) — catalog,
job topology, DML table state, and committed checkpoints all survive a
process death; a new process replays the DDL log, reloads DML history,
and resumes from the last committed epoch.
"""

import json

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def _cfg() -> PlannerConfig:
    return PlannerConfig(
        chunk_capacity=128,
        agg_table_size=512,
        agg_emit_capacity=256,
        mv_table_size=1 << 10,
        mv_ring_size=1 << 11,
        join_table_size=1 << 10,
        join_bucket_cap=32,
        join_out_capacity=1 << 11,
    )


def test_cold_start_recovery(tmp_path):
    data = str(tmp_path / "data")
    sink_path = str(tmp_path / "out.jsonl")

    eng = Engine(_cfg(), data_dir=data)
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    rows1 = [(k, 10 * k + r) for k in range(40) for r in range(2)]
    vals = ",".join(f"({a},{b})" for a, b in rows1)
    eng.execute(f"INSERT INTO t VALUES {vals}")
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS "
        "SELECT k, count(*) AS n, sum(v) AS s FROM t GROUP BY k"
    )
    # a cascaded MV exercises the DagJob/MvTap replay path
    eng.execute(
        "CREATE MATERIALIZED VIEW mv2 AS "
        "SELECT k, s FROM mv WHERE s > 100"
    )
    eng.execute(
        f"CREATE SINK snk FROM mv2 WITH "
        f"(connector='file', path='{sink_path}')"
    )
    eng.execute("FLUSH")
    want_mv = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    want_mv2 = sorted(map(tuple, eng.execute("SELECT * FROM mv2")))
    assert len(want_mv) == 40 and want_mv2

    with open(sink_path) as f:
        lines1 = [json.loads(x) for x in f]
    delivered1 = [x for x in lines1 if x["op"] != "commit"]
    assert delivered1, "sink delivered nothing before the restart"

    # process dies with NO clean shutdown; a brand-new engine gets
    # only data_dir — no DDL, no inserts
    del eng
    eng2 = Engine(_cfg(), data_dir=data)

    names = sorted(e.name for e in eng2.catalog.list())
    assert names == ["mv", "mv2", "snk", "t"]
    got_mv = sorted(map(tuple, eng2.execute("SELECT * FROM mv")))
    got_mv2 = sorted(map(tuple, eng2.execute("SELECT * FROM mv2")))
    assert got_mv == want_mv
    assert got_mv2 == want_mv2

    # sink delivery continues from the recovered cursors: new rows are
    # delivered exactly once, and the pre-restart rows are not re-sent
    # (the last FLUSH committed them durably before the "crash")
    rows2 = [(k, 1000 + k) for k in range(40)]
    vals = ",".join(f"({a},{b})" for a, b in rows2)
    eng2.execute(f"INSERT INTO t VALUES {vals}")
    eng2.execute("FLUSH")

    with open(sink_path) as f:
        lines2 = [json.loads(x) for x in f]
    new = lines2[len(lines1):]
    assert new, "no post-restart delivery"
    # closed-epoch reader protocol: fold UPDATE pairs per key, expect
    # each key's final s to match the recomputed MV exactly once
    final_mv2 = {int(r[0]): int(r[1])
                 for r in eng2.execute("SELECT * FROM mv2")}
    seen: dict[int, int] = {}
    for rec in lines2:
        if rec["op"] in ("insert", "update_insert"):
            seen[int(rec["k"])] = int(rec["s"])
        elif rec["op"] == "delete":
            seen.pop(int(rec["k"]), None)
    assert seen == final_mv2


def test_cold_start_empty_dir(tmp_path):
    """A data_dir with no catalog bootstraps to an empty engine."""
    eng = Engine(_cfg(), data_dir=str(tmp_path / "data"))
    assert eng.catalog.list() == []
    eng.execute("CREATE TABLE t (k BIGINT)")
    assert [e.name for e in eng.catalog.list()] == ["t"]
