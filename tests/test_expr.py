"""Expression engine tests (ref: src/expr/impl tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import Chunk, DataType, Schema
from risingwave_tpu.common.types import Field
from risingwave_tpu.expr import FUNCTION_REGISTRY, col, lit, input_ref
from risingwave_tpu.expr.node import case


def _chunk():
    return Chunk.from_pretty(
        """
        i I F
        + 1 10 1.5
        + 2 20 2.5
        + 3 30 3.5
        """
    )


def test_arith_promotion():
    c = _chunk()
    e = col("c0") + col("c1")  # int32 + int64 -> int64
    assert e.return_type(c.schema) == DataType.INT64
    assert np.asarray(e.eval(c)).tolist() == [11, 22, 33]
    e2 = col("c0") * col("c2")  # int32 * float64 -> float64
    assert e2.return_type(c.schema) == DataType.FLOAT64
    assert np.asarray(e2.eval(c)).tolist() == [1.5, 5.0, 10.5]


def test_decimal_math():
    schema = Schema.of(("price", DataType.INT64))
    c = Chunk.from_numpy(schema, [np.asarray([100, 200, 300])])
    # 0.908 * price : decimal literal * int64 -> decimal (ref nexmark q1)
    e = lit(0.908, DataType.DECIMAL) * col("price")
    assert e.return_type(schema) == DataType.DECIMAL
    out = np.asarray(e.eval(c))[:3]
    assert out.tolist() == [90_800_000, 181_600_000, 272_400_000]  # scaled 1e6


def test_comparison_and_logic():
    c = _chunk()
    e = (col("c0") > 1) & (col("c1") < lit(30))
    got = np.asarray(e.eval(c))[:3]
    assert got.tolist() == [False, True, False]


def test_case_expr():
    c = _chunk()
    e = case(col("c0") == 2, col("c1") * 10, lit(0))
    assert np.asarray(e.eval(c))[:3].tolist() == [0, 200, 0]


def test_string_funcs():
    schema = Schema.of(("s", DataType.VARCHAR))
    c = Chunk.from_numpy(
        schema, [np.asarray(["apple", "Banana", "apple pie", "zz"], object)]
    )
    eq = col("s") == "apple"
    assert np.asarray(eq.eval(c))[:4].tolist() == [True, False, False, False]
    lt = col("s") < "b"
    # 'apple' < 'b', 'Banana' < 'b' (ascii B=66<98), 'apple pie' < 'b', 'zz' > 'b'
    assert np.asarray(lt.eval(c))[:4].tolist() == [True, True, True, False]
    # prefix ordering: 'apple' < 'apple pie'
    schema2 = Schema.of(("a", DataType.VARCHAR), ("b", DataType.VARCHAR))
    c2 = Chunk.from_numpy(
        schema2,
        [np.asarray(["apple"], object), np.asarray(["apple pie"], object)],
    )
    assert bool(np.asarray((col("a") < col("b")).eval(c2))[0])
    ln = FUNCTION_REGISTRY.resolve(
        "char_length", [Field("s", DataType.VARCHAR)]
    )
    assert np.asarray(ln.impl(c.column(0)))[:4].tolist() == [5, 6, 9, 2]


def test_temporal():
    schema = Schema.of(("ts", DataType.TIMESTAMP))
    us = 3_600_000_000  # 1 hour in micros
    c = Chunk.from_numpy(schema, [np.asarray([us + 5, 3 * us + 999, 42])])
    from risingwave_tpu.expr.node import FuncCall

    e = FuncCall("date_trunc_hour", (col("ts"),))
    assert e.return_type(schema) == DataType.TIMESTAMP
    assert np.asarray(e.eval(c))[:3].tolist() == [us, 3 * us, 0]
    tumble = FuncCall("tumble_start", (col("ts"), lit(10, DataType.INTERVAL)))
    assert np.asarray(tumble.eval(c))[:3].tolist() == [us, 3 * us + 990, 40]


def test_cast():
    c = _chunk()
    e = col("c0").cast(DataType.FLOAT64) / lit(2.0)
    assert np.asarray(e.eval(c))[:3].tolist() == [0.5, 1.0, 1.5]
    e2 = col("c2").cast(DataType.INT64)
    assert np.asarray(e2.eval(c))[:3].tolist() == [1, 2, 3]


def test_div_by_zero_guarded():
    schema = Schema.of(("a", DataType.INT64), ("b", DataType.INT64))
    c = Chunk.from_numpy(
        schema, [np.asarray([10, 10]), np.asarray([0, 2])]
    )
    out = np.asarray((col("a") / col("b")).eval(c))[:2]
    assert out.tolist() == [0, 5]  # guarded, no crash/trap


def test_registry_no_overload():
    schema = Schema.of(("s", DataType.VARCHAR))
    c = Chunk.from_numpy(schema, [np.asarray(["x"], object)])
    with pytest.raises(KeyError, match="no overload"):
        (col("s") + lit(1)).eval(c)


def test_expr_inside_jit():
    """Whole expr tree must trace into one jitted program."""
    c = _chunk()
    e = (col("c0") + col("c1")) * lit(2)

    @jax.jit
    def step(ch):
        return e.eval(ch)

    out = np.asarray(step(c))[:3]
    assert out.tolist() == [22, 44, 66]


def test_agg_specs():
    from risingwave_tpu.expr.agg import AGG_REGISTRY

    s = AGG_REGISTRY["sum"]
    signs = jnp.asarray([1, -1, 1], jnp.int32)
    vals = jnp.asarray([10, 20, 30], jnp.int64)
    contrib = np.asarray(s.states[0].lift(vals, signs))
    assert contrib.tolist() == [10, -20, 30]
    a = AGG_REGISTRY["avg"]
    assert len(a.states) == 2
    mn = AGG_REGISTRY["min"]
    lifted = np.asarray(mn.states[0].lift(vals, signs))
    assert lifted[1] == np.iinfo(np.int64).max  # delete -> neutral
