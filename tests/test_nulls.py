"""NULL support: validity planes, three-valued logic, null-aware state.

Reference counterpart: every array carries a null bitmap
(src/common/src/array/mod.rs:279-296); expression strictness and
Kleene logic mirror src/expr semantics.
"""

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Chunk, NCol
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.node import FuncCall, InputRef, Literal
from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def _engine(cap=64):
    return Engine(PlannerConfig(
        chunk_capacity=cap, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1 << 12, topn_pool_size=256,
        topn_emit_capacity=64, join_table_size=256, join_bucket_cap=8,
        join_out_capacity=256,
    ))


# ---------------------------------------------------------------------------
# chunk plumbing


def test_pretty_dsl_null_round_trip():
    ch = Chunk.from_pretty(
        """
        i I
        +  1 10
        +  . 20
        +  3  .
        """
    )
    assert isinstance(ch.columns[0], NCol)
    assert isinstance(ch.columns[1], NCol)
    rows = ch.to_rows()
    assert rows == [(0, 1, 10), (0, None, 20), (0, 3, None)]
    assert ch.schema[0].nullable and ch.schema[1].nullable


def test_null_into_not_null_column_raises():
    schema = Schema((Field("a", DataType.INT64),))
    with pytest.raises(ValueError, match="NOT NULL"):
        Chunk.from_numpy(schema, [np.asarray([1, None], object)])


# ---------------------------------------------------------------------------
# expression three-valued logic


def _eval(expr, chunk):
    from risingwave_tpu.common.chunk import split_col

    col = expr.eval(chunk)
    data, null = split_col(col)
    d = np.asarray(data)
    if null is None:
        return [bool(v) if d.dtype == np.bool_ else v for v in d]
    n = np.asarray(null)
    return [None if n[i] else (bool(d[i]) if d.dtype == np.bool_ else d[i])
            for i in range(len(d))]


def test_strict_propagation_and_is_null():
    ch = Chunk.from_pretty(
        """
        I I
        + 1 10
        + . 20
        + 3  .
        """
    )
    s = _eval(InputRef(0) + InputRef(1), ch)
    assert s[0] == 11 and s[1] is None and s[2] is None
    assert _eval(FuncCall("is_null", (InputRef(0),)), ch) == \
        [False, True, False]
    assert _eval(FuncCall("is_not_null", (InputRef(1),)), ch) == \
        [True, True, False]
    cmp = _eval(InputRef(0) < InputRef(1), ch)
    assert cmp == [True, None, None]


def test_kleene_and_or():
    # a: T, F, NULL in all combinations against b
    ch = Chunk.from_pretty(
        """
        b b
        + t t
        + t f
        + t .
        + f t
        + f f
        + f .
        + . t
        + . f
        + . .
        """
    )
    a, b = InputRef(0), InputRef(1)
    assert _eval(a & b, ch) == [
        True, False, None, False, False, False, None, False, None
    ]
    assert _eval(a | b, ch) == [
        True, True, True, True, False, None, True, None, None
    ]


def test_coalesce_and_case_null():
    ch = Chunk.from_pretty(
        """
        I I
        + 1 10
        + . 20
        """
    )
    assert _eval(FuncCall("coalesce", (InputRef(0), InputRef(1))), ch) == \
        [1, 20]
    # CASE WHEN a IS NULL THEN b (no else) -> NULL for first row
    cond = FuncCall("is_null", (InputRef(0),))
    e = FuncCall("case", (cond, InputRef(1),
                          Literal(None, DataType.INT64)))
    assert _eval(e, ch) == [None, 20]


# ---------------------------------------------------------------------------
# SQL end-to-end


def test_sql_nullable_agg_and_filter():
    eng = _engine()
    eng.execute("""
        CREATE TABLE t (k BIGINT, v BIGINT NULL);
        CREATE MATERIALIZED VIEW m AS
        SELECT k, count(*) AS n, count(v) AS nv, sum(v) AS sv
        FROM t GROUP BY k;
    """)
    eng.execute(
        "INSERT INTO t VALUES (1, 10), (1, NULL), (2, NULL), (2, 5), "
        "(2, 7)"
    )
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = {int(r[0]): (int(r[1]), int(r[2]), int(r[3]))
            for r in eng.execute("SELECT k, n, nv, sv FROM m")}
    # count(*) counts NULL rows; count(v)/sum(v) skip them
    assert rows == {1: (2, 1, 10), 2: (3, 2, 12)}


def test_sql_where_null_and_is_null():
    eng = _engine()
    eng.execute("""
        CREATE TABLE t (k BIGINT, v BIGINT NULL);
        CREATE MATERIALIZED VIEW big AS
        SELECT k FROM t WHERE v > 5;
        CREATE MATERIALIZED VIEW missing AS
        SELECT k FROM t WHERE v IS NULL;
    """)
    eng.execute("INSERT INTO t VALUES (1, 10), (2, NULL), (3, 3)")
    eng.tick(barriers=1, chunks_per_barrier=1)
    # NULL > 5 is NULL -> row dropped (not an error, not kept)
    assert [int(r[0]) for r in eng.execute("SELECT k FROM big")] == [1]
    assert [int(r[0]) for r in eng.execute("SELECT k FROM missing")] == [2]


def test_sql_group_by_nullable_key():
    eng = _engine()
    eng.execute("""
        CREATE TABLE t (g BIGINT NULL, v BIGINT);
        CREATE MATERIALIZED VIEW m AS
        SELECT g, count(*) AS n FROM t GROUP BY g;
    """)
    eng.execute(
        "INSERT INTO t VALUES (1, 1), (NULL, 2), (NULL, 3), (1, 4)"
    )
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = {r[0]: int(r[1]) for r in eng.execute("SELECT g, n FROM m")}
    # SQL GROUP BY: all NULL keys form ONE group
    assert rows == {1: 2, None: 2}


def test_sql_case_without_else_and_projection_null():
    eng = _engine()
    eng.execute("""
        CREATE TABLE t (v BIGINT);
        CREATE MATERIALIZED VIEW m AS
        SELECT v, CASE WHEN v > 2 THEN v END AS big FROM t;
    """)
    eng.execute("INSERT INTO t VALUES (1), (5)")
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = sorted(
        ((int(r[0]), None if r[1] is None else int(r[1]))
         for r in eng.execute("SELECT v, big FROM m")),
    )
    assert rows == [(1, None), (5, 5)]


def test_insert_omitting_nullable_column():
    eng = _engine()
    eng.execute("CREATE TABLE t (a BIGINT, b BIGINT NULL)")
    eng.execute("INSERT INTO t (a) VALUES (7)")
    with pytest.raises(ValueError, match="NOT NULL"):
        eng.execute("INSERT INTO t (b) VALUES (1)")
    with pytest.raises(ValueError, match="NOT NULL"):
        eng.execute("INSERT INTO t VALUES (NULL, 1)")


def test_sql_join_null_keys_never_match():
    eng = _engine()
    eng.execute("""
        CREATE TABLE l (k BIGINT NULL, lv BIGINT);
        CREATE TABLE r (k BIGINT NULL, rv BIGINT);
        CREATE MATERIALIZED VIEW j AS
        SELECT l.lv AS lv, r.rv AS rv FROM l JOIN r ON l.k = r.k;
    """)
    eng.execute("INSERT INTO l VALUES (1, 10), (NULL, 20)")
    eng.execute("INSERT INTO r VALUES (1, 100), (NULL, 200)")
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = [(int(a), int(b)) for a, b in eng.execute(
        "SELECT lv, rv FROM j")]
    # SQL join equality: NULL = NULL is NOT a match
    assert rows == [(10, 100)]


def test_sql_all_null_group_sum_is_null():
    eng = _engine()
    eng.execute("""
        CREATE TABLE t (g BIGINT, v BIGINT NULL);
        CREATE MATERIALIZED VIEW m AS
        SELECT g, sum(v) AS sv, min(v) AS mv FROM t GROUP BY g;
    """)
    eng.execute("INSERT INTO t VALUES (1, NULL), (1, NULL), (2, 5)")
    eng.tick(barriers=1, chunks_per_barrier=1)
    rows = {int(r[0]): (r[1], r[2])
            for r in eng.execute("SELECT g, sv, mv FROM m")}
    assert rows[1] == (None, None)
    assert (int(rows[2][0]), int(rows[2][1])) == (5, 5)
