"""Slow wrapper over scripts/ch_bench.py (the ISSUE 16 acceptance
harness), matching the cluster_stress wrapper pattern: a short
``--small`` run against the real 4-role cluster with every SLO gate
asserted."""

import pytest


@pytest.mark.slow
def test_ch_bench_small(tmp_path):
    from risingwave_tpu.workload.driver import check, run

    summary = run(rounds=8, seed=11, workers=2, readers=2,
                  small=True, data_dir=str(tmp_path))
    bad = check(summary, min_ingest_rows_s=1.0,
                max_barrier_p99_s=300.0,
                max_serve_p999_ms=10000.0)
    assert not bad, (bad, summary)
    assert summary["txn_total"] > 0
    assert summary["reads"] > 0
    assert summary["mv_mismatches"] == 0
