"""Deterministic failure/recovery simulation (the madsim analog).

Reference counterpart: src/tests/simulation — kill nodes mid-stream and
assert the maintained MVs converge to the same result as an undisturbed
run (e.g. recovery/nexmark_recovery.rs, SURVEY.md §4.4).  Determinism
here comes for free: sources are counter-addressed, so replay after
recovery is exact.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


DDL = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid');
CREATE MATERIALIZED VIEW q7 AS
SELECT window_start, max(price) AS max_price, count(*) AS bids
FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
GROUP BY window_start;
"""


def _cfg():
    return PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10, agg_emit_capacity=256,
        mv_table_size=1 << 10,
    )


def _mv(eng):
    return sorted(eng.execute("SELECT window_start, max_price, bids FROM q7"))


def test_nexmark_recovery_converges(tmp_path):
    # undisturbed run: 6 barriers
    a = Engine(_cfg())
    a.execute(DDL)
    a.tick(barriers=6, chunks_per_barrier=1)
    want = _mv(a)

    # chaotic run: crash after 2 and 4 barriers (uncommitted progress in
    # flight), recover from the durable store each time
    b = Engine(_cfg(), data_dir=str(tmp_path))
    b.execute(DDL)
    b.tick(barriers=2, chunks_per_barrier=1)
    # progress past the last checkpoint, then "crash"
    b.jobs[0].run_chunk()
    # cold start: fresh engines bootstrap DDL + state from data_dir
    b2 = Engine(_cfg(), data_dir=str(tmp_path))
    b2.tick(barriers=2, chunks_per_barrier=1)
    b2.jobs[0].run_chunk()
    b3 = Engine(_cfg(), data_dir=str(tmp_path))
    b3.tick(barriers=2, chunks_per_barrier=1)

    assert _mv(b3) == want


_CLUSTER_CFG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
    "storage": {"checkpoint_keep_epochs": 4},
}

_CLUSTER_DDL = [
    """CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid')""",
    """CREATE MATERIALIZED VIEW q7 AS
    SELECT window_start, max(price) AS max_price, count(*) AS bids
    FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
    GROUP BY window_start""",
    """CREATE MATERIALIZED VIEW qcnt AS
    SELECT auction % 16 AS a, count(*) AS n, sum(price) AS vol
    FROM bid GROUP BY auction % 16""",
]

_CLUSTER_READS = [
    "SELECT window_start, max_price, bids FROM q7",
    "SELECT a, n, vol FROM qcnt",
]


def _spawn_worker(meta_port: int, data_dir: str, log_path: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "compute", "--meta", f"127.0.0.1:{meta_port}",
         "--data-dir", data_dir,
         "--config-json", json.dumps(_CLUSTER_CFG),
         "--heartbeat-interval", "0.25"],
        stdout=subprocess.DEVNULL,
        stderr=open(log_path, "wb"),
        env=env,
    )


def _drive_rounds(meta, n: int, deadline_s: float = 240.0) -> None:
    """Advance the cluster by n COMMITTED global rounds (incomplete
    rounds — failover in progress — retry until they commit)."""
    deadline = time.monotonic() + deadline_s
    for _ in range(n):
        while True:
            res = meta.tick(1)
            if res["committed"]:
                break
            assert time.monotonic() < deadline, \
                f"round {res['round']} never committed: {res}"
            time.sleep(0.2)


def _spawn_serving(meta_port: int, data_dir: str, log_path: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "serving", "--meta", f"127.0.0.1:{meta_port}",
         "--data-dir", data_dir,
         "--heartbeat-interval", "0.1"],
        stdout=subprocess.PIPE,
        stderr=open(log_path, "wb"),
        env=env,
    )


def test_cluster_sigkill_failover_converges(tmp_path):
    """The ISSUE 3 acceptance run, extended with the ISSUE 5 serving
    tier: a 1-meta + 2-compute + 1-SERVING-REPLICA cluster with 2
    nexmark MVs survives a SIGKILL of one worker — the dead worker's
    job is reassigned and replayed from the last committed cluster
    epoch; serving reads issued THROUGHOUT the failover (routed to
    the replica — an engine-free process whose handshake proves jax
    never loaded — with engine-only shapes falling back to the owning
    worker) observe only committed epochs with ZERO errors while
    vacuum + the meta's compactor churn the shared store underneath;
    and the final MV contents are byte-identical to an undisturbed
    single-node run."""
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.common.config import RwConfig

    rounds_before, rounds_after = 3, 3
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=4.0)
    meta.start(port=0)  # heartbeat monitor AND compactor both live
    procs = [
        _spawn_worker(meta.rpc_port, str(tmp_path),
                      str(tmp_path / f"worker{i}.log"))
        for i in range(2)
    ]
    serving = _spawn_serving(meta.rpc_port, str(tmp_path),
                             str(tmp_path / "serving.log"))
    stop_reads = threading.Event()
    read_errors: list = []
    try:
        # the engine-free contract, asserted at the process boundary
        handshake = json.loads(serving.stdout.readline().decode())
        assert handshake["jax_loaded"] is False, handshake

        deadline = time.monotonic() + 120
        while len(meta.live_workers()) < 2:
            assert time.monotonic() < deadline, "workers never registered"
            for p in procs:
                assert p.poll() is None, \
                    f"worker died at startup (see {tmp_path})"
            time.sleep(0.25)
        assert serving.poll() is None, \
            f"serving replica died at startup (see {tmp_path})"

        for sql in _CLUSTER_DDL:
            meta.execute_ddl(sql)
        _drive_rounds(meta, rounds_before)

        # the serving loop runs ACROSS the kill: every read must come
        # back from a committed epoch with no error.  The aggregate
        # shape exercises the OWNER fallback path through the same
        # window (replicas refuse it); vacuum churns concurrently.
        def read_loop():
            while not stop_reads.is_set():
                for sql in _CLUSTER_READS + [
                        "SELECT count(*) FROM qcnt"]:
                    try:
                        meta.serve(sql)
                    except Exception as e:  # noqa: BLE001
                        read_errors.append(repr(e))
                time.sleep(0.05)

        def vacuum_loop():
            while not stop_reads.is_set():
                try:
                    meta.storage_vacuum()
                except Exception as e:  # noqa: BLE001
                    read_errors.append(f"vacuum: {e!r}")
                time.sleep(0.1)

        threads = [threading.Thread(target=read_loop, daemon=True),
                   threading.Thread(target=vacuum_loop, daemon=True)]
        for t in threads:
            t.start()

        # SIGKILL the worker owning qcnt (pid registered at handshake)
        st = meta.state()
        owner = next(j["worker"] for j in st["jobs"]
                     if j["name"] == "qcnt")
        pid = next(w["pid"] for w in st["workers"] if w["id"] == owner)
        os.kill(pid, signal.SIGKILL)

        _drive_rounds(meta, rounds_after)
        stop_reads.set()
        for t in threads:
            t.join(timeout=10)
        assert read_errors == [], read_errors[:3]
        assert meta.failovers == 1
        assert meta.cluster_epoch == rounds_before + rounds_after
        # the replica actually carried reads (not just owner fallback)
        assert meta.metrics.get("cluster_serving_reads_total") > 0
        assert meta.state()["serving"], "replica lost its registration"

        got = [sorted(tuple(r) for r in meta.serve(sql)[1])
               for sql in _CLUSTER_READS]

        # undisturbed single-node run, same config + rounds
        eng = Engine(RwConfig.from_dict(_CLUSTER_CFG))
        for sql in _CLUSTER_DDL:
            eng.execute(sql)
        eng.tick(barriers=rounds_before + rounds_after,
                 chunks_per_barrier=1)
        want = [sorted(tuple(int(v) for v in r) for r in eng.execute(sql))
                for sql in _CLUSTER_READS]
        assert got == want
    finally:
        stop_reads.set()
        for p in procs + [serving]:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        meta.stop()


def test_transient_upload_fault_retries_invisibly(tmp_path):
    """ISSUE 6 satellite: a TRANSIENT store failure mid-upload (one
    lost manifest put) is absorbed by the uploader's RetryPolicy —
    the barrier loop never sees it, durable progress continues, and
    the retry is visible on the budget counter."""
    from risingwave_tpu.storage.hummock.object_store import StoreFaults

    b = Engine(_cfg(), data_dir=str(tmp_path))
    b.execute(DDL)
    b.tick(barriers=2, chunks_per_barrier=1)
    store = b.checkpoint_store
    faults = StoreFaults()
    faults.fail("put", substr="MANIFEST", mode="before")  # once
    store.store.faults = faults
    b.tick(barriers=1, chunks_per_barrier=1)  # must NOT raise
    store.store.faults = None
    job = b.jobs[0]
    assert store.committed_epoch(job.name) == job.sealed_epoch
    assert job._uploader.retries_total >= 1
    assert faults.injected_errors == 1


def test_crash_mid_upload_rewinds_to_durable_epoch(tmp_path):
    """ISSUE 4 satellite (reworked for the ISSUE 6 retry budget): a
    PERSISTENT failure between the checkpoint object write and the
    manifest commit exhausts the uploader's retries, vacuums the
    partial epoch objects, and surfaces on the barrier loop; a cold
    restart rewinds to the previous DURABLE epoch and converges to
    the undisturbed result."""
    import pytest

    from risingwave_tpu.storage.hummock.object_store import StoreFaults

    # undisturbed reference: 6 barriers
    a = Engine(_cfg())
    a.execute(DDL)
    a.tick(barriers=6, chunks_per_barrier=1)
    want = _mv(a)

    b = Engine(_cfg(), data_dir=str(tmp_path))
    b.execute(DDL)
    b.tick(barriers=2, chunks_per_barrier=1)
    store = b.checkpoint_store
    durable = store.committed_epoch(b.jobs[0].name)
    # arm: EVERY manifest write is lost (the npz lands each attempt)
    # until the retry budget (4 attempts) exhausts
    faults = StoreFaults()
    faults.fail("put", substr="MANIFEST", mode="before", times=16)
    store.store.faults = faults
    with pytest.raises(RuntimeError, match="upload failed"):
        b.tick(barriers=1, chunks_per_barrier=1)
    store.store.faults = None
    assert store.committed_epoch(b.jobs[0].name) == durable
    # the retry budget was spent before surfacing...
    assert b.jobs[0]._uploader.retries_total >= 3
    # ...and the partial epoch objects were vacuumed with the failure
    orphan = f"{b.jobs[0].name}/epoch_{b.jobs[0].sealed_epoch}.npz"
    assert not store.store.exists(orphan)

    # "SIGKILL": a cold engine bootstraps from the durable chain only
    b2 = Engine(_cfg(), data_dir=str(tmp_path))
    job2 = b2.jobs[0]
    assert job2.committed_epoch == durable
    assert not b2.checkpoint_store.store.exists(orphan)
    # the crashed barrier replays; convergence is exact
    b2.tick(barriers=4, chunks_per_barrier=1)
    assert _mv(b2) == want


def test_pause_resume_mutation():
    """Pause/Resume mutations ride barriers (ref Mutation::Pause)."""
    from risingwave_tpu.stream.message import Barrier, BarrierKind, Mutation
    from risingwave_tpu.common.epoch import EpochPair

    eng = Engine(_cfg())
    eng.execute(DDL)
    eng.tick(barriers=1, chunks_per_barrier=1)
    job = eng.jobs[0]
    n_before = _mv(eng)

    pair = EpochPair(job.epoch.curr.next(), job.epoch.curr)
    job.inject_barrier(Barrier(pair, BarrierKind.CHECKPOINT,
                               Mutation("pause")))
    assert job.run_chunk() == 0  # paused: nothing processed
    pair = EpochPair(job.epoch.curr.next(), job.epoch.curr)
    job.inject_barrier(Barrier(pair, BarrierKind.CHECKPOINT,
                               Mutation("resume")))
    assert job.run_chunk() > 0


def test_soak_windowed_agg_state_stays_bounded():
    """50 barriers of windowed agg with watermarks: live groups, dirty
    sets and tombstones must stay bounded (cleaning + rehash working),
    and counters must stay clean — the unbounded-growth failure mode."""
    import numpy as np

    eng = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10, agg_emit_capacity=256,
        mv_table_size=1 << 12,
    ))
    eng.execute("""
        CREATE SOURCE bid (
            auction BIGINT, bidder BIGINT, price BIGINT,
            channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
            WATERMARK FOR date_time AS date_time
        ) WITH (connector = 'nexmark', nexmark.table = 'bid',
                nexmark.event.rate = '1000');
        CREATE MATERIALIZED VIEW w AS
        SELECT window_start, count(*) AS n
        FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
        GROUP BY window_start;
    """)
    occupied_samples = []
    for _ in range(10):
        eng.tick(barriers=5, chunks_per_barrier=1)
        st = eng.jobs[0].states
        agg_state = next(
            s for s in st if hasattr(s, "row_count")
        )
        occupied_samples.append(int(np.asarray(
            agg_state.table.occupied
        ).sum()))
        assert int(agg_state.overflow) == 0
        assert int(agg_state.inconsistency) == 0
    # live windows bounded: cleaning keeps only open windows (~a few),
    # not the ~14 windows that have closed by the end of the run
    assert max(occupied_samples[3:]) <= 8, occupied_samples
    # and the MV still answers
    rows = eng.execute("SELECT count(*) FROM w")
    assert int(rows[0][0]) > 0
