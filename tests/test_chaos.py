"""Deterministic failure/recovery simulation (the madsim analog).

Reference counterpart: src/tests/simulation — kill nodes mid-stream and
assert the maintained MVs converge to the same result as an undisturbed
run (e.g. recovery/nexmark_recovery.rs, SURVEY.md §4.4).  Determinism
here comes for free: sources are counter-addressed, so replay after
recovery is exact.
"""

import numpy as np

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


DDL = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid');
CREATE MATERIALIZED VIEW q7 AS
SELECT window_start, max(price) AS max_price, count(*) AS bids
FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
GROUP BY window_start;
"""


def _cfg():
    return PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10, agg_emit_capacity=256,
        mv_table_size=1 << 10,
    )


def _mv(eng):
    return sorted(eng.execute("SELECT window_start, max_price, bids FROM q7"))


def test_nexmark_recovery_converges(tmp_path):
    # undisturbed run: 6 barriers
    a = Engine(_cfg())
    a.execute(DDL)
    a.tick(barriers=6, chunks_per_barrier=1)
    want = _mv(a)

    # chaotic run: crash after 2 and 4 barriers (uncommitted progress in
    # flight), recover from the durable store each time
    b = Engine(_cfg(), data_dir=str(tmp_path))
    b.execute(DDL)
    b.tick(barriers=2, chunks_per_barrier=1)
    # progress past the last checkpoint, then "crash"
    b.jobs[0].run_chunk()
    # cold start: fresh engines bootstrap DDL + state from data_dir
    b2 = Engine(_cfg(), data_dir=str(tmp_path))
    b2.tick(barriers=2, chunks_per_barrier=1)
    b2.jobs[0].run_chunk()
    b3 = Engine(_cfg(), data_dir=str(tmp_path))
    b3.tick(barriers=2, chunks_per_barrier=1)

    assert _mv(b3) == want


def test_pause_resume_mutation():
    """Pause/Resume mutations ride barriers (ref Mutation::Pause)."""
    from risingwave_tpu.stream.message import Barrier, BarrierKind, Mutation
    from risingwave_tpu.common.epoch import EpochPair

    eng = Engine(_cfg())
    eng.execute(DDL)
    eng.tick(barriers=1, chunks_per_barrier=1)
    job = eng.jobs[0]
    n_before = _mv(eng)

    pair = EpochPair(job.epoch.curr.next(), job.epoch.curr)
    job.inject_barrier(Barrier(pair, BarrierKind.CHECKPOINT,
                               Mutation("pause")))
    assert job.run_chunk() == 0  # paused: nothing processed
    pair = EpochPair(job.epoch.curr.next(), job.epoch.curr)
    job.inject_barrier(Barrier(pair, BarrierKind.CHECKPOINT,
                               Mutation("resume")))
    assert job.run_chunk() > 0


def test_soak_windowed_agg_state_stays_bounded():
    """50 barriers of windowed agg with watermarks: live groups, dirty
    sets and tombstones must stay bounded (cleaning + rehash working),
    and counters must stay clean — the unbounded-growth failure mode."""
    import numpy as np

    eng = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10, agg_emit_capacity=256,
        mv_table_size=1 << 12,
    ))
    eng.execute("""
        CREATE SOURCE bid (
            auction BIGINT, bidder BIGINT, price BIGINT,
            channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
            WATERMARK FOR date_time AS date_time
        ) WITH (connector = 'nexmark', nexmark.table = 'bid',
                nexmark.event.rate = '1000');
        CREATE MATERIALIZED VIEW w AS
        SELECT window_start, count(*) AS n
        FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
        GROUP BY window_start;
    """)
    occupied_samples = []
    for _ in range(10):
        eng.tick(barriers=5, chunks_per_barrier=1)
        st = eng.jobs[0].states
        agg_state = next(
            s for s in st if hasattr(s, "row_count")
        )
        occupied_samples.append(int(np.asarray(
            agg_state.table.occupied
        ).sum()))
        assert int(agg_state.overflow) == 0
        assert int(agg_state.inconsistency) == 0
    # live windows bounded: cleaning keeps only open windows (~a few),
    # not the ~14 windows that have closed by the end of the run
    assert max(occupied_samples[3:]) <= 8, occupied_samples
    # and the MV still answers
    rows = eng.execute("SELECT count(*) FROM w")
    assert int(rows[0][0]) > 0
