"""sqllogictest-format e2e tests (the reference's e2e mechanism)."""

import glob
import os

import pytest

from risingwave_tpu.slt import run_slt
from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig

SLT_DIR = os.path.join(os.path.dirname(__file__), "slt")


@pytest.mark.parametrize(
    "path", sorted(glob.glob(os.path.join(SLT_DIR, "*.slt")))
)
def test_slt_file(path):
    eng = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10, agg_emit_capacity=256,
        mv_table_size=1 << 10, mv_ring_size=1 << 13,
        join_table_size=1 << 10, join_bucket_cap=1024,
        join_out_capacity=1 << 14,
    ))
    n = run_slt(eng, path)
    assert n > 0
